"""Synthetic dataset trees in the on-disk layouts the adapters expect.

Each builder writes a tiny but *layout-faithful* tree for one benchmark
(reference directory conventions: core/stereo_datasets.py:123-274), so that
dataset readers, evaluators, the CLI-to-CLI parity harness
(scripts/parity_cli.py) and the convergence demo can all run on hosts with
no real data.  The trees are intentionally readable by BOTH this framework's
adapters and the reference's ``stereo_datasets.py`` — that equivalence is
what the parity harness relies on.
"""

from __future__ import annotations

import os
from os.path import join

import numpy as np
from PIL import Image

from .codecs import write_pfm
from .png16 import write_png16

__all__ = [
    "make_synthetic_kitti", "make_learnable_kitti", "make_synthetic_eth3d",
    "make_synthetic_middlebury", "make_synthetic_things_test",
    "make_synthetic_sl", "ShiftStereoDataset", "StereoVideoSequence",
]


class ShiftStereoDataset:
    """In-memory, *learnable* stereo pairs: a smooth random texture and its
    horizontally shifted copy, ground-truth disparity = the shift.

    Matched texture makes the correlation volume genuinely informative, so a
    model can drive EPE toward zero by learning — unlike the independent
    random images in the tree builders above, which have no learnable
    structure.  Used by the convergence demonstration
    (scripts/overfit_demo.py, tests/test_convergence.py): overfitting this
    set proves the whole training pipeline (loss, optimizer, schedule,
    gradients) *learns*, not just runs.

    Items use the data-layer protocol: (meta, img1, img2, flow(H,W,1), valid).
    """

    def __init__(self, n=16, hw=(64, 96), max_disp=8.0, seed=0):
        h, w = hw
        rng = np.random.default_rng(seed)
        self._items = []
        for i in range(n):
            d = float(rng.uniform(2.0, max_disp))
            di = int(round(d))
            # Smooth texture (random low-res upsampled) so matching is
            # locally unambiguous at integer-pixel precision.
            low = rng.uniform(0, 255, (h // 4 + 1, (w + di) // 4 + 2, 3))
            tex = np.kron(low, np.ones((4, 4, 1)))[:h, :w + di]
            # left(x) matches right(x - d): right(y) = left(y + d).
            img1 = tex[:, :w].astype(np.float32)          # left
            img2 = tex[:, di:di + w].astype(np.float32)   # right
            flow = np.full((h, w, 1), -float(di), np.float32)
            valid = np.ones((h, w), np.float32)
            self._items.append((["synthetic", i], img1, img2, flow, valid))

    def reseed(self, seed):  # loader protocol; the set is static
        pass

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i % len(self._items)]


class StereoVideoSequence:
    """Temporally coherent synthetic moving-camera stereo sequence with
    exact ground-truth disparity — the CPU-testable workload for the
    streaming subsystem (stream/, docs/streaming.md).

    One shared smooth texture (same construction as
    :class:`ShiftStereoDataset`, so the correlation volume is genuinely
    informative); per frame ``t`` the camera pans ``pan`` px across it and
    the scene depth drifts so the disparity is ``round(d0 + drift * t)``
    px.  Integer per-frame disparities keep the ground truth exact
    (``right(y) = left(y + d_t)`` by slicing, no resampling), while
    consecutive frames stay close enough that forward-warping frame t-1's
    disparity is a good init for frame t — exactly the property the
    warm-start policy exploits.

    ``frames`` is a list of ``(left, right, flow)`` with images (H, W, 3)
    float32 in [0, 255] and ``flow`` the (H, W, 1) NEGATIVE disparity
    (dataset sign convention, reference: core/stereo_datasets.py:77).
    """

    def __init__(self, n_frames=8, hw=(64, 96), d0=4.0, drift=0.5, pan=2,
                 seed=0):
        h, w = hw
        rng = np.random.default_rng(seed)
        ds = [int(round(d0 + drift * t)) for t in range(n_frames)]
        assert all(d >= 1 for d in ds), (
            f"disparity must stay >= 1 px over the sequence, got {ds}")
        span = w + abs(pan) * (n_frames - 1) + max(ds) + 4
        low = rng.uniform(0, 255, (h // 4 + 1, span // 4 + 2, 3))
        tex = np.kron(low, np.ones((4, 4, 1)))[:h, :span]
        self.frames = []
        for t, d in enumerate(ds):
            x0 = abs(pan) * t if pan >= 0 else abs(pan) * (n_frames - 1 - t)
            left = tex[:, x0:x0 + w].astype(np.float32)
            right = tex[:, x0 + d:x0 + d + w].astype(np.float32)
            flow = np.full((h, w, 1), -float(d), np.float32)
            self.frames.append((left, right, flow))

    def __len__(self):
        return len(self.frames)

    def __getitem__(self, t):
        return self.frames[t]


def make_synthetic_kitti(root, n=6, hw=(120, 160), rng=None):
    """KITTI-2015 training split: image_2/image_3 pairs + 16-bit disp_occ_0
    (reference: core/stereo_datasets.py:246-257)."""
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    os.makedirs(join(root, "training", "image_2"))
    os.makedirs(join(root, "training", "image_3"))
    os.makedirs(join(root, "training", "disp_occ_0"))
    for i in range(n):
        for cam in ("image_2", "image_3"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(
                join(root, "training", cam, f"{i:06d}_10.png"))
        disp = (rng.uniform(1, 60, (h, w)) * 256).astype(np.uint16)
        write_png16(join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
                    disp)


def make_learnable_kitti(root, n=48, hw=(352, 744), max_disp=24, rng=None):
    """KITTI-2015-layout tree whose pairs are actually LEARNABLE: smooth
    textures with a constant integer shift per image, ground truth = the
    shift (the on-disk twin of :class:`ShiftStereoDataset`, same
    ``right(y) = left(y + d)`` convention).

    The plain :func:`make_synthetic_kitti` writes independent random images
    — fine for layout/reader tests, useless for a training run whose loss
    curve should DECREASE.  This builder feeds the long-horizon chip
    training demonstration (scripts/longrun_tpu.py): training on it through
    the full KITTI adapter + sparse-augmentor path drives EPE toward zero,
    so the recorded curve proves optimization health, not just throughput.
    """
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    os.makedirs(join(root, "training", "image_2"))
    os.makedirs(join(root, "training", "image_3"))
    os.makedirs(join(root, "training", "disp_occ_0"))
    for i in range(n):
        d = int(rng.integers(4, max_disp + 1))
        low = rng.uniform(0, 255, (h // 4 + 1, (w + d) // 4 + 2, 3))
        tex = np.kron(low, np.ones((4, 4, 1)))[:h, :w + d]
        left = tex[:, :w].astype(np.uint8)
        right = tex[:, d:d + w].astype(np.uint8)
        Image.fromarray(left).save(
            join(root, "training", "image_2", f"{i:06d}_10.png"))
        Image.fromarray(right).save(
            join(root, "training", "image_3", f"{i:06d}_10.png"))
        disp = np.full((h, w), d * 256, np.uint16)  # KITTI 16-bit: px * 256
        write_png16(join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
                    disp)


def make_synthetic_eth3d(root, n=3, hw=(96, 128), rng=None):
    """ETH3D two-view training split with PFM ground truth
    (reference: core/stereo_datasets.py:187-197)."""
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    for i in range(n):
        scene = join(root, "two_view_training", f"scene{i}")
        gt = join(root, "two_view_training_gt", f"scene{i}")
        os.makedirs(scene), os.makedirs(gt)
        for name in ("im0.png", "im1.png"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(join(scene, name))
        disp = rng.uniform(1, 40, (h, w)).astype(np.float32)
        write_pfm(join(gt, "disp0GT.pfm"), disp)


def make_synthetic_middlebury(root, scenes=("Adirondack", "Jadeplant"),
                              hw=(96, 128), rng=None):
    """MiddEval3 trainingF scenes with official_train.txt filter and nocc
    masks (reference: core/stereo_datasets.py:260-274)."""
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    base = join(root, "MiddEval3")
    os.makedirs(base)
    with open(join(base, "official_train.txt"), "w") as f:
        f.write("\n".join(scenes) + "\n")
    for scene in scenes:
        d = join(base, "trainingF", scene)
        os.makedirs(d)
        for name in ("im0.png", "im1.png"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(join(d, name))
        disp = rng.uniform(1, 40, (h, w)).astype(np.float32)
        disp[:4] = np.inf  # occluded/unknown rows -> flow -inf, filtered
        write_pfm(join(d, "disp0GT.pfm"), disp)
        mask = np.full((h, w), 255, np.uint8)
        mask[:8] = 128  # occluded band
        Image.fromarray(mask).save(join(d, "mask0nocc.png"))


def make_synthetic_things_test(root, n=2, hw=(96, 128), rng=None):
    """FlyingThings3D finalpass TEST split
    (reference: core/stereo_datasets.py:137-155)."""
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    # 400-image seeded val subset selects indices from the TEST file list
    # (reference: core/stereo_datasets.py:146-149); with n<=400 all survive.
    for i in range(n):
        img_dir = join(root, "FlyingThings3D", "frames_finalpass", "TEST",
                       "A", f"{i:04d}", "left")
        rdir = join(root, "FlyingThings3D", "frames_finalpass", "TEST",
                    "A", f"{i:04d}", "right")
        ddir = join(root, "FlyingThings3D", "disparity", "TEST",
                    "A", f"{i:04d}", "left")
        os.makedirs(img_dir), os.makedirs(rdir), os.makedirs(ddir)
        for d in (img_dir, rdir):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(join(d, "0006.png"))
        disp = rng.uniform(1, 40, (h, w)).astype(np.float32)
        disp[0, :] = 300.0  # beyond the |gt|<192 filter
        write_pfm(join(ddir, "0006.pfm"), disp)


def make_synthetic_sl(root, scenes=("sceneA",), poses=("0001",), hw=(32, 40),
                      rng=None):
    """Structured-light capture tree: ambient pair + 9 pattern pairs +
    three-phase images + depth maps (reference: core/sl_datasets.py:100-141)."""
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    for scene in scenes:
        amb = join(root, scene, "ambient_light")
        os.makedirs(amb)
        for pose in poses:
            for side in ("L", "R"):
                img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                Image.fromarray(img).save(join(amb, f"{pose}_{side}.png"))
            tp = join(root, scene, "three_phase")
            os.makedirs(tp, exist_ok=True)
            base = rng.integers(60, 190, (h, w), dtype=np.uint8)
            for i, phase in enumerate((0, 40, 80)):
                for side in ("l", "r"):
                    Image.fromarray((base + phase) % 255).save(
                        join(tp, f"{pose}_tp{i+1}_{side}.png"))
            for k in range(9):
                pd = join(root, scene, f"pattern_{k}")
                os.makedirs(pd, exist_ok=True)
                for side in ("l", "r"):
                    pat = (rng.random((h, w)) > 0.5).astype(np.uint8) * 255
                    Image.fromarray(pat).save(join(pd, f"{pose}_B_{side}.png"))
            dp = join(root, scene, "depth")
            os.makedirs(dp, exist_ok=True)
            for side in ("L", "R"):
                np.save(join(dp, f"{pose}_depth_{side}.npy"),
                        rng.uniform(50, 200, (h, w)).astype(np.float32))
