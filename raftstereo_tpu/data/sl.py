"""Structured-light (SL) dataset pipeline — the fork's WIP feature, working.

The reference fork ships a half-finished SL pipeline: its dataset always has
length 0 (``__len__`` reads a never-populated list, reference:
core/sl_datasets.py:199-200 vs :209) and its trainer imports a module that
does not exist (reference: train_stereo.py:18).  This is the same capability
in working form.

Scene layout (reference: core/sl_datasets.py:104-154,
utils/dataset_original.py:104-180):

    root/<scene>/ambient_light/<pose>_L.png, <pose>_R.png
    root/<scene>/pattern_<k>/<pose>_B_l.png, <pose>_B_r.png      k = 0..8
    root/<scene>/three_phase/<pose>_tp{1,2,3}_l.png, _tp{1,2,3}_r.png
    root/<scene>/depth/<pose>_depth_L.npy, _depth_R.npy          (optional)

Per sample: ambient left/right images; an 18-channel pattern mask stack
(9 right + 9 left) gated by a phase-modulation uncertainty mask; and, when
depth is present, disparity targets via disp = focal * baseline / depth
(configurable — the reference hardcodes focal 911.70 / baseline 5.563,
utils/dataset_original.py:159-161).
"""

from __future__ import annotations

import glob as globlib
import os
import os.path as osp
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np
from PIL import Image

from .augment import resize_bilinear


def modulation(i1: np.ndarray, i2: np.ndarray, i3: np.ndarray) -> np.ndarray:
    """Three-phase modulation amplitude (reference: core/sl_datasets.py:123-126):
    M = (2*sqrt(2)/3) * sqrt((I1-I2)^2 + (I1-I3)^2 + (I2-I3)^2)."""
    d12 = i1.astype(np.float32) - i2.astype(np.float32)
    d13 = i1.astype(np.float32) - i3.astype(np.float32)
    d23 = i2.astype(np.float32) - i3.astype(np.float32)
    return (2.0 * np.sqrt(2.0) / 3.0) * np.sqrt(d12 ** 2 + d13 ** 2 + d23 ** 2)


@dataclass(frozen=True)
class SLCalibration:
    """Stereo rig calibration for depth->disparity conversion."""
    focal: float = 911.7019228756361
    baseline: float = 5.563167785169519


class StructuredLightDataset:
    """Map-style SL dataset returning
    (imgL, imgR, mask18[, disparity, depth_mask]).

    * ``split='training'`` gates pattern masks by a randomised modulation
      threshold |10 + 9*N(0,1)| per sample; ``'validation'`` uses the fixed
      threshold 5 (reference: core/sl_datasets.py:135-141).
    * Images/masks are optionally downscaled by ``scale``.
    * When ``with_depth``, returns normalised signed disparities
      (left->right positive, right->left negative, both /W) and validity
      masks, mirroring utils/dataset_original.py:159-180.
    """

    def __init__(self, root: str, split: str = "training", scale: float = 0.5,
                 num_patterns: int = 9, with_depth: bool = False,
                 calibration: SLCalibration = SLCalibration(),
                 file_list: Optional[str] = None):
        assert split in ("training", "validation"), split
        self.root = root
        self.split = split
        self.scale = scale
        self.num_patterns = num_patterns
        self.with_depth = with_depth
        self.calib = calibration
        self.rng = np.random.default_rng(0)

        if file_list is not None:
            with open(file_list, "r") as f:
                entries = [ln.strip() for ln in f if ln.strip()]
            self.samples = [self._parse_entry(e) for e in entries]
        else:
            ambients = sorted(globlib.glob(
                osp.join(root, "*", "ambient_light", "*_L.png")))
            self.samples = [
                (osp.basename(osp.dirname(osp.dirname(p))),
                 osp.basename(p)[:-len("_L.png")])
                for p in ambients]

    def _parse_entry(self, entry: str) -> Tuple[str, str]:
        """File-list entries are paths like <...>/<scene>/<anything>/<pose>_R.png
        (the fork's SL/img_r_list_full.txt format, core/sl_datasets.py:165-167)."""
        parts = entry.split("/")
        return parts[-3], parts[-1][:-len("_R.png")]

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self.samples)

    def _scene_path(self, scene: str, sub: str, name: str) -> str:
        return osp.join(self.root, scene, sub, name)

    def _load(self, scene, sub, name, gray=False) -> np.ndarray:
        img = Image.open(self._scene_path(scene, sub, name))
        if gray:
            img = img.convert("L")
        arr = np.asarray(img)
        if self.scale != 1.0:
            arr = resize_bilinear(arr, self.scale, self.scale)
        return arr

    def __getitem__(self, index: int):
        scene, pose = self.samples[index]

        img_l = self._load(scene, "ambient_light", f"{pose}_L.png")
        img_r = self._load(scene, "ambient_light", f"{pose}_R.png")
        if img_l.ndim == 2:
            img_l = np.tile(img_l[..., None], (1, 1, 3))
            img_r = np.tile(img_r[..., None], (1, 1, 3))

        # Phase-modulation uncertainty gates (full resolution, pre-scaling
        # in the reference; we compute at native res then scale the gated
        # masks like the reference does).
        def load_tp(side):
            return [np.asarray(Image.open(self._scene_path(
                scene, "three_phase", f"{pose}_tp{i}_{side}.png")).convert("L"),
                np.float32) for i in (1, 2, 3)]

        mod_l = modulation(*load_tp("l"))
        mod_r = modulation(*load_tp("r"))
        if self.split == "training":
            threshold = abs(10.0 + 9.0 * self.rng.standard_normal())
        else:
            threshold = 5.0
        gate_l = (mod_l > threshold).astype(np.float32)
        gate_r = (mod_r > threshold).astype(np.float32)

        masks = []
        for side, gate in (("r", gate_r), ("l", gate_l)):
            for k in range(self.num_patterns):
                pat = np.asarray(Image.open(self._scene_path(
                    scene, f"pattern_{k}", f"{pose}_B_{side}.png")).convert("L"),
                    np.float32)
                gated = pat * gate
                if self.scale != 1.0:
                    gated = resize_bilinear(gated, self.scale, self.scale)
                masks.append(np.round(gated / 255.0))
        mask = np.stack(masks, axis=-1).astype(np.float32)   # (H, W, 18)

        out = (img_l.astype(np.float32), img_r.astype(np.float32), mask)
        if not self.with_depth:
            return out

        depth_l = np.load(self._scene_path(scene, "depth", f"{pose}_depth_L.npy"))
        depth_r = np.load(self._scene_path(scene, "depth", f"{pose}_depth_R.npy"))
        if self.scale != 1.0:
            depth_l = resize_bilinear(depth_l, self.scale, self.scale)
            depth_r = resize_bilinear(depth_r, self.scale, self.scale)
        w = depth_l.shape[1]
        num = self.calib.focal * self.calib.baseline
        disp_l2r = np.clip(num / (depth_l + 1e-9), 0.0, w) / w
        disp_r2l = -np.clip(num / (depth_r + 1e-9), 0.0, w) / w
        disparity = np.stack([disp_r2l, disp_l2r], axis=-1).astype(np.float32)
        depth_mask = np.stack([(depth_r > 1e-9), (depth_l > 1e-9)],
                              axis=-1).astype(np.float32)
        return out + (disparity, depth_mask)


class SLStereoView:
    """Adapter exposing the SL dataset through the standard stereo-loader
    contract ``(meta, img1, img2, disp_flow, valid)`` so it can feed
    ``DataLoader`` / the trainer directly.

    The raw ``StructuredLightDataset`` tuples (imgL, imgR, mask18[, ...]) are
    a different modality and MUST NOT be passed to the generic loader — its
    worker would silently mislabel the fields.  This view converts the
    left->right normalised disparity back to pixel units and to the
    framework's negative-x-flow convention (core/stereo_datasets.py:77).
    """

    def __init__(self, dataset: "StructuredLightDataset",
                 crop_size: Optional[Tuple[int, int]] = None):
        assert dataset.with_depth, "stereo view needs with_depth=True"
        self._ds = dataset
        # Fixed-size random crop so batches have static shapes for the
        # jitted train step. SL captures must NOT be photometrically
        # jittered (it would destroy the projected-pattern modulation the
        # masks encode), so cropping is the only augmentation here.
        self.crop_size = tuple(crop_size) if crop_size else None
        self.rng = np.random.default_rng(0)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)
        self._ds.reseed(seed)

    def __len__(self) -> int:
        return len(self._ds)

    def __getitem__(self, index: int):
        img_l, img_r, _mask, disparity, depth_mask = self._ds[index]
        w = disparity.shape[1]
        flow = (-disparity[..., 1:2] * w).astype(np.float32)  # px, negative
        valid = depth_mask[..., 1].astype(np.float32)
        meta = list(self._ds.samples[index])
        if self.crop_size is not None:
            ch, cw = self.crop_size
            h, w_ = img_l.shape[:2]
            if h < ch or w_ < cw:
                raise ValueError(f"SL frame {h}x{w_} smaller than crop "
                                 f"{ch}x{cw}; lower crop_size or raise scale")
            y0 = int(self.rng.integers(0, h - ch + 1))
            x0 = int(self.rng.integers(0, w_ - cw + 1))
            sl = np.s_[y0:y0 + ch, x0:x0 + cw]
            img_l, img_r = img_l[sl], img_r[sl]
            flow, valid = flow[sl], valid[sl]
        return meta, img_l, img_r, flow, valid


def fetch_sl_dataset(root: str, **kwargs) -> StructuredLightDataset:
    """Working equivalent of the fork's ``sl_datasets.fetch_dataloader``
    (reference: core/sl_datasets.py:214-234, broken as shipped)."""
    ds = StructuredLightDataset(root, **kwargs)
    assert len(ds) > 0, f"no SL samples under {root}"
    return ds
