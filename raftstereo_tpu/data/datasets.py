"""Dataset registry: map-style stereo datasets + mixing logic.

Capability mirror of the reference's dataset layer
(reference: core/stereo_datasets.py), torch-free.  Samples are NHWC numpy:
``(meta, img1, img2, flow, valid)`` with flow = [-disparity] single-channel
(the stereo sign convention, reference: core/stereo_datasets.py:77,107).
Directory layouts match the reference so existing dataset trees drop in.
"""

from __future__ import annotations

import copy
import glob as globlib
import logging
import os
import os.path as osp
import re
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from . import codecs
from .augment import FlowAugmentor, SparseFlowAugmentor

logger = logging.getLogger(__name__)


class StereoDataset:
    """Base map-style dataset (reference: core/stereo_datasets.py:21-120)."""

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False,
                 reader: Optional[Callable] = None):
        aug_params = dict(aug_params) if aug_params is not None else None
        self.augmentor = None
        self.sparse = sparse
        self.img_pad = (aug_params.pop("img_pad", None)
                        if aug_params is not None else None)
        if aug_params is not None and "crop_size" in aug_params:
            cls = SparseFlowAugmentor if sparse else FlowAugmentor
            self.augmentor = cls(**aug_params)
        self.disparity_reader = reader or codecs.read_gen
        self.is_test = False
        self.rng = np.random.default_rng(0)
        self.flow_list: List[str] = []
        self.disparity_list: List[str] = []
        self.image_list: List[List[str]] = []
        self.extra_info: List = []

    def reseed(self, seed: int) -> None:
        """Per-worker/per-epoch reseeding hook (the reference seeds torch
        worker processes instead: core/stereo_datasets.py:55-61)."""
        self.rng = np.random.default_rng(seed)

    def __getitem__(self, index: int):
        if self.is_test:
            img1 = np.asarray(codecs.read_gen(self.image_list[index][0]),
                              np.uint8)[..., :3]
            img2 = np.asarray(codecs.read_gen(self.image_list[index][1]),
                              np.uint8)[..., :3]
            return (img1.astype(np.float32), img2.astype(np.float32),
                    self.extra_info[index])

        index = index % len(self.image_list)
        disp = self.disparity_reader(self.disparity_list[index])
        if isinstance(disp, tuple):
            disp, valid = disp
        else:
            valid = disp < 512

        img1 = np.asarray(codecs.read_gen(self.image_list[index][0]), np.uint8)
        img2 = np.asarray(codecs.read_gen(self.image_list[index][1]), np.uint8)
        disp = np.asarray(disp, np.float32)
        flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)

        if img1.ndim == 2:
            img1 = np.tile(img1[..., None], (1, 1, 3))
            img2 = np.tile(img2[..., None], (1, 1, 3))
        else:
            img1 = img1[..., :3]
            img2 = img2[..., :3]

        if self.augmentor is not None:
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(img1, img2, flow,
                                                         valid, self.rng)
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow, self.rng)

        img1 = img1.astype(np.float32)
        img2 = img2.astype(np.float32)
        flow = flow.astype(np.float32)
        if self.sparse:
            valid = valid.astype(np.float32)
        else:
            valid = ((np.abs(flow[..., 0]) < 512)
                     & (np.abs(flow[..., 1]) < 512)).astype(np.float32)

        if self.img_pad is not None:
            pad_h, pad_w = self.img_pad
            pad = ((pad_h, pad_h), (pad_w, pad_w), (0, 0))
            img1 = np.pad(img1, pad)
            img2 = np.pad(img2, pad)

        meta = self.image_list[index] + [self.disparity_list[index]]
        return meta, img1, img2, flow[..., :1], valid

    def __mul__(self, v: int) -> "StereoDataset":
        out = copy.deepcopy(self)
        out.flow_list = v * out.flow_list
        out.image_list = v * out.image_list
        out.disparity_list = v * out.disparity_list
        out.extra_info = v * out.extra_info
        return out

    def __add__(self, other: "StereoDataset") -> "ConcatDataset":
        return ConcatDataset([self, other])

    def __len__(self) -> int:
        return len(self.image_list)


class ConcatDataset:
    """Dataset concatenation (torch's `+` equivalent)."""

    def __init__(self, parts: Sequence):
        self.parts = []
        for p in parts:
            if isinstance(p, ConcatDataset):
                self.parts.extend(p.parts)
            else:
                self.parts.append(p)

    def reseed(self, seed: int) -> None:
        for i, p in enumerate(self.parts):
            p.reseed(seed + i)

    def __add__(self, other):
        return ConcatDataset([self, other])

    def __len__(self):
        return sum(len(p) for p in self.parts)

    def __getitem__(self, index):
        for p in self.parts:
            if index < len(p):
                return p[index]
            index -= len(p)
        raise IndexError(index)


# ----------------------------------------------------------------- adapters

class SceneFlowDatasets(StereoDataset):
    """FlyingThings3D + Monkaa + Driving
    (reference: core/stereo_datasets.py:123-184)."""

    def __init__(self, aug_params=None, root="datasets",
                 dstype="frames_cleanpass", things_test=False):
        super().__init__(aug_params)
        self.root = root
        self.dstype = dstype
        if things_test:
            self._add_things("TEST")
        else:
            self._add_things("TRAIN")
            self._add_monkaa()
            self._add_driving()

    def _add_things(self, split="TRAIN"):
        original = len(self.disparity_list)
        root = osp.join(self.root, "FlyingThings3D")
        left = sorted(globlib.glob(osp.join(root, self.dstype, split,
                                            "*/*/left/*.png")))
        right = [p.replace("left", "right") for p in left]
        disp = [p.replace(self.dstype, "disparity").replace(".png", ".pfm")
                for p in left]
        # Seeded 400-image validation subset
        # (reference: core/stereo_datasets.py:146-149).
        val_idxs = set(np.random.RandomState(1000).permutation(len(left))[:400])
        for idx, (i1, i2, d) in enumerate(zip(left, right, disp)):
            if (split == "TEST" and idx in val_idxs) or split == "TRAIN":
                self.image_list.append([i1, i2])
                self.disparity_list.append(d)
        logger.info("Added %d from FlyingThings %s",
                    len(self.disparity_list) - original, self.dstype)

    def _add_monkaa(self):
        root = osp.join(self.root, "Monkaa")
        left = sorted(globlib.glob(osp.join(root, self.dstype, "*/left/*.png")))
        for i1 in left:
            self.image_list.append([i1, i1.replace("left", "right")])
            self.disparity_list.append(
                i1.replace(self.dstype, "disparity").replace(".png", ".pfm"))

    def _add_driving(self):
        root = osp.join(self.root, "Driving")
        left = sorted(globlib.glob(osp.join(root, self.dstype,
                                            "*/*/*/left/*.png")))
        for i1 in left:
            self.image_list.append([i1, i1.replace("left", "right")])
            self.disparity_list.append(
                i1.replace(self.dstype, "disparity").replace(".png", ".pfm"))


class ETH3D(StereoDataset):
    """(reference: core/stereo_datasets.py:187-197)"""

    def __init__(self, aug_params=None, root="datasets/ETH3D", split="training"):
        super().__init__(aug_params, sparse=True)
        im0 = sorted(globlib.glob(osp.join(root, f"two_view_{split}/*/im0.png")))
        im1 = sorted(globlib.glob(osp.join(root, f"two_view_{split}/*/im1.png")))
        if split == "training":
            disp = sorted(globlib.glob(
                osp.join(root, "two_view_training_gt/*/disp0GT.pfm")))
        else:
            disp = [osp.join(root, "two_view_training_gt/playground_1l/disp0GT.pfm")
                    ] * len(im0)
        for i1, i2, d in zip(im0, im1, disp):
            self.image_list.append([i1, i2])
            self.disparity_list.append(d)


class SintelStereo(StereoDataset):
    """(reference: core/stereo_datasets.py:199-210)"""

    def __init__(self, aug_params=None, root="datasets/SintelStereo"):
        super().__init__(aug_params, sparse=True,
                         reader=codecs.read_disp_sintel)
        im0 = sorted(globlib.glob(osp.join(root, "training/*_left/*/frame_*.png")))
        im1 = sorted(globlib.glob(osp.join(root, "training/*_right/*/frame_*.png")))
        disp = sorted(globlib.glob(
            osp.join(root, "training/disparities/*/frame_*.png"))) * 2
        for i1, i2, d in zip(im0, im1, disp):
            assert i1.split("/")[-2:] == d.split("/")[-2:], (i1, d)
            self.image_list.append([i1, i2])
            self.disparity_list.append(d)


class FallingThings(StereoDataset):
    """(reference: core/stereo_datasets.py:212-226)"""

    def __init__(self, aug_params=None, root="datasets/FallingThings"):
        super().__init__(aug_params, reader=codecs.read_disp_fallingthings)
        assert os.path.exists(root), root
        with open(osp.join(root, "filenames.txt"), "r") as f:
            filenames = sorted(f.read().splitlines())
        for e in filenames:
            self.image_list.append([osp.join(root, e),
                                    osp.join(root, e.replace("left.jpg",
                                                             "right.jpg"))])
            self.disparity_list.append(
                osp.join(root, e.replace("left.jpg", "left.depth.png")))


class TartanAir(StereoDataset):
    """(reference: core/stereo_datasets.py:228-244)"""

    def __init__(self, aug_params=None, root="datasets", keywords=()):
        super().__init__(aug_params, reader=codecs.read_disp_tartanair)
        assert os.path.exists(root), root
        with open(osp.join(root, "tartanair_filenames.txt"), "r") as f:
            filenames = sorted(
                s for s in f.read().splitlines()
                if "seasonsforest_winter/Easy" not in s)
        for kw in keywords:
            filenames = sorted(s for s in filenames if kw in s.lower())
        for e in filenames:
            self.image_list.append([osp.join(root, e),
                                    osp.join(root, e.replace("_left", "_right"))])
            self.disparity_list.append(
                osp.join(root, e.replace("image_left", "depth_left")
                         .replace("left.png", "left_depth.npy")))


class KITTI(StereoDataset):
    """(reference: core/stereo_datasets.py:246-257)"""

    def __init__(self, aug_params=None, root="datasets/KITTI",
                 image_set="training"):
        super().__init__(aug_params, sparse=True, reader=codecs.read_disp_kitti)
        assert os.path.exists(root), root
        im0 = sorted(globlib.glob(osp.join(root, image_set, "image_2/*_10.png")))
        im1 = sorted(globlib.glob(osp.join(root, image_set, "image_3/*_10.png")))
        if image_set == "training":
            disp = sorted(globlib.glob(osp.join(root, "training",
                                                "disp_occ_0/*_10.png")))
        else:
            disp = [osp.join(root, "training/disp_occ_0/000085_10.png")] * len(im0)
        for i1, i2, d in zip(im0, im1, disp):
            self.image_list.append([i1, i2])
            self.disparity_list.append(d)


class Middlebury(StereoDataset):
    """(reference: core/stereo_datasets.py:260-274)"""

    def __init__(self, aug_params=None, root="datasets/Middlebury", split="F"):
        super().__init__(aug_params, sparse=True,
                         reader=codecs.read_disp_middlebury)
        assert os.path.exists(root), root
        assert split in "FHQ", split
        lines = [osp.basename(p) for p in
                 globlib.glob(osp.join(root, "MiddEval3/trainingF/*"))]
        official = Path(osp.join(root, "MiddEval3/official_train.txt")
                        ).read_text().splitlines()
        lines = [p for p in lines if any(s in p.split("/") for s in official)]
        im0 = sorted(osp.join(root, "MiddEval3", f"training{split}",
                              f"{name}/im0.png") for name in lines)
        im1 = sorted(osp.join(root, "MiddEval3", f"training{split}",
                              f"{name}/im1.png") for name in lines)
        disp = sorted(osp.join(root, "MiddEval3", f"training{split}",
                               f"{name}/disp0GT.pfm") for name in lines)
        assert len(im0) == len(im1) == len(disp) > 0, (root, split)
        for i1, i2, d in zip(im0, im1, disp):
            self.image_list.append([i1, i2])
            self.disparity_list.append(d)


# ----------------------------------------------------------------- mixing

def expand_img_gamma(img_gamma):
    """(GMIN, GMAX) shorthand -> (GMIN, GMAX, GAIN_MIN, GAIN_MAX)."""
    g = tuple(img_gamma)
    if len(g) == 2:
        g = g + (1.0, 1.0)
    if len(g) != 4:
        raise ValueError(f"img_gamma needs 2 or 4 values, got {g}")
    return g


def take_photometric_params(dataset):
    """Disable host photometric augmentation on every leaf of ``dataset``
    and return the parameters the host WOULD have used, as kwargs for
    ``device_aug.DevicePhotometric`` — so --device_photometric mirrors the
    exact per-dataset distribution (sparse augmentors use smaller ranges
    and are always symmetric; reference: core/utils/augmentor.py:78,200).

    Raises if leaves disagree on any photometric parameter (including the
    dense/sparse default split): one device parameter set cannot reproduce
    two host distributions.
    """
    leaves = dataset.parts if isinstance(dataset, ConcatDataset) else [dataset]
    params = None
    for leaf in leaves:
        aug = getattr(leaf, "augmentor", None)
        if aug is None:
            continue
        aug.photometric = False
        leaf_params = dict(
            brightness=aug.photo.brightness, contrast=aug.photo.contrast,
            saturation=aug.photo.saturation, hue=aug.photo.hue,
            gamma=aug.photo.gamma,
            asymmetric_prob=getattr(aug, "asymmetric_color_aug_prob", 0.0),
            eraser_prob=aug.eraser_aug_prob,
            # Host erases pre-flip img2; a stereo eye-swap flip makes that
            # the left eye with the flip's probability (device_aug.__init__).
            erase_left_prob=(aug.h_flip_prob
                             if getattr(aug, "do_flip", False) == "h"
                             else 0.0))
        if params is not None and leaf_params != params:
            raise ValueError(
                "--device_photometric cannot mix datasets whose host "
                "augmentors use different photometric parameters "
                f"({params} vs {leaf_params}); one device parameter set "
                "cannot reproduce two host distributions — train with host "
                "augmentation or in separate runs")
        params = leaf_params
    if params is None:
        raise ValueError(
            "--device_photometric needs an augmented training dataset "
            "(crop_size in aug_params)")
    return params


def build_aug_params(image_size, spatial_scale=(0.0, 0.0), noyjitter=False,
                     saturation_range=None, img_gamma=None, do_flip=None):
    """Flag translation (reference: core/stereo_datasets.py:280-286)."""
    aug_params = {"crop_size": tuple(image_size),
                  "min_scale": spatial_scale[0], "max_scale": spatial_scale[1],
                  "do_flip": False, "yjitter": not noyjitter}
    if saturation_range is not None:
        aug_params["saturation_range"] = tuple(saturation_range)
    if img_gamma is not None:
        aug_params["gamma"] = expand_img_gamma(img_gamma)
    if do_flip is not None:
        aug_params["do_flip"] = do_flip
    return aug_params


def fetch_dataset(train_datasets: Sequence[str], aug_params: dict,
                  root_overrides: Optional[dict] = None):
    """Mix datasets by name with the reference's hand-tuned replication
    (reference: core/stereo_datasets.py:288-309)."""
    roots = root_overrides or {}
    train_dataset = None
    for name in train_datasets:
        if re.fullmatch("middlebury_.*", name):
            new = Middlebury(aug_params, split=name.replace("middlebury_", ""),
                             **({"root": roots["middlebury"]}
                                if "middlebury" in roots else {}))
        elif name == "sceneflow":
            kw = {"root": roots["sceneflow"]} if "sceneflow" in roots else {}
            clean = SceneFlowDatasets(aug_params, dstype="frames_cleanpass", **kw)
            final = SceneFlowDatasets(aug_params, dstype="frames_finalpass", **kw)
            new = (clean * 4) + (final * 4)
        elif "kitti" in name:
            kw = {"root": roots["kitti"]} if "kitti" in roots else {}
            new = KITTI(aug_params, **kw)
        elif name == "sintel_stereo":
            kw = {"root": roots["sintel"]} if "sintel" in roots else {}
            new = SintelStereo(aug_params, **kw) * 140
        elif name == "falling_things":
            kw = {"root": roots["falling_things"]} if "falling_things" in roots else {}
            new = FallingThings(aug_params, **kw) * 5
        elif name.startswith("tartan_air"):
            kw = {"root": roots["tartanair"]} if "tartanair" in roots else {}
            new = TartanAir(aug_params, keywords=name.split("_")[2:], **kw)
        elif name == "sl":
            # Structured-light captures (the fork's WIP pipeline, working
            # form): random fixed-size crops only — photometric jitter would
            # destroy the projected-pattern modulation.
            from .sl import SLStereoView, fetch_sl_dataset
            new = SLStereoView(
                fetch_sl_dataset(roots.get("sl", "datasets/SL"),
                                 with_depth=True, split="training"),
                crop_size=(aug_params or {}).get("crop_size"))
        else:
            raise ValueError(f"unknown dataset: {name}")
        logger.info("Adding %d samples from %s", len(new), name)
        train_dataset = new if train_dataset is None else train_dataset + new
    return train_dataset
