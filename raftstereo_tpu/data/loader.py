"""Multiprocess prefetching data loader feeding the TPU.

Replaces the reference's torch DataLoader usage
(reference: core/stereo_datasets.py:311-312): shuffling, fixed-size batches
with drop_last, N worker processes with per-worker seeding, and bounded
prefetch.  Batches are stacked NHWC numpy arrays ready for ``jax.device_put``;
``prefetch_to_device`` overlaps the host->HBM copy with compute.
"""

from __future__ import annotations

import collections
import os
from typing import Iterator, Optional, Tuple

import numpy as np

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]

_WORKER_DATASET = None


def _init_worker(dataset, seed, counter):
    global _WORKER_DATASET
    with counter.get_lock():
        worker_id = counter.value
        counter.value += 1
    dataset.reseed(seed + worker_id)
    _WORKER_DATASET = dataset


def _load_indices(indices):
    out = []
    for i in indices:
        meta, img1, img2, flow, valid = _WORKER_DATASET[i]
        out.append((img1, img2, flow, valid))
    return out


def default_num_workers() -> int:
    """SLURM-aware default (reference: core/stereo_datasets.py:312)."""
    return max(int(os.environ.get("SLURM_CPUS_PER_TASK", 6)) - 2, 0)


class DataLoader:
    """Iterable over stacked (img1, img2, flow, valid) batches.

    num_workers=0 loads inline (deterministic, used by tests); otherwise a
    process pool decodes and augments ahead of the training step.
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 drop_last: bool = True, num_workers: Optional[int] = None,
                 seed: int = 0, prefetch_batches: int = 4):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = (default_num_workers() if num_workers is None
                            else num_workers)
        self.seed = seed
        self.prefetch_batches = max(prefetch_batches, 1)
        self.epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _batches(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self.epoch).permutation(n)
        end = n - n % self.batch_size if self.drop_last else n
        for i in range(0, end, self.batch_size):
            yield order[i:i + self.batch_size].tolist()

    @staticmethod
    def _collate(samples) -> Batch:
        img1, img2, flow, valid = (np.stack(x) for x in zip(*samples))
        return img1, img2, flow, valid

    def __iter__(self) -> Iterator[Batch]:
        self.epoch += 1
        if self.num_workers == 0:
            self.dataset.reseed(self.seed + self.epoch)
            for idxs in self._batches():
                yield self._collate([self.dataset[i][1:] for i in idxs])
            return

        import multiprocessing as mp

        # Spawn, not fork: the parent process has JAX's thread pool running
        # and fork()ing a multithreaded process can deadlock workers.
        # Workers are pure numpy/PIL — scrub accelerator env vars while the
        # workers spawn so site hooks don't initialise a TPU client per
        # worker.  Spawned children inherit os.environ at process-creation
        # time, so the scrub must be parent-side and cover every spawn.
        # mp.Pool (unlike ProcessPoolExecutor) starts ALL workers eagerly in
        # its constructor, so the scrub window is exactly the Pool() call and
        # the env is restored before the first yield — consumer code (e.g.
        # jax.device_put in prefetch_to_device) never sees scrubbed values.
        # (Caveat: if a worker dies, Pool's maintenance thread respawns it
        # with the restored env; worker death is already a hard error.)
        ctx = mp.get_context("spawn")
        counter = ctx.Value("i", 0)

        scrub_keys = ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")
        saved = {k: os.environ.pop(k, None) for k in scrub_keys}
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            pool = ctx.Pool(self.num_workers, initializer=_init_worker,
                            initargs=(self.dataset,
                                      self.seed + 1000 * self.epoch, counter))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        try:
            pending = collections.deque()
            batches = self._batches()
            try:
                for _ in range(self.num_workers * self.prefetch_batches):
                    pending.append(pool.apply_async(_load_indices,
                                                    (next(batches),)))
            except StopIteration:
                batches = iter(())
            while pending:
                done = pending.popleft()
                try:
                    pending.append(pool.apply_async(_load_indices,
                                                    (next(batches),)))
                except StopIteration:
                    pass
                yield self._collate(done.get())
        finally:
            pool.terminate()
            pool.join()


def prefetch_to_device(iterator, size: int = 2, devices=None):
    """Move batches to device ahead of use (host->HBM overlap).

    The TPU analogue of pin_memory+non_blocking copies in the reference's
    loader; with a sharding it also shards the batch across the mesh.
    """
    import itertools

    import jax

    queue = collections.deque()

    def put(batch):
        if devices is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(lambda x: jax.device_put(x, devices), batch)

    it = iter(iterator)
    for batch in itertools.islice(it, size):
        queue.append(put(batch))
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
