"""Multiprocess prefetching data loader feeding the TPU.

Replaces the reference's torch DataLoader usage
(reference: core/stereo_datasets.py:311-312): shuffling, fixed-size batches
with drop_last, N worker processes with per-worker seeding, and bounded
prefetch.  Batches are stacked NHWC numpy arrays ready for ``jax.device_put``;
``prefetch_to_device`` overlaps the host->HBM copy with compute.

Self-healing (tests/test_faults.py): per-sample retry with exponential
backoff, a bounded quarantine that replaces persistently-bad indices with
deterministically resampled ones (counted in :attr:`DataLoader.stats`,
never silently), and a timeout on batch results with a worker-pool recycle
so one hung decoder cannot stall training forever.

Concurrency model (checked by the RSA3xx lock-discipline pass,
docs/static_analysis.md): the loader is multi*process*, not
multi-threaded — workers communicate via the pool only, and
``quarantined``/``stats``/``epoch`` are mutated exclusively by the single
consumer thread iterating the loader, so no attribute here carries a
``# guarded_by:`` annotation.  The one cross-process value,
``_worker_counter``, is an ``mp.Value`` updated under its own
``get_lock()`` in ``_init_worker``.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils.faults import FaultPlan

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]

logger = logging.getLogger(__name__)

_WORKER_DATASET = None
_WORKER_PLAN = None
_WORKER_ID = None


def _init_worker(dataset, seed, counter, plan):
    global _WORKER_DATASET, _WORKER_PLAN, _WORKER_ID
    with counter.get_lock():
        worker_id = counter.value
        counter.value += 1
    dataset.reseed(seed + worker_id)
    _WORKER_DATASET = dataset
    _WORKER_PLAN = plan
    _WORKER_ID = worker_id


def _load_one(dataset, i, plan):
    if plan is not None:
        plan.on_sample(i)
    meta, img1, img2, flow, valid = dataset[i]
    return (img1, img2, flow, valid)


def _load_indices(indices, retries=2, backoff=0.05):
    """Worker task: load each index with per-sample retry.

    Returns ``(ok, bad, n_retries)``: ``ok`` is ``[(pos, sample), ...]``,
    ``bad`` is ``[(pos, index, error_string), ...]`` for indices that failed
    every attempt.  Failures are *reported*, not raised — the parent owns
    quarantine/resampling policy and a raise would poison the whole batch.
    """
    if _WORKER_PLAN is not None:
        _WORKER_PLAN.on_worker(_WORKER_ID)
    ok, bad, n_retries = [], [], 0
    for pos, i in enumerate(indices):
        for attempt in range(retries + 1):
            try:
                ok.append((pos, _load_one(_WORKER_DATASET, i, _WORKER_PLAN)))
                break
            except Exception as e:  # noqa: BLE001 — any decode error counts
                if attempt >= retries:
                    bad.append((pos, i, f"{type(e).__name__}: {e}"))
                else:
                    n_retries += 1
                    time.sleep(backoff * (2 ** attempt))
    return ok, bad, n_retries


def default_num_workers() -> int:
    """SLURM-aware default (reference: core/stereo_datasets.py:312)."""
    return max(int(os.environ.get("SLURM_CPUS_PER_TASK", 6)) - 2, 0)


class DataLoader:
    """Iterable over stacked (img1, img2, flow, valid) batches.

    num_workers=0 loads inline (deterministic, used by tests); otherwise a
    process pool decodes and augments ahead of the training step.

    Robustness knobs:

    * ``sample_retries`` / ``retry_backoff``: per-sample retry with
      exponential backoff inside the load task (transient I/O).
    * ``quarantine_limit``: indices that fail every retry are quarantined
      (at most this many — beyond it the dataset is considered broken and
      the loader raises) and replaced with a deterministic resample; both
      are counted in :attr:`stats`.
    * ``batch_timeout``: seconds to wait for a worker batch before the pool
      is recycled (terminate + respawn) and in-flight batches resubmitted;
      a batch that times out twice raises.  ``None`` disables.
    * ``fault_plan``: deterministic fault injection (utils/faults.py);
      defaults to the ``RAFTSTEREO_FAULTS`` env var.
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 drop_last: bool = True, num_workers: Optional[int] = None,
                 seed: int = 0, prefetch_batches: int = 4,
                 sample_retries: int = 2, retry_backoff: float = 0.05,
                 quarantine_limit: int = 64,
                 batch_timeout: Optional[float] = 300.0,
                 fault_plan: Optional[FaultPlan] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = (default_num_workers() if num_workers is None
                            else num_workers)
        self.seed = seed
        self.prefetch_batches = max(prefetch_batches, 1)
        self.epoch = 0
        self.sample_retries = sample_retries
        self.retry_backoff = retry_backoff
        self.quarantine_limit = quarantine_limit
        self.batch_timeout = batch_timeout
        self.fault_plan = (FaultPlan.from_env() if fault_plan is None
                           else fault_plan)
        self.quarantined: set = set()
        self.stats = collections.Counter()
        self._worker_counter = None  # created lazily, lives for the loader

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def health_metrics(self):
        """Cumulative robustness counters as float gauges for the metrics
        logger (quarantines must be countable, never silent)."""
        return {"data_" + k: float(self.stats[k]) for k in
                ("samples_retried", "samples_quarantined", "samples_replaced",
                 "load_timeouts", "pool_recycles")}

    # -- quarantine / resampling --------------------------------------------

    def _quarantine(self, index: int, err: str) -> None:
        if index in self.quarantined:
            return
        if len(self.quarantined) >= self.quarantine_limit:
            raise RuntimeError(
                f"quarantine limit reached ({self.quarantine_limit} bad "
                f"samples; latest: index {index}: {err}) — the dataset is "
                "broken beyond what resampling should paper over")
        self.quarantined.add(index)
        self.stats["samples_quarantined"] += 1
        logger.warning("quarantined dataset index %d (%s) — %d/%d slots used",
                       index, err, len(self.quarantined),
                       self.quarantine_limit)

    def _substitute(self, index: int) -> int:
        """Deterministic replacement for a quarantined index (seeded by
        (seed, epoch, index) so reruns resample identically)."""
        n = len(self.dataset)
        if len(self.quarantined) >= n:
            raise RuntimeError(f"all {n} dataset indices quarantined")
        rng = np.random.default_rng((self.seed, self.epoch, index))
        while True:
            j = int(rng.integers(n))
            if j != index and j not in self.quarantined:
                self.stats["samples_replaced"] += 1
                return j

    def _resolve(self, idxs):
        """Replace already-quarantined indices at dispatch time."""
        return [self._substitute(i) if i in self.quarantined else i
                for i in idxs]

    def _batches(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self.epoch).permutation(n)
        end = n - n % self.batch_size if self.drop_last else n
        for i in range(0, end, self.batch_size):
            yield order[i:i + self.batch_size].tolist()

    @staticmethod
    def _collate(samples) -> Batch:
        img1, img2, flow, valid = (np.stack(x) for x in zip(*samples))
        return img1, img2, flow, valid

    # -- inline path --------------------------------------------------------

    def _load_resilient_inline(self, index: int):
        """Inline load with the same retry/quarantine/resample policy as the
        worker path (minus the pool timeout — nothing to recycle)."""
        i = index
        while True:
            for attempt in range(self.sample_retries + 1):
                try:
                    return _load_one(self.dataset, i, self.fault_plan)
                except Exception as e:  # noqa: BLE001
                    if attempt >= self.sample_retries:
                        self._quarantine(i, f"{type(e).__name__}: {e}")
                        i = self._substitute(i)
                    else:
                        self.stats["samples_retried"] += 1
                        time.sleep(self.retry_backoff * (2 ** attempt))

    def __iter__(self) -> Iterator[Batch]:
        self.epoch += 1
        if self.num_workers == 0:
            self.dataset.reseed(self.seed + self.epoch)
            for idxs in self._batches():
                yield self._collate([self._load_resilient_inline(i)
                                     for i in self._resolve(idxs)])
            return
        yield from self._iter_pool()

    # -- worker-pool path ---------------------------------------------------

    def _make_pool(self, ctx, counter):
        # Spawn, not fork: the parent process has JAX's thread pool running
        # and fork()ing a multithreaded process can deadlock workers.
        # Workers are pure numpy/PIL — scrub accelerator env vars while the
        # workers spawn so site hooks don't initialise a TPU client per
        # worker.  Spawned children inherit os.environ at process-creation
        # time, so the scrub must be parent-side and cover every spawn.
        # mp.Pool (unlike ProcessPoolExecutor) starts ALL workers eagerly in
        # its constructor, so the scrub window is exactly the Pool() call and
        # the env is restored before the first yield — consumer code (e.g.
        # jax.device_put in prefetch_to_device) never sees scrubbed values.
        # (Caveat: if a worker dies, Pool's maintenance thread respawns it
        # with the restored env; a lost task then surfaces as a batch
        # timeout and the recycle path rebuilds the pool under a scrub.)
        scrub_keys = ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")
        saved = {k: os.environ.pop(k, None) for k in scrub_keys}
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            return ctx.Pool(self.num_workers, initializer=_init_worker,
                            initargs=(self.dataset,
                                      self.seed + 1000 * self.epoch, counter,
                                      self.fault_plan))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def _iter_pool(self) -> Iterator[Batch]:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        # One counter for the LIFETIME of the loader (not per epoch, not
        # per pool): recycled pools and later epochs get fresh worker ids,
        # so a fire-once per-worker fault can never re-fire.
        if self._worker_counter is None:
            self._worker_counter = ctx.Value("i", 0)
        counter = self._worker_counter
        pool = self._make_pool(ctx, counter)

        def submit(p, idxs):
            return p.apply_async(_load_indices, (idxs, self.sample_retries,
                                                 self.retry_backoff))

        try:
            # pending entries: [async_result, idxs, timeouts_so_far]
            pending = collections.deque()
            batches = self._batches()
            try:
                for _ in range(self.num_workers * self.prefetch_batches):
                    idxs = self._resolve(next(batches))
                    pending.append([submit(pool, idxs), idxs, 0])
            except StopIteration:
                batches = iter(())
            while pending:
                entry = pending.popleft()
                try:
                    idxs = self._resolve(next(batches))
                    pending.append([submit(pool, idxs), idxs, 0])
                except StopIteration:
                    pass
                try:
                    ok, bad, n_retries = entry[0].get(self.batch_timeout)
                except mp.TimeoutError:
                    self.stats["load_timeouts"] += 1
                    entry[2] += 1
                    if entry[2] > 1:
                        raise RuntimeError(
                            f"batch {entry[1]} timed out twice "
                            f"({self.batch_timeout}s each) across a pool "
                            "recycle — giving up instead of deadlocking")
                    # Recycle: a hung/lost worker never returns its task, so
                    # terminate the whole pool and resubmit every in-flight
                    # batch (order preserved) on a fresh one.
                    logger.warning(
                        "no batch within %.1fs — recycling the %d-worker "
                        "pool and resubmitting %d in-flight batches",
                        self.batch_timeout, self.num_workers,
                        len(pending) + 1)
                    self.stats["pool_recycles"] += 1
                    pool.terminate()
                    pool.join()
                    pool = self._make_pool(ctx, counter)
                    entry[0] = submit(pool, entry[1])
                    for other in pending:
                        other[0] = submit(pool, other[1])
                    pending.appendleft(entry)
                    continue
                self.stats["samples_retried"] += n_retries
                if bad:
                    # Quarantine the persistently-bad indices and re-run the
                    # batch (quarantined indices resolve to substitutes at
                    # dispatch).  Substitutes that also fail get quarantined
                    # on the next pass until the bounded quarantine raises.
                    for _pos, i, err in bad:
                        self._quarantine(i, err)
                    idxs = self._resolve(entry[1])
                    pending.appendleft([submit(pool, idxs), idxs, 0])
                    continue
                yield self._collate([s for _pos, s in sorted(ok)])
        finally:
            pool.terminate()
            pool.join()


def prefetch_to_device(iterator, size: int = 2, devices=None):
    """Move batches to device ahead of use (host->HBM overlap).

    The TPU analogue of pin_memory+non_blocking copies in the reference's
    loader; with a sharding it also shards the batch across the mesh.
    """
    import itertools

    import jax

    queue = collections.deque()

    def put(batch):
        if devices is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(lambda x: jax.device_put(x, devices), batch)

    it = iter(iterator)
    for batch in itertools.islice(it, size):
        queue.append(put(batch))
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
