"""On-device photometric augmentation: the TPU answer to a CPU-bound host.

The reference runs every augmentation op on the host inside torch DataLoader
workers (reference: core/utils/augmentor.py:78-111 via core/stereo_datasets.py:311).
That scales with host cores — and starves the chip when cores are scarce:
the photometric chain (jitter + eraser) is roughly half the per-sample host
cost measured on the KITTI (sparse-augmentor) pipeline of ``bench.py --data``. This module moves exactly that chain into
the jitted training step, where it fuses with the input normalization and
costs microseconds of TPU time; shape-changing work (decode, scale/stretch,
flip, crop, sparse scatter) stays on the host, which is the natural split —
everything on-device is fixed-shape.

Semantics mirror the host ``ColorJitter``/eraser (same factor ranges, same
random op order, same asymmetric/eraser probabilities and eye-swap-flip
eraser-target distribution, per-op [0,255] clipping) with two documented
differences:

* hue rotates in continuous fp32 HSV rather than PIL's 8-bit quantized HSV;
* ops apply after the spatial crop rather than before the resize, and
  intermediate values are never rounded to uint8.

Both change the augmentation distribution imperceptibly (augmentation is
noise by design); the host path remains the reference-exact default.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


# ----------------------------------------------------------- color space

def rgb_to_hsv(rgb: jax.Array) -> jax.Array:
    """(3, ...) channel-FIRST in [0,1] -> HSV (3, ...) in [0,1]."""
    r, g, b = rgb[0], rgb[1], rgb[2]
    mx = jnp.max(rgb, axis=0)
    mn = jnp.min(rgb, axis=0)
    d = mx - mn
    safe = jnp.where(d > 0, d, 1.0)
    h = jnp.where(
        mx == r, (g - b) / safe,
        jnp.where(mx == g, 2.0 + (b - r) / safe, 4.0 + (r - g) / safe))
    h = jnp.where(d > 0, (h / 6.0) % 1.0, 0.0)
    s = jnp.where(mx > 0, d / jnp.where(mx > 0, mx, 1.0), 0.0)
    return jnp.stack([h, s, mx])


def hsv_to_rgb(hsv: jax.Array) -> jax.Array:
    h, s, v = hsv[0], hsv[1], hsv[2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6

    def sector(table):
        # Explicit select cascade: jnp.choose lowers to per-element GATHERS
        # on TPU (measured ~5x an elementwise pass); wheres stay on the VPU.
        out = table[5]
        for k in range(4, -1, -1):
            out = jnp.where(i == k, table[k], out)
        return out

    r = sector([v, q, p, p, t, v])
    g = sector([t, v, v, q, p, p])
    b = sector([p, p, t, v, v, q])
    return jnp.stack([r, g, b])


# ----------------------------------------------------------- jitter ops
# All ops run CHANNEL-FIRST, (3, H, W) float32 in [0, 255], W in the lane
# dimension: with NHWC's C=3 minor, every elementwise/reduce pass uses 3 of
# 128 VPU lanes and the whole chain measured ~700 ms per step; channel-first
# it is bandwidth-bound and negligible. Each op clips like the host _blend
# (augment.py). Contrast blends against the CURRENT image's gray mean
# (order-dependent, like the host's adjust_contrast); the symmetric path
# feeds both eyes stacked as one image, so the mean is the joint one —
# exactly the host's stacked-image call (color_transform).

def _gray(img):
    """(3, H, W) -> (1, H, W) luma."""
    return (img[0] * 0.299 + img[1] * 0.587 + img[2] * 0.114)[None]


def _brightness(img, f):
    return jnp.clip(img * f, 0, 255)


def _contrast(img, f, mean_map):
    # mean_map: per-pixel blend target — each eye's own gray mean in the
    # asymmetric case, the joint mean in the symmetric case (host stacks
    # the eyes into one image, so its adjust_contrast sees the joint mean).
    return jnp.clip(mean_map + f * (img - mean_map), 0, 255)


def _saturation(img, f):
    g = _gray(img)
    return jnp.clip(g + f * (img - g), 0, 255)


def _hue(img, shift):
    """(3, H, W), shift scalar or (H, 1)-broadcastable per-row map."""
    hsv = rgb_to_hsv(jnp.clip(img, 0, 255) / 255.0)
    h = (hsv[0] + shift) % 1.0
    return hsv_to_rgb(jnp.stack([h, hsv[1], hsv[2]])) * 255.0


class DevicePhotometric:
    """Batched, jittable photometric augmentation (jitter + eraser).

    Call with a PRNG key and (B, H, W, 3) float32 [0,255] image batches:
        img1, img2 = aug(key, img1, img2)
    Per-sample randomness comes from splitting the key over the batch, so a
    given (key, step) reproduces exactly — fold the step counter into the
    key upstream (see train.step).
    """

    def __init__(self, brightness=0.4, contrast=0.4,
                 saturation: Sequence[float] = (0.6, 1.4), hue=0.5 / 3.14,
                 gamma: Sequence[float] = (1, 1, 1, 1),
                 asymmetric_prob=0.2, eraser_prob=0.5,
                 eraser_bounds: Tuple[int, int] = (50, 100),
                 erase_left_prob=0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = tuple(saturation)
        self.hue = hue
        self.gamma = tuple(gamma)
        self.asymmetric_prob = asymmetric_prob
        self.eraser_prob = eraser_prob
        self.eraser_bounds = eraser_bounds
        # The host erases PRE-flip img2; a stereo eye-swap flip (do_flip='h',
        # augment.py spatial_transform) then turns the erased eye into the
        # LEFT input with the flip's probability. The host flip draw is
        # independent of the eraser, so an independent target-eye draw here
        # reproduces the distribution exactly.
        self.erase_left_prob = erase_left_prob

    # ---- per-sample pieces ------------------------------------------------

    def _factors(self, key):
        kb, kc, ks, kh = jax.random.split(key, 4)
        return (
            jax.random.uniform(kb, (), minval=max(0, 1 - self.brightness),
                               maxval=1 + self.brightness),
            jax.random.uniform(kc, (), minval=max(0, 1 - self.contrast),
                               maxval=1 + self.contrast),
            jax.random.uniform(ks, (), minval=self.saturation[0],
                               maxval=self.saturation[1]),
            jax.random.uniform(kh, (), minval=-self.hue, maxval=self.hue),
        )

    # NO per-sample lax.cond/lax.switch anywhere: under vmap those execute
    # EVERY branch for every sample (measured 7x the whole train step).
    # Random op order is instead expressed data-parallel: every op has a
    # neutral factor (brightness/contrast/saturation 1, hue 0) that makes it
    # an exact identity, so one fixed chain per position with
    # position-scheduled factors applies each op exactly once, in the
    # per-eye random order. 4 positions x 4 ops = 16 cheap elementwise
    # evaluations per pair instead of 2 x 24 branch bodies.

    def _jitter_stacked(self, x, factors2, order2, gamma2, gain2, asym):
        """x: (3, 2H, W) channel-first stacked pair; factors2/order2: (2, 4)
        per-eye op factors and op-order (op index at each position);
        gamma2/gain2: (2,); asym: scalar bool selecting per-eye vs joint
        contrast mean."""
        h2 = x.shape[1]
        half = jnp.arange(h2) >= h2 // 2            # row -> eye index
        neutral = jnp.asarray([1.0, 1.0, 1.0, 0.0])

        def fmap(v2):                               # (2,) -> (2H, 1)
            return jnp.where(half, v2[1], v2[0])[:, None]

        for k in range(4):
            active = order2[:, k]                   # (2,) op id at position k
            fk = jnp.where(jnp.arange(4)[None, :] == active[:, None],
                           factors2, neutral[None, :])   # (2, 4)
            m_top = jnp.mean(_gray(x[:, : h2 // 2]))
            m_bot = jnp.mean(_gray(x[:, h2 // 2:]))
            joint = 0.5 * (m_top + m_bot)
            mean_map = jnp.where(
                asym, fmap(jnp.stack([m_top, m_bot])), joint)
            x = _brightness(x, fmap(fk[:, 0]))
            x = _contrast(x, fmap(fk[:, 1]), mean_map)
            x = _saturation(x, fmap(fk[:, 2]))
            x = _hue(x, fmap(fk[:, 3]))
        if self.gamma != (1, 1, 1, 1):
            x = jnp.clip(255.0 * fmap(gain2)
                         * (x / 255.0) ** fmap(gamma2), 0, 255)
        return jnp.clip(x, 0, 255)

    def _eraser_one(self, key, stacked):
        """stacked: (3, 2H, W) channel-first pair; erases ONE eye — the
        right one, or the left with ``erase_left_prob`` (the post-flip image
        of the host's pre-flip img2; see __init__)."""
        h2, w = stacked.shape[1:]
        h = h2 // 2
        ka, kn, kr, ke = jax.random.split(key, 4)
        apply = jax.random.uniform(ka, ()) < self.eraser_prob
        n = jax.random.randint(kn, (), 1, 3)       # 1 or 2 rectangles
        left = jax.random.uniform(ke, ()) < self.erase_left_prob
        row0 = jnp.where(left, 0, h)               # target eye's first row
        m_top = jnp.mean(stacked[:, :h], axis=(1, 2))
        m_bot = jnp.mean(stacked[:, h:], axis=(1, 2))
        mean_color = jnp.where(left, m_top, m_bot)  # (3,)
        yy = jnp.arange(h2)[:, None]
        xx = jnp.arange(w)[None, :]
        lo, hi = self.eraser_bounds
        for r, krr in enumerate(jax.random.split(kr, 2)):
            kx, ky, kdx, kdy = jax.random.split(krr, 4)
            x0 = jax.random.randint(kx, (), 0, w)
            y0 = jax.random.randint(ky, (), 0, h) + row0
            dx = jax.random.randint(kdx, (), lo, hi)
            dy = jax.random.randint(kdy, (), lo, hi)
            # The rectangle clips at the target eye's bottom edge, exactly
            # like the host slice assignment clips at the image edge.
            mask = (apply & (r < n) & (yy >= y0) & (yy < y0 + dy)
                    & (yy < row0 + h)
                    & (xx >= x0) & (xx < x0 + dx))
            stacked = jnp.where(mask[None], mean_color[:, None, None],
                                stacked)
        return stacked

    def _sample(self, key, img1, img2):
        k_asym, k_p1, k_p2, k_ord1, k_ord2, kg1, kg2, k_er = \
            jax.random.split(key, 8)
        asym = jax.random.uniform(k_asym, ()) < self.asymmetric_prob

        def eye_params(kp, ko, kg):
            f = jnp.stack(self._factors(kp))                      # (4,)
            order = jnp.argsort(jax.random.uniform(ko, (4,)))     # random perm
            gmin, gmax, gainmin, gainmax = self.gamma
            ka, kb = jax.random.split(kg)
            gamma = jax.random.uniform(ka, (), minval=gmin, maxval=gmax)
            gain = jax.random.uniform(kb, (), minval=gainmin, maxval=gainmax)
            return f, order, gamma, gain

        f1, o1, gamma1, gain1 = eye_params(k_p1, k_ord1, kg1)
        f2_, o2_, gamma2_, gain2_ = eye_params(k_p2, k_ord2, kg2)
        # Symmetric draw shares eye 1's parameters (host jitters the stacked
        # pair once); the select is on the small parameter vectors only.
        f2 = jnp.where(asym, f2_, f1)
        o2 = jnp.where(asym, o2_, o1)
        gamma2 = jnp.where(asym, gamma2_, gamma1)
        gain2 = jnp.where(asym, gain2_, gain1)

        # Channel-first throughout (W in lanes; see the op-block comment).
        # The transposes are two cheap bandwidth-bound copies per pair.
        stacked = jnp.concatenate([img1, img2], axis=0).transpose(2, 0, 1)
        out = self._jitter_stacked(
            stacked,
            jnp.stack([f1, f2]), jnp.stack([o1, o2]),
            jnp.stack([gamma1, gamma2]), jnp.stack([gain1, gain2]), asym)
        out = self._eraser_one(k_er, out)
        h = img1.shape[0]
        return (out[:, :h].transpose(1, 2, 0),
                out[:, h:].transpose(1, 2, 0))

    def __call__(self, key: jax.Array, img1: jax.Array, img2: jax.Array):
        keys = jax.random.split(key, img1.shape[0])
        return jax.vmap(self._sample)(keys, img1, img2)
