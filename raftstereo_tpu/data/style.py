"""LAB-space style transfer + benchmark image-list getters.

Working, dependency-free form of the reference's style-transfer utilities
(reference: core/utils/augmentor.py:18-45), which rely on scikit-image.  The
sRGB <-> CIELAB conversions are implemented here directly (D65 white point,
the same convention skimage uses) so the capability exists without cv2/skimage.

``transfer_color`` re-colors an image to match a style's LAB channel
statistics: subtract the image's per-channel LAB mean, rescale by the ratio of
standard deviations, add the style mean, clip L to [0, 100].
"""

from __future__ import annotations

import os
from glob import glob
from typing import List, Tuple

import numpy as np

__all__ = ["rgb2lab", "lab2rgb", "lab_stats", "transfer_color",
           "get_middlebury_images", "get_eth3d_images", "get_kitti_images"]

# D65 reference white (2-degree observer), as used by skimage.color.
_WHITE = np.array([0.95047, 1.0, 1.08883])
_RGB2XYZ = np.array([[0.412453, 0.357580, 0.180423],
                     [0.212671, 0.715160, 0.072169],
                     [0.019334, 0.119193, 0.950227]])
_XYZ2RGB = np.linalg.inv(_RGB2XYZ)


def _srgb_to_linear(c: np.ndarray) -> np.ndarray:
    return np.where(c > 0.04045, ((c + 0.055) / 1.055) ** 2.4, c / 12.92)


def _linear_to_srgb(c: np.ndarray) -> np.ndarray:
    return np.where(c > 0.0031308, 1.055 * c ** (1.0 / 2.4) - 0.055, 12.92 * c)


def _f(t: np.ndarray) -> np.ndarray:
    d = 6.0 / 29.0
    return np.where(t > d ** 3, np.cbrt(t), t / (3 * d * d) + 4.0 / 29.0)


def _finv(t: np.ndarray) -> np.ndarray:
    d = 6.0 / 29.0
    return np.where(t > d, t ** 3, 3 * d * d * (t - 4.0 / 29.0))


def rgb2lab(rgb: np.ndarray) -> np.ndarray:
    """(H, W, 3) RGB in [0, 1] (or [0, 255] uint8) -> CIELAB float64."""
    rgb = np.asarray(rgb)
    if rgb.dtype == np.uint8:
        rgb = rgb.astype(np.float64) / 255.0
    xyz = _srgb_to_linear(rgb.astype(np.float64)) @ _RGB2XYZ.T
    fxyz = _f(xyz / _WHITE)
    l = 116.0 * fxyz[..., 1] - 16.0
    a = 500.0 * (fxyz[..., 0] - fxyz[..., 1])
    b = 200.0 * (fxyz[..., 1] - fxyz[..., 2])
    return np.stack([l, a, b], axis=-1)


def lab2rgb(lab: np.ndarray) -> np.ndarray:
    """CIELAB -> (H, W, 3) RGB in [0, 1], clipped."""
    lab = np.asarray(lab, np.float64)
    fy = (lab[..., 0] + 16.0) / 116.0
    fx = fy + lab[..., 1] / 500.0
    fz = fy - lab[..., 2] / 200.0
    xyz = np.stack([_finv(fx), _finv(fy), _finv(fz)], axis=-1) * _WHITE
    rgb = xyz @ _XYZ2RGB.T
    return np.clip(_linear_to_srgb(np.clip(rgb, 0.0, None)), 0.0, 1.0)


def lab_stats(image: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel (mean, std) of an image in LAB — the 'style' statistics."""
    lab = rgb2lab(image)
    return (np.mean(lab, axis=(0, 1), keepdims=True),
            np.std(lab, axis=(0, 1), keepdims=True))


def transfer_color(image: np.ndarray, style_mean: np.ndarray,
                   style_stddev: np.ndarray) -> np.ndarray:
    """Re-color ``image`` to the style's LAB statistics
    (reference: core/utils/augmentor.py:30-45).  Returns float RGB in
    [0, 255] like the reference (which multiplies lab2rgb by 255)."""
    lab = rgb2lab(image)
    mean = np.mean(lab, axis=(0, 1), keepdims=True)
    # Guard constant channels (grayscale images have a == b == const): a zero
    # std would turn the rescale into inf * 0 = NaN.
    std = np.maximum(np.std(lab, axis=(0, 1), keepdims=True), 1e-6)
    out = (style_stddev / std) * (lab - mean) + style_mean
    out[..., 0] = np.clip(out[..., 0], 0.0, 100.0)
    return lab2rgb(out) * 255.0


def get_middlebury_images(root: str = "datasets/Middlebury/MiddEval3") -> List[str]:
    """(reference: core/utils/augmentor.py:18-22)"""
    with open(os.path.join(root, "official_train.txt")) as f:
        lines = f.read().splitlines()
    return sorted(os.path.join(root, "trainingQ", name, "im0.png")
                  for name in lines)


def get_eth3d_images(root: str = "datasets/ETH3D") -> List[str]:
    return sorted(glob(os.path.join(root, "two_view_training", "*", "im0.png")))


def get_kitti_images(root: str = "datasets/KITTI") -> List[str]:
    return sorted(glob(os.path.join(root, "training", "image_2", "*_10.png")))
