"""Minimal pure-numpy 16-bit PNG codec (read + write, non-interlaced).

The reference reads/writes KITTI's 16-bit PNGs through OpenCV
(reference: core/utils/frame_utils.py:117-127,166-170); this image has no
cv2/imageio, and PIL cannot handle 16-bit RGB PNGs.  KITTI needs exactly two
shapes: 16-bit grayscale (disparity) and 16-bit RGB (flow+valid), both
non-interlaced — small enough to implement directly on zlib.
"""

from __future__ import annotations

import ctypes as _ct
import struct
import zlib

import numpy as np

_SIG = b"\x89PNG\r\n\x1a\n"
_c_u8p = _ct.POINTER(_ct.c_uint8)
_c_i64 = _ct.c_int64


def _defilter(raw: bytes, h: int, stride: int, bpp: int) -> np.ndarray:
    """Undo PNG scanline filters -> (h, stride) bytes.

    Uses the native C kernel (native/pngfilter.c) when a compiler is
    available — the pure-python path is decode-bound on KITTI-sized 16-bit
    maps (Sub/Average/Paeth are per-byte sequential)."""
    from ..native import load

    lib = load("pngfilter")
    if lib is not None:
        out = np.empty((h, stride), np.uint8)
        rc = lib.png_defilter(raw, out.ctypes.data_as(_c_u8p),
                              _c_i64(h), _c_i64(stride), _c_i64(bpp))
        if rc != 0:
            raise ValueError("bad PNG filter byte")
        return out

    out = np.empty((h, stride), np.uint8)
    prev = np.zeros((stride,), np.int32)
    rows = np.frombuffer(raw, np.uint8).reshape(h, stride + 1)
    for y in range(h):
        ftype = int(rows[y, 0])
        line = rows[y, 1:].astype(np.int32)
        if ftype == 0:
            pass
        elif ftype == 1:                        # Sub: per-lane prefix sum
            lanes = line[: (stride // bpp) * bpp].reshape(-1, bpp)
            np.cumsum(lanes, axis=0, out=lanes)
            line[: lanes.size] = lanes.reshape(-1)
        elif ftype == 2:                        # Up
            line += prev
        elif ftype == 3:                        # Average
            for x in range(stride):
                a = line[x - bpp] & 0xFF if x >= bpp else 0
                line[x] += (a + prev[x]) >> 1
        elif ftype == 4:                        # Paeth
            lp = line.tolist()
            pv = prev.tolist()
            for x in range(stride):
                a = lp[x - bpp] & 0xFF if x >= bpp else 0
                b = pv[x]
                c = pv[x - bpp] & 0xFF if x >= bpp else 0
                p = a + b - c
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                lp[x] += a if (pa <= pb and pa <= pc) else (b if pb <= pc else c)
            line = np.asarray(lp, np.int32)
        else:
            raise ValueError(f"bad filter {ftype}")
        line &= 0xFF
        out[y] = line
        prev = line
    return out


def read_png16(path: str) -> np.ndarray:
    """Read an 8- or 16-bit, gray/RGB/RGBA, non-interlaced PNG -> (H, W[, C])."""
    with open(path, "rb") as f:
        data = f.read()
    assert data[:8] == _SIG, "not a PNG"
    pos = 8
    idat = b""
    meta = None
    while pos < len(data):
        (length,), ctype = struct.unpack(">I", data[pos:pos + 4]), data[pos + 4:pos + 8]
        chunk = data[pos + 8:pos + 8 + length]
        if ctype == b"IHDR":
            w, h, depth, color, comp, filt, interlace = struct.unpack(">IIBBBBB", chunk)
            assert interlace == 0, "interlaced PNG unsupported"
            meta = (w, h, depth, color)
        elif ctype == b"IDAT":
            idat += chunk
        elif ctype == b"IEND":
            break
        pos += 12 + length
    assert meta is not None, "missing IHDR"
    w, h, depth, color = meta
    channels = {0: 1, 2: 3, 4: 2, 6: 4}[color]
    bpp = channels * (depth // 8)              # bytes per pixel
    stride = w * bpp
    raw = zlib.decompress(idat)
    assert len(raw) == h * (stride + 1), "bad IDAT size"
    out = _defilter(raw, h, stride, bpp)

    if depth == 16:
        arr = out.reshape(h, w, channels, 2)
        arr = (arr[..., 0].astype(np.uint16) << 8) | arr[..., 1]
    else:
        arr = out.reshape(h, w, channels).astype(np.uint8)
    return arr[..., 0] if channels == 1 else arr


def write_png16(path: str, arr: np.ndarray) -> None:
    """Write uint16 (H, W) or (H, W, 3) as a 16-bit non-interlaced PNG."""
    assert arr.dtype == np.uint16, arr.dtype
    if arr.ndim == 2:
        color, channels = 0, 1
    else:
        assert arr.shape[2] == 3, arr.shape
        color, channels = 2, 3
    h, w = arr.shape[:2]
    be = arr.astype(">u2").tobytes()
    stride = w * channels * 2
    raw = bytearray()
    for y in range(h):
        raw.append(0)                           # filter: None
        raw += be[y * stride:(y + 1) * stride]

    def chunk(ctype: bytes, payload: bytes) -> bytes:
        out = struct.pack(">I", len(payload)) + ctype + payload
        return out + struct.pack(">I", zlib.crc32(ctype + payload) & 0xFFFFFFFF)

    ihdr = struct.pack(">IIBBBBB", w, h, 16, color, 0, 0, 0)
    with open(path, "wb") as f:
        f.write(_SIG + chunk(b"IHDR", ihdr)
                + chunk(b"IDAT", zlib.compress(bytes(raw), 6))
                + chunk(b"IEND", b""))
