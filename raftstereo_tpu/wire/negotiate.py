"""Content negotiation for the binary frame dialect.

The rule set is deliberately tiny (docs/wire_format.md has the full
matrix):

* A request IS binary iff its ``Content-Type`` is the wire media type.
* A response is binary iff the request's ``Accept`` header names the
  wire media type explicitly with a non-zero q.  ``*/*`` (or a missing
  Accept) does NOT select binary: a JSON-only client that never heard
  of the format must never receive a frame it cannot parse — wildcard
  acceptance of an unknown binary type is how negotiation 500s start.
* Error replies are ALWAYS JSON, whatever was negotiated: an error
  body must be readable by whatever logged it.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["JSON_CONTENT_TYPE", "WIRE_CONTENT_TYPE", "accepts_wire",
           "is_wire_content_type"]

WIRE_CONTENT_TYPE = "application/x-raftstereo-frame"
JSON_CONTENT_TYPE = "application/json"


def _media_type(token: str) -> str:
    return token.split(";", 1)[0].strip().lower()


def is_wire_content_type(ctype: Optional[str]) -> bool:
    """True when a Content-Type header selects the binary dialect."""
    return bool(ctype) and _media_type(ctype) == WIRE_CONTENT_TYPE


def accepts_wire(accept: Optional[str]) -> bool:
    """True when an Accept header explicitly lists the wire media type
    with q > 0.  Wildcards never match — see the module docstring."""
    if not accept:
        return False
    for token in accept.split(","):
        if _media_type(token) != WIRE_CONTENT_TYPE:
            continue
        q = 1.0
        for param in token.split(";")[1:]:
            k, _, v = param.partition("=")
            if k.strip().lower() == "q":
                try:
                    q = float(v.strip())
                except ValueError:
                    q = 0.0
        if q > 0:
            return True
    return False
