"""raftstereo_tpu.wire — versioned binary frame format for the serving
data plane (docs/wire_format.md).

Dependency-free by design (stdlib ``struct``/``zlib``/``json`` + numpy):
this package is imported by the model-free cluster router and the
client, neither of which may pull in the engine stack.  Encode/decode is
pure host-side byte work — it creates no jax values and compiles no
executables, so adopting the format leaves the retrace budget at 0.

Two frame types over one fixed little-endian header:

* **request** — a stereo pair (two image planes) plus the JSON field
  dict the ``/predict`` dialect already speaks (iters, session_id, ...);
* **response** — one disparity plane, either raw float32 (bitwise equal
  to the JSON dialect's base64 payload) or int16 fixed-point carrying a
  per-response exactness manifest (scale, measured max quantization
  error) modeled on the accuracy-tier certification manifests.

Planes ship raw or lossless-tile-compressed (zlib over a byte-shuffle
filter); ``FrameDecoder`` decodes chunk-at-a-time into preallocated
plane staging so callers never hold body + decoded copies of a
bucket-scale pair at once.
"""

from .format import (
    FLAG_INT16,
    FLAG_SHUFFLE,
    FLAG_ZLIB,
    FRAME_REQUEST,
    FRAME_RESPONSE,
    HEADER_SIZE,
    MAGIC,
    VERSION,
    FrameDecoder,
    WireError,
    WireRequest,
    WireResponse,
    WireVersionError,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    parse_header,
)
from .negotiate import (
    JSON_CONTENT_TYPE,
    WIRE_CONTENT_TYPE,
    accepts_wire,
    is_wire_content_type,
)

__all__ = [
    "FLAG_INT16", "FLAG_SHUFFLE", "FLAG_ZLIB", "FRAME_REQUEST",
    "FRAME_RESPONSE", "HEADER_SIZE", "JSON_CONTENT_TYPE", "MAGIC",
    "VERSION", "WIRE_CONTENT_TYPE", "FrameDecoder", "WireError",
    "WireRequest", "WireResponse", "WireVersionError", "accepts_wire",
    "decode_request", "decode_response", "encode_request",
    "encode_response", "is_wire_content_type", "parse_header",
]
