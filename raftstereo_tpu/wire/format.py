"""Binary frame codec: fixed header + JSON meta + image/disparity planes.

Byte layout (all integers little-endian; full table in
docs/wire_format.md):

    offset  size  field
    0       4     magic       b"RSWF"
    4       2     version     u16, this module speaks exactly 1
    6       1     frame_type  u8: 1 = request, 2 = response
    7       1     flags       u8 bitfield: 1 ZLIB, 2 SHUFFLE, 4 INT16
    8       1     dtype       u8 payload dtype code (see _DTYPES)
    9       1     channels    u8 channels per plane (disparity: 1)
    10      2     plane_count u16 (request: 2 — left, right; response: 1)
    12      4     height      u32
    16      4     width       u32
    20      4     meta_len    u32 bytes of UTF-8 JSON following the header
    24      8     payload_len u64 bytes of plane data following the meta
    32            meta, then planes

Plane payload, per plane in order:

* flags & ZLIB: ``u32 tile_count``, then per tile ``u32 raw_len``,
  ``u32 comp_len``, ``comp_len`` bytes of a complete zlib stream.
  Tiles partition the (possibly shuffled) plane bytes in order, at most
  ``TILE_BYTES`` raw bytes each — so a streaming decoder never stages
  more than one compressed tile.
* otherwise: the raw (possibly shuffled) plane bytes.

The SHUFFLE flag applies an HDF5-style byte-shuffle filter before
compression: plane bytes are regrouped so all 0th bytes of each element
come first, then all 1st bytes, etc.  Same-magnitude floats share
exponent/high-mantissa bytes, so the grouped stream is far more
zlib-compressible than interleaved float32 — measured ~3.3x vs ~2.6x
for plain zlib on synthetic camera pairs.  Lossless: decode is a
transpose.

Float32 images whose values are exactly uint8-representable (the
overwhelmingly common case — stereo cameras produce 8-bit intensities
later promoted to float) are demoted to uint8 planes on encode and
re-promoted on decode; ``astype`` in both directions is exact, so the
round-trip stays bitwise and the wire carries 4x fewer bytes before
compression even starts.
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "FLAG_INT16", "FLAG_SHUFFLE", "FLAG_ZLIB", "FRAME_REQUEST",
    "FRAME_RESPONSE", "HEADER_SIZE", "MAGIC", "TILE_BYTES", "VERSION",
    "FrameDecoder", "WireError", "WireRequest", "WireResponse",
    "WireVersionError", "decode_request", "decode_response",
    "encode_request", "encode_response", "parse_header",
]

MAGIC = b"RSWF"
VERSION = 1
# Versions this codec can decode (inclusive range, named in the 400 the
# server returns for anything outside it).
SUPPORTED_VERSIONS = (1, 1)

_HEADER = struct.Struct("<4sHBBBBHIIIQ")
HEADER_SIZE = _HEADER.size  # 32

FRAME_REQUEST = 1
FRAME_RESPONSE = 2

FLAG_ZLIB = 1     # planes are tile-compressed
FLAG_SHUFFLE = 2  # byte-shuffle filter applied before compression
FLAG_INT16 = 4    # response payload is int16 fixed-point (meta manifest)

TILE_BYTES = 1 << 20  # raw bytes per compression tile

# u8 dtype code -> numpy dtype.  The code describes the PAYLOAD bytes;
# meta may direct a post-decode promotion (uint8 image -> float32).
_DTYPES: Dict[int, np.dtype] = {
    1: np.dtype("<f4"),
    2: np.dtype("<f2"),
    3: np.dtype("u1"),
    4: np.dtype("<i2"),
}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}

_META_LIMIT = 16 << 20  # sanity cap on the JSON meta blob


class WireError(ValueError):
    """Malformed or unsupported frame (everything except version skew)."""


class WireVersionError(WireError):
    """Frame version outside SUPPORTED_VERSIONS — the server names the
    range in its 400 so old clients learn what to downgrade to."""


class WireRequest:
    """Decoded request frame: float32 (or as-sent dtype) image pair plus
    the /predict field dict (iters, session_id, seq_no, ...)."""

    def __init__(self, left: np.ndarray, right: np.ndarray,
                 fields: Dict):
        self.left = left
        self.right = right
        self.fields = fields


class WireResponse:
    """Decoded response frame: float32 disparity plus server meta; for
    int16 frames, ``manifest`` carries the exactness certificate."""

    def __init__(self, disparity: np.ndarray, meta: Dict,
                 manifest: Optional[Dict] = None):
        self.disparity = disparity
        self.meta = meta
        self.manifest = manifest


# --------------------------------------------------------------- filters

def _shuffle(raw: bytes, itemsize: int) -> bytes:
    if itemsize <= 1 or not raw:
        return raw
    a = np.frombuffer(raw, dtype=np.uint8).reshape(-1, itemsize)
    return np.ascontiguousarray(a.T).tobytes()


def _unshuffle(raw: bytes, itemsize: int) -> bytes:
    if itemsize <= 1 or not raw:
        return raw
    a = np.frombuffer(raw, dtype=np.uint8).reshape(itemsize, -1)
    return np.ascontiguousarray(a.T).tobytes()


# --------------------------------------------------------------- encode

def _encode_plane(raw: bytes, flags: int, level: int,
                  itemsize: int) -> bytes:
    if flags & FLAG_SHUFFLE:
        raw = _shuffle(raw, itemsize)
    if not flags & FLAG_ZLIB:
        return raw
    parts = []
    tiles = range(0, len(raw), TILE_BYTES)
    parts.append(struct.pack("<I", len(tiles)))
    for off in tiles:
        tile = raw[off:off + TILE_BYTES]
        comp = zlib.compress(tile, level)
        parts.append(struct.pack("<II", len(tile), len(comp)))
        parts.append(comp)
    return b"".join(parts)


def _build_frame(frame_type: int, flags: int, dtype: np.dtype,
                 channels: int, planes: List[np.ndarray], meta: Dict,
                 level: int) -> bytes:
    h, w = planes[0].shape[:2]
    meta_raw = json.dumps(meta, separators=(",", ":")).encode()
    payload_parts = [
        _encode_plane(np.ascontiguousarray(p, dtype=dtype).tobytes(),
                      flags, level, dtype.itemsize)
        for p in planes
    ]
    payload = b"".join(payload_parts)
    header = _HEADER.pack(MAGIC, VERSION, frame_type, flags,
                          _DTYPE_CODES[dtype], channels, len(planes),
                          h, w, len(meta_raw), len(payload))
    return header + meta_raw + payload


def _uint8_exact(a: np.ndarray) -> bool:
    """True when a float image is exactly a promoted 8-bit capture."""
    if a.dtype != np.float32 or a.size == 0:
        return False
    return bool(np.all((a >= 0) & (a <= 255) & (a == np.floor(a))))


def encode_request(left: np.ndarray, right: np.ndarray,
                   fields: Optional[Dict] = None, *,
                   compress: bool = True, level: int = 6,
                   shuffle: bool = True,
                   allow_uint8: bool = True) -> bytes:
    """Encode a stereo pair + /predict fields as one request frame.

    ``fields`` is the JSON dialect's top-level dict minus the images
    (iters, session_id, seq_no, deadline_ms, priority, accuracy,
    spatial, and the optional ``response`` preference dict).  Decode
    returns the images bitwise: float32 pairs that are exactly
    uint8-representable travel as uint8 and are re-promoted."""
    left = np.asarray(left)
    right = np.asarray(right)
    if left.ndim != 3 or right.ndim != 3 or left.shape != right.shape:
        raise WireError(f"expected matching (H, W, C) pairs, got "
                        f"{left.shape} / {right.shape}")
    meta: Dict = {"fields": dict(fields or {})}
    dtype = np.dtype(left.dtype)
    if right.dtype != left.dtype:
        raise WireError("left/right dtype mismatch: "
                        f"{left.dtype} / {right.dtype}")
    if allow_uint8 and _uint8_exact(left) and _uint8_exact(right):
        dtype = np.dtype("u1")
        left = left.astype(np.uint8)
        right = right.astype(np.uint8)
        meta["promote"] = "float32"
    if dtype.newbyteorder("<") not in _DTYPE_CODES:
        raise WireError(f"unsupported image dtype {dtype}")
    dtype = dtype.newbyteorder("<")
    flags = 0
    if compress:
        flags |= FLAG_ZLIB
        if shuffle and dtype.itemsize > 1:
            flags |= FLAG_SHUFFLE
    h, w, c = left.shape
    _check_dims(h, w, c, 2)
    return _build_frame(FRAME_REQUEST, flags, dtype, c,
                        [left, right], meta, level)


def _int16_manifest(d: np.ndarray) -> Optional[Tuple[np.ndarray, Dict]]:
    """Power-of-two fixed-point quantization with a measured error cert.

    Returns None when int16 cannot represent the plane (non-finite
    values, or magnitudes that would need a sub-unit scale past the
    exponent clamp) — the caller falls back to bitwise float32."""
    if d.size == 0 or not np.isfinite(d).all():
        return None
    max_abs = float(np.max(np.abs(d)))
    if max_abs == 0.0:
        k = 0
    else:
        # Largest power-of-two gain that keeps max_abs inside int16.
        k = int(math.floor(math.log2(32766.0 / max_abs)))
        if not -120 <= k <= 120:
            return None
    gain = np.float64(2.0) ** k
    q = np.clip(np.rint(d.astype(np.float64) * gain),
                -32767, 32767).astype(np.int16)
    deq = (q.astype(np.float64) / gain).astype(np.float32)
    max_err = float(np.max(np.abs(deq.astype(np.float64)
                                  - d.astype(np.float64))))
    bound = float(2.0 ** -(k + 1))
    manifest = {
        "encoding": "int16_fixed",
        "scale_log2": -k,          # disparity = q * 2**scale_log2
        "scale": float(2.0 ** -k),
        "max_abs_err": max_err,    # measured on THIS response
        "err_bound": bound,        # half-ULP of the fixed-point grid
    }
    return q, manifest


def encode_response(disparity: np.ndarray, meta: Optional[Dict] = None, *,
                    encoding: str = "f32", compress: bool = True,
                    level: int = 6, shuffle: bool = True) -> bytes:
    """Encode one disparity plane as a response frame.

    ``encoding='f32'`` is bitwise; ``encoding='int16'`` quantizes to a
    power-of-two fixed-point grid and attaches the exactness manifest
    (falling back to f32 when int16 cannot represent the plane)."""
    d = np.asarray(disparity)
    if d.ndim != 2:
        raise WireError(f"disparity must be (H, W), got {d.shape}")
    if encoding not in ("f32", "int16"):
        raise WireError(f"unknown response encoding {encoding!r}")
    meta_obj: Dict = {"meta": dict(meta or {})}
    flags = 0
    if d.dtype != np.float32:
        d = d.astype(np.float32)
    dtype = np.dtype("<f4")
    plane = d
    if encoding == "int16":
        packed = _int16_manifest(d)
        if packed is not None:
            plane, manifest = packed
            meta_obj["manifest"] = manifest
            dtype = np.dtype("<i2")
            flags |= FLAG_INT16
    if compress:
        flags |= FLAG_ZLIB
        if shuffle and dtype.itemsize > 1:
            flags |= FLAG_SHUFFLE
    h, w = d.shape
    _check_dims(h, w, 1, 1)
    return _build_frame(FRAME_RESPONSE, flags, dtype, 1, [plane],
                        meta_obj, level)


def _check_dims(h: int, w: int, c: int, planes: int) -> None:
    if not (1 <= h <= 0xFFFFFFFF and 1 <= w <= 0xFFFFFFFF
            and 1 <= c <= 255 and 1 <= planes <= 0xFFFF):
        raise WireError(f"dims out of range: h={h} w={w} c={c} "
                        f"planes={planes}")


# --------------------------------------------------------------- decode

def parse_header(buf: bytes, expect: Optional[int] = None,
                 max_payload_bytes: Optional[int] = None) -> Dict:
    """Parse + validate the fixed 32-byte header (no payload needed).

    Standalone so a proxy can peek a frame's dims/meta length and
    forward the rest chunk-wise without ever constructing a decoder —
    plane staging is never allocated here.  Raises ``WireVersionError``
    for version skew and ``WireError`` for everything else malformed;
    ``max_payload_bytes`` bounds what the header may claim (checked
    against both the on-wire payload and the decoded plane bytes)."""
    if len(buf) != HEADER_SIZE:
        raise WireError(f"header needs {HEADER_SIZE} bytes, got "
                        f"{len(buf)}")
    (magic, version, frame_type, flags, dtype_code, channels,
     plane_count, h, w, meta_len, payload_len) = _HEADER.unpack(buf)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (not a wire frame)")
    lo, hi = SUPPORTED_VERSIONS
    if not lo <= version <= hi:
        raise WireVersionError(
            f"unsupported wire version {version}; this build speaks "
            f"versions {lo}..{hi}")
    if frame_type not in (FRAME_REQUEST, FRAME_RESPONSE):
        raise WireError(f"unknown frame type {frame_type}")
    if expect is not None and frame_type != expect:
        want = "request" if expect == FRAME_REQUEST else "response"
        raise WireError(f"expected a {want} frame, got type {frame_type}")
    if dtype_code not in _DTYPES:
        raise WireError(f"unknown dtype code {dtype_code}")
    if flags & ~(FLAG_ZLIB | FLAG_SHUFFLE | FLAG_INT16):
        raise WireError(f"unknown flag bits in {flags:#x}")
    if not (h and w and channels and plane_count):
        raise WireError("zero-sized frame dims")
    if meta_len > _META_LIMIT:
        raise WireError(f"meta blob {meta_len} bytes exceeds "
                        f"{_META_LIMIT}")
    dtype = _DTYPES[dtype_code]
    plane_bytes = h * w * channels * dtype.itemsize
    decoded = plane_count * plane_bytes
    if max_payload_bytes is not None and (payload_len > max_payload_bytes
                                          or decoded > max_payload_bytes):
        raise WireError(
            f"frame claims {max(payload_len, decoded)} payload bytes, "
            f"over the {max_payload_bytes}-byte cap")
    return {
        "version": version, "frame_type": frame_type, "flags": flags,
        "dtype": dtype, "channels": channels,
        "plane_count": plane_count, "height": h, "width": w,
        "meta_len": meta_len, "payload_len": payload_len,
        "plane_bytes": plane_bytes,
    }


class FrameDecoder:
    """Streaming frame decoder: ``feed(chunk)`` bytes in any sizes, read
    the result with ``request()`` / ``response()`` once ``done``.

    Decodes straight into preallocated per-plane staging: raw planes are
    copied chunk-by-chunk into their buffer; compressed planes stage at
    most one tile's compressed bytes (~1 MiB) and stream the inflate
    output into place.  Peak transient memory is therefore one decoded
    frame + one chunk, never body + decoded copies — the point of the
    streaming read path (serve/httpbase.py).

    ``max_payload_bytes`` bounds what a header may ask this decoder to
    allocate; a hostile header claiming absurd dims fails before any
    allocation.  All state is touched by exactly one reader thread (the
    HTTP handler feeding its own request); no locking."""

    _S_HEADER = 0
    _S_META = 1
    _S_TILE_COUNT = 2
    _S_TILE_HEADER = 3
    _S_TILE_BODY = 4
    _S_RAW_PLANE = 5
    _S_DONE = 6

    def __init__(self, expect: Optional[int] = None,
                 max_payload_bytes: Optional[int] = None):
        self._expect = expect
        self._max_payload = max_payload_bytes
        self._state = self._S_HEADER
        self._small = bytearray()
        self._need = HEADER_SIZE
        self.header: Optional[Dict] = None
        self.meta: Dict = {}
        self._dtype: Optional[np.dtype] = None
        self._plane_bytes = 0
        self._planes: List[bytearray] = []
        self._plane_idx = -1
        self._plane_view: Optional[memoryview] = None
        self._plane_pos = 0
        self._tiles_left = 0
        self._tile_raw = 0
        self._payload_seen = 0
        self._payload_len = 0

    # ------------------------------------------------------------- feed
    @property
    def done(self) -> bool:
        return self._state == self._S_DONE

    def feed(self, chunk: bytes) -> None:
        """Consume the next body bytes; raises WireError on malformed
        input (including trailing bytes past payload_len)."""
        mv = memoryview(chunk)
        while mv.nbytes:
            if self._state in (self._S_HEADER, self._S_META,
                               self._S_TILE_COUNT, self._S_TILE_HEADER,
                               self._S_TILE_BODY):
                take = min(mv.nbytes, self._need - len(self._small))
                self._small += mv[:take]
                mv = mv[take:]
                if len(self._small) == self._need:
                    buf = bytes(self._small)
                    self._small = bytearray()
                    self._advance(buf)
            elif self._state == self._S_RAW_PLANE:
                take = min(mv.nbytes, self._plane_bytes - self._plane_pos)
                self._plane_view[self._plane_pos:
                                 self._plane_pos + take] = mv[:take]
                self._plane_pos += take
                self._payload_seen += take
                mv = mv[take:]
                if self._plane_pos == self._plane_bytes:
                    self._finish_plane()
            else:  # _S_DONE
                raise WireError(
                    f"{mv.nbytes} trailing bytes past payload_len")

    # ---------------------------------------------------- state advance
    def _advance(self, buf: bytes) -> None:
        if self._state == self._S_HEADER:
            self._parse_header(buf)
        elif self._state == self._S_META:
            try:
                self.meta = json.loads(buf.decode("utf-8"))
            except Exception as e:
                raise WireError(f"bad frame meta: {e}")
            if not isinstance(self.meta, dict):
                raise WireError("frame meta must be a JSON object")
            self._begin_plane()
        elif self._state == self._S_TILE_COUNT:
            self._tiles_left = struct.unpack("<I", buf)[0]
            self._payload_seen += 4
            self._check_payload_budget()
            if self._tiles_left == 0:
                raise WireError("compressed plane with zero tiles")
            self._state = self._S_TILE_HEADER
            self._need = 8
        elif self._state == self._S_TILE_HEADER:
            self._tile_raw, comp_len = struct.unpack("<II", buf)
            self._payload_seen += 8
            if self._tile_raw > TILE_BYTES or comp_len > 2 * TILE_BYTES \
                    or self._tile_raw == 0 or comp_len == 0:
                raise WireError(
                    f"bad tile lengths raw={self._tile_raw} "
                    f"comp={comp_len}")
            if self._plane_pos + self._tile_raw > self._plane_bytes:
                raise WireError("tile overruns plane")
            self._check_payload_budget(comp_len)
            self._state = self._S_TILE_BODY
            self._need = comp_len
        elif self._state == self._S_TILE_BODY:
            self._payload_seen += len(buf)
            try:
                raw = zlib.decompress(buf)
            except zlib.error as e:
                raise WireError(f"bad tile: {e}")
            if len(raw) != self._tile_raw:
                raise WireError(
                    f"tile decompressed to {len(raw)} bytes, header "
                    f"said {self._tile_raw}")
            self._plane_view[self._plane_pos:
                             self._plane_pos + len(raw)] = raw
            self._plane_pos += len(raw)
            self._tiles_left -= 1
            if self._tiles_left:
                self._state = self._S_TILE_HEADER
                self._need = 8
            else:
                if self._plane_pos != self._plane_bytes:
                    raise WireError(
                        f"plane {self._plane_idx}: tiles covered "
                        f"{self._plane_pos} of {self._plane_bytes} bytes")
                self._finish_plane()

    def _parse_header(self, buf: bytes) -> None:
        self.header = parse_header(buf, expect=self._expect,
                                   max_payload_bytes=self._max_payload)
        self._dtype = self.header["dtype"]
        self._plane_bytes = self.header["plane_bytes"]
        self._payload_len = self.header["payload_len"]
        meta_len = self.header["meta_len"]
        if meta_len:
            self._state = self._S_META
            self._need = meta_len
        else:
            self.meta = {}
            self._begin_plane()

    def _begin_plane(self) -> None:
        self._plane_idx += 1
        if self._plane_idx >= self.header["plane_count"]:
            if self._payload_seen != self._payload_len:
                raise WireError(
                    f"payload_len {self._payload_len} != "
                    f"{self._payload_seen} bytes consumed")
            self._state = self._S_DONE
            return
        self._planes.append(bytearray(self._plane_bytes))
        self._plane_view = memoryview(self._planes[-1])
        self._plane_pos = 0
        if self.header["flags"] & FLAG_ZLIB:
            self._state = self._S_TILE_COUNT
            self._need = 4
        else:
            self._check_payload_budget(self._plane_bytes)
            self._state = self._S_RAW_PLANE

    def _finish_plane(self) -> None:
        if self.header["flags"] & FLAG_SHUFFLE:
            raw = _unshuffle(bytes(self._planes[self._plane_idx]),
                             self._dtype.itemsize)
            self._planes[self._plane_idx] = bytearray(raw)
        self._plane_view = None
        self._begin_plane()

    def _check_payload_budget(self, upcoming: int = 0) -> None:
        if self._payload_seen + upcoming > self._payload_len:
            raise WireError(
                f"payload overruns declared payload_len "
                f"{self._payload_len}")

    # ----------------------------------------------------------- results
    def _array(self, idx: int, shape: Tuple[int, ...]) -> np.ndarray:
        # View over the staging bytearray — no extra copy; promotion /
        # dequantization below copies only where it must.
        return np.frombuffer(self._planes[idx],
                             dtype=self._dtype).reshape(shape)

    def request(self) -> WireRequest:
        if not self.done:
            raise WireError("frame incomplete")
        if self.header["frame_type"] != FRAME_REQUEST:
            raise WireError("not a request frame")
        hd = self.header
        if hd["plane_count"] != 2:
            raise WireError("request frames carry two image planes")
        shape = (hd["height"], hd["width"], hd["channels"])
        left = self._array(0, shape)
        right = self._array(1, shape)
        if self.meta.get("promote") == "float32":
            left = left.astype(np.float32)
            right = right.astype(np.float32)
        fields = self.meta.get("fields") or {}
        if not isinstance(fields, dict):
            raise WireError("meta.fields must be an object")
        return WireRequest(left, right, fields)

    def response(self) -> WireResponse:
        if not self.done:
            raise WireError("frame incomplete")
        if self.header["frame_type"] != FRAME_RESPONSE:
            raise WireError("not a response frame")
        hd = self.header
        shape = (hd["height"], hd["width"])
        if hd["channels"] != 1 or hd["plane_count"] != 1:
            raise WireError("response frames carry one disparity plane")
        plane = self._array(0, shape)
        manifest = None
        if hd["flags"] & FLAG_INT16:
            manifest = self.meta.get("manifest")
            if not isinstance(manifest, dict) \
                    or "scale_log2" not in manifest:
                raise WireError("int16 frame without a manifest")
            scale = np.float64(2.0) ** int(manifest["scale_log2"])
            plane = (plane.astype(np.float64) * scale).astype(np.float32)
        elif plane.dtype != np.float32:
            plane = plane.astype(np.float32)
        meta = self.meta.get("meta") or {}
        return WireResponse(plane, meta, manifest)


def _decode(buf: bytes, expect: int) -> FrameDecoder:
    dec = FrameDecoder(expect=expect)
    dec.feed(buf)
    if not dec.done:
        raise WireError(f"truncated frame: {len(buf)} bytes")
    return dec


def decode_request(buf: bytes) -> WireRequest:
    """One-shot inverse of ``encode_request``."""
    return _decode(buf, FRAME_REQUEST).request()


def decode_response(buf: bytes) -> WireResponse:
    """One-shot inverse of ``encode_response``."""
    return _decode(buf, FRAME_RESPONSE).response()
