"""Single typed configuration shared by all entry points.

The reference duplicates argparse model flags across four scripts
(reference: train_stereo.py:233-241, evaluate_stereo.py:199-207, demo.py:64-72,
test.py:26-34).  Here every entry point consumes one frozen dataclass, which is
also hashable so it can be passed as a static argument through ``jax.jit``.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RAFTStereoConfig:
    """Architecture hyper-parameters of the RAFT-Stereo model.

    Mirrors the capability surface of the reference flags
    (reference: train_stereo.py:233-241) while staying a single typed object.
    Level index 0 is the finest GRU resolution (1/2^n_downsample); higher
    indices are coarser, matching the reference's ``net_list`` ordering
    (reference: core/raft_stereo.py:84-85).
    """

    # Correlation engine.  Backends: "reg" (precomputed pyramid + XLA gather
    # lookup), "alt" (on-demand, O(H*W) memory), "pallas" (precomputed pyramid +
    # Pallas TPU lookup kernel — the reg_cuda analogue; reference: core/corr.py),
    # "pallas_alt" (on-demand Pallas kernel, O(H*W) memory — working form of
    # the reference's dead alt_cuda backend, core/corr.py:159-188), "auto"
    # (the fastest backend for the active platform: pallas_alt on TPU — also
    # O(H*W) memory — reg elsewhere; resolved at trace time, ops/corr.py).
    corr_implementation: str = "reg"
    corr_levels: int = 4
    corr_radius: int = 4

    # Resolution of the disparity field: 1/2^n_downsample.
    n_downsample: int = 2

    # GRU stack.
    n_gru_layers: int = 3
    hidden_dims: Tuple[int, ...] = (128, 128, 128)  # finest -> coarsest
    slow_fast_gru: bool = False

    # Encoders.
    shared_backbone: bool = False
    context_norm: str = "batch"

    # Precision policy.  "float32" or "bfloat16" compute for encoders + GRUs.
    # The correlation volume dtype is controlled separately because lookup
    # accuracy is precision-sensitive (reference: evaluate_stereo.py:227-230).
    compute_dtype: str = "float32"
    corr_dtype: str = "float32"
    # MXU multiply precision for the fp32 correlation matmuls: "highest"
    # (6-pass bf16 emulation, exact fp32), "high" (3-pass, ~fp32-accurate at
    # half the MXU cost), "default" (single bf16 pass).  Only consulted when
    # the inputs are fp32 — bf16 corr_dtype always takes the native path.
    corr_precision: str = "highest"
    # Int8-quantized correlation volume (ops/quant.py): symmetric per-row
    # int8 quantization of both feature maps, int8 x int8 -> int32
    # all-pairs product, scales folded into the dequant epilogue.  Forces
    # a precomputed-volume lookup backend (the on-demand backends would
    # re-quantize per lookup); the serving "turbo" accuracy tier sets it
    # via ops/quant.config_for_mode.  Inference-only numerics knob —
    # training always runs unquantized.
    corr_quant: bool = False

    # Fused Pallas encoder stem (ops/pallas_encoder.py).  None = auto
    # (enabled on TPU backends, incl. under a partitionable corr mesh via
    # shard_map); True/False force one numeric path — the fused stage's
    # instance-norm stats are fp32 kernel sums, which differ from the XLA
    # stage at stat-precision level (~1e-3 relative on bf16 activations),
    # so evaluations comparing runs across device counts can pin the path.
    fused_encoder: Optional[bool] = None

    # Test-mode GRU step backend (ops/pallas_gru.py).  "auto" resolves to
    # the fused Pallas megakernel (motion encoder + gru0 gates + flow head
    # in one VMEM-resident kernel per iteration) on a single-device TPU
    # backend and to the XLA reference step everywhere else; "fused"/"xla"
    # pin one numeric path (the fused step matches the XLA step to fp32
    # accumulation-order tolerance, not bitwise).  Train-mode tracing and
    # device meshes always take the XLA step.  Serving executables are
    # cache-keyed by the RESOLVED backend (serve/engine.py).
    gru_backend: str = "auto"

    # Rematerialize each GRU iteration in the backward pass (jax.checkpoint
    # on the scan body): activation memory drops from O(iters) to O(1) at the
    # cost of one extra forward per iteration.  Required to fit the reference
    # training recipe (batch 8, 320x720, 16+ iters) in one chip's HBM; free
    # for inference (no backward pass to rematerialize for).
    remat: bool = False

    # Input modality (sl/, docs/structured_light.md).  "passive" is the
    # classic 3-channel RGB pair; "sl" stacks the 9 projected-pattern
    # channels from data/sl.py onto each side (ambient 3 + patterns 9 = 12
    # channels per image) and routes both stacks through a learned
    # projection before the shared feature encoders.  The passive path is
    # bitwise-unchanged: no projection module exists, no extra params are
    # created, and the traced program is identical to pre-SL builds.
    # Serving executables are cache-keyed by this field (serve/engine.py),
    # and it joins the certification architecture fingerprint
    # (eval/certify.ARCH_FIELDS).
    input_mode: str = "passive"

    # Spatial sharding (parallel/spatial.py, docs/serving.md "Spatial
    # sharding"): shard one inference's image height across this many
    # chips on the ``space`` axis of a (1, N) mesh under shard_map —
    # single-request multi-chip inference for pairs whose corr pyramid +
    # activations exceed one chip's HBM.  1 = the classic single-chip
    # forward.  A model-level default: ``ServeConfig.spatial_shards``
    # overrides it serverside, and the engine cache-keys every spatial
    # executable by the resolved count.  v1 is XLA-GRU-only
    # (parallel/spatial.validate_spatial_config rejects the fused
    # megakernel, shared_backbone, group context norm and corr_quant).
    spatial_shards: int = 1

    def __post_init__(self):
        if isinstance(self.hidden_dims, list):
            object.__setattr__(self, "hidden_dims", tuple(self.hidden_dims))
        assert self.corr_implementation in (
            "auto", "reg", "alt", "pallas", "pallas_alt"), self.corr_implementation
        assert self.corr_precision in (
            "highest", "high", "default"), self.corr_precision
        assert self.gru_backend in ("auto", "fused", "xla"), self.gru_backend
        assert self.input_mode in ("passive", "sl"), self.input_mode
        assert 1 <= self.n_gru_layers <= 3, self.n_gru_layers
        assert len(self.hidden_dims) >= self.n_gru_layers
        assert self.spatial_shards >= 1, self.spatial_shards

    @property
    def factor(self) -> int:
        """Full-resolution upsampling factor for the disparity field."""
        return 2 ** self.n_downsample

    @property
    def cor_planes(self) -> int:
        """Correlation feature channels fed to the motion encoder."""
        return self.corr_levels * (2 * self.corr_radius + 1)

    @property
    def input_channels(self) -> int:
        """Channels per input image: 3 (passive RGB) or 12 (ambient RGB +
        9 pattern channels, sl/adapter.py)."""
        return 3 if self.input_mode == "passive" else 12


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training-loop hyper-parameters (reference: train_stereo.py:216-248)."""

    name: str = "raft-stereo"
    batch_size: int = 6
    train_datasets: Tuple[str, ...] = ("sceneflow",)
    lr: float = 2e-4
    num_steps: int = 100000
    image_size: Tuple[int, int] = (320, 720)
    train_iters: int = 16
    valid_iters: int = 32
    wdecay: float = 1e-5
    loss_gamma: float = 0.9
    max_flow: float = 700.0
    grad_clip: float = 1.0
    seed: int = 1234
    validation_frequency: int = 10000
    checkpoint_dir: str = "checkpoints"
    restore_ckpt: Optional[str] = None
    keep_checkpoints: int = 5

    # Data augmentation (reference: train_stereo.py:244-248).
    # img_gamma: (GMIN, GMAX) or (GMIN, GMAX, GAIN_MIN, GAIN_MAX).
    img_gamma: Optional[Tuple[float, ...]] = None
    saturation_range: Optional[Tuple[float, float]] = None
    do_flip: Optional[str] = None  # None | "h" | "v"
    spatial_scale: Tuple[float, float] = (0.0, 0.0)
    noyjitter: bool = False
    # Run the photometric chain (jitter + eraser) on-device inside the
    # jitted train step instead of in host workers (data/device_aug.py) —
    # for hosts whose CPUs can't feed the chip.
    device_photometric: bool = False

    # Parallelism: number of data-parallel shards (devices along the "data"
    # mesh axis); None = all visible devices.
    data_parallel: Optional[int] = None

    # Failure handling.  "abort": raise on a non-finite loss/gradient (the
    # reference's assert behaviour, train_stereo.py:49-52); "skip": drop the
    # bad update on-device, keep params/optimizer unchanged, advance the
    # schedule (the GradScaler-skip behaviour of torch AMP).
    nan_policy: str = "abort"
    # Auto-restart-from-checkpoint budget for the train loop (elastic
    # recovery; the reference's only recovery is a manual --restore_ckpt).
    # The budget counts restarts WITHOUT progress: a restart that resumes
    # from a later step than the previous one resets the count, so a long
    # run with occasional transient failures is never killed by an absolute
    # cap, while a crash loop stuck at one step exhausts it quickly.
    max_restarts: int = 0
    # Base of the exponential backoff between restarts (seconds; doubles per
    # consecutive no-progress restart, capped at 60s).
    restart_backoff: float = 1.0

    # Self-healing data pipeline (data/loader.py): per-sample retries with
    # backoff, bounded quarantine of persistently-bad indices (replaced by
    # deterministic resamples, counted in metrics), and a timeout on worker
    # batches after which the pool is recycled (0 disables).
    sample_retries: int = 2
    quarantine_limit: int = 64
    loader_timeout_s: float = 300.0

    # Step watchdog: flag (log + metric) any device step slower than this
    # multiple of the running median step time (0 disables).  Flags only —
    # a hung XLA collective is for the operator/restart policy to kill.
    watchdog_factor: float = 10.0

    def __post_init__(self):
        assert self.nan_policy in ("abort", "skip"), self.nan_policy
        for f in ("train_datasets", "image_size", "spatial_scale"):
            v = getattr(self, f)
            if isinstance(v, list):
                object.__setattr__(self, f, tuple(v))
        if isinstance(self.img_gamma, list):
            object.__setattr__(self, "img_gamma", tuple(self.img_gamma))
        if isinstance(self.saturation_range, list):
            object.__setattr__(self, "saturation_range", tuple(self.saturation_range))


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Temporal warm-start streaming parameters (stream/, docs/streaming.md).

    ``ladder`` is the small fixed set of GRU iteration counts the subsystem
    ever runs — each (bucket, level) pair is one compiled executable, so the
    adaptive controller can move between levels without ever paying an XLA
    compile mid-stream.  ``ladder[0]`` is the cold-start (full) count; warm
    frames use ``ladder[1:]``, picked per frame from an EMA of the update
    magnitude (mean |refined - warm-start init| at 1/factor resolution, in
    pixels).  Frozen + hashable like the other configs."""

    ladder: Tuple[int, ...] = (32, 16, 8)
    # EMA decay of the per-frame update magnitude (higher = smoother).
    ema_decay: float = 0.6
    # Controller thresholds on that EMA, in low-res pixels:
    # above promote -> more iterations next frame; below demote -> fewer;
    # above cold_reset -> the warm start is not tracking the scene (cut,
    # fast motion), next frame re-runs cold at ladder[0].
    promote_threshold: float = 1.0
    demote_threshold: float = 0.25
    cold_reset_threshold: float = 4.0
    # Session store bounds: LRU-evict beyond session_limit, treat sessions
    # idle past session_ttl_s as expired (next frame is cold, never an
    # error).
    session_limit: int = 256
    session_ttl_s: float = 300.0
    # Byte budget for the in-replica session store (docs/streaming.md
    # "Durable sessions"): LRU-evict while the byte-accurate state total
    # (disparity nbytes + controller overhead) exceeds it.  0 keeps the
    # historical count-only bound; the count cap stays as a secondary
    # limit either way.
    session_budget_mb: float = 0.0
    # Snapshot wire compression for exports + write-behind tier pushes:
    # "off" ships raw f32 planes (bitwise); "int8" rides ops/quant.py's
    # per-row symmetric int8 with a per-snapshot exactness manifest and
    # a bitwise f32 fallback when the manifest bound would be violated.
    snapshot_compress: str = "off"
    # Quantization-error bound (low-res px) the int8 manifest must
    # certify; a snapshot whose max |dequant - f32| exceeds it ships raw.
    snapshot_compress_bound: float = 0.05
    # External durable session tier (stream/tier.py, cli.sessiontier):
    # when set, every completed frame's snapshot is pushed write-behind
    # (bounded coalescing queue, never on the request path) so any
    # replica resumes any stream warm.  None = local-pin-only (PR 13).
    tier: Optional[Tuple[str, int]] = None
    # Write-behind robustness: per-call socket timeout, bounded
    # retry/backoff (utils/backoff.py), coalescing-queue bound, and the
    # re-probe cadence while degraded (tier unreachable -> local-pin
    # behavior, never an error).
    tier_timeout_s: float = 2.0
    tier_retries: int = 2
    tier_backoff_ms: float = 50.0
    tier_queue_limit: int = 1024
    tier_reprobe_s: float = 1.0

    def __post_init__(self):
        if isinstance(self.ladder, list):
            object.__setattr__(self, "ladder", tuple(self.ladder))
        if isinstance(self.tier, list):
            object.__setattr__(self, "tier", tuple(self.tier))
        assert self.snapshot_compress in ("off", "int8"), \
            self.snapshot_compress
        assert self.snapshot_compress_bound >= 0, \
            self.snapshot_compress_bound
        assert self.session_budget_mb >= 0, self.session_budget_mb
        assert self.tier_timeout_s > 0, self.tier_timeout_s
        assert self.tier_retries >= 0, self.tier_retries
        assert self.tier_backoff_ms >= 0, self.tier_backoff_ms
        assert self.tier_queue_limit >= 1, self.tier_queue_limit
        assert self.tier_reprobe_s > 0, self.tier_reprobe_s
        assert len(self.ladder) >= 2, (
            f"ladder {self.ladder} needs a cold level and at least one "
            f"warm level")
        assert all(i >= 1 for i in self.ladder), self.ladder
        assert all(a > b for a, b in zip(self.ladder, self.ladder[1:])), (
            f"ladder {self.ladder} must be strictly descending "
            f"(cold/full first)")
        # The design contract the stream subsystem is built around (and the
        # acceptance tests assert): warm frames run at most HALF the cold
        # iteration count.
        assert 2 * self.ladder[1] <= self.ladder[0], (
            f"first warm level {self.ladder[1]} must be <= half the cold "
            f"level {self.ladder[0]}")
        assert 0.0 <= self.ema_decay < 1.0, self.ema_decay
        assert (self.demote_threshold < self.promote_threshold
                < self.cold_reset_threshold), (
            self.demote_threshold, self.promote_threshold,
            self.cold_reset_threshold)
        assert self.session_limit >= 1, self.session_limit
        assert self.session_ttl_s > 0, self.session_ttl_s


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Iteration-level continuous batching (serve/sched/, docs/serving.md).

    Replaces whole-request dispatch with iteration-granular scheduling:
    the engine advances one running batch per shape bucket through
    single-iteration step executables, and requests join/leave at
    iteration boundaries — so a 32-iteration request never head-of-line
    blocks a 7-iteration stream frame.  Frozen + hashable like the other
    configs."""

    # GRU iterations per scheduler boundary.  1 gives the finest
    # join/leave granularity (lowest short-job latency); larger values
    # amortize per-boundary dispatch overhead.  Per-request iteration
    # targets must be divisible by it.
    iters_per_step: int = 1
    # Aging interval for the priority queue: a queued request is promoted
    # one priority class for every starvation_ms it has waited, so low
    # priority means "later", never "never".
    starvation_ms: float = 2000.0
    # Upper bound on a request's explicit per-request iteration target.
    # Unlike the monolithic path, ANY value up to this cap is served from
    # the same step executable — no per-iters compile to protect against.
    max_iters: int = 64

    def __post_init__(self):
        assert self.iters_per_step >= 1, self.iters_per_step
        assert self.starvation_ms > 0, self.starvation_ms
        assert self.max_iters >= self.iters_per_step, (
            self.max_iters, self.iters_per_step)
        assert self.max_iters % self.iters_per_step == 0, (
            f"max_iters {self.max_iters} not divisible by iters_per_step "
            f"{self.iters_per_step}")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Replicated multi-chip serving (serve/cluster/, docs/serving.md
    "Cluster").

    When set on a :class:`ServeConfig`, the server runs N independent
    engine replicas — one per device from ``parallel.mesh`` (or N
    thread-backed replicas on the CPU host platform under
    ``--xla_force_host_platform_device_count``) — behind a dispatcher
    that places cold work on the least-loaded ready replica and pins
    session/scheduled work to one replica (warm-start state and running
    batches must stay put).  Frozen + hashable like the other configs."""

    # Engine replicas.  None = one per visible device.
    replicas: Optional[int] = None
    # Bound on the session -> replica pin table (LRU beyond it; a
    # re-routed session degrades to a cold frame, never an error).
    session_pin_limit: int = 4096
    # Consecutive engine failures after which a replica is marked
    # ``failed`` and stops receiving new work (existing futures already
    # carry their error; the dispatcher never retries state-carrying
    # work on another replica).
    fail_threshold: int = 3
    # Warm replicas concurrently (one thread each; every engine owns its
    # compile cache and lock, so warmups never contend).
    warmup_parallel: bool = True
    # Optional fitted capacity model (JSON from ``cli.loadgen fit``,
    # docs/slo_harness.md) + the planned aggregate request rate: with
    # both, the dispatcher's autoscaler advice carries a model-based
    # recommended replica count and the ``cluster_capacity_headroom``
    # gauge reports headroom against target_rps.
    capacity_model: Optional[str] = None
    target_rps: float = 0.0

    def __post_init__(self):
        assert self.replicas is None or self.replicas >= 1, self.replicas
        assert self.session_pin_limit >= 1, self.session_pin_limit
        assert self.fail_threshold >= 1, self.fail_threshold
        assert self.target_rps >= 0, self.target_rps


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Front-end HTTP router over N backend stereo servers
    (serve/cluster/router.py, ``python -m raftstereo_tpu.cli.router``).

    The router owns no model: it probes each backend's ``/healthz``
    (``live``/``ready``/``draining``), places cold ``/predict`` traffic
    on the least-outstanding ready backend with bounded
    retry-with-backoff failover on backend failure (cold inference is
    idempotent), pins session frames to one backend (warm-start state is
    backend-local), and exports the ``cluster_*`` autoscaling metric
    families."""

    host: str = "127.0.0.1"
    port: int = 8081  # 0 = ephemeral (tests bind a free port)
    # (host, port) of each backend stereo server.
    backends: Tuple[Tuple[str, int], ...] = ()
    # Health probing: poll each backend's /healthz on this cadence; a
    # backend is unroutable after fail_after consecutive probe failures
    # (an in-flight connection error marks it unroutable immediately).
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    fail_after: int = 2
    # Failover for idempotent cold requests: total attempts are
    # retries + 1, spaced by retry_backoff_ms * 2^attempt with +-50%
    # jitter.  Session frames never retry a possibly-processed send
    # (a duplicate would advance the session state) — they re-pin on
    # connect-time failure only.
    retries: int = 2
    retry_backoff_ms: float = 50.0
    # Per-attempt socket timeout for forwarded requests; sized for one
    # in-flight batch plus a cold XLA compile behind it.
    request_timeout_s: float = 660.0
    # Same body cap as the backends: refuse before buffering.
    max_body_mb: float = 160.0
    # Span ring capacity behind the router's /debug/trace.
    trace_buffer: int = 4096
    # Bound on the session -> backend pin table (LRU beyond it, same
    # contract as ClusterConfig.session_pin_limit: an evicted session's
    # next frame re-pins and runs cold).
    session_pin_limit: int = 4096
    # Optional fitted capacity model + planned aggregate rate (same
    # contract as ClusterConfig.capacity_model/target_rps; the router
    # loads the JSON via the stdlib ops/autoscale.load_capacity_model,
    # staying model-free).
    capacity_model: Optional[str] = None
    target_rps: float = 0.0
    # Circuit breaker (serve/cluster/router.py, docs/fault_tolerance.md):
    # a backend's breaker opens after fail_after consecutive
    # connect/timeout failures (request path or probes); after
    # breaker_reset_s it admits ONE half-open trial, whose outcome
    # closes or re-opens it.
    breaker_reset_s: float = 5.0
    # (host, port) of a durable session tier (stream/tier.py,
    # ``python -m raftstereo_tpu.cli.sessiontier``): when set, a session
    # whose home backend is lost is resumed WARM from the tier's latest
    # snapshot instead of the PR 13 ``cold_lost`` fallback.
    session_tier: Optional[Tuple[str, int]] = None
    # Hedged requests for idempotent cold JSON /predict forwards:
    # 0 disables hedging (default).  When > 0, a hedge to the next
    # ready backend fires after max(hedge_floor_ms, live forward p99)
    # — the p99 term engages once hedge_min_samples forwards have been
    # observed.  Never for sessions or streamed binary bodies.
    hedge_floor_ms: float = 0.0
    hedge_min_samples: int = 20
    # Tail-based trace retention ring (obs/stitch.py): how many
    # kept-trace records GET /debug/vars surfaces.  Error traces and
    # traces slower than the live hop p99 are retained; the boring
    # middle is dropped deterministically.
    tail_ring: int = 256
    # Burn-rate alerting (obs/alerts.py): fast evaluation window; the
    # slow window is 5x it (the standard multi-window pairing).
    alert_window_s: float = 30.0
    # Error-rate budget per alert class: observed error rate divided by
    # this IS the burn rate (1.0 = consuming budget exactly at limit).
    alert_error_budget: float = 0.05
    # Shed-rate budget, same semantics.
    alert_shed_budget: float = 0.25
    # Both windows burning at >= this rate -> PAGE (state 2) and an
    # autoscaler scale-up signal.
    alert_page_burn: float = 2.0
    # Per-target timeout for GET /metrics/fleet federation scrapes.
    fleet_timeout_s: float = 2.0

    def __post_init__(self):
        if isinstance(self.backends, list):
            object.__setattr__(
                self, "backends", tuple(tuple(b) for b in self.backends))
        if isinstance(self.session_tier, list):
            object.__setattr__(
                self, "session_tier", tuple(self.session_tier))
        assert self.probe_interval_s > 0, self.probe_interval_s
        assert self.probe_timeout_s > 0, self.probe_timeout_s
        assert self.fail_after >= 1, self.fail_after
        assert self.retries >= 0, self.retries
        assert self.retry_backoff_ms >= 0, self.retry_backoff_ms
        assert self.request_timeout_s > 0, self.request_timeout_s
        assert self.max_body_mb > 0, self.max_body_mb
        assert self.trace_buffer >= 1, self.trace_buffer
        assert self.session_pin_limit >= 1, self.session_pin_limit
        assert self.target_rps >= 0, self.target_rps
        assert self.breaker_reset_s > 0, self.breaker_reset_s
        assert self.hedge_floor_ms >= 0, self.hedge_floor_ms
        assert self.hedge_min_samples >= 1, self.hedge_min_samples
        assert self.tail_ring >= 1, self.tail_ring
        assert self.alert_window_s > 0, self.alert_window_s
        assert 0 < self.alert_error_budget <= 1, self.alert_error_budget
        assert 0 < self.alert_shed_budget <= 1, self.alert_shed_budget
        assert self.alert_page_burn >= 1, self.alert_page_burn
        assert self.fleet_timeout_s > 0, self.fleet_timeout_s


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """Durable session tier (stream/tier.py,
    ``python -m raftstereo_tpu.cli.sessiontier``).

    The tier is model-free: it stores each session's latest snapshot as
    the verbatim wire JSON the backends already exchange over
    ``/debug/sessions`` (docs/serving.md "Session migration"), never
    decoding the arrays — so it starts in milliseconds, like the
    router, and any schema the backends agree on rides through it
    untouched."""

    host: str = "127.0.0.1"
    port: int = 8082  # 0 = ephemeral (tests bind a free port)
    # Count cap on stored sessions (LRU beyond it — an evicted
    # session's next resume falls back cold, never an error).
    session_limit: int = 65536
    # Byte budget over the stored wire bodies; LRU eviction while over
    # it (0 disables the byte bound; the count cap stays either way).
    budget_mb: float = 256.0
    # Snapshot bodies are small (a low-res disparity plane), so the
    # body cap is far below the serving default.
    max_body_mb: float = 16.0

    def __post_init__(self):
        assert self.session_limit >= 1, self.session_limit
        assert self.budget_mb >= 0, self.budget_mb
        assert self.max_body_mb > 0, self.max_body_mb


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-layer parameters (serve/): dynamic micro-batching, the
    shape-bucketed compile cache, admission control and graceful
    degradation.  Consumed by ``python -m raftstereo_tpu.cli.serve`` and by
    ``bench.py --serve``; frozen + hashable like the other configs."""

    host: str = "127.0.0.1"
    port: int = 8080  # 0 = ephemeral (tests/bench bind a free port)

    # Shape policy, shared bitwise with the Evaluator via
    # ops/image.BucketPadder: align to divis_by, round up to bucket_multiple.
    divis_by: int = 32
    bucket_multiple: int = 64
    # Image shapes (H, W) whose buckets are compiled at startup so the first
    # real request in each never pays an XLA compile.
    buckets: Tuple[Tuple[int, int], ...] = ((540, 960),)
    warmup: bool = True

    # Dynamic micro-batching: a batch closes at max_batch_size or when the
    # oldest member has waited max_wait_ms, whichever comes first.  Every
    # dispatched batch is zero-padded to max_batch_size so each shape bucket
    # compiles exactly once.
    max_batch_size: int = 8
    max_wait_ms: float = 5.0

    # Robustness: bounded queue (admission control sheds above the limit),
    # per-request timeout, and load-adaptive GRU-iteration reduction once
    # the queue backlog crosses degrade_queue_depth.
    queue_limit: int = 64
    request_timeout_ms: float = 30000.0
    iters: int = 32
    degraded_iters: int = 16
    degrade_queue_depth: int = 16

    # Request-size admission caps (each compile and each oversized tensor
    # costs everyone queued behind it): reject bodies above max_body_mb
    # (413) and images with a side above max_image_dim (400) before any
    # decode/allocation.  The body default is sized to what max_image_dim
    # actually needs (a 2048^2 fp32 pair is ~134 MB base64), not beyond
    # it.  cold_buckets=False additionally rejects shapes whose bucket
    # was not warmed at startup (400) — the production setting; True
    # compiles on demand (development, tests).
    max_body_mb: float = 160.0
    max_image_dim: int = 2048
    cold_buckets: bool = True

    # Temporal warm-start streaming (stream/, docs/streaming.md): when set,
    # ``/predict`` accepts ``session_id``/``seq_no`` and frames of a session
    # are warm-started from the previous frame's forward-warped disparity at
    # an adaptively reduced iteration count.  None disables the session
    # endpoints.  ``stream_warmup`` eagerly compiles the ladder levels for
    # every configured bucket at startup (the stream analogue of
    # ``warmup``), so mid-stream level switches never pay an XLA compile.
    stream: Optional[StreamConfig] = None
    stream_warmup: bool = False

    # Iteration-level continuous batching (serve/sched/): when set, the
    # server replaces the whole-request micro-batcher with the per-request
    # scheduler — requests join/leave one running batch per bucket at
    # iteration boundaries, ``/predict`` accepts ``deadline_ms`` +
    # ``priority``, and session frames ride the same scheduler as
    # high-priority short jobs instead of the batch-size-1 bypass.  None
    # keeps the monolithic dispatch path.
    sched: Optional[SchedConfig] = None

    # Replicated serving (serve/cluster/, docs/serving.md "Cluster"):
    # when set, the server runs N engine replicas (one per device)
    # behind a least-outstanding-work dispatcher with session-sticky
    # routing.  None keeps the single-engine path.
    cluster: Optional[ClusterConfig] = None

    # Spatial sharding (parallel/spatial.py, serve/spatial/,
    # docs/serving.md "Spatial sharding"): when > 1 the server can run
    # ONE request with its image height sharded across that many chips
    # on the ``space`` axis of a (1, N) mesh — the path for resolutions
    # above the single-chip bucket ceiling (``max_image_dim``).  0
    # inherits the model config's ``spatial_shards``.
    # ``spatial_buckets`` are the (H, W) image shapes the spatial path
    # serves (warmed at startup like ``buckets``); spatial requests to
    # other shapes — or with an ``accuracy`` tier / ``session_id``, both
    # unsupported under sharding in v1 — are 400s at admission, never a
    # compile.  When spatial buckets are configured, ``max_body_mb`` is
    # auto-raised to fit the largest one (see ``spatial_body_mb``), so a
    # 4K pair is not 413'd before admission ever sees it.
    spatial_shards: int = 0
    spatial_buckets: Tuple[Tuple[int, int], ...] = ()

    # Per-request accuracy tiers (ops/quant.py, docs/serving.md "Accuracy
    # tiers"): tier names ("certified"/"fast"/"turbo") the server should
    # OFFER on /predict's ``accuracy`` field.  "fast"/"turbo" are only
    # ADVERTISED (accepted + warmed) when ``cert_manifest`` certifies
    # their EPE delta within bound for this model (eval/certify.py;
    # python -m raftstereo_tpu.cli.certify writes it) — an uncertified
    # tier is refused with a clean 400, never served silently.  Empty =
    # the historical single-precision server: any ``accuracy`` field is
    # a 400 and no extra executables are compiled.
    tiers: Tuple[str, ...] = ()
    cert_manifest: Optional[str] = None

    # Speculative tier cascades (serve/cascade/, docs/serving.md "Tier
    # cascade"): schedule strings like "int8:24+fp32:8" — most GRU
    # iterations drafted on a cheap precision tier, the last K run on
    # the certified fp32 executables.  Requires ``sched`` (the handoff
    # is an iteration-boundary leave+join) and a ``cert_manifest``
    # certifying each schedule's EPE delta (cli.certify cascade);
    # uncertified schedules are refused at startup, never served.
    # ``cascade_divergence`` arms the early-promotion trigger: when the
    # EMA of a drafting slot's per-step low-res disparity delta (px)
    # exceeds it, the slot promotes to the certified tier before its
    # scheduled boundary.  0 = scheduled handoffs only.
    cascades: Tuple[str, ...] = ()
    cascade_divergence: float = 0.0

    # Observability (obs/, docs/observability.md): capacity of the span
    # ring buffer behind /debug/trace.  Spans are a few hundred bytes; the
    # ring bounds memory no matter the traffic.
    trace_buffer: int = 4096

    def __post_init__(self):
        if isinstance(self.buckets, list):
            object.__setattr__(
                self, "buckets", tuple(tuple(b) for b in self.buckets))
        if isinstance(self.spatial_buckets, list):
            object.__setattr__(
                self, "spatial_buckets",
                tuple(tuple(b) for b in self.spatial_buckets))
        assert self.spatial_shards >= 0, self.spatial_shards
        if self.spatial_shards > 1 and self.spatial_buckets:
            # The whole point of the spatial path is payloads above the
            # single-chip cap — refusing them at the body cap would make
            # the capability unreachable (serve/httpbase.py 413s before
            # admission ever sees the request).
            need = spatial_body_mb(self.spatial_buckets)
            if need > self.max_body_mb:
                object.__setattr__(self, "max_body_mb", need)
        _known_tiers = ("certified", "fast", "turbo")  # ops/quant.TIERS
        bad_tiers = [t for t in self.tiers if t not in _known_tiers]
        assert not bad_tiers, (
            f"unknown accuracy tiers {bad_tiers}; choose from "
            f"{list(_known_tiers)}")
        if isinstance(self.cascades, list):
            object.__setattr__(self, "cascades", tuple(self.cascades))
        assert self.cascade_divergence >= 0, self.cascade_divergence
        if self.cascades or self.cascade_divergence > 0:
            assert self.sched is not None, (
                "cascades require --sched: the tier handoff is an "
                "iteration-boundary leave+join on the scheduler's "
                "running batches (docs/serving.md \"Tier cascade\")")
            assert self.cascades or self.cascade_divergence == 0, (
                "--cascade_divergence without --cascades arms a trigger "
                "nothing can fire")
            # Parse + canonicalize each schedule against the grammar and
            # the scheduler's granularity, fail-fast at config time (the
            # grammar module is jax-free, so this costs no import
            # weight in client-side processes).
            from .serve.cascade.schedule import (parse_schedule,
                                                 validate_schedule)
            canon = []
            for text in self.cascades:
                s = validate_schedule(
                    parse_schedule(text),
                    iters_per_step=self.sched.iters_per_step,
                    max_iters=self.sched.max_iters)
                canon.append(s.schedule)
            assert len(set(canon)) == len(canon), (
                f"duplicate cascade schedules in {list(self.cascades)} "
                f"(canonical: {canon})")
            object.__setattr__(self, "cascades", tuple(canon))
        # Degradation can only reduce work: a degraded_iters above iters
        # (e.g. the default 16 with --serve_iters 8) clamps down rather
        # than rejecting the config.
        if self.degraded_iters > self.iters:
            object.__setattr__(self, "degraded_iters", self.iters)
        assert self.max_batch_size >= 1, self.max_batch_size
        assert self.queue_limit >= self.max_batch_size, (
            f"queue_limit {self.queue_limit} < max_batch_size "
            f"{self.max_batch_size}: no full batch could ever form")
        assert self.iters >= 1 and self.degraded_iters >= 1, (
            self.iters, self.degraded_iters)
        assert self.max_wait_ms >= 0, self.max_wait_ms
        assert self.divis_by >= 1 and self.bucket_multiple >= 1
        assert self.max_body_mb > 0 and self.max_image_dim >= 1
        assert self.trace_buffer >= 1, self.trace_buffer
        if self.sched is not None:
            assert self.iters % self.sched.iters_per_step == 0, (
                f"iters {self.iters} not divisible by sched.iters_per_step "
                f"{self.sched.iters_per_step}")
            assert self.iters <= self.sched.max_iters, (
                f"iters {self.iters} exceeds sched.max_iters "
                f"{self.sched.max_iters}")
            if self.stream is not None:
                # Session frames ride the scheduler: every ladder level
                # must be a reachable iteration target.
                bad = [lv for lv in self.stream.ladder
                       if lv % self.sched.iters_per_step
                       or lv > self.sched.max_iters]
                assert not bad, (
                    f"stream ladder levels {bad} unreachable under sched "
                    f"(iters_per_step {self.sched.iters_per_step}, "
                    f"max_iters {self.sched.max_iters})")


def spatial_body_mb(buckets: Tuple[Tuple[int, int], ...],
                    channels: int = 3) -> float:
    """Request-body cap (MB) the largest spatial bucket needs: two fp32
    images base64-encoded (4/3 expansion) plus 25% JSON/meta headroom.
    ``ServeConfig`` raises ``max_body_mb`` to this when spatial buckets
    are configured — a 4K pair is ~265 MB on the wire, well above the
    single-chip default cap."""
    if not buckets:
        return 0.0
    h, w = max(buckets, key=lambda b: b[0] * b[1])
    raw = 2 * h * w * channels * 4  # two fp32 images
    return round(raw * (4 / 3) * 1.25 / 2 ** 20, 1)


def _parse_bucket(text: str) -> Tuple[int, int]:
    try:
        h, w = (int(v) for v in text.lower().split("x"))
        return h, w
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bucket {text!r} is not HxW (e.g. 540x960)")


def add_serve_args(parser: argparse.ArgumentParser) -> None:
    d = ServeConfig()
    g = parser.add_argument_group("serve")
    g.add_argument("--host", default=d.host)
    g.add_argument("--port", type=int, default=d.port,
                   help="0 binds an ephemeral port")
    g.add_argument("--divis_by", type=int, default=d.divis_by)
    g.add_argument("--bucket_multiple", type=int, default=d.bucket_multiple,
                   help="round padded shapes up to this grid so "
                        "near-identical sizes share one compile")
    g.add_argument("--buckets", nargs="+", type=_parse_bucket,
                   default=list(d.buckets), metavar="HxW",
                   help="image shapes warmed at startup (e.g. 540x960)")
    g.add_argument("--no_warmup", action="store_true",
                   help="skip startup compilation of --buckets")
    g.add_argument("--max_batch_size", type=int, default=d.max_batch_size)
    g.add_argument("--max_wait_ms", type=float, default=d.max_wait_ms,
                   help="batching deadline: max time the oldest queued "
                        "request waits for a batch to fill")
    g.add_argument("--queue_limit", type=int, default=d.queue_limit,
                   help="admission control: requests beyond this backlog "
                        "are shed with an 'overloaded' response")
    g.add_argument("--request_timeout_ms", type=float,
                   default=d.request_timeout_ms)
    g.add_argument("--serve_iters", type=int, default=d.iters,
                   help="GRU iterations per request under normal load")
    g.add_argument("--degraded_iters", type=int, default=d.degraded_iters,
                   help="reduced GRU iterations once the queue backlog "
                        "crosses --degrade_queue_depth (graceful "
                        "degradation; RAFT-Stereo quality falls smoothly "
                        "with iteration count)")
    g.add_argument("--degrade_queue_depth", type=int,
                   default=d.degrade_queue_depth)
    g.add_argument("--max_body_mb", type=float, default=d.max_body_mb,
                   help="reject request bodies above this size (HTTP 413)")
    g.add_argument("--max_image_dim", type=int, default=d.max_image_dim,
                   help="reject images with a side above this (HTTP 400)")
    g.add_argument("--no_cold_buckets", action="store_true",
                   help="reject shapes whose bucket was not warmed at "
                        "startup instead of compiling on demand (recommended "
                        "in production: a compile stalls everyone queued)")
    g.add_argument("--trace_buffer", type=int, default=d.trace_buffer,
                   help="span ring-buffer capacity behind /debug/trace "
                        "(docs/observability.md)")
    g.add_argument("--spatial_shards", type=int, default=d.spatial_shards,
                   help="shard one request's image height across this many "
                        "chips (space axis, parallel/spatial.py) for "
                        "resolutions above --max_image_dim; 0 inherits the "
                        "model config, 1 disables "
                        "(docs/serving.md \"Spatial sharding\")")
    g.add_argument("--spatial_buckets", nargs="+", type=_parse_bucket,
                   default=list(d.spatial_buckets), metavar="HxW",
                   help="image shapes the spatial path serves (warmed at "
                        "startup; other spatial shapes are a 400). "
                        "Raises --max_body_mb to fit the largest one.")
    g.add_argument("--tiers", nargs="+", default=list(d.tiers),
                   choices=["certified", "fast", "turbo"], metavar="TIER",
                   help="accuracy tiers offered on /predict's 'accuracy' "
                        "field (certified=fp32, fast=bf16, turbo=int8 "
                        "corr + bf16); fast/turbo also need a "
                        "--cert_manifest certifying their EPE delta "
                        "(docs/serving.md \"Accuracy tiers\")")
    g.add_argument("--cert_manifest", default=d.cert_manifest,
                   help="certification manifest written by "
                        "'python -m raftstereo_tpu.cli.certify'; "
                        "validated at startup before a tier is advertised")
    g.add_argument("--cascades", nargs="+", default=list(d.cascades),
                   metavar="SCHEDULE",
                   help="speculative tier-cascade schedules to offer, "
                        "e.g. int8:24+fp32:8 (draft on the cheap tier, "
                        "certify on fp32); requires --sched and a "
                        "--cert_manifest certifying each schedule "
                        "('cli.certify cascade'; docs/serving.md "
                        "\"Tier cascade\")")
    g.add_argument("--cascade_divergence", type=float,
                   default=d.cascade_divergence,
                   help="early-promotion trigger: EMA of a drafting "
                        "slot's per-step low-res disparity delta (px) "
                        "above which it hands off to the certified tier "
                        "before its scheduled boundary; 0 = scheduled "
                        "handoffs only")


def add_sched_args(parser: argparse.ArgumentParser) -> None:
    d = SchedConfig()
    g = parser.add_argument_group("sched")
    g.add_argument("--sched_iters_per_step", type=int,
                   default=d.iters_per_step,
                   help="GRU iterations per scheduler boundary (1 = finest "
                        "join/leave granularity; per-request iteration "
                        "targets must be divisible by it)")
    g.add_argument("--sched_starvation_ms", type=float,
                   default=d.starvation_ms,
                   help="queued requests gain one priority class per this "
                        "many ms waited, so low priority is never starved")
    g.add_argument("--sched_max_iters", type=int, default=d.max_iters,
                   help="cap on per-request iteration targets (any value "
                        "up to it is served from the same step executable)")


def sched_config_from_args(args: argparse.Namespace) -> SchedConfig:
    return SchedConfig(
        iters_per_step=args.sched_iters_per_step,
        starvation_ms=args.sched_starvation_ms,
        max_iters=args.sched_max_iters,
    )


def add_cluster_args(parser: argparse.ArgumentParser) -> None:
    d = ClusterConfig()
    g = parser.add_argument_group("cluster")
    g.add_argument("--replicas", type=int, default=None,
                   help="engine replicas, one per device (0/unset = "
                        "single-engine serving; -1 = one per visible "
                        "device); each replica owns its compile cache "
                        "and is warmed in-process before it is routable")
    g.add_argument("--session_pin_limit", type=int,
                   default=d.session_pin_limit,
                   help="bound on the session->replica pin table (LRU "
                        "beyond it; a re-routed session re-runs cold)")
    g.add_argument("--replica_fail_threshold", type=int,
                   default=d.fail_threshold,
                   help="consecutive engine failures after which a "
                        "replica stops receiving new work")
    g.add_argument("--capacity_model", default=None,
                   help="fitted capacity-model JSON (cli.loadgen fit, "
                        "docs/slo_harness.md) for model-based autoscale "
                        "advice + the cluster_capacity_headroom gauge")
    g.add_argument("--target_rps", type=float, default=d.target_rps,
                   help="planned aggregate request rate the capacity "
                        "model sizes the fleet for")


def cluster_config_from_args(args: argparse.Namespace
                             ) -> Optional[ClusterConfig]:
    if not args.replicas:
        return None
    return ClusterConfig(
        replicas=None if args.replicas < 0 else args.replicas,
        session_pin_limit=args.session_pin_limit,
        fail_threshold=args.replica_fail_threshold,
        capacity_model=args.capacity_model,
        target_rps=args.target_rps,
    )


def _parse_backend(text: str) -> Tuple[str, int]:
    host, _, port = text.rpartition(":")
    try:
        return (host or "127.0.0.1"), int(port)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"backend {text!r} is not HOST:PORT (e.g. 127.0.0.1:8080)")


def add_router_args(parser: argparse.ArgumentParser) -> None:
    d = RouterConfig()
    g = parser.add_argument_group("router")
    g.add_argument("--host", default=d.host)
    g.add_argument("--port", type=int, default=d.port,
                   help="0 binds an ephemeral port")
    g.add_argument("--backends", nargs="+", type=_parse_backend,
                   required=True, metavar="HOST:PORT",
                   help="backend stereo servers to route over")
    g.add_argument("--probe_interval_s", type=float,
                   default=d.probe_interval_s,
                   help="seconds between /healthz probes per backend")
    g.add_argument("--probe_timeout_s", type=float,
                   default=d.probe_timeout_s)
    g.add_argument("--fail_after", type=int, default=d.fail_after,
                   help="consecutive probe failures before a backend is "
                        "unroutable")
    g.add_argument("--router_retries", type=int, default=d.retries,
                   help="failover attempts beyond the first for "
                        "idempotent cold requests on backend failure")
    g.add_argument("--retry_backoff_ms", type=float,
                   default=d.retry_backoff_ms,
                   help="base backoff between failover attempts "
                        "(doubles per attempt, +-50%% jitter)")
    g.add_argument("--router_timeout_s", type=float,
                   default=d.request_timeout_s,
                   help="per-attempt socket timeout for forwarded "
                        "requests")
    g.add_argument("--max_body_mb", type=float, default=d.max_body_mb)
    g.add_argument("--trace_buffer", type=int, default=d.trace_buffer)
    g.add_argument("--session_pin_limit", type=int,
                   default=d.session_pin_limit,
                   help="bound on the session -> backend pin table (LRU "
                        "beyond it; an evicted session's next frame "
                        "re-pins and runs cold)")
    g.add_argument("--capacity_model", default=None,
                   help="fitted capacity-model JSON (cli.loadgen fit, "
                        "docs/slo_harness.md) for model-based autoscale "
                        "advice + the cluster_capacity_headroom gauge")
    g.add_argument("--target_rps", type=float, default=d.target_rps,
                   help="planned aggregate request rate the capacity "
                        "model sizes the backend fleet for")
    g.add_argument("--session_tier", type=_parse_backend, default=None,
                   metavar="HOST:PORT",
                   help="durable session tier (cli.sessiontier): resume "
                        "a session warm from it when its home backend "
                        "is lost (docs/streaming.md \"Durable sessions\")")
    g.add_argument("--breaker_reset_s", type=float,
                   default=d.breaker_reset_s,
                   help="seconds an open circuit breaker waits before "
                        "admitting a half-open trial request")
    g.add_argument("--hedge_floor_ms", type=float,
                   default=d.hedge_floor_ms,
                   help="floor on the hedged-request delay for idempotent "
                        "cold JSON requests; 0 disables hedging")
    g.add_argument("--hedge_min_samples", type=int,
                   default=d.hedge_min_samples,
                   help="forward-latency samples required before the hedge "
                        "delay tracks live p99 instead of the floor")
    g.add_argument("--tail_ring", type=int, default=d.tail_ring,
                   help="tail-based trace retention ring capacity: error "
                        "and slower-than-live-p99 traces kept, the "
                        "boring middle dropped (docs/observability.md)")
    g.add_argument("--alert_window_s", type=float,
                   default=d.alert_window_s,
                   help="fast burn-rate evaluation window; the slow "
                        "window is 5x it")
    g.add_argument("--alert_error_budget", type=float,
                   default=d.alert_error_budget,
                   help="error-rate budget: observed error rate over "
                        "this is the burn rate")
    g.add_argument("--alert_shed_budget", type=float,
                   default=d.alert_shed_budget,
                   help="shed-rate budget, same burn semantics")
    g.add_argument("--alert_page_burn", type=float,
                   default=d.alert_page_burn,
                   help="both windows burning at >= this pages (alert "
                        "state 2) and signals the autoscaler")
    g.add_argument("--fleet_timeout_s", type=float,
                   default=d.fleet_timeout_s,
                   help="per-target scrape timeout for GET /metrics/fleet "
                        "federation")


def router_config_from_args(args: argparse.Namespace) -> RouterConfig:
    return RouterConfig(
        host=args.host,
        port=args.port,
        backends=tuple(tuple(b) for b in args.backends),
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        fail_after=args.fail_after,
        retries=args.router_retries,
        retry_backoff_ms=args.retry_backoff_ms,
        request_timeout_s=args.router_timeout_s,
        max_body_mb=args.max_body_mb,
        trace_buffer=args.trace_buffer,
        session_pin_limit=args.session_pin_limit,
        capacity_model=args.capacity_model,
        target_rps=args.target_rps,
        session_tier=(tuple(args.session_tier)
                      if args.session_tier is not None else None),
        breaker_reset_s=args.breaker_reset_s,
        hedge_floor_ms=args.hedge_floor_ms,
        hedge_min_samples=args.hedge_min_samples,
        tail_ring=args.tail_ring,
        alert_window_s=args.alert_window_s,
        alert_error_budget=args.alert_error_budget,
        alert_shed_budget=args.alert_shed_budget,
        alert_page_burn=args.alert_page_burn,
        fleet_timeout_s=args.fleet_timeout_s,
    )


def add_tier_args(parser: argparse.ArgumentParser) -> None:
    d = TierConfig()
    g = parser.add_argument_group("session tier")
    g.add_argument("--host", default=d.host)
    g.add_argument("--port", type=int, default=d.port,
                   help="0 binds an ephemeral port")
    g.add_argument("--session_limit", type=int, default=d.session_limit,
                   help="max stored sessions (LRU beyond it; an evicted "
                        "session's next resume falls back cold)")
    g.add_argument("--budget_mb", type=float, default=d.budget_mb,
                   help="byte budget over stored snapshot bodies (LRU "
                        "eviction while over it; 0 = count-bounded only)")
    g.add_argument("--max_body_mb", type=float, default=d.max_body_mb)


def tier_config_from_args(args: argparse.Namespace) -> TierConfig:
    return TierConfig(
        host=args.host,
        port=args.port,
        session_limit=args.session_limit,
        budget_mb=args.budget_mb,
        max_body_mb=args.max_body_mb,
    )


def add_stream_args(parser: argparse.ArgumentParser) -> None:
    d = StreamConfig()
    g = parser.add_argument_group("stream")
    g.add_argument("--stream_ladder", nargs="+", type=int,
                   default=list(d.ladder), metavar="ITERS",
                   help="descending GRU-iteration levels; ladder[0] is the "
                        "cold-start count, warm frames pick from the rest "
                        "(each level is one pre-compilable executable)")
    g.add_argument("--ema_decay", type=float, default=d.ema_decay,
                   help="EMA decay of the per-frame update magnitude that "
                        "drives the adaptive iteration controller")
    g.add_argument("--promote_threshold", type=float,
                   default=d.promote_threshold,
                   help="EMA (low-res px) above which the next frame runs "
                        "more iterations")
    g.add_argument("--demote_threshold", type=float,
                   default=d.demote_threshold,
                   help="EMA below which the next frame runs fewer "
                        "iterations")
    g.add_argument("--cold_reset_threshold", type=float,
                   default=d.cold_reset_threshold,
                   help="EMA above which the warm start is judged lost and "
                        "the next frame re-runs cold at ladder[0]")
    g.add_argument("--session_limit", type=int, default=d.session_limit,
                   help="max live sessions; beyond this the LRU session is "
                        "evicted (its next frame re-runs cold)")
    g.add_argument("--session_ttl_s", type=float, default=d.session_ttl_s,
                   help="idle seconds after which a session expires (its "
                        "next frame re-runs cold, never an error)")
    g.add_argument("--session_budget_mb", type=float,
                   default=d.session_budget_mb,
                   help="byte budget for in-replica session state (LRU "
                        "eviction while over it; 0 = count-bounded only)")
    g.add_argument("--snapshot_compress", choices=["off", "int8"],
                   default=d.snapshot_compress,
                   help="snapshot wire compression for exports + tier "
                        "pushes: int8 = per-row symmetric quantization "
                        "with an exactness manifest and a bitwise f32 "
                        "fallback (docs/streaming.md)")
    g.add_argument("--session_tier", type=_parse_backend, default=None,
                   metavar="HOST:PORT",
                   help="durable session tier (cli.sessiontier) to push "
                        "completed-frame snapshots to, write-behind; "
                        "unset = local-pin-only sessions")


def stream_config_from_args(args: argparse.Namespace) -> StreamConfig:
    return StreamConfig(
        ladder=tuple(args.stream_ladder),
        ema_decay=args.ema_decay,
        promote_threshold=args.promote_threshold,
        demote_threshold=args.demote_threshold,
        cold_reset_threshold=args.cold_reset_threshold,
        session_limit=args.session_limit,
        session_ttl_s=args.session_ttl_s,
        session_budget_mb=args.session_budget_mb,
        snapshot_compress=args.snapshot_compress,
        tier=(tuple(args.session_tier)
              if args.session_tier is not None else None),
    )


def serve_config_from_args(args: argparse.Namespace,
                           stream: Optional[StreamConfig] = None,
                           stream_warmup: bool = False,
                           sched: Optional[SchedConfig] = None,
                           cluster: Optional[ClusterConfig] = None
                           ) -> ServeConfig:
    return ServeConfig(
        stream=stream,
        stream_warmup=stream_warmup,
        sched=sched,
        cluster=cluster,
        host=args.host,
        port=args.port,
        divis_by=args.divis_by,
        bucket_multiple=args.bucket_multiple,
        buckets=tuple(tuple(b) for b in args.buckets),
        warmup=not args.no_warmup,
        max_batch_size=args.max_batch_size,
        max_wait_ms=args.max_wait_ms,
        queue_limit=args.queue_limit,
        request_timeout_ms=args.request_timeout_ms,
        iters=args.serve_iters,
        degraded_iters=args.degraded_iters,
        degrade_queue_depth=args.degrade_queue_depth,
        max_body_mb=args.max_body_mb,
        max_image_dim=args.max_image_dim,
        cold_buckets=not args.no_cold_buckets,
        spatial_shards=args.spatial_shards,
        spatial_buckets=tuple(tuple(b) for b in args.spatial_buckets),
        trace_buffer=args.trace_buffer,
        tiers=tuple(args.tiers),
        cert_manifest=args.cert_manifest,
        cascades=tuple(args.cascades),
        cascade_divergence=args.cascade_divergence,
    )


# ---------------------------------------------------------------------------
# CLI plumbing: one flag set, shared by every entry point.
# ---------------------------------------------------------------------------

def add_model_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("model")
    g.add_argument("--corr_implementation",
                   choices=["auto", "reg", "alt", "pallas", "pallas_alt"],
                   default="reg",
                   help="correlation backend; 'auto' = fastest for the "
                        "active platform (pallas_alt on TPU, reg elsewhere)")
    g.add_argument("--corr_levels", type=int, default=4)
    g.add_argument("--corr_radius", type=int, default=4)
    g.add_argument("--n_downsample", type=int, default=2)
    g.add_argument("--n_gru_layers", type=int, default=3)
    g.add_argument("--hidden_dims", nargs="+", type=int, default=[128, 128, 128])
    g.add_argument("--slow_fast_gru", action="store_true")
    g.add_argument("--shared_backbone", action="store_true")
    g.add_argument("--context_norm", choices=["group", "batch", "instance", "none"],
                   default="batch")
    g.add_argument("--mixed_precision", action="store_true",
                   help="bfloat16 compute for encoders and GRUs")
    g.add_argument("--corr_dtype", choices=["float32", "bfloat16"], default="float32")
    g.add_argument("--corr_precision", choices=["highest", "high", "default"],
                   default="highest",
                   help="MXU multiply precision for fp32 correlation matmuls "
                        "(highest=exact 6-pass, high=3-pass, default=1-pass)")
    g.add_argument("--corr_quant", action="store_true",
                   help="int8-quantized correlation volume (symmetric "
                        "per-row scales, int8 matmul + dequant epilogue; "
                        "ops/quant.py) — the 'turbo' serving tier's "
                        "numeric policy, inference only")
    g.add_argument("--gru_backend", choices=["auto", "fused", "xla"],
                   default="auto",
                   help="test-mode GRU step backend: 'auto' = fused Pallas "
                        "megakernel on single-device TPU, XLA elsewhere "
                        "(ops/pallas_gru.py)")
    g.add_argument("--remat", action="store_true",
                   help="rematerialize each GRU iteration in backward: "
                        "O(1) activation memory instead of O(iters); "
                        "needed to fit the full training recipe on one chip")
    g.add_argument("--input_mode", choices=["passive", "sl"],
                   default="passive",
                   help="input modality: 'passive' = 3-channel RGB pairs; "
                        "'sl' = 12-channel structured-light stacks (ambient "
                        "+ 9 pattern channels per side) through a learned "
                        "projection (docs/structured_light.md)")


def model_config_from_args(args: argparse.Namespace) -> RAFTStereoConfig:
    return RAFTStereoConfig(
        corr_implementation=args.corr_implementation,
        corr_levels=args.corr_levels,
        corr_radius=args.corr_radius,
        n_downsample=args.n_downsample,
        n_gru_layers=args.n_gru_layers,
        hidden_dims=tuple(args.hidden_dims),
        slow_fast_gru=args.slow_fast_gru,
        shared_backbone=args.shared_backbone,
        context_norm=args.context_norm,
        compute_dtype="bfloat16" if args.mixed_precision else "float32",
        corr_dtype=args.corr_dtype,
        corr_precision=args.corr_precision,
        corr_quant=args.corr_quant,
        gru_backend=args.gru_backend,
        remat=args.remat,
        input_mode=args.input_mode,
    )
