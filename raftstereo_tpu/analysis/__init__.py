"""raftstereo_tpu.analysis — JAX/TPU hygiene + thread-safety lint, and a
runtime retrace guard (docs/static_analysis.md).

The system's headline guarantees — "one compile per bucket" (serve),
"streaming adds zero compiles beyond the ladder" (stream), "tracing adds
zero XLA compiles" (obs) — are invariants nothing used to enforce except
hand-written e2e assertions.  This package enforces them mechanically:

* **Static checkers** (AST, stdlib-only, nothing imported): jit/Pallas
  hygiene (RSA1xx), donation safety (RSA2xx), ``# guarded_by:`` lock
  discipline (RSA3xx), executable-cache key coverage (RSA4xx), plus the
  consolidated metric-name lint (RSA5xx, runtime).  Runner:
  ``python -m raftstereo_tpu.analysis [paths]``, wired into tier-1 via
  tests/test_analysis.py.  Per-line ``# noqa: RSA###`` suppressions and
  a checked-in baseline (``analysis_baseline.txt``, empty on the shipped
  tree) gate CI on NEW findings only.
* **Retrace guard** (``analysis/retrace_guard.py``): a context manager +
  pytest fixture that counts actual XLA backend compiles via
  ``jax.monitoring`` and fails any test whose compiles exceed its
  declared budget — the runtime complement the serve/stream/obs e2e
  tests run under.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from .core import (Finding, SourceFile, apply_baseline, format_finding,
                   iter_python_files, load_baseline, save_baseline)

__all__ = ["Finding", "analyze", "apply_baseline", "baseline_entries",
           "default_baseline_path", "format_finding", "iter_python_files",
           "load_baseline", "save_baseline"]

# Env override so tests and tooling can point at a scratch baseline.
_BASELINE_ENV = "RAFTSTEREO_ANALYSIS_BASELINE"


def default_baseline_path() -> str:
    """``analysis_baseline.txt`` at the repo root (next to the package),
    overridable via ``RAFTSTEREO_ANALYSIS_BASELINE``."""
    env = os.environ.get(_BASELINE_ENV)
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "analysis_baseline.txt")


def baseline_entries(path: Optional[str] = None):
    """The baseline multiset (empty Counter when the file is absent) —
    bench.py's smoke modes refuse to run when this is non-empty."""
    return load_baseline(path or default_baseline_path())


def _ast_checkers():
    from . import cache_keys, donation, jit_hygiene, locks
    return (jit_hygiene.check, donation.check, locks.check,
            cache_keys.check)


def analyze(paths: Sequence[str], repo_root: Optional[str] = None,
            metrics: bool = False) -> List[Finding]:
    """Run every checker over ``paths``; returns noqa-filtered findings
    (baseline application is the caller's job — see ``__main__``).

    ``metrics=True`` appends the runtime metric-lint pass (RSA5xx),
    which imports the package under analysis; leave it off for fixture
    runs."""
    findings: List[Finding] = []
    checkers = _ast_checkers()
    for abspath, relpath in iter_python_files(paths, repo_root):
        try:
            sf = SourceFile(abspath, relpath)
        except SyntaxError as e:
            # A finding, not a crash (flake8's E999 convention): one
            # broken scratch file must not take down the whole gate
            # with a traceback.
            findings.append(Finding(
                "RSA001", relpath, e.lineno or 1,
                f"file does not parse: {e.msg}", "<module>"))
            continue
        seen = set()
        for checker in checkers:
            for f in checker(sf):
                dedupe = (f.code, f.line, f.message)
                if dedupe in seen or sf.suppressed(f.code, f.line):
                    continue
                seen.add(dedupe)
                findings.append(f)
    if metrics:
        from .metrics_lint import run_metrics_lint
        findings.extend(run_metrics_lint())
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
