"""RSA4xx — executable-cache keys must cover every key-relevant input.

The serving engine promises "one compile per (bucket, iters, mode)"
(serve/engine.py): each executable-cache entry is keyed by everything
that changes the compiled program.  A key that *omits* one of those
inputs is the worst kind of bug — the cache HIT serves an executable
compiled for different parameters and silently returns wrong numerics
(e.g. an ``iters=32`` request answered by the ``iters=8`` program).

The checker cross-checks key construction against method signatures: in
every ``infer_*`` / ``warmup_*`` method, it finds the cache-key
expressions — the first argument of ``*dispatch*`` calls, operands of
``... in self._compiled``-style membership tests, and arguments of
``.add(...)`` on ``*compiled*``/``*cache*`` attributes — then computes
which names flow into them (transitively through simple assignments and
``for`` targets) and demands that every *key-relevant parameter* of the
method reaches the key:

* key-relevant = the parameter name contains ``iters``, ``mode``,
  ``precision``, ``dtype``, ``backend``, ``accuracy``, ``tier``,
  ``quant``, ``shards``, ``cascade`` or ``schedule`` — the inputs that
  select a distinct executable (shape inputs are carried by the bucket,
  which every key already starts from; ``backend`` covers
  kernel-backend selectors like the fused-GRU ``gru_backend``,
  ``accuracy``/``tier``/``quant`` the per-request accuracy tiers whose
  precision mode joins every serving key, serve/engine.py +
  ops/quant.py, ``shards`` the spatial mesh width — a 2-shard and a
  4-shard program at the same bucket are different executables,
  parallel/spatial.py — and ``cascade``/``schedule`` the tier-cascade
  selectors, serve/cascade/: a cascade executable is keyed by BOTH its
  precision modes, and a resolver keyed by the canonical schedule
  string must carry it).

Note the dual-mode cascade shape (serve/engine.py ``infer_cascade_*``):
``cheap_mode`` and ``cert_mode`` are two *independent* key-relevant
parameters — a key carrying only one of them hits the wrong
(cheap, certified) pair's handoff program, which silently casts into
the wrong dtype tree.  The token match is per-parameter, so both are
demanded individually; no cascade-specific logic is needed.

Codes:

* RSA401 — a key-relevant parameter does not flow into the cache key.
* RSA402 — a cache key with no data flow from any name at all (a
  constant key: every call shares one executable slot).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set

from .core import Finding, SourceFile, qualname_of

__all__ = ["check"]

_METHOD_RE = re.compile(r"^(infer|warmup)_")
_KEY_TOKENS = ("iters", "mode", "precision", "dtype", "backend",
               "accuracy", "tier", "quant", "input_mode", "shards",
               "cascade", "schedule")
_CACHE_ATTR_RE = re.compile(r"compiled|cache", re.IGNORECASE)
_DISPATCH_RE = re.compile(r"dispatch", re.IGNORECASE)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _key_exprs(fn: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and _DISPATCH_RE.search(func.attr) and node.args):
                out.append(node.args[0])
            elif (isinstance(func, ast.Attribute) and func.attr == "add"
                  and isinstance(func.value, ast.Attribute)
                  and _CACHE_ATTR_RE.search(func.value.attr)
                  and node.args):
                out.append(node.args[0])
        elif isinstance(node, ast.Compare):
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and isinstance(node.comparators[0], ast.Attribute)
                    and _CACHE_ATTR_RE.search(node.comparators[0].attr)):
                out.append(node.left)
    return out


def _flow_closure(fn: ast.AST, seeds: Set[str]) -> Set[str]:
    """Names reachable backwards from ``seeds`` through assignments,
    tuple unpacking and ``for`` targets within ``fn`` (fixpoint)."""
    pairs: List[tuple] = []  # (target names, source names) per assignment
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            tgts: Set[str] = set()
            for t in node.targets:
                tgts |= _names_in(t)
            pairs.append((tgts, _names_in(node.value)))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                pairs.append((_names_in(node.target),
                              _names_in(node.value)))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            pairs.append((_names_in(node.target), _names_in(node.iter)))
        elif isinstance(node, ast.NamedExpr):
            pairs.append((_names_in(node.target),
                          _names_in(node.value)))
    closure = set(seeds)
    changed = True
    while changed:
        changed = False
        for tgts, srcs in pairs:
            if tgts & closure and not srcs <= closure:
                closure |= srcs
                changed = True
    return closure


def check(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _METHOD_RE.match(node.name):
            continue
        key_exprs = _key_exprs(node)
        if not key_exprs:
            continue
        params = [a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)
                  if a.arg not in ("self", "cls")]
        relevant = [p for p in params
                    if any(tok in p.lower() for tok in _KEY_TOKENS)]
        qual = qualname_of(node)
        reported: Set[str] = set()
        for expr in key_exprs:
            seeds = _names_in(expr)
            if not seeds:
                yield Finding(
                    "RSA402", sf.path, expr.lineno,
                    f"`{node.name}` uses a constant executable-cache "
                    "key: every call shares one cache slot regardless "
                    "of its inputs", qual)
                continue
            closure = _flow_closure(node, seeds)
            for p in relevant:
                if p in closure or p in reported:
                    continue
                reported.add(p)
                yield Finding(
                    "RSA401", sf.path, expr.lineno,
                    f"executable-cache key in `{node.name}` does not "
                    f"include key-relevant parameter `{p}`: a cache hit "
                    "would serve an executable compiled for a different "
                    f"{p}", qual)
