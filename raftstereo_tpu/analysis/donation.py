"""RSA2xx — donation safety: use-after-donate and bad donate indices.

``donate_argnums`` hands the argument's buffers to XLA for reuse; the
Python reference still exists but the array is *deleted* — touching it
afterwards raises ``RuntimeError: Array has been deleted`` (and on this
container's broken persistent-cache path it SIGSEGVs, see CHANGES.md
PR 2).  Donation bugs only trip at runtime on the donated call's SECOND
use, so they routinely survive unit tests; this checker catches them at
lint time:

* RSA201 — a variable passed at a donated position is read again later
  in the same function without being reassigned first.
* RSA202 — ``donate_argnums`` names a position the wrapped function does
  not have (when the callee is resolvable in the same module).

Analysis is linear-flow within one function body (statement line order,
reassignment clears the taint) — the same approximation every
use-after-move lint makes.  Reads *before* a donation inside a loop body
that re-executes are not modeled (documented in docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from .core import (Finding, SourceFile, dotted_name, literal_argnums,
                   module_functions, qualname_of)

__all__ = ["check"]

_JIT_NAMES = ("jax.jit", "jit", "jax.pmap", "pmap")


def _is_jit(sf: SourceFile, func: ast.AST) -> bool:
    dn = dotted_name(func)
    if dn is None:
        return False
    resolved = sf.resolve(dn)
    return any(resolved == n or resolved.endswith("." + n)
               for n in _JIT_NAMES)


def _donate_positions(call: ast.Call) -> Optional[List[int]]:
    return literal_argnums(call, "donate_argnums")


def _param_count(fn: ast.AST) -> int:
    a = fn.args
    return len(a.posonlyargs) + len(a.args)


def _function_bodies(tree: ast.AST) -> List[ast.AST]:
    """Module root + every def, for per-scope linear analysis."""
    out: List[ast.AST] = [tree]
    out.extend(n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    return out


def _local_walk(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk a scope without descending into nested defs/lambdas (those
    are separate scopes with their own bindings)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _enclosing_scope(node: ast.AST, tree: ast.AST) -> ast.AST:
    cur = getattr(node, "rsa_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "rsa_parent", None)
    return tree


def check(sf: SourceFile) -> Iterator[Finding]:
    defs = module_functions(sf.tree)

    # Donating callables per declaring scope (name -> positions); a
    # nested function resolves through its lexical scope chain, and two
    # functions' same-named locals never collide.
    by_scope: Dict[int, Dict[str, List[int]]] = {}
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and _is_jit(sf, node.func)):
            continue
        pos = _donate_positions(node)
        if pos is None:
            continue
        # RSA202: positions beyond the wrapped function's signature
        # (a *args callee accepts any index — skip it).
        callee = node.args[0] if node.args else None
        if isinstance(callee, ast.Name) and callee.id in defs \
                and defs[callee.id].args.vararg is None:
            n_params = _param_count(defs[callee.id])
            for p in pos:
                if p >= n_params:
                    yield Finding(
                        "RSA202", sf.path, node.lineno,
                        f"donate_argnums position {p} is out of range: "
                        f"`{callee.id}` takes {n_params} positional "
                        "argument(s)",
                        qualname_of(node))
        parent = getattr(node, "rsa_parent", None)
        if isinstance(parent, ast.Assign):
            scope = _enclosing_scope(node, sf.tree)
            for tgt in parent.targets:
                if isinstance(tgt, ast.Name):
                    by_scope.setdefault(id(scope), {})[tgt.id] = pos

    if not by_scope:
        return

    for scope in _function_bodies(sf.tree):
        # Lexical resolution: outer scopes first, the scope's own
        # bindings win.
        chain = [scope]
        cur = scope
        while cur is not sf.tree:
            cur = _enclosing_scope(cur, sf.tree)
            chain.append(cur)
        donating: Dict[str, List[int]] = {}
        for s in reversed(chain):
            donating.update(by_scope.get(id(s), {}))
        if not donating:
            continue
        # Linear event lists: (line, name) for donations / stores / loads.
        donations: List[Tuple[int, str]] = []
        stores: List[Tuple[int, str]] = []
        loads: List[Tuple[int, str, ast.AST]] = []
        for node in _local_walk(scope):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Name):
                pos = donating.get(node.func.id)
                if pos:
                    for p in pos:
                        if p < len(node.args) and isinstance(node.args[p],
                                                             ast.Name):
                            donations.append((node.lineno,
                                              node.args[p].id))
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    stores.append((node.lineno, node.id))
                elif isinstance(node.ctx, ast.Load):
                    loads.append((node.lineno, node.id, node))
        if not donations:
            continue
        flagged = set()
        for lline, name, node in loads:
            for dline, dname in donations:
                if dname != name or lline <= dline:
                    continue
                # A reassignment at or after the donating call (and
                # before the read) clears the taint.
                if any(sname == name and dline <= sline < lline
                       for sline, sname in stores):
                    continue
                if (name, lline) in flagged:
                    continue
                flagged.add((name, lline))
                yield Finding(
                    "RSA201", sf.path, lline,
                    f"`{name}` read after being donated (line {dline}): "
                    "donated buffers are deleted by XLA — rebind the "
                    "result or drop the donation",
                    qualname_of(node))
