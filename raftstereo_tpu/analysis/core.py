"""Shared infrastructure for the RSA static-analysis checkers.

Everything here is stdlib-only and import-side-effect free: the checkers
parse source with ``ast``/``tokenize`` and never import the code under
analysis, so ``python -m raftstereo_tpu.analysis`` is safe to run in any
environment (CI, a TPU pod, a laptop without jax configured).

Building blocks:

* :class:`Finding` — one diagnostic, with a stable ``RSA###`` code, a
  repo-relative ``path:line`` anchor and a *context* (the enclosing
  ``Class.method`` qualname) that keys the baseline, so baselined findings
  survive unrelated line drift.
* :class:`SourceFile` — parsed module + its comment-derived side tables:
  per-line ``# noqa: RSA###`` suppressions and ``# guarded_by: <lock>``
  annotations (locks.py), extracted with ``tokenize`` so strings that
  merely *contain* those markers don't count.
* baseline load/save/apply — a checked-in multiset of known findings
  (``code path context``, one line per occurrence) that lets the runner
  gate on NEW findings only.  The shipped baseline is empty and the tier-1
  suite keeps it that way (tests/test_analysis.py).
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "SourceFile", "attach_parents", "qualname_of",
           "iter_python_files", "load_baseline", "save_baseline",
           "apply_baseline", "format_finding", "dotted_name",
           "resolve_root", "module_functions", "literal_argnums"]

_NOQA_RE = re.compile(r"#\s*noqa\s*:\s*(RSA\d{3}(?:\s*,\s*RSA\d{3})*)",
                      re.IGNORECASE)
_GUARDED_RE = re.compile(r"#\s*guarded_by\s*:\s*([A-Za-z_][A-Za-z0-9_]*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: ``code`` is the stable RSA### id, ``path``/``line``
    the anchor, ``context`` the enclosing qualname used as the baseline
    key (lines drift; qualnames rarely do)."""

    code: str
    path: str
    line: int
    message: str
    context: str = "<module>"

    @property
    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.code, self.path, self.context)


def format_finding(f: Finding) -> str:
    return f"{f.path}:{f.line}: {f.code} [{f.context}] {f.message}"


def attach_parents(tree: ast.AST) -> None:
    """Give every node a ``.rsa_parent`` pointer (the checkers walk
    ancestry for ``with`` containment and qualnames)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.rsa_parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "rsa_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "rsa_parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda (not
    counting ``node`` itself)."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


def qualname_of(node: ast.AST) -> str:
    """``Class.method`` style context for a node (baseline key)."""
    parts: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(anc.name)
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        parts.insert(0, node.name)
    return ".".join(reversed(parts)) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class SourceFile:
    """One parsed module plus its comment side tables."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath
        with open(abspath, "r", encoding="utf-8") as fh:
            self.source = fh.read()
        self.tree = ast.parse(self.source, filename=relpath)
        attach_parents(self.tree)
        # line -> set of suppressed codes; line -> guarded_by lock name.
        self.noqa: Dict[int, Set[str]] = {}
        self.guarded_by: Dict[int, str] = {}
        self._scan_comments()
        # Import alias table: local name -> canonical module path
        # ("np" -> "numpy", "jnp" -> "jax.numpy", "pl" -> ...pallas).
        self.import_aliases: Dict[str, str] = {}
        self._scan_imports()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _NOQA_RE.search(tok.string)
                if m:
                    codes = {c.strip().upper()
                             for c in m.group(1).split(",")}
                    self.noqa.setdefault(tok.start[0], set()).update(codes)
                g = _GUARDED_RE.search(tok.string)
                if g:
                    self.guarded_by[tok.start[0]] = g.group(1)
        except tokenize.TokenError:  # pragma: no cover - defensive
            pass

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = \
                        alias.name if alias.asname else \
                        alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.import_aliases[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    def resolve(self, dotted: str) -> str:
        """Canonicalize the leading segment of ``a.b.c`` through the
        import table (``np.random.rand`` -> ``numpy.random.rand``)."""
        head, _, rest = dotted.partition(".")
        head = self.import_aliases.get(head, head)
        return f"{head}.{rest}" if rest else head

    def suppressed(self, code: str, line: int) -> bool:
        return code in self.noqa.get(line, set())


def resolve_root(sf: SourceFile, call_func: ast.AST) -> Optional[str]:
    """Canonical dotted name of a call target, or None."""
    name = dotted_name(call_func)
    return sf.resolve(name) if name else None


def module_functions(tree: ast.AST) -> Dict[str, ast.AST]:
    """name -> FunctionDef for every def in the file (scope-flattened
    approximation; good enough to resolve ``jax.jit(fn)`` references)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def literal_argnums(call: ast.Call, keyword: str) -> Optional[List[int]]:
    """The literal int positions of ``keyword=`` (e.g. ``static_argnums``
    / ``donate_argnums``) on a call, or None when absent or not
    statically known."""
    for kw in call.keywords:
        if kw.arg != keyword:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out: List[int] = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                out.append(e.value)
            return out
        return None
    return None


# -------------------------------------------------------------- file walking

def iter_python_files(paths: Sequence[str],
                      repo_root: Optional[str] = None) -> List[Tuple[str,
                                                                     str]]:
    """(abspath, relpath) for every .py under ``paths`` (files or dirs),
    sorted, skipping __pycache__.  ``relpath`` is relative to
    ``repo_root`` (default: cwd) — the stable identity in findings and
    the baseline."""
    root = os.path.abspath(repo_root or os.getcwd())
    out = []
    for p in paths:
        ap = os.path.abspath(p)
        if not os.path.exists(ap):
            # Loud, not an empty result: a typo'd path in a CI hook must
            # not report the tree green with zero files analyzed.
            raise FileNotFoundError(f"analysis target does not exist: {p}")
        if os.path.isfile(ap):
            out.append(ap)
        else:
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out.extend(os.path.join(dirpath, f) for f in filenames
                           if f.endswith(".py"))
    uniq = sorted(set(out))
    return [(ap, os.path.relpath(ap, root).replace(os.sep, "/"))
            for ap in uniq]


# ----------------------------------------------------------------- baseline

def load_baseline(path: str) -> "collections.Counter[Tuple[str, str, str]]":
    """Baseline multiset from a ``code path context`` per-line file.
    Missing file = empty baseline."""
    counter: collections.Counter = collections.Counter()
    if not os.path.exists(path):
        return counter
    with open(path, "r", encoding="utf-8") as fh:
        for n, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 3 or not re.match(r"^RSA\d{3}$", parts[0]):
                raise ValueError(
                    f"{path}:{n}: malformed baseline entry {line!r} "
                    "(expected 'RSA### path context')")
            counter[(parts[0], parts[1], parts[2])] += 1
    return counter


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    lines = sorted(" ".join(f.baseline_key) for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            "# RSA static-analysis baseline (docs/static_analysis.md).\n"
            "# One 'RSA### path context' line per known finding; empty\n"
            "# means the tree is clean.  Regenerate with:\n"
            "#   python -m raftstereo_tpu.analysis --update-baseline\n")
        for line in lines:
            fh.write(line + "\n")


def apply_baseline(findings: Sequence[Finding],
                   baseline: "collections.Counter[Tuple[str, str, str]]",
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Split findings against the baseline multiset.

    Returns ``(new_findings, stale_entries)``: findings not covered by
    the baseline, and baseline entries no finding matched (fixed code
    whose baseline line should be deleted).
    """
    remaining = collections.Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        if remaining.get(f.baseline_key, 0) > 0:
            remaining[f.baseline_key] -= 1
        else:
            new.append(f)
    stale = sorted(remaining.elements())
    return new, stale
