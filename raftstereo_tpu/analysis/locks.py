"""RSA3xx — lock discipline over ``# guarded_by:`` annotations.

The serve/stream/obs threads (HTTP handlers, the batcher worker, stream
sessions, metric scrapes) share mutable state with ad-hoc locking; this
checker makes the locking contract explicit and mechanical:

* Annotate the attribute where it is initialized::

      self._depth = 0  # guarded_by: _cv

  Every later ``<base>._depth`` read or write — any base expression, so
  ``self._depth`` in the owning class and ``srv.stream_inflight`` in an
  HTTP handler are both covered — must then sit lexically inside
  ``with <base>._cv:`` in the SAME function body.
* Annotate a method on its ``def`` line when its CALLERS hold the lock
  (the "caller must hold" contract)::

      def _oldest_key(self):  # guarded_by: _cv

Codes:

* RSA301 — guarded attribute accessed outside its lock.
* RSA302 — annotation names a lock attribute the class never assigns.
* RSA303 — ``guarded_by`` comment on a line that declares nothing.

Scope and limits (docs/static_analysis.md): the ``with`` containment is
lexical per function — a nested ``def`` does not inherit the enclosing
``with`` (it may run later, unlocked), which is the conservative
direction; lambdas ARE transparent (they evaluate inline — ``key=``
functions, dispatch thunks).  ``__init__``/``__post_init__`` of the declaring
class are exempt (construction happens-before publication).  Accesses
from other modules are out of scope; annotate at the owning class and
keep cross-module callers on properties/methods.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, SourceFile, qualname_of

__all__ = ["check"]

_CTOR_NAMES = ("__init__", "__post_init__", "__new__")


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - defensive
        return "<expr>"


def _self_attr_target(stmt: ast.AST) -> Optional[Tuple[str, str]]:
    """(base, attr) for ``<base>.attr = ...`` / ``attr: T = ...``
    declarations (class-level AnnAssign covers dataclass fields)."""
    if isinstance(stmt, ast.Assign) and stmt.targets:
        tgt = stmt.targets[0]
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        tgt = stmt.target
    else:
        return None
    if isinstance(tgt, ast.Attribute):
        return _unparse(tgt.value), tgt.attr
    if isinstance(tgt, ast.Name):  # class-body field declaration
        return "", tgt.id
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guarded: Dict[str, str] = {}        # attr -> lock attr
        self.assigned_attrs: Set[str] = set()    # every self.X = ...
        self.held_methods: Dict[int, Set[str]] = {}  # id(def) -> locks
        self.decl_lines: Set[int] = set()        # annotated declarations


def _def_header_lines(fn: ast.AST) -> range:
    """Lines a def-line annotation may sit on: the signature lines."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    return range(fn.lineno, end)


def _collect(sf: SourceFile) -> Tuple[List[_ClassInfo], List[Finding]]:
    infos: List[_ClassInfo] = []
    findings: List[Finding] = []
    claimed_lines: Set[int] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = _ClassInfo(node)
        # Declarations live where the attribute is INITIALIZED: the class
        # body or a constructor.  An annotated assignment anywhere else
        # declares nothing (RSA303) and its access is still checked —
        # otherwise a guarded_by comment on a mutation site would exempt
        # exactly the access it mislabels.
        decl_stmts = list(node.body)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                locks = {sf.guarded_by[ln]
                         for ln in _def_header_lines(sub)
                         if ln in sf.guarded_by}
                if locks:
                    info.held_methods[id(sub)] = locks
                    claimed_lines.update(
                        ln for ln in _def_header_lines(sub)
                        if ln in sf.guarded_by)
                if sub.name in _CTOR_NAMES:
                    decl_stmts.extend(ast.walk(sub))
            target = _self_attr_target(sub)
            if target is not None:
                info.assigned_attrs.add(target[1])
        for sub in decl_stmts:
            target = _self_attr_target(sub)
            if target is None:
                continue
            _base, attr = target
            # The annotation sits on the assignment line, or on a
            # standalone comment directly above it (long lines).
            for ln in (sub.lineno, sub.lineno - 1):
                lock = sf.guarded_by.get(ln)
                if lock is not None and ln not in claimed_lines:
                    info.guarded[attr] = lock
                    claimed_lines.add(ln)
                    info.decl_lines.add(sub.lineno)
                    break
        for attr, lock in sorted(info.guarded.items()):
            if lock not in info.assigned_attrs:
                line = next((ln for ln, lk in sorted(sf.guarded_by.items())
                             if lk == lock), node.lineno)
                findings.append(Finding(
                    "RSA302", sf.path, line,
                    f"guarded_by names `{lock}`, but class "
                    f"`{node.name}` never assigns `self.{lock}`",
                    node.name))
        if info.guarded:
            infos.append(info)
    # RSA303: guarded_by comments that attached to nothing.
    for line, lock in sorted(sf.guarded_by.items()):
        if line not in claimed_lines:
            findings.append(Finding(
                "RSA303", sf.path, line,
                f"`# guarded_by: {lock}` is not on an attribute "
                "assignment or a def line — the annotation guards "
                "nothing", "<module>"))
    return infos, findings


def _enclosing_def(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing def.  Lambdas are transparent: they evaluate
    inline (``min(..., key=lambda ...)``, dispatch thunks) so they
    inherit the surrounding lock scope; a nested ``def`` is deferred
    work and does NOT."""
    cur = getattr(node, "rsa_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "rsa_parent", None)
    return None


def _locks_held(node: ast.AST, base: str,
                held_methods: Dict[int, Set[str]]) -> Set[str]:
    """Lock attr names held at ``node`` for accesses on ``base``:
    ``with <base>.<lock>:`` blocks in the same function body (lambdas
    transparent), plus the function's own def-line contract (self-based
    only)."""
    held: Set[str] = set()
    fn = _enclosing_def(node)
    cur = getattr(node, "rsa_parent", None)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                expr = item.context_expr
                if (isinstance(expr, ast.Attribute)
                        and _unparse(expr.value) == base):
                    held.add(expr.attr)
        cur = getattr(cur, "rsa_parent", None)
    if fn is not None and base == "self":
        held |= held_methods.get(id(fn), set())
    return held


def _inside_ctor_of(node: ast.AST, cls: ast.ClassDef) -> bool:
    cur = getattr(node, "rsa_parent", None)
    fn = None
    while cur is not None:
        if fn is None and isinstance(cur, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
            fn = cur
        if isinstance(cur, ast.ClassDef):
            return (cur is cls and fn is not None
                    and fn.name in _CTOR_NAMES)
        cur = getattr(cur, "rsa_parent", None)
    return False


def check(sf: SourceFile) -> Iterator[Finding]:
    infos, findings = _collect(sf)
    yield from findings
    if not infos:
        return
    # Module-wide guard map: attr -> every (lock, declaring class).
    # Several classes may declare the same attr (Counter._value and
    # Gauge._value): an access is fine if it satisfies ANY declaration —
    # its own class's constructor, a declaration line, or holding one of
    # the declared locks.
    guard: Dict[str, List[Tuple[str, _ClassInfo]]] = {}
    held_methods: Dict[int, Set[str]] = {}
    for info in infos:
        held_methods.update(info.held_methods)
        for attr, lock in info.guarded.items():
            guard.setdefault(attr, []).append((lock, info))
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Attribute):
            continue
        entries = guard.get(node.attr)
        if entries is None:
            continue
        base = _unparse(node.value)
        held = _locks_held(node, base, held_methods)
        if any(
            (base == "self" and _inside_ctor_of(node, info.node))
            # The declaring assignment itself (claimed in _collect) —
            # NOT any line that merely carries a guarded_by comment.
            or node.lineno in info.decl_lines
            or lock in held
                for lock, info in entries):
            continue
        lock, info = entries[0]
        kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                else "read")
        yield Finding(
            "RSA301", sf.path, node.lineno,
            f"{kind} of `{base}.{node.attr}` outside `with "
            f"{base}.{lock}:` (declared guarded_by {lock} on class "
            f"`{info.node.name}`)",
            qualname_of(node))
