"""RSA5xx — the metric-name/exposition lint, behind the analysis runner.

This is the runtime half of the suite (imports the metrics bundles, so
it needs the package importable — unlike the AST checkers): it
instantiates ``ServeMetrics`` + ``TrainMetrics`` on ONE registry (a name
collision between the bundles fails here instead of when both are
mounted on one process), runs the naming lint, populates one child per
labeled family and validates the full Prometheus 0.0.4 render.

Formerly ``scripts/check_metrics.py`` (PR 5); that script is now a thin
shim over this module so tier-1 has a single lint entry point
(``python -m raftstereo_tpu.analysis``).

Codes:

* RSA501 — metric-name lint violation (obs/prom.py ``lint_registry``).
* RSA502 — rendered exposition fails the format validator.
* RSA503 — serve/train bundles collide on one registry.
"""

from __future__ import annotations

from typing import List

from .core import Finding

__all__ = ["run_metrics_lint"]

# Findings anchor at the bundle definitions — the registry names are
# declared there, so that is where a violation is fixed.
_SERVE_PATH = "raftstereo_tpu/serve/metrics.py"
_TRAIN_PATH = "raftstereo_tpu/train/telemetry.py"
_LOADGEN_PATH = "raftstereo_tpu/loadgen/metrics.py"
_TIER_PATH = "raftstereo_tpu/stream/tier.py"
_OBS_PATH = "raftstereo_tpu/obs/fleet.py"


def run_metrics_lint() -> List[Finding]:
    """Instantiate + lint + render-validate the repo's metric bundles."""
    from ..loadgen.metrics import LoadgenMetrics
    from ..obs import (BurnRateAlerts, FleetFederator, lint_registry,
                       validate_prometheus)
    from ..serve.metrics import (ClusterMetrics, MetricsRegistry,
                                 ServeMetrics)
    from ..stream.tier import TierMetrics
    from ..train.telemetry import TrainMetrics

    findings: List[Finding] = []
    registry = MetricsRegistry()
    try:
        serve = ServeMetrics(registry)
        # The cluster dispatcher mounts its families on the SAME
        # registry as the serve bundle (server /metrics is one render),
        # so collisions between the two must fail here.
        cluster = ClusterMetrics(registry)
        TrainMetrics(registry)
        # Harness-side families (loadgen_*/slo_*): a soak rig may mount
        # them next to a scrape of any other bundle.
        loadgen = LoadgenMetrics(registry)
        # The durable session tier's families (tier_*): its own process
        # normally, but they must stay collision-free with the rest.
        tier = TierMetrics(registry)
        # The fleet observability plane (fleet_*): the router mounts
        # the federator's scrape counters and the burn-rate alert
        # gauges next to the cluster bundle — one registry, one render.
        federator = FleetFederator(registry)
        alerts = BurnRateAlerts(registry)
    except ValueError as e:  # duplicate registration across bundles
        return [Finding("RSA503", _TRAIN_PATH, 1,
                        f"bundle collision: {e}", "metrics")]
    for msg in lint_registry(registry.entries()):
        name = msg.split(":")[0]
        path = _TRAIN_PATH if name.startswith("train") \
            else _LOADGEN_PATH \
            if name.startswith(("loadgen", "slo", "chaos")) \
            else _TIER_PATH if name.startswith("tier") \
            else _OBS_PATH if name.startswith("fleet") \
            else _SERVE_PATH
        findings.append(Finding("RSA501", path, 1, msg, "metrics"))

    # Populate one child per labeled family (families render no samples
    # until first use) and validate the full exposition.
    serve.requests.labels(endpoint="predict", outcome="ok").inc()
    serve.tier_requests.labels(tier="default").inc()
    serve.compile_misses.labels(bucket="64x96", iters="8",
                                mode="batch", tier="fp32").inc()
    serve.compile_hits.labels(bucket="64x96", iters="8",
                              mode="stream", tier="bf16").inc()
    serve.stream_cold_frames.labels(reason="new").inc()
    serve.stream_tier_pushes.labels(outcome="ok").inc()
    serve.wire_bytes.labels(direction="in", format="binary").inc(1024)
    serve.wire_negotiations.labels(request="binary",
                                   response="json").inc()
    serve.cascade_schedules.labels(schedule="int8:24+fp32:8").inc()
    serve.cascade_promotions.labels(kind="scheduled").inc()
    serve.cascade_iterations.labels(phase="certified").inc(8)
    serve.latency.observe(0.01)
    cluster.set_states({"ready": 1})
    cluster.queue_depth.labels(replica="r0").set(0)
    cluster.dispatch.labels(replica="r0", outcome="ok").inc()
    cluster.session_repins.labels(reason="draining").inc()
    cluster.session_handoffs.labels(outcome="warm").inc()
    cluster.autoscale_recommendation.set(0)
    cluster.probe_failures.labels(replica="r0").inc()
    cluster.router_latency.observe(0.001)
    cluster.capacity_headroom.set(0.5)
    cluster.wire_stream_bytes.labels(direction="in").inc(65536)
    cluster.wire_stream_peak_chunk.set(65536)
    cluster.breaker_state.labels(backend="b0").set(0)
    cluster.breaker_transitions.labels(backend="b0", to="open").inc()
    cluster.hedges.labels(outcome="won").inc()
    loadgen.requests.labels(outcome="ok", tier="default").inc()
    loadgen.chaos_actions.labels(kind="slow_replica",
                                 outcome="armed").inc()
    loadgen.send_lag.observe(0.001)
    loadgen.latency.observe(0.01)
    loadgen.slo_checks.labels(status="pass").inc()
    loadgen.slo_pass.set(1)
    tier.requests.labels(op="put", outcome="ok").inc()
    federator.scrapes.labels(backend="b0").inc()
    federator.scrape_failures.labels(backend="b0").inc()
    alerts.alert_state.labels(**{"class": "tier=*,priority=*"}).set(0)
    alerts.alert_burn.labels(**{"class": "tier=*,priority=*"}).set(0.0)
    for msg in validate_prometheus(registry.render()):
        findings.append(Finding("RSA502", _SERVE_PATH, 1, msg, "metrics"))
    return findings
