"""Runtime retrace guard: fail tests whose XLA compile count exceeds a
declared budget.

The static checkers (RSA1xx) catch retrace hazards they can see in the
AST; this is the runtime backstop that catches the rest: a context
manager (and pytest fixture, tests/conftest.py) that counts **actual
XLA backend compiles** through ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event stream and raises
:class:`RetraceBudgetExceeded` when a guarded block compiles more than
its budget.

Two knobs::

    with retrace_guard(budget=2, what="2 buckets compile once"):
        ...                      # every compile counts

    with retrace_guard(0, min_duration_s=0.5, what="warm traffic"):
        ...                      # only model-scale compiles count

``min_duration_s`` exists because *any* first-seen host-side jnp op
(a new pad/concat shape) is a real-but-tiny XLA compile (milliseconds);
a model retrace is seconds.  E2e tests guard warm traffic with a 0.5 s
floor — far above op compiles, far below the tiny test models'
2-4 s compiles — so their budgets measure exactly the "zero compiles
beyond warmup" invariants (serve PR 1, stream PR 3, obs PR 5).  The
seeded-hazard unit tests use the default floor of 0 and count
everything.

The guard counts process-wide (any thread): e2e budgets deliberately
include compiles triggered on the batcher/stream worker threads.  It
REFUSES to run when a persistent JAX compilation cache is configured —
deserialized executables skip the backend-compile event, so the count
would be meaningless (and that cache is known-broken on this container:
CHANGES.md PR 2).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator, List, Optional

__all__ = ["RetraceBudgetExceeded", "retrace_guard", "compile_events"]

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_durations: List[float] = []  # every backend compile since install


class RetraceBudgetExceeded(AssertionError):
    """A guarded block compiled more XLA executables than its budget."""


def _listener(event: str, duration: float, **kwargs) -> None:
    if event == _BACKEND_COMPILE_EVENT:
        with _lock:
            _durations.append(duration)


def _ensure_installed() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        # Flag flips only AFTER successful registration: a failure here
        # must stay loud on the next guard use, never leave the guard
        # silently counting zero compiles (registration itself only
        # appends to a listener list — it fires no events, so holding
        # the lock across it cannot deadlock with _listener).
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def _persistent_cache_dir() -> Optional[str]:
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return os.environ["JAX_COMPILATION_CACHE_DIR"]
    try:
        import jax

        return jax.config.jax_compilation_cache_dir
    except Exception:  # config flag not present on this jax
        return None


def compile_events() -> int:
    """Backend compiles observed since the guard was first installed."""
    _ensure_installed()
    with _lock:
        return len(_durations)


class GuardReport:
    """Filled in when the guarded block exits."""

    def __init__(self, budget: int, min_duration_s: float, what: str):
        self.budget = budget
        self.min_duration_s = min_duration_s
        self.what = what
        self.compiles = 0        # compiles >= min_duration_s
        self.all_compiles = 0    # every backend compile in the window
        self.durations: List[float] = []


@contextlib.contextmanager
def retrace_guard(budget: int, what: str = "",
                  min_duration_s: float = 0.0) -> Iterator[GuardReport]:
    """Fail with :class:`RetraceBudgetExceeded` when the block compiles
    more than ``budget`` XLA executables (of at least
    ``min_duration_s`` each).  Yields a :class:`GuardReport` whose
    counts are valid after the block exits."""
    assert budget >= 0, budget
    cache_dir = _persistent_cache_dir()
    if cache_dir:
        raise RuntimeError(
            f"retrace_guard requires no persistent JAX compile cache "
            f"(JAX_COMPILATION_CACHE_DIR={cache_dir!r}): deserialized "
            "executables skip the backend-compile event, so budgets "
            "would not measure compiles — and that cache is "
            "known-broken on this container (CHANGES.md PR 2)")
    _ensure_installed()
    with _lock:
        start = len(_durations)
    report = GuardReport(budget, min_duration_s, what)
    yield report
    with _lock:
        window = _durations[start:]
    report.durations = window
    report.all_compiles = len(window)
    relevant = [d for d in window if d >= min_duration_s]
    report.compiles = len(relevant)
    if report.compiles > budget:
        label = f" [{what}]" if what else ""
        raise RetraceBudgetExceeded(
            f"retrace budget exceeded{label}: {report.compiles} XLA "
            f"compile(s) >= {min_duration_s:g}s against a budget of "
            f"{budget} ({report.all_compiles} total in the window; "
            f"durations "
            f"{[round(d, 3) for d in sorted(window, reverse=True)[:8]]})"
            " — a shape/closure/executable-cache key is retracing; see "
            "docs/static_analysis.md")
