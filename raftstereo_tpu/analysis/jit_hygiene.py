"""RSA1xx — jit/Pallas hygiene: impurity, host syncs and retrace hazards.

The serving stack's latency guarantees assume "one compile per bucket"
(serve/engine.py) and that traced code is pure.  These checkers catch the
ways that goes wrong at lint time:

* RSA101 — impure call inside a traced function (``time.*``,
  ``np.random.*``, ``random.*``, ``print`` ...): executes once at trace
  time, silently freezes into the executable, and diverges from eager.
* RSA102 — host sync on a traced value (``float()``/``int()``/``bool()``,
  ``np.asarray``/``np.array``, ``.item()``/``.tolist()``): forces a
  device->host transfer mid-program, or fails outright under jit.
* RSA103 — ``global``/``nonlocal`` mutation inside a traced function:
  runs at trace time only, so the mutation happens once per *compile*,
  not once per call.
* RSA104 — unhashable literal (list/dict/set) passed in a
  ``static_argnums`` position: raises at runtime on every call.
* RSA105 — ``jax.jit(...)(...)`` built and invoked in one expression:
  the wrapper (and its dispatch cache) is discarded per call, so every
  call re-traces.
* RSA106 — ``jax.jit`` created inside a ``for``/``while`` body: a fresh
  wrapper per iteration re-traces per iteration (the classic
  Python-scalar-closure silent retrace).

Traced functions are discovered structurally: ``@jax.jit``-style
decorators (including ``partial(jax.jit, ...)``), lambdas or same-module
function names passed to ``jax.jit`` / ``jax.pmap`` / ``jax.vmap`` /
``jax.grad`` / ``jax.checkpoint`` / ``pl.pallas_call`` /
``shard_map``, and body/cond callables of ``lax.scan`` / ``while_loop``
/ ``fori_loop`` / ``cond``.  Calls *out* of a traced function into other
module code are not followed (documented limit — docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import (Finding, SourceFile, dotted_name, enclosing_function,
                   literal_argnums, module_functions, qualname_of)

__all__ = ["check"]

# Canonical call roots that are jit-like wrappers (their first positional
# argument is traced).  Key: resolved dotted suffix.
_TRACING_WRAPPERS = ("jax.jit", "jit", "jax.pmap", "pmap", "jax.vmap",
                     "vmap", "jax.grad", "grad", "jax.value_and_grad",
                     "value_and_grad", "jax.checkpoint", "checkpoint",
                     "jax.remat", "remat", "pallas_call", "shard_map")
# (canonical-suffix, positional indices of traced callables)
_BODY_TAKERS = (("lax.scan", (0,)), ("lax.while_loop", (0, 1)),
                ("lax.fori_loop", (2,)), ("lax.cond", (1, 2)),
                ("lax.switch", ()),)

_JIT_NAMES = ("jax.jit", "jit")

# RSA101: canonical dotted prefixes that are impure at trace time.
_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.", "os.urandom",
                    "uuid.uuid", "datetime.datetime.now",
                    "datetime.datetime.utcnow", "secrets.")
_IMPURE_BUILTINS = ("print", "input", "open")

# RSA102: host-sync calls.
_SYNC_BUILTINS = ("float", "int", "bool", "complex")
_SYNC_NUMPY = ("numpy.asarray", "numpy.array", "numpy.copy",
               "numpy.float32", "numpy.float64", "numpy.int32",
               "numpy.int64")
_SYNC_METHODS = ("item", "tolist", "__array__")


def _is_wrapper(sf: SourceFile, func: ast.AST,
                names: Tuple[str, ...] = _TRACING_WRAPPERS) -> bool:
    dn = dotted_name(func)
    if dn is None:
        return False
    resolved = sf.resolve(dn)
    return any(resolved == n or resolved.endswith("." + n) for n in names)


def _partial_of_wrapper(sf: SourceFile, node: ast.AST) -> bool:
    """``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    return (isinstance(node, ast.Call)
            and _is_wrapper(sf, node.func, ("partial", "functools.partial"))
            and node.args
            and _is_wrapper(sf, node.args[0]))


def _traced_roots(sf: SourceFile) -> List[ast.AST]:
    """Every function/lambda node whose body executes under a trace."""
    defs = module_functions(sf.tree)
    roots: List[ast.AST] = []
    seen: Set[int] = set()

    def add(node: Optional[ast.AST]) -> None:
        if node is None:
            return
        if isinstance(node, ast.Name):
            node = defs.get(node.id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and id(node) not in seen:
            seen.add(id(node))
            roots.append(node)

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_wrapper(sf, dec) or _partial_of_wrapper(sf, dec):
                    add(node)
        elif isinstance(node, ast.Call):
            if _is_wrapper(sf, node.func) and node.args:
                add(node.args[0])
            for suffix, idxs in _BODY_TAKERS:
                dn = dotted_name(node.func)
                if dn is None:
                    continue
                resolved = sf.resolve(dn)
                if resolved == suffix or resolved.endswith("." + suffix):
                    for i in idxs:
                        if i < len(node.args):
                            add(node.args[i])
    return roots


def _walk_within(root: ast.AST) -> Iterator[ast.AST]:
    """Walk a traced function's body, including nested lambdas/defs (they
    trace too when called)."""
    yield from ast.walk(root)


def _check_traced_body(sf: SourceFile, root: ast.AST) -> Iterator[Finding]:
    ctx = qualname_of(root if not isinstance(root, ast.Lambda)
                      else (enclosing_function(root) or root))
    if isinstance(root, ast.Lambda) and ctx == "<module>":
        ctx = "<lambda>"
    for node in _walk_within(root):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            yield Finding(
                "RSA103", sf.path, node.lineno,
                f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" {', '.join(node.names)}` inside a traced function: the "
                "mutation runs at trace time (once per compile), not per "
                "call", ctx)
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        resolved = sf.resolve(dn) if dn else None
        if resolved is not None:
            if (any(resolved.startswith(p) for p in _IMPURE_PREFIXES)
                    or resolved in _IMPURE_BUILTINS):
                yield Finding(
                    "RSA101", sf.path, node.lineno,
                    f"impure call `{dn}(...)` inside a traced function: "
                    "executes at trace time and freezes into the "
                    "executable", ctx)
                continue
            if resolved in _SYNC_NUMPY:
                yield Finding(
                    "RSA102", sf.path, node.lineno,
                    f"`{dn}(...)` inside a traced function forces a "
                    "host sync (or fails on a tracer); use jnp instead",
                    ctx)
                continue
            if (resolved in _SYNC_BUILTINS and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                yield Finding(
                    "RSA102", sf.path, node.lineno,
                    f"`{dn}(...)` on a traced value is a host sync "
                    "(ConcretizationError under jit); keep it as an "
                    "array or hoist it out of the traced function", ctx)
                continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS):
            yield Finding(
                "RSA102", sf.path, node.lineno,
                f"`.{node.func.attr}()` inside a traced function is a "
                "host sync; return the array instead", ctx)


def _static_positions(call: ast.Call) -> Optional[List[int]]:
    """Literal static_argnums of a jax.jit call, if statically known."""
    return literal_argnums(call, "static_argnums")


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _check_call_sites(sf: SourceFile) -> Iterator[Finding]:
    # name -> static positions, for `f = jax.jit(g, static_argnums=...)`.
    static_of: Dict[str, List[int]] = {}
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                and _is_wrapper(sf, node.value.func, _JIT_NAMES)):
            pos = _static_positions(node.value)
            if pos:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        static_of[tgt.id] = pos

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        ctx = qualname_of(enclosing_function(node) or node)
        # RSA105: jax.jit(...)(...) in one expression.
        if (isinstance(node.func, ast.Call)
                and _is_wrapper(sf, node.func.func, _JIT_NAMES)):
            yield Finding(
                "RSA105", sf.path, node.lineno,
                "jax.jit(...) built and called in one expression: the "
                "wrapper is discarded after the call, so every call "
                "re-traces — cache the jitted callable", ctx)
        # RSA106: jax.jit created inside a loop body.
        if _is_wrapper(sf, node.func, _JIT_NAMES):
            fn = enclosing_function(node)
            anc: Optional[ast.AST] = node
            while anc is not None and anc is not fn:
                anc = getattr(anc, "rsa_parent", None)
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                    yield Finding(
                        "RSA106", sf.path, node.lineno,
                        "jax.jit(...) inside a loop body creates a fresh "
                        "wrapper (and a fresh trace) per iteration — "
                        "hoist and cache it; closures over loop "
                        "variables silently retrace", ctx)
                    break
        # RSA104: unhashable literal in a static position.
        positions: Optional[List[int]] = None
        if isinstance(node.func, ast.Name):
            positions = static_of.get(node.func.id)
        elif (isinstance(node.func, ast.Call)
              and _is_wrapper(sf, node.func.func, _JIT_NAMES)):
            positions = _static_positions(node.func)
        if positions:
            for i in positions:
                if i < len(node.args) and isinstance(node.args[i],
                                                     _UNHASHABLE):
                    yield Finding(
                        "RSA104", sf.path, node.args[i].lineno,
                        f"unhashable literal passed at static_argnums "
                        f"position {i}: jit static args must be "
                        "hashable (use a tuple)", ctx)


def check(sf: SourceFile) -> Iterator[Finding]:
    for root in _traced_roots(sf):
        yield from _check_traced_body(sf, root)
    yield from _check_call_sites(sf)
