"""Runner: ``python -m raftstereo_tpu.analysis [paths...]``.

Exit 0 when every finding is suppressed (``# noqa: RSA###``) or
baselined; exit 1 on any NEW finding.  The default target is the
``raftstereo_tpu`` package and the default baseline is
``analysis_baseline.txt`` at the repo root (empty on the shipped tree).

Tier-1 runs this via tests/test_analysis.py; ``bench.py`` smoke modes
refuse to start while the baseline is dirty (known hazards must be fixed
before perf rounds land on top of them).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import (analyze, apply_baseline, default_baseline_path,
               format_finding, load_baseline, save_baseline)

_CODE_TABLE = """\
RSA001 file does not parse (syntax error)
RSA101 impure call inside a traced (jit/Pallas) function
RSA102 host sync on a traced value (float()/np.asarray/.item())
RSA103 global/nonlocal mutation inside a traced function
RSA104 unhashable literal in a jit static_argnums position
RSA105 jax.jit(...)(...) built and invoked per call (silent retrace)
RSA106 jax.jit created inside a loop body (retrace per iteration)
RSA201 variable read after being passed at a donated position
RSA202 donate_argnums position out of the callee's signature
RSA301 guarded attribute accessed outside `with <base>.<lock>:`
RSA302 guarded_by names a lock the class never assigns
RSA303 guarded_by comment attached to nothing
RSA401 executable-cache key omits a key-relevant parameter
RSA402 constant executable-cache key
RSA501 metric-name lint violation (obs/prom.py)
RSA502 metrics render fails the Prometheus format validator
RSA503 serve/train metric bundles collide on one registry
"""


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m raftstereo_tpu.analysis",
        description="RSA static-analysis suite (docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "raftstereo_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: analysis_baseline.txt "
                        "at the repo root, or $RAFTSTEREO_ANALYSIS_"
                        "BASELINE)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip the runtime metric-lint pass (RSA5xx) — "
                        "for fixture/adhoc runs that don't import the "
                        "package")
    p.add_argument("--list-codes", action="store_true",
                   help="print the RSA code table and exit")
    args = p.parse_args(argv)
    if args.list_codes:
        print(_CODE_TABLE, end="")
        return 0

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(os.path.dirname(pkg_dir))
    paths = args.paths or [os.path.dirname(pkg_dir)]
    try:
        findings = analyze(paths, repo_root=repo_root,
                           metrics=not args.no_metrics)
    except FileNotFoundError as e:
        print(f"analysis: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    if args.update_baseline:
        save_baseline(baseline_path, findings)
        print(f"analysis: baseline updated ({len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'}) -> "
              f"{baseline_path}")
        return 0
    try:
        baseline = load_baseline(baseline_path)
    except ValueError as e:
        print(f"analysis: {e}", file=sys.stderr)
        return 2
    new, stale = apply_baseline(findings, baseline)
    for f in new:
        print(format_finding(f))
    for key in stale:
        print(f"analysis: stale baseline entry {' '.join(key)} — the "
              "finding is gone; remove the line (or --update-baseline)",
              file=sys.stderr)
    n_base = len(findings) - len(new)
    print(f"analysis: {'FAIL' if new else 'OK'} ({len(new)} new finding"
          f"{'' if len(new) == 1 else 's'}, {n_base} baselined, "
          f"{len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'})")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
