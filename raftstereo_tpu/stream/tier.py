"""Durable session tier: a shared external store for warm-start state
(docs/streaming.md "Durable sessions").

The PR 13 snapshot seam made session state portable — any backend can
export/import a session over ``/debug/sessions``.  This module makes it
DURABLE: a model-free, stdlib-HTTP service
(``python -m raftstereo_tpu.cli.sessiontier``) that holds each
session's latest snapshot, so any replica resumes any stream WARM even
after its home backend is gone, without the router pinning state to a
process lifetime.  Three parts:

* ``SessionTier``  — the service.  Stores each session's snapshot as
                     the VERBATIM wire JSON the backends already
                     exchange (never decodes the arrays — it is
                     model-free, starts in milliseconds like the
                     router) behind a byte-accounted LRU with a byte
                     budget; refuses stale writes by sequence number.
* ``TierClient``   — bounded-timeout stdlib HTTP client for both the
                     backends' write path and the router's resume path.
* ``TierPublisher``— write-behind durability on the backend side: after
                     each completed frame the StreamRunner enqueues the
                     session id (never the snapshot — the worker exports
                     the FRESHEST state at send time, which coalesces a
                     burst of frames into one push), and a single worker
                     thread pushes outside the request path with bounded
                     retry/backoff.  A tier outage degrades cleanly to
                     the PR 13 local-pin behaviour: pushes are counted
                     ``stream_tier_degraded_total`` and suppressed,
                     never surfaced as request errors, and the publisher
                     re-probes every ``tier_reprobe_s`` and re-attaches
                     (re-enqueuing every live session so the tier
                     catches back up).

Chaos hooks (utils/faults.py, armable over ``POST /debug/faults`` on
the tier): ``tier_outage@t_ms=OFF:SECS`` holds every reply for the
window (connections accepted, nothing answered — clients time out
against their own budgets), ``tier_slow@request=N:SECS`` delays the
next N replies by SECS each.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..config import TierConfig
from ..obs import Tracer, build_info, trace_response
from ..serve.httpbase import JsonRequestHandler
from ..serve.metrics import MetricsRegistry
from ..utils.backoff import backoff_delay
from ..utils.faults import FaultPlan

__all__ = ["SessionTier", "TierClient", "TierMetrics", "TierPublisher",
           "build_session_tier"]

logger = logging.getLogger(__name__)


class TierMetrics:
    """The session tier's own instrument bundle (the tier process has no
    serve bundle — it is model-free, like the router)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.sessions_active = r.gauge(
            "tier_sessions_active",
            "sessions currently stored in the durable session tier")
        self.session_bytes = r.gauge(
            "tier_session_bytes",
            "byte-accurate total of stored snapshot bodies (the value "
            "the budget_mb byte-budget eviction bounds)")
        self.requests = r.counter(
            "tier_requests_total",
            "tier requests by op (get/put/healthz/faults) and outcome "
            "(ok/miss/stale/bad_request)",
            labels=("op", "outcome"))
        self.evictions = r.counter(
            "tier_evictions_total",
            "stored sessions LRU-evicted because the tier hit "
            "session_limit or its byte budget — the evicted session's "
            "next resume falls back cold, never an error")

    def render(self) -> str:
        return self.registry.render()


class _TierStore:
    """Byte-accounted LRU map of ``session_id -> latest wire body``.

    Bodies are the verbatim serialized JSON the backends POST — the
    tier never decodes the arrays inside, so accounting is exact:
    ``len(body)``.  Stale writes (a snapshot whose ``next_seq`` is not
    newer than what is stored) are refused with outcome ``"stale"`` —
    the same monotonic guard SessionStore.import_state applies, moved
    to the shared tier so two replicas racing pushes for one session
    can never rewind its durable state.
    """

    def __init__(self, limit: int, budget_mb: float,
                 metrics: Optional[TierMetrics] = None):
        assert limit >= 1, limit
        self.limit = limit
        self.budget_bytes = int(budget_mb * 2 ** 20)
        self.metrics = metrics
        self._lock = threading.Lock()
        # sid -> (wire body bytes, next_seq)  # guarded_by: _lock
        self._sessions: "collections.OrderedDict[str, Tuple[bytes, int]]" \
            = collections.OrderedDict()
        self._total_bytes = 0  # guarded_by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def total_bytes(self) -> int:
        with self._lock:
            return self._total_bytes

    def get(self, sid: str) -> Optional[bytes]:
        """Latest stored body for ``sid`` (touches LRU order), or None."""
        with self._lock:
            entry = self._sessions.get(sid)
            if entry is None:
                return None
            self._sessions.move_to_end(sid)
            return entry[0]

    def put(self, sid: str, body: bytes, next_seq: int) -> str:
        """Store ``body`` as the session's latest snapshot; returns
        ``"stored"``, or ``"stale"`` when the stored snapshot is already
        at least as fresh (nothing is overwritten — a stale push is
        harmless, never an error)."""
        with self._lock:
            entry = self._sessions.get(sid)
            if entry is not None and entry[1] >= next_seq:
                self._sessions.move_to_end(sid)
                return "stale"
            if entry is not None:
                self._total_bytes -= len(entry[0])
            self._sessions[sid] = (body, next_seq)
            self._sessions.move_to_end(sid)
            self._total_bytes += len(body)
            self._evict_over_limits()
            self._refresh_gauges()
            return "stored"

    def _evict_over_limits(self) -> None:  # guarded_by: _lock
        """LRU-evict while over the count cap OR the byte budget; the
        byte bound never evicts the last stored session (mirrors
        SessionStore)."""
        while (len(self._sessions) > self.limit
               or (self.budget_bytes > 0
                   and self._total_bytes > self.budget_bytes
                   and len(self._sessions) > 1)):
            _, (body, _) = self._sessions.popitem(last=False)
            self._total_bytes -= len(body)
            if self.metrics is not None:
                self.metrics.evictions.inc()

    def _refresh_gauges(self) -> None:  # guarded_by: _lock
        if self.metrics is not None:
            self.metrics.sessions_active.set(float(len(self._sessions)))
            self.metrics.session_bytes.set(float(self._total_bytes))


class _TierHandler(JsonRequestHandler):
    """The tier's HTTP dialect — the server side of the PR 13 snapshot
    seam (``GET/POST /debug/sessions``), plus /healthz, /metrics and
    the chaos arming endpoint."""

    server_version = "raftstereo-sessiontier/1"
    _log = logger

    def _chaos_gate(self) -> None:
        """tier_outage / tier_slow chaos seams: hold this reply for an
        active outage window, then apply any armed per-request delay."""
        srv: "SessionTier" = self.server
        srv.fault_plan.tier_outage_hold()
        delay = srv.fault_plan.tier_slow_delay()
        if delay:
            time.sleep(delay)

    def do_GET(self):
        srv: "SessionTier" = self.server
        self._chaos_gate()
        # Observability parity with the router/backends (PR 20): every
        # reply carries X-Request-Id, tier ops continue the caller's
        # X-Trace-Context, and /debug/trace + /debug/vars exist so the
        # tier is a first-class stitch/federation target.
        rid = self.request_id()
        hdrs = {"X-Request-Id": rid}
        t0 = time.perf_counter()
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            store = srv.store
            self._json(200, {
                "status": "ok",
                "live": True,
                "ready": True,
                "sessions": len(store),
                "session_bytes": store.total_bytes(),
                "session_limit": store.limit,
                "budget_mb": srv.config.budget_mb,
            }, hdrs)
        elif path == "/metrics":
            self._send(200, srv.metrics.render().encode(),
                       "text/plain; version=0.0.4", hdrs)
        elif path == "/debug/trace":
            try:
                body, extra = trace_response(srv.tracer, query)
            except ValueError as e:
                self._json(400, {"error": f"bad query: {e}"}, hdrs)
                return
            extra = dict(extra, **hdrs)
            self._send(200, body, "application/json", extra)
        elif path == "/debug/vars":
            store = srv.store
            self._json(200, {
                "sessions": len(store),
                "session_bytes": store.total_bytes(),
                "session_limit": store.limit,
                "budget_mb": srv.config.budget_mb,
                "build": build_info(),
            }, hdrs)
        elif path.startswith("/debug/sessions/"):
            from urllib.parse import unquote

            sid = unquote(path[len("/debug/sessions/"):])
            body = srv.store.get(sid)
            tid, parent = self.trace_of(rid)
            if body is None:
                srv.metrics.requests.labels(op="get", outcome="miss").inc()
                srv.tracer.record("tier_get", t0, time.perf_counter(),
                                  tid, parent_id=parent,
                                  attrs={"outcome": "miss"})
                self._json(404, {"error": f"no snapshot for session "
                                          f"{sid!r}"}, hdrs)
            else:
                srv.metrics.requests.labels(op="get", outcome="ok").inc()
                srv.tracer.record("tier_get", t0, time.perf_counter(),
                                  tid, parent_id=parent,
                                  attrs={"outcome": "ok",
                                         "bytes": len(body)})
                self._send(200, body, "application/json", hdrs)
        else:
            self._json(404, {"error": f"unknown path {path!r}"}, hdrs)

    def do_POST(self):
        srv: "SessionTier" = self.server
        self._chaos_gate()
        rid = self.request_id()
        hdrs = {"X-Request-Id": rid}
        t0 = time.perf_counter()
        path = self.path.split("?", 1)[0]
        if path == "/debug/sessions":
            raw = self._read_body(srv.config.max_body_mb)
            if raw is None:
                return
            tid, parent = self.trace_of(rid)
            try:
                obj = json.loads(raw)
                sid = str(obj["session_id"])
                next_seq = int(obj["next_seq"])
            except Exception:
                srv.metrics.requests.labels(
                    op="put", outcome="bad_request").inc()
                srv.tracer.record("tier_put", t0, time.perf_counter(),
                                  tid, parent_id=parent,
                                  attrs={"outcome": "bad_request"})
                self._json(400, {"error": "bad snapshot: session_id and "
                                          "next_seq required"}, hdrs)
                return
            outcome = srv.store.put(sid, raw, next_seq)
            srv.metrics.requests.labels(
                op="put",
                outcome="ok" if outcome == "stored" else outcome).inc()
            srv.tracer.record("tier_put", t0, time.perf_counter(),
                              tid, parent_id=parent,
                              attrs={"outcome": outcome,
                                     "bytes": len(raw)})
            self._json(200, {"session_id": sid, "outcome": outcome}, hdrs)
        elif path == "/debug/faults":
            raw = self._read_body(srv.config.max_body_mb)
            if raw is None:
                return
            try:
                spec = json.loads(raw or b"{}").get("faults", "")
                armed = srv.fault_plan.extend(str(spec or ""))
            except ValueError as e:
                self._json(400, {"error": f"bad fault spec: {e}"}, hdrs)
                return
            self._json(200, {"armed": [f.spec() for f in armed]}, hdrs)
        else:
            self._json(404, {"error": f"unknown path {path!r}"}, hdrs)


class SessionTier(ThreadingHTTPServer):
    """The durable session tier service (one per fleet, like the
    router).  ``build_session_tier`` assembles it; the caller drives
    ``serve_forever()`` and ``close()``."""

    daemon_threads = True

    def __init__(self, config: TierConfig,
                 metrics: Optional[TierMetrics] = None,
                 fault_plan: Optional[FaultPlan] = None):
        self.config = config
        self.metrics = metrics or TierMetrics()
        self.fault_plan = (fault_plan if fault_plan is not None
                           else FaultPlan.from_env()).arm()
        self.store = _TierStore(config.session_limit, config.budget_mb,
                                self.metrics)
        # Small ring: tier ops are tiny spans, and the tier is one
        # stitch source among many (GET /debug/trace serves it).
        self.tracer = Tracer(capacity=512)
        super().__init__((config.host, config.port), _TierHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]

    def close(self) -> None:
        self.shutdown()
        self.server_close()


def build_session_tier(config: TierConfig,
                       metrics: Optional[TierMetrics] = None
                       ) -> SessionTier:
    tier = SessionTier(config, metrics=metrics)
    logger.info("session tier on %s:%d (limit=%d, budget=%.1f MiB)",
                config.host, tier.port, config.session_limit,
                config.budget_mb)
    return tier


class TierClient:
    """Bounded-timeout stdlib HTTP client for the tier's dialect.

    One fresh connection per call (no pooling): callers are the
    write-behind publisher (one worker, low rate) and the router's
    lost-home resume path (rare) — correctness under tier restarts
    beats connection reuse here.  Every method raises ``OSError``-family
    exceptions on failure; the CALLER owns degradation policy."""

    def __init__(self, host: str, port: int, timeout_s: float = 2.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None) -> Tuple[int, bytes]:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def healthz(self) -> bool:
        """True when the tier answers /healthz ok within the timeout."""
        try:
            status, _ = self._request("GET", "/healthz")
            return status == 200
        except OSError:
            return False

    def get_session(self, sid: str) -> Optional[Dict]:
        """Latest stored snapshot wire dict for ``sid``, or None when
        the tier has nothing (404).  Raises on transport failure."""
        from urllib.parse import quote

        status, body = self._request(
            "GET", f"/debug/sessions/{quote(sid, safe='')}")
        if status == 404:
            return None
        if status != 200:
            raise OSError(f"tier GET {sid!r} -> {status}")
        return json.loads(body)

    def put_wire(self, wire_obj: Dict) -> Dict:
        """POST one snapshot wire dict; returns the tier's reply
        (``{"session_id", "outcome": "stored"|"stale"}``).  Raises on
        transport failure or a non-200."""
        status, body = self._request("POST", "/debug/sessions",
                                     json.dumps(wire_obj).encode())
        if status != 200:
            raise OSError(f"tier PUT -> {status}")
        return json.loads(body)


class TierPublisher:
    """Write-behind snapshot publisher: backend-side durability without
    ever touching the frame request path.

    ``StreamRunner.step`` calls ``enqueue(sid)`` after each completed
    frame; a single worker thread drains the queue, exporting the
    FRESHEST snapshot at send time (so N queued frames of one session
    collapse into one push — natural coalescing) and POSTing it to the
    tier with bounded retry/backoff (utils/backoff.py).  Failure
    degrades to local-pin behaviour: the publisher detaches, counts
    ``stream_tier_degraded_total``, suppresses pushes, and re-probes
    the tier every ``reprobe_s`` — on re-attach it re-enqueues every
    live session (``resync_fn``) so the tier catches back up.  Nothing
    here ever raises at a frame.

    ``export_fn``/``to_wire`` are injected callables (the server wires
    ``StereoServer.export_session`` and ``snapshot_to_wire``) so this
    module never imports the engine stack and stays model-free
    importable — the tier service itself lives in the same file.
    ``clock``/``sleep`` are injectable so retry/reprobe tests never
    sleep for real.
    """

    def __init__(self, client: TierClient,
                 export_fn: Callable[[str], Optional[Dict]],
                 to_wire: Callable[[Dict], Dict],
                 metrics=None, *,
                 queue_limit: int = 1024,
                 retries: int = 2,
                 backoff_ms: float = 50.0,
                 reprobe_s: float = 1.0,
                 resync_fn: Optional[Callable[[], List[str]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        assert queue_limit >= 1, queue_limit
        self.client = client
        self._export = export_fn
        self._to_wire = to_wire
        self.metrics = metrics
        self.queue_limit = queue_limit
        self.retries = retries
        self.backoff_ms = backoff_ms
        self.reprobe_s = reprobe_s
        self._resync = resync_fn
        self._clock = clock
        self._sleep = sleep
        self._cv = threading.Condition()
        # Pending session ids, oldest first; values unused (OrderedDict
        # as an ordered set, so re-enqueueing a queued sid coalesces by
        # moving it to the back).  # guarded_by: _cv
        self._pending: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self._closed = False     # guarded_by: _cv
        self._inflight = False   # worker mid-push  # guarded_by: _cv
        self._attached = True    # guarded_by: _cv
        self._next_probe = 0.0   # guarded_by: _cv
        self._thread: Optional[threading.Thread] = None
        self._set_attached_gauge(True)

    # ------------------------------------------------------------- public

    def start(self) -> "TierPublisher":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tier-publisher")
        self._thread.start()
        return self

    def enqueue(self, sid: str) -> None:
        """Queue one session for a write-behind push (coalescing:
        re-enqueueing a queued sid just refreshes its position).  Over
        ``queue_limit`` the OLDEST pending sid is dropped and counted
        — its state is not lost, only its push is deferred to its next
        completed frame.  Never blocks beyond the lock."""
        with self._cv:
            if self._closed:
                return
            self._pending[sid] = None
            self._pending.move_to_end(sid)
            if len(self._pending) > self.queue_limit:
                self._pending.popitem(last=False)
                self._count_push("dropped")
            self._cv.notify()

    def attached(self) -> bool:
        with self._cv:
            return self._attached

    def pending(self) -> int:
        with self._cv:
            return len(self._pending)

    def state(self) -> Dict:
        """One-line publisher state for /healthz's stream block."""
        with self._cv:
            return {
                "host": self.client.host,
                "port": self.client.port,
                "attached": self._attached,
                "pending": len(self._pending),
            }

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue is drained and no push is in flight
        (tests and drain paths); False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._pending or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                sid, _ = self._pending.popitem(last=False)
                self._inflight = True
                attached = self._attached
                probe_due = (not attached
                             and self._clock() >= self._next_probe)
            try:
                if not attached and not probe_due:
                    # Degraded (tier unreachable, re-probe not due):
                    # suppress the push — local-pin behaviour, the
                    # session stays perfectly servable on this backend.
                    self._count_push("degraded")
                    self._count_degraded()
                    continue
                if not attached:
                    if not self._probe():
                        self._count_push("degraded")
                        self._count_degraded()
                        continue
                self._push(sid)
            except Exception:
                # The worker must survive anything (an export racing a
                # drop, a codec surprise) — durability is best-effort,
                # frames never depend on it.
                logger.exception("tier push failed unexpectedly (sid=%s)",
                                 sid)
                self._count_push("error")
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    def _probe(self) -> bool:
        """Re-probe a detached tier; on success re-attach and re-enqueue
        every live session so the tier catches up on what it missed."""
        if not self.client.healthz():
            with self._cv:
                self._next_probe = self._clock() + self.reprobe_s
            return False
        with self._cv:
            self._attached = True
        self._set_attached_gauge(True)
        logger.info("session tier reattached (%s:%d)",
                    self.client.host, self.client.port)
        if self._resync is not None:
            for sid in self._resync():
                self.enqueue(sid)
        return True

    def _detach(self) -> None:
        with self._cv:
            self._attached = False
            self._next_probe = self._clock() + self.reprobe_s
        self._set_attached_gauge(False)
        self._count_degraded()
        logger.warning("session tier unreachable; degrading to "
                       "local-pin sessions (re-probe in %.1fs)",
                       self.reprobe_s)

    def _push(self, sid: str) -> None:
        snapshot = self._export(sid)
        if snapshot is None:
            # Session dropped/expired between frame and push, or no
            # completed frame yet — nothing durable to write.
            self._count_push("skipped")
            return
        wire_obj = self._to_wire(snapshot)
        for attempt in range(self.retries + 1):
            try:
                reply = self.client.put_wire(wire_obj)
                outcome = str(reply.get("outcome", "stored"))
                self._count_push("stale" if outcome == "stale" else "ok")
                return
            except (OSError, ValueError):
                if attempt < self.retries:
                    self._sleep(backoff_delay(self.backoff_ms, attempt))
        self._count_push("error")
        self._detach()
        # The missed push is re-covered by the next completed frame's
        # enqueue or the re-attach resync — no local retry queue to
        # grow unboundedly during an outage.

    # ------------------------------------------------------------ metrics

    def _count_push(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.stream_tier_pushes.labels(outcome=outcome).inc()

    def _count_degraded(self) -> None:
        if self.metrics is not None:
            self.metrics.stream_tier_degraded.inc()

    def _set_attached_gauge(self, attached: bool) -> None:
        if self.metrics is not None:
            self.metrics.stream_tier_attached.set(1.0 if attached else 0.0)
