"""Temporal warm-start sequence inference over the serving engine.

One :class:`StreamRunner` turns the serve layer's stateless per-request
engine into stateful video inference: each frame of a session is
initialized from the previous frame's disparity, forward-warped on the host
by ``ops/image.forward_interpolate`` (the RAFT warm-start policy — Teed &
Deng, ECCV 2020; see PAPERS.md) and fed through the model's ``flow_init``
hook at an adaptively reduced iteration count (controller.py).  All device
work goes through ``BatchEngine.infer_stream_batch``, so streams share the
serve layer's per-(bucket, iters) compile cache and shape policy — the HTTP
session path (serve/server.py) and the offline ``cli/stream.py`` runner
produce bitwise-identical disparities on the same frames (tested).

``run_sequence`` / ``compare_warm_cold`` are the offline evaluation
harness shared by ``cli/stream.py``, ``bench.py --stream`` and the tier-1
acceptance tests: warm-start streaming vs a cold-start full-iteration
baseline on the same frames, reporting EPE, temporal-consistency EPE, and
the iterations/latency saved.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import StreamConfig
from ..ops.image import forward_interpolate
from .controller import AdaptiveIterController
from .session import SessionStore

__all__ = ["StreamResult", "StreamRunner", "build_stream_engine",
           "run_sequence", "compare_warm_cold"]


@dataclasses.dataclass
class StreamResult:
    """One answered frame: the disparity plus how it was computed."""

    disparity: np.ndarray  # (H, W) float32, dataset sign convention
    iters: int
    warm: bool
    frame_idx: int
    seq_no: int
    session_id: str
    update_ema: float
    latency_s: float
    included_compile: bool
    # Which cluster replica answered (serve/cluster/dispatcher.py);
    # None on the single-engine path.
    replica: Optional[str] = None


class StreamRunner:
    """Session-aware frame stepper over a ``BatchEngine``.

    The engine contract is ``bucket_of``, ``low_hw`` and
    ``infer_stream_batch`` (serve/engine.py).  Frames of one session
    serialize on the session lock; different sessions contend only on the
    engine's dispatch lock.
    """

    def __init__(self, engine, cfg: StreamConfig, metrics=None,
                 store: Optional[SessionStore] = None, tracer=None,
                 scheduler=None, publisher=None):
        self.engine = engine
        self.cfg = cfg
        self.metrics = metrics
        self.tracer = tracer  # obs.Tracer or None (tracing is optional)
        # Iteration-level scheduler (serve/sched/): when set, frames are
        # submitted as HIGH-priority short jobs through the shared
        # scheduler instead of dispatching batch-size-1 on the engine —
        # so a long plain request never head-of-line blocks a stream.
        self.scheduler = scheduler
        # Write-behind publisher to the durable session tier
        # (stream/tier.TierPublisher): completed frames enqueue their
        # session id, never block on the tier (docs/streaming.md
        # "Durable sessions").
        self.publisher = publisher
        self.controller = AdaptiveIterController(cfg)
        self.store = store or SessionStore(
            cfg.session_limit, cfg.session_ttl_s, metrics,
            budget_mb=cfg.session_budget_mb)

    # ---------------------------------------------- migration (PR 13)
    #
    # The replica-to-replica handoff seam: the cluster dispatcher and the
    # /debug/sessions HTTP endpoints move warm-start state between
    # StreamRunners through these two calls.  Pure host-side numpy plus
    # engine metadata — no device dispatch, no compiles, so migration is
    # invisible to the retrace guard.

    def export_session(self, session_id: str) -> Optional[Dict]:
        """Versioned snapshot of one session's warm-start state stamped
        with this engine's schema fingerprint, or None when there is
        nothing warm to move."""
        return self.store.export_state(
            session_id, schema=self.engine.session_schema())

    def import_session(self, snapshot: Dict) -> str:
        """Install a snapshot exported elsewhere; returns ``"warm"`` or
        the documented ``"cold_schema"`` fallback (never raises)."""
        return self.store.import_state(
            snapshot, schema=self.engine.session_schema())

    def evict_all(self) -> int:
        """Drop every live session (the ``evict_sessions`` chaos hook:
        session-store pressure as one event).  Returns sessions
        dropped.  Losing state is the store's documented cold fallback
        — each stream's next frame re-anchors cold, nothing errors.  A
        frame racing the sweep either finishes first (its session drops
        a moment later) or re-creates the session cold."""
        dropped = 0
        for sid in self.store.session_ids():
            if self.store.drop(sid):
                dropped += 1
        return dropped

    def step(self, session_id: str, seq_no: Optional[int],
             left: np.ndarray, right: np.ndarray,
             trace_id: Optional[str] = None,
             mode: Optional[str] = None) -> StreamResult:
        """Run one frame of a session; always answers (cold on any session
        miss — new, expired, evicted, out-of-sequence, or resized).
        ``trace_id`` tags the frame's warp/forward spans in the tracer.
        ``mode`` is the frame's resolved precision mode (accuracy tier,
        ops/quant.py): it selects the executable only — session state is
        a plain fp32 disparity field, so frames of one session may move
        between tiers without losing the warm start."""
        sess, _ = self.store.get_or_create(session_id)
        ctl = self.controller
        tracer = self.tracer
        if tracer is not None and trace_id is None:
            trace_id = tracer.new_trace_id()
        with sess.lock:
            t0 = time.perf_counter()
            if seq_no is None:
                seq_no = sess.next_seq  # implicit in-order client
            bucket = self.engine.bucket_of(left.shape)
            warm = (sess.prev_disp_low is not None
                    and not sess.force_cold
                    and seq_no == sess.next_seq
                    and sess.bucket_hw == bucket)
            if warm:
                init = forward_interpolate(sess.prev_disp_low)
                t_warp = time.perf_counter()
                if tracer is not None:
                    tracer.record("warp", t0, t_warp, trace_id,
                                  attrs={"session_id": session_id,
                                         "seq_no": seq_no})
                iters = ctl.warm_iters(sess.level)
                cold_reason = None
            elif sess.prev_disp_low is None:
                # Includes expired/evicted sessions: the store already
                # re-created them, so to this frame they are new.
                init, iters, cold_reason = None, ctl.cold_iters, "new"
            elif sess.force_cold:
                init, iters, cold_reason = None, ctl.cold_iters, "reset"
            elif seq_no != sess.next_seq:
                init, iters, cold_reason = None, ctl.cold_iters, \
                    "out_of_order"
            else:
                init, iters, cold_reason = None, ctl.cold_iters, "resized"
            t_fwd0 = time.perf_counter()
            if self.scheduler is not None:
                # High-priority short job through the shared scheduler:
                # the frame joins the running batch at the next iteration
                # boundary (its join/step/epilogue spans are recorded by
                # the scheduler under this trace id).
                res = self.scheduler.submit(
                    left, right, iters=iters, flow_init=init,
                    priority="high", trace_id=trace_id,
                    mode=mode).result(timeout=600)
                disp, low, compiled = (res.disparity, res.disp_low,
                                       res.included_compile)
                if tracer is not None:
                    tracer.record("forward", t_fwd0, time.perf_counter(),
                                  trace_id,
                                  attrs={"session_id": session_id,
                                         "seq_no": seq_no, "iters": iters,
                                         "warm": warm, "compile": compiled,
                                         "sched": True})
            else:
                disp, low, compiled = self.engine.infer_stream_batch(
                    [(left, right)], iters, [init], mode=mode)[0]
                if tracer is not None:
                    seg = getattr(self.engine, "last_segments", None)
                    fwd_end = (seg["dispatch"][1] if seg
                               else time.perf_counter())
                    tracer.record("forward", t_fwd0, fwd_end, trace_id,
                                  attrs={"session_id": session_id,
                                         "seq_no": seq_no, "iters": iters,
                                         "warm": warm, "compile": compiled})
                    if seg is not None:
                        tracer.record("host_fetch", *seg["host_fetch"],
                                      trace_id)
            if warm:
                delta = float(np.mean(np.abs(low - init)))
                sess.ema = ctl.update_ema(sess.ema, delta)
                sess.level, sess.force_cold = ctl.next_level(sess.level,
                                                             sess.ema)
                sess.warm_frames += 1
            else:
                sess.ema = 0.0
                sess.level = ctl.first_warm_level
                sess.force_cold = False
                sess.cold_frames += 1
            sess.prev_disp_low = low
            sess.bucket_hw = bucket
            sess.next_seq = seq_no + 1
            frame_idx = sess.frame_idx
            sess.frame_idx += 1
            ema = sess.ema
            # Byte-accurate store accounting: the plane just changed
            # (session lock held; the store lock nests strictly inside).
            self.store.account(sess)
            latency = time.perf_counter() - t0
        if self.publisher is not None:
            # Write-behind durability: enqueue the SID only — the
            # publisher's worker exports the freshest snapshot at send
            # time (natural per-session coalescing), so the frame's
            # request path never touches the tier.
            self.publisher.enqueue(session_id)
        if self.metrics is not None:
            if warm:
                self.metrics.stream_warm_frames.inc()
            else:
                self.metrics.stream_cold_frames.labels(
                    reason=cold_reason).inc()
            self.metrics.stream_frame_iters.observe(iters)
            if not compiled:
                self.metrics.stream_frame_latency.observe(latency)
        return StreamResult(
            disparity=disp, iters=iters, warm=warm, frame_idx=frame_idx,
            seq_no=seq_no, session_id=session_id, update_ema=ema,
            latency_s=latency, included_compile=compiled)


def build_stream_engine(model, variables, image_hw: Tuple[int, int],
                        stream_cfg: StreamConfig, *,
                        max_batch_size: int = 1, divis_by: int = 32,
                        bucket_multiple: int = 64, metrics=None):
    """An offline ``BatchEngine`` matching the serving shape policy.

    For bitwise parity with an HTTP server, pass the SAME ``divis_by``,
    ``bucket_multiple`` and ``max_batch_size`` the server runs — XLA only
    guarantees identical numerics for identical program shapes, and the
    engine pads every batch to ``max_batch_size``.
    """
    from ..config import ServeConfig
    from ..serve.engine import BatchEngine

    cfg = ServeConfig(
        port=0, divis_by=divis_by, bucket_multiple=bucket_multiple,
        buckets=(tuple(image_hw),), warmup=False,
        max_batch_size=max_batch_size,
        queue_limit=max(8 * max_batch_size, 16),
        iters=stream_cfg.ladder[0], degraded_iters=stream_cfg.ladder[-1],
        stream=stream_cfg)
    return BatchEngine(model, variables, cfg, metrics)


def _epe(pred: np.ndarray, gt: Optional[np.ndarray]) -> Optional[float]:
    if gt is None:
        return None
    return float(np.mean(np.abs(pred - gt[..., 0])))


def run_sequence(engine, frames: Sequence[Tuple], stream_cfg: StreamConfig,
                 warm: bool = True, session_id: str = "offline",
                 metrics=None, tracer=None) -> Dict:
    """Drive ``frames`` (``(left, right, gt?)`` tuples) through a fresh
    ``StreamRunner`` on ``engine``.

    ``warm=True`` replays them as ONE session (frame 0 cold, the rest
    warm-started); ``warm=False`` is the cold-start baseline — every frame
    in its own session, so each runs at ``ladder[0]`` with a zero init
    through the SAME executable (no extra compiles, directly comparable
    latencies).  Returns per-frame records plus the predictions (kept for
    temporal-consistency metrics and parity tests).
    """
    runner = StreamRunner(engine, stream_cfg, metrics, tracer=tracer)
    records: List[Dict] = []
    preds: List[np.ndarray] = []
    for t, frame in enumerate(frames):
        left, right, gt = (frame + (None,))[:3]
        sid = session_id if warm else f"{session_id}-cold-{t}"
        res = runner.step(sid, t if warm else 0, left, right)
        preds.append(res.disparity)
        records.append({
            "frame": t, "iters": res.iters, "warm": res.warm,
            "latency_ms": round(res.latency_s * 1e3, 3),
            "included_compile": res.included_compile,
            "update_ema": round(res.update_ema, 4),
            "epe": _epe(res.disparity, gt),
        })
    return {"records": records, "preds": preds}


def _tc_epe(preds: Sequence[np.ndarray],
            frames: Sequence[Tuple]) -> Optional[float]:
    """Temporal-consistency EPE: how far the predicted frame-to-frame
    disparity CHANGE strays from the ground-truth change, averaged over
    consecutive pairs — flicker that plain per-frame EPE cannot see."""
    if len(preds) < 2 or len(frames[0]) < 3 or frames[0][2] is None:
        return None
    errs = []
    for t in range(1, len(preds)):
        dp = preds[t] - preds[t - 1]
        dg = frames[t][2][..., 0] - frames[t - 1][2][..., 0]
        errs.append(float(np.mean(np.abs(dp - dg))))
    return float(np.mean(errs))


def _mean_latency(records: Sequence[Dict]) -> Optional[float]:
    """Mean over compile-free frames only (an FPS protocol must not charge
    the model for XLA compiles — same rule as eval/runner.py)."""
    xs = [r["latency_ms"] for r in records if not r["included_compile"]]
    return round(float(np.mean(xs)), 3) if xs else None


def compare_warm_cold(engine, frames: Sequence[Tuple],
                      stream_cfg: StreamConfig, metrics=None,
                      tracer=None) -> Dict:
    """Warm-start streaming vs the cold full-iteration baseline on the same
    frames; the summary is what ``cli/stream.py`` and ``bench.py --stream``
    report and what the acceptance test asserts."""
    # Cold first: it compiles only ladder[0]; the warm pass then adds the
    # warm levels, so each pass's first-frame compile flags are honest.
    cold = run_sequence(engine, frames, stream_cfg, warm=False,
                        session_id="baseline", metrics=metrics,
                        tracer=tracer)
    warm = run_sequence(engine, frames, stream_cfg, warm=True,
                        session_id="stream", metrics=metrics,
                        tracer=tracer)
    wr, cr = warm["records"], cold["records"]
    warm_iters_after_first = [r["iters"] for r in wr[1:]]
    warm_epe = wr[-1]["epe"]
    cold_epe = cr[-1]["epe"]
    summary = {
        "frames": len(frames),
        "ladder": list(stream_cfg.ladder),
        "warm_frames": sum(1 for r in wr if r["warm"]),
        "cold_iters_per_frame": float(stream_cfg.ladder[0]),
        "warm_mean_iters_after_first": (
            round(float(np.mean(warm_iters_after_first)), 3)
            if warm_iters_after_first else None),
        "warm_final_epe": warm_epe,
        "cold_final_epe": cold_epe,
        "final_epe_ratio": (round(warm_epe / cold_epe, 4)
                            if warm_epe is not None and cold_epe else None),
        "warm_tc_epe": _tc_epe(warm["preds"], frames),
        "cold_tc_epe": _tc_epe(cold["preds"], frames),
        "warm_mean_latency_ms": _mean_latency(wr),
        "cold_mean_latency_ms": _mean_latency(cr),
    }
    if warm_iters_after_first:
        summary["iters_saved_frac"] = round(
            1.0 - float(np.mean(warm_iters_after_first))
            / stream_cfg.ladder[0], 4)
    if summary["warm_mean_latency_ms"] and summary["cold_mean_latency_ms"]:
        summary["latency_saved_frac"] = round(
            1.0 - summary["warm_mean_latency_ms"]
            / summary["cold_mean_latency_ms"], 4)
    return {"summary": summary, "warm": wr, "cold": cr,
            "warm_preds": warm["preds"], "cold_preds": cold["preds"]}
