"""Adaptive iteration controller: pick each warm frame's GRU iteration
count from a small fixed ladder of pre-compiled levels.

RAFT-Stereo's refinement makes iteration count a smooth quality/latency
knob (the serving layer already exploits it for load shedding —
serve/batcher.py); for video the right count per frame depends on how much
the scene MOVED.  The observable is the update magnitude: mean
|refined disparity - warm-start init| at 1/factor resolution, i.e. how far
the GRU had to move the forward-warped previous estimate.  An EMA of that
signal steers a ladder index:

* EMA > ``promote_threshold``  -> the warp is lagging the scene, climb to a
  higher iteration level next frame;
* EMA < ``demote_threshold``   -> near-static scene, descend a level;
* EMA > ``cold_reset_threshold`` -> the warm start is not tracking at all
  (scene cut, fast motion, bad warp): the next frame re-runs COLD at
  ``ladder[0]`` with a zero init and the stream re-converges.

Levels are indices into ``StreamConfig.ladder``: index 0 is the cold/full
count, warm frames use indices >= 1 only — the config asserts
``ladder[1] <= ladder[0] / 2``, so every warm frame costs at most half a
cold frame.  Decisions are pure functions of (level, EMA), which is what
makes the HTTP session path and the offline ``cli/stream.py`` runner
bit-reproducible against each other (docs/streaming.md).
"""

from __future__ import annotations

from typing import Tuple

from ..config import StreamConfig

__all__ = ["AdaptiveIterController"]


class AdaptiveIterController:
    """Deterministic ladder walker over ``StreamConfig`` thresholds."""

    def __init__(self, cfg: StreamConfig):
        self.cfg = cfg

    @property
    def cold_iters(self) -> int:
        return self.cfg.ladder[0]

    @property
    def first_warm_level(self) -> int:
        """Ladder index a stream starts warm frames at (after any cold
        frame): the highest warm level, so a fresh stream converges before
        the controller is allowed to demote it."""
        return 1

    def warm_iters(self, level: int) -> int:
        return self.cfg.ladder[level]

    def update_ema(self, ema: float, delta: float) -> float:
        d = self.cfg.ema_decay
        return d * ema + (1.0 - d) * delta

    def next_level(self, level: int, ema: float) -> Tuple[int, bool]:
        """(next warm level, force_cold) after a warm frame at ``level``."""
        cfg = self.cfg
        last = len(cfg.ladder) - 1
        if ema > cfg.cold_reset_threshold:
            return self.first_warm_level, True
        if ema > cfg.promote_threshold:
            return max(1, level - 1), False
        if ema < cfg.demote_threshold:
            return min(last, level + 1), False
        return level, False
