"""Per-stream session state and its bounded store.

A :class:`Session` is everything the warm-start policy carries between the
frames of one video stream: the previous frame's low-resolution disparity
(kept at the PADDED bucket's 1/factor grid, so it is already the shape the
next dispatch's ``flow_init`` needs), the next expected sequence number, the
EMA of the per-frame update magnitude that drives the adaptive iteration
controller, and the controller's current ladder level.

The :class:`SessionStore` is deliberately forgiving: hitting the session
limit evicts the least-recently-used session, and an idle session past its
TTL expires — in both cases the client's next frame simply runs COLD (full
iterations, zero init) and re-establishes state.  Losing a session is a
performance event, never a correctness error, so the store never raises at
a client.  Evictions/expirations/active count are exported through
``ServeMetrics`` (``/metrics``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["Session", "SessionStore"]


@dataclasses.dataclass
class Session:
    """Warm-start state for one stream (mutated under ``lock``)."""

    sid: str
    last_used: float = 0.0
    next_seq: int = 0
    frame_idx: int = 0
    # Previous frame's disparity at the padded bucket's 1/factor grid
    # ((H/f, W/f) float32, dataset sign convention); None until the first
    # frame completes.
    prev_disp_low: Optional[np.ndarray] = None
    bucket_hw: Optional[Tuple[int, int]] = None
    # EMA of mean |refined - warm-start init| (low-res px) and the
    # controller's current ladder level for the NEXT warm frame.
    ema: float = 0.0
    level: int = 1
    # Set by the controller when the EMA says the warm start lost the
    # scene: the next frame re-runs cold even though state exists.
    force_cold: bool = False
    warm_frames: int = 0
    cold_frames: int = 0
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)


class SessionStore:
    """Bounded LRU + TTL map of ``session_id -> Session``.

    ``now_fn`` is injectable so TTL tests don't sleep.  Thread-safe: the
    store lock covers only lookup/eviction bookkeeping; per-frame work
    serializes on each session's own lock (two frames of one session never
    interleave, while different sessions only contend on the engine).
    """

    def __init__(self, limit: int, ttl_s: float, metrics=None,
                 now_fn=time.monotonic):
        assert limit >= 1, limit
        self.limit = limit
        self.ttl_s = ttl_s
        self.metrics = metrics
        self._now = now_fn
        self._lock = threading.Lock()
        # guarded_by: _lock
        self._sessions: "collections.OrderedDict[str, Session]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def get_or_create(self, sid: str) -> Tuple[Session, bool]:
        """Return ``(session, created)``, touching LRU order.

        An expired session is dropped and replaced by a fresh one
        (``created=True`` — the caller runs the frame cold); exceeding the
        limit evicts the least-recently-used session.  Never raises.
        """
        with self._lock:
            now = self._now()
            sess = self._sessions.get(sid)
            if sess is not None:
                if now - sess.last_used > self.ttl_s:
                    del self._sessions[sid]
                    if self.metrics is not None:
                        self.metrics.stream_expired.inc()
                        self.metrics.stream_active.add(-1)
                    sess = None
                else:
                    sess.last_used = now
                    self._sessions.move_to_end(sid)
                    return sess, False
            sess = Session(sid, last_used=now)
            self._sessions[sid] = sess
            if self.metrics is not None:
                # Gauge.add is locked: concurrent HTTP threads create and
                # expire sessions in parallel, and an unlocked
                # read-modify-write would lose counts.
                self.metrics.stream_active.add(1)
            while len(self._sessions) > self.limit:
                self._sessions.popitem(last=False)
                if self.metrics is not None:
                    self.metrics.stream_evicted.inc()
                    self.metrics.stream_active.add(-1)
            return sess, True

    def drop(self, sid: str) -> bool:
        """Explicitly end a session; True if it existed."""
        with self._lock:
            existed = self._sessions.pop(sid, None) is not None
            if existed and self.metrics is not None:
                self.metrics.stream_active.add(-1)
            return existed
