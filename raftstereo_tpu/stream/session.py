"""Per-stream session state and its bounded store.

A :class:`Session` is everything the warm-start policy carries between the
frames of one video stream: the previous frame's low-resolution disparity
(kept at the PADDED bucket's 1/factor grid, so it is already the shape the
next dispatch's ``flow_init`` needs), the next expected sequence number, the
EMA of the per-frame update magnitude that drives the adaptive iteration
controller, and the controller's current ladder level.

The :class:`SessionStore` is deliberately forgiving: hitting the session
limit evicts the least-recently-used session, and an idle session past its
TTL expires — in both cases the client's next frame simply runs COLD (full
iterations, zero init) and re-establishes state.  Losing a session is a
performance event, never a correctness error, so the store never raises at
a client.  Evictions/expirations/active count are exported through
``ServeMetrics`` (``/metrics``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["STATE_VERSION", "Session", "SessionStore"]

# Versioned snapshot format for export_state/import_state.  Bump when the
# Session fields carried across replicas change shape or meaning; an
# importer seeing an unknown version falls back cold, never errors.
STATE_VERSION = 1

# The engine-level keys of the state-schema fingerprint.  Two stores may
# exchange warm state only when these agree: ``factor`` fixes the 1/f
# grid ``prev_disp_low`` lives on, ``input_mode``/``gru_backend`` fix
# which executables the state feeds (a bucket served by one engine and
# not the other simply re-buckets cold at the next frame, so the bucket
# itself rides along informationally, not as a hard gate).
_SCHEMA_KEYS = ("factor", "input_mode", "gru_backend")

# Fixed accounted overhead of one session beyond the disparity plane:
# the controller scalars carried across frames (next_seq, frame_idx,
# ema, level, force_cold, warm/cold frame counters) at 8 bytes each.
_SESSION_OVERHEAD = 56


@dataclasses.dataclass
class Session:
    """Warm-start state for one stream (mutated under ``lock``)."""

    sid: str
    last_used: float = 0.0
    next_seq: int = 0
    frame_idx: int = 0
    # Previous frame's disparity at the padded bucket's 1/factor grid
    # ((H/f, W/f) float32, dataset sign convention); None until the first
    # frame completes.
    prev_disp_low: Optional[np.ndarray] = None
    bucket_hw: Optional[Tuple[int, int]] = None
    # EMA of mean |refined - warm-start init| (low-res px) and the
    # controller's current ladder level for the NEXT warm frame.
    ema: float = 0.0
    level: int = 1
    # Set by the controller when the EMA says the warm start lost the
    # scene: the next frame re-runs cold even though state exists.
    force_cold: bool = False
    warm_frames: int = 0
    cold_frames: int = 0
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)


class SessionStore:
    """Bounded LRU + TTL map of ``session_id -> Session``.

    ``now_fn`` is injectable so TTL tests don't sleep.  Thread-safe: the
    store lock covers only lookup/eviction bookkeeping; per-frame work
    serializes on each session's own lock (two frames of one session never
    interleave, while different sessions only contend on the engine).
    """

    def __init__(self, limit: int, ttl_s: float, metrics=None,
                 now_fn=time.monotonic, budget_mb: float = 0.0):
        assert limit >= 1, limit
        assert budget_mb >= 0, budget_mb
        self.limit = limit
        self.ttl_s = ttl_s
        # Byte budget over the accounted state total; 0 disables the
        # byte bound (count cap stays either way).
        self.budget_bytes = int(budget_mb * 2 ** 20)
        self.metrics = metrics
        self._now = now_fn
        self._lock = threading.Lock()
        # guarded_by: _lock
        self._sessions: "collections.OrderedDict[str, Session]" = \
            collections.OrderedDict()
        self._bytes: Dict[str, int] = {}    # guarded_by: _lock
        self._total_bytes = 0               # guarded_by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def total_bytes(self) -> int:
        """Accounted bytes of all live session state (the value of the
        ``stream_session_bytes`` gauge)."""
        with self._lock:
            return self._total_bytes

    @staticmethod
    def _state_bytes(sess: Session) -> int:
        """Exact accounted bytes of one session's warm-start state: the
        disparity plane's nbytes plus the fixed controller overhead and
        the key.  Caller holds ``sess.lock`` (the plane is mutated
        under it)."""
        n = _SESSION_OVERHEAD + len(sess.sid.encode())
        if sess.prev_disp_low is not None:
            n += int(sess.prev_disp_low.nbytes)
        return n

    def account(self, sess: Session) -> None:
        """Re-account one session's state bytes after its plane changed
        (``StreamRunner.step`` / ``import_state`` call this right after
        writing ``prev_disp_low``).  Caller holds ``sess.lock``; the
        store lock is only ever taken after a session lock, never the
        reverse, so the order is deadlock-free.  May byte-budget-evict
        LRU sessions (never the one being accounted — it was just
        touched, so it is most-recent)."""
        n = self._state_bytes(sess)
        with self._lock:
            if sess.sid not in self._sessions:
                return  # evicted while its frame ran; nothing to track
            self._total_bytes += n - self._bytes.get(sess.sid, 0)
            self._bytes[sess.sid] = n
            self._evict_over_limits()
            self._refresh_bytes_gauge()

    def _forget_bytes(self, sid: str) -> None:  # guarded_by: _lock
        self._total_bytes -= self._bytes.pop(sid, 0)

    def _evict_over_limits(self) -> None:  # guarded_by: _lock
        """LRU-evict while over the count cap OR the byte budget.  The
        byte bound never evicts the last live session: a single
        over-budget stream is served (and surfaced on the gauge), not
        erroneously dropped mid-use."""
        while (len(self._sessions) > self.limit
               or (self.budget_bytes > 0
                   and self._total_bytes > self.budget_bytes
                   and len(self._sessions) > 1)):
            sid, _ = self._sessions.popitem(last=False)
            self._forget_bytes(sid)
            if self.metrics is not None:
                self.metrics.stream_evicted.inc()
                self.metrics.stream_active.add(-1)

    def _refresh_bytes_gauge(self) -> None:  # guarded_by: _lock
        if self.metrics is not None:
            self.metrics.stream_session_bytes.set(float(self._total_bytes))

    def get_or_create(self, sid: str) -> Tuple[Session, bool]:
        """Return ``(session, created)``, touching LRU order.

        An expired session is dropped and replaced by a fresh one
        (``created=True`` — the caller runs the frame cold); exceeding the
        limit evicts the least-recently-used session.  Never raises.
        """
        with self._lock:
            now = self._now()
            sess = self._sessions.get(sid)
            if sess is not None:
                if now - sess.last_used > self.ttl_s:
                    del self._sessions[sid]
                    self._forget_bytes(sid)
                    if self.metrics is not None:
                        self.metrics.stream_expired.inc()
                        self.metrics.stream_active.add(-1)
                    sess = None
                else:
                    sess.last_used = now
                    self._sessions.move_to_end(sid)
                    return sess, False
            sess = Session(sid, last_used=now)
            self._sessions[sid] = sess
            if self.metrics is not None:
                # Gauge.add is locked: concurrent HTTP threads create and
                # expire sessions in parallel, and an unlocked
                # read-modify-write would lose counts.
                self.metrics.stream_active.add(1)
            self._evict_over_limits()
            self._refresh_bytes_gauge()
            return sess, True

    def drop(self, sid: str) -> bool:
        """Explicitly end a session; True if it existed."""
        with self._lock:
            existed = self._sessions.pop(sid, None) is not None
            self._forget_bytes(sid)
            self._refresh_bytes_gauge()
            if existed and self.metrics is not None:
                self.metrics.stream_active.add(-1)
            return existed

    # ------------------------------------------------- migration (PR 13)

    def session_ids(self) -> List[str]:
        """Live session ids, LRU order (drain-time handoff iterates this)."""
        with self._lock:
            return list(self._sessions)

    def export_state(self, sid: str,
                     schema: Optional[Dict] = None) -> Optional[Dict]:
        """Versioned host-side snapshot of one session's warm-start state,
        or ``None`` when there is nothing warm to move (unknown session,
        or no completed frame yet — a session without ``prev_disp_low``
        re-establishes itself cold anywhere, so there is no asset).

        ``schema`` is the exporting engine's state-schema fingerprint
        (``BatchEngine.session_schema()``); the importer refuses a
        mismatched snapshot with a cold fallback, never an error.  The
        export serializes on the session's own lock, so a frame in
        flight completes first and the snapshot is always consistent
        (and the disparity copy is bitwise — a warm import is
        indistinguishable from having stayed)."""
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            return None
        with sess.lock:
            if sess.prev_disp_low is None:
                return None
            return {
                "version": STATE_VERSION,
                "schema": dict(schema or {},
                               bucket=(list(sess.bucket_hw)
                                       if sess.bucket_hw else None)),
                "session_id": sess.sid,
                "next_seq": int(sess.next_seq),
                "frame_idx": int(sess.frame_idx),
                "prev_disp_low": np.ascontiguousarray(
                    sess.prev_disp_low).copy(),
                "bucket_hw": (tuple(sess.bucket_hw)
                              if sess.bucket_hw else None),
                "ema": float(sess.ema),
                "level": int(sess.level),
                "force_cold": bool(sess.force_cold),
                "warm_frames": int(sess.warm_frames),
                "cold_frames": int(sess.cold_frames),
            }

    def import_state(self, snapshot: Dict,
                     schema: Optional[Dict] = None) -> str:
        """Install an exported snapshot; returns the handoff outcome:

        * ``"warm"`` — state installed (or already at least as fresh
          here); the session's next in-order frame runs warm;
        * ``"cold_schema"`` — version or schema-fingerprint mismatch
          (documented cold fallback: nothing is installed, the next
          frame re-establishes state cold).

        Never raises at a caller: a malformed snapshot is a cold
        fallback, exactly like a lost session."""
        try:
            if int(snapshot.get("version", -1)) != STATE_VERSION:
                return "cold_schema"
            theirs = snapshot.get("schema") or {}
            ours = schema or {}
            if any(theirs.get(k) != ours.get(k) for k in _SCHEMA_KEYS):
                return "cold_schema"
            sid = str(snapshot["session_id"])
            prev = np.ascontiguousarray(snapshot["prev_disp_low"],
                                        dtype=np.float32)
            next_seq = int(snapshot["next_seq"])
            bucket = snapshot.get("bucket_hw")
            bucket = tuple(int(x) for x in bucket) if bucket else None
        except Exception:
            return "cold_schema"
        with self._lock:
            now = self._now()
            sess = self._sessions.get(sid)
            if sess is None:
                sess = Session(sid, last_used=now)
                self._sessions[sid] = sess
                if self.metrics is not None:
                    self.metrics.stream_active.add(1)
                self._evict_over_limits()
            else:
                sess.last_used = now
                self._sessions.move_to_end(sid)
        with sess.lock:
            # Monotonic guard: a concurrent per-frame handoff (or a frame
            # that already ran here) may have produced FRESHER state than
            # this snapshot — a stale import would rewind next_seq and
            # turn the client's next in-order frame cold (out_of_order).
            if sess.prev_disp_low is not None and sess.next_seq >= next_seq:
                return "warm"
            sess.next_seq = next_seq
            sess.frame_idx = int(snapshot["frame_idx"])
            sess.prev_disp_low = prev
            sess.bucket_hw = bucket
            sess.ema = float(snapshot["ema"])
            sess.level = int(snapshot["level"])
            sess.force_cold = bool(snapshot["force_cold"])
            sess.warm_frames = int(snapshot["warm_frames"])
            sess.cold_frames = int(snapshot["cold_frames"])
            self.account(sess)
        return "warm"
