"""Temporal warm-start streaming/video stereo (docs/streaming.md).

Video makes RAFT-Stereo's iterative refinement a sequence problem: warm-
starting each frame from the previous frame's forward-warped disparity
(the RAFT warm-start policy, Teed & Deng ECCV 2020 — PAPERS.md) lets the
ConvGRU converge in a fraction of the cold-start iterations at equal
accuracy.  Layers, bottom-up:

* ``session``    — per-stream state (previous low-res disparity, sequence
                   number, update-magnitude EMA) in a bounded LRU + TTL
                   store; losing a session means a cold frame, never an
                   error.
* ``controller`` — adaptive iteration controller: picks each warm frame's
                   GRU iteration count from a small fixed ladder of
                   pre-compiled levels, steered by the EMA.
* ``runner``     — ``StreamRunner`` (frame stepper over the serve
                   ``BatchEngine``'s warm-start executables) plus the
                   offline ``run_sequence``/``compare_warm_cold`` harness
                   shared by ``cli/stream.py``, ``bench.py --stream`` and
                   the acceptance tests.
* ``tier``       — durable session tier: a model-free shared store for
                   session snapshots (``cli.sessiontier`` service +
                   ``TierClient`` + the backends' write-behind
                   ``TierPublisher``), so any replica resumes any
                   stream warm even after its home backend is gone
                   (docs/streaming.md "Durable sessions").

Entry points: ``python -m raftstereo_tpu.cli.stream`` (offline sequence
runner), session-aware ``/predict`` (``session_id``/``seq_no``) on
``python -m raftstereo_tpu.cli.serve``; smoke benchmark:
``python bench.py --stream --quick``.
"""

from .controller import AdaptiveIterController  # noqa: F401
from .runner import (  # noqa: F401
    StreamResult,
    StreamRunner,
    build_stream_engine,
    compare_warm_cold,
    run_sequence,
)
from .session import Session, SessionStore  # noqa: F401
from .tier import (  # noqa: F401
    SessionTier,
    TierClient,
    TierMetrics,
    TierPublisher,
    build_session_tier,
)
