"""Evaluation harness: compiled inference runner + benchmark validators."""

from .runner import Evaluator  # noqa: F401
from .tiled import plan_tiles, tile_weight, tiled_infer  # noqa: F401
from .validate import (  # noqa: F401
    VALIDATORS,
    validate,
    validate_eth3d,
    validate_kitti,
    validate_middlebury,
    validate_things,
)
