"""Tiled inference for very large stereo pairs (Middlebury 4K, 6000x4000).

BASELINE.json config #5: "Middlebury 4K tiled inference, alt corr + host-HBM
pyramid streaming".  The reference has no tiling support at all — its answer to
large images is the low-memory ``alt`` correlation backend plus
``--n_downsample 3`` (reference: README.md:111,121) and it still holds the
whole image's activations on one GPU.  This module goes further, the TPU way:

* the image is cut into a grid of FIXED-SHAPE overlapping tiles, so the whole
  run reuses ONE compiled XLA program (static shapes — no recompiles);
* only one tile's feature/correlation pyramid ever lives in HBM; the full-res
  disparity is accumulated on the host (the "host-HBM streaming" part) —
  peak HBM is O(tile), independent of image size;
* per-tile disparity fields are blended with linear feather weights over the
  overlap, and the left ``disp_margin`` strip of each interior tile is given
  zero weight: stereo matches sit at x - d (disparity looks LEFT along the
  epipolar line), so a pixel within ``disp_margin`` of an interior tile's left
  edge cannot see its true match inside the tile and its prediction is
  untrusted.  Tiles touching the true image border keep full weight there —
  the truncation is then physical, not an artifact of tiling.

Each tile is a completely standard forward pass, so every correlation backend
works; ``alt`` (O(H*W) memory, ops/corr.py) is the intended one for 4K+.

Caveat: the feature encoder uses instance norm (reference:
core/extractor.py norm_fn='instance'), whose statistics are computed per
input — per TILE here — so tile features are not bit-identical to a
full-frame pass even away from seams.  Measured (round 4): this
tiled-vs-full difference IS the model's crop variance — with
briefly-trained (30-step) weights it is O(field magnitude) (median 2.4 px
on a field of p95 18.5), and only a converged checkpoint shrinks it; no
weights-independent interior bound exists.  What IS guaranteed exactly,
for any weights (tests/test_tiled.py): wherever one tile owns a pixel at
full weight the stitched value equals direct model inference on that
tile's crop, blend bands are convex combinations of the contributing
tiles, and a single tile covering the image reproduces the full-frame
pass identically.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["plan_tiles", "plan_geometry", "tile_weight", "tiled_infer",
           "seam_gradient"]


def seam_gradient(pred: np.ndarray, gt: np.ndarray) -> float:
    """Seam-quality metric: the largest one-pixel jump of the ERROR field.

    ``max |∇(pred - gt)|`` over both axes.  Subtracting the ground truth
    removes the scene's own gradients, so what remains is stitching
    artifacts: a hard (unfeathered) tile boundary with per-tile bias ``b``
    shows a jump of ~``b`` at the seam, while a correct ``overlap``-pixel
    feather bounds the jump by ~``b / overlap``.  Guarded by
    tests/test_tiled.py::test_seam_gradient_bounded so feathering
    regressions are caught quantitatively.
    """
    err = np.asarray(pred, np.float64) - np.asarray(gt, np.float64)
    jumps = [np.abs(np.diff(err, axis=0)).max() if err.shape[0] > 1 else 0.0,
             np.abs(np.diff(err, axis=1)).max() if err.shape[1] > 1 else 0.0]
    return float(max(jumps))


def plan_tiles(size: int, tile: int, stride: int) -> List[int]:
    """Start offsets covering ``[0, size)`` with fixed ``tile`` length.

    Regular grid at ``stride``, with the last tile shifted left so it ends
    exactly at ``size`` (all tiles stay in-bounds and identically shaped).
    """
    if tile >= size:
        return [0]
    n = math.ceil((size - tile) / stride) + 1
    starts = [min(i * stride, size - tile) for i in range(n)]
    # Dedupe (shifting can collide) while preserving order.
    out: List[int] = []
    for s in starts:
        if not out or s != out[-1]:
            out.append(s)
    return out


def plan_geometry(h: int, w: int, tile_hw: Tuple[int, int], overlap: int,
                  disp_margin: int):
    """The exact tile plan ``tiled_infer`` executes for an (h, w) image:
    (th, tw, ys, xs, ph, pw) — rounded tile shape, start offsets, padded
    image shape.  One home for the rounding/stride/clamp rules so callers
    reporting tile counts (bench.py --tiled) can never drift from what
    actually runs."""
    th = min(-(-tile_hw[0] // 32) * 32, -(-h // 32) * 32)
    tw = min(-(-tile_hw[1] // 32) * 32, -(-w // 32) * 32)
    ph, pw = max(h, th), max(w, tw)
    if tw < pw and tw <= disp_margin + overlap:
        raise ValueError(
            f"tile width {tw} must exceed disp_margin+overlap "
            f"({disp_margin}+{overlap}) when tiling horizontally")
    if th < ph and th <= overlap:
        raise ValueError(
            f"tile height {th} must exceed overlap ({overlap}) when tiling "
            f"vertically")
    sy = max(th - overlap, 1)
    sx = max(tw - overlap - (disp_margin if tw < pw else 0), 1)
    return th, tw, plan_tiles(ph, th, sy), plan_tiles(pw, tw, sx), ph, pw


def tile_weight(tile_h: int, tile_w: int, y0: int, x0: int, h: int, w: int,
                overlap: int, disp_margin: int) -> np.ndarray:
    """(tile_h, tile_w) feather-blend weights for a tile placed at (y0, x0).

    Linear ramp 1/(o+1)..1 over ``overlap`` pixels on every edge that is
    interior to the image; weight 0 over the left ``disp_margin`` strip of
    tiles with x0 > 0 (see module docstring).  Edges that coincide with the
    image border keep weight 1 right up to the border.
    """
    wy = np.ones(tile_h, np.float64)
    wx = np.ones(tile_w, np.float64)

    def feather(vec, at_start, o):
        ramp = np.arange(1, o + 1, dtype=np.float64) / (o + 1)
        if at_start:
            vec[:o] = np.minimum(vec[:o], ramp)
        else:
            vec[-o:] = np.minimum(vec[-o:], ramp[::-1])

    oy = max(min(overlap, tile_h), 1)
    ox = max(min(overlap, tile_w), 1)
    if y0 > 0:
        feather(wy, True, oy)
    if y0 + tile_h < h:
        feather(wy, False, oy)
    if x0 > 0:
        feather(wx, True, ox)
        if disp_margin > 0:
            m = min(disp_margin, tile_w)
            wx[:m] = 0.0
            # Restart the feather after the dead strip.
            e = min(m + ox, tile_w)
            ramp = np.arange(1, ox + 1, dtype=np.float64) / (ox + 1)
            wx[m:e] = np.minimum(wx[m:e], ramp[: e - m])
    if x0 + tile_w < w:
        feather(wx, False, ox)
    return (wy[:, None] * wx[None, :]).astype(np.float32)


def tiled_infer(model, variables, image1: np.ndarray, image2: np.ndarray, *,
                iters: int = 32,
                tile_hw: Tuple[int, int] = (1056, 1568),
                overlap: int = 128,
                disp_margin: int = 512,
                infer_fn=None,
                callback=None,
                tile_batch: int = 1) -> np.ndarray:
    """Full-resolution disparity for an arbitrarily large pair.

    Args:
      model/variables: a ``RAFTStereo`` bundle (any corr backend; use
        ``alt`` for 4K+).
      image1, image2: (H, W, 3) or (1, H, W, 3) host arrays, [0, 255].
      tile_hw: fixed tile shape; rounded up to a multiple of 32 internally.
      overlap: feather width; stride = tile - overlap (y) and
        tile - overlap - disp_margin (x) so the zero-weight strip is always
        covered by the tile to its left.
      disp_margin: max expected disparity at full resolution; interior tiles
        contribute nothing within this strip of their left edge.
      infer_fn: optional pre-jitted ``(vars, i1, i2) -> (low, up)`` override
        (lets callers reuse a compiled fn across pairs).
      callback: optional ``f(done, total)`` progress hook.
      tile_batch: tiles per device dispatch.  Tiles are fixed-shape, so
        stacking ``B`` of them down the batch axis keeps the one-compiled-
        program property while amortizing per-dispatch latency (the
        remote-TPU tunnel costs ~190 ms per call — at 30 tiles that is 6 s
        of pure dispatch).  Peak HBM becomes O(tile_batch x tile); the
        last group is padded by repeating its final tile (discarded).

    Returns (H, W) float32 disparity field (negative-flow convention).
    """
    import jax
    import jax.numpy as jnp

    img1 = np.asarray(image1, np.float32)
    img2 = np.asarray(image2, np.float32)
    if img1.ndim == 4:
        img1, img2 = img1[0], img2[0]
    h, w = img1.shape[:2]

    th, tw, ys, xs, ph, pw = plan_geometry(h, w, tile_hw, overlap,
                                           disp_margin)
    pad_h, pad_w = ph - h, pw - w
    if pad_h or pad_w:
        # Small images: replicate-pad up to one tile (mirrors InputPadder).
        img1 = np.pad(img1, ((0, pad_h), (0, pad_w), (0, 0)), mode="edge")
        img2 = np.pad(img2, ((0, pad_h), (0, pad_w), (0, 0)), mode="edge")

    if infer_fn is None:
        infer_fn = model.jitted_infer(iters=iters)

    acc = np.zeros((ph, pw), np.float64)
    wacc = np.zeros((ph, pw), np.float64)
    positions = [(y0, x0) for y0 in ys for x0 in xs]
    total = len(positions)
    done = 0
    bsz = max(int(tile_batch), 1)
    for g in range(0, total, bsz):
        group = positions[g:g + bsz]
        # Pad the tail group by repeating its last tile: the compiled
        # program sees one fixed batch shape; padded outputs are dropped.
        padded = group + [group[-1]] * (bsz - len(group))
        t1 = jnp.asarray(np.stack(
            [img1[y0:y0 + th, x0:x0 + tw] for y0, x0 in padded]))
        t2 = jnp.asarray(np.stack(
            [img2[y0:y0 + th, x0:x0 + tw] for y0, x0 in padded]))
        _, up = infer_fn(variables, t1, t2)
        d = np.asarray(jax.device_get(up))[:, :, :, 0]
        for k, (y0, x0) in enumerate(group):
            wt = tile_weight(th, tw, y0, x0, ph, pw, overlap, disp_margin)
            acc[y0:y0 + th, x0:x0 + tw] += wt.astype(np.float64) * d[k]
            wacc[y0:y0 + th, x0:x0 + tw] += wt
            done += 1
            if callback is not None:
                callback(done, total)

    np.maximum(wacc, 1e-12, out=wacc)
    return (acc / wacc)[:h, :w].astype(np.float32)
