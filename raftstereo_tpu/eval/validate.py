"""Benchmark validators: EPE / D1 per dataset, with the reference's exact
aggregation semantics (reference: evaluate_stereo.py:18-189):

* ETH3D       — D1 threshold 1px; EPE and D1 averaged per-image
  (reference: evaluate_stereo.py:19-56)
* KITTI-2015  — D1 threshold 3px; EPE per-image mean, D1 pooled over ALL
  valid pixels; runtime/FPS measured after a 50-image warmup
  (reference: evaluate_stereo.py:60-108)
* FlyingThings (TEST, finalpass) — D1 threshold 1px, validity additionally
  requires |gt| < 192; D1 pooled over pixels
  (reference: evaluate_stereo.py:112-146)
* Middlebury F/H/Q — D1 threshold 2px; validity uses the reference's
  ``valid >= -0.5 & gt_flow > -1000`` test; per-image averages
  (reference: evaluate_stereo.py:150-189)

Each validator takes the functional model + variables (no wrapper objects)
and an optional pre-built dataset so tests and the training loop can inject
synthetic or subsetted data.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from ..data import datasets as ds
from .runner import Evaluator

logger = logging.getLogger(__name__)


def _epe_map(pred: np.ndarray, flow_gt: np.ndarray) -> np.ndarray:
    """Per-pixel endpoint error.  Both carry x-flow only (the y component is
    identically zero on both sides — reference: core/raft_stereo.py:120 —
    so the reference's 2-channel L2 reduces to |Δx|)."""
    return np.abs(pred - flow_gt[..., 0])


def _unpack(sample):
    meta, image1, image2, flow, valid = sample
    return image1, image2, flow, valid


def validate_eth3d(model, variables, iters: int = 32,
                   dataset=None, root: Optional[str] = None,
                   evaluator: Optional[Evaluator] = None) -> Dict[str, float]:
    """ETH3D two-view training split (reference: evaluate_stereo.py:19-56)."""
    if dataset is None:
        dataset = ds.ETH3D(aug_params=None, **({"root": root} if root else {}))
    run = evaluator or Evaluator(model, variables, iters=iters)
    epe_list, out_list = [], []
    for i in range(len(dataset)):
        image1, image2, flow_gt, valid_gt = _unpack(dataset[i])
        pred = run(image1, image2)
        epe = _epe_map(pred, flow_gt).ravel()
        val = valid_gt.ravel() >= 0.5
        image_epe = float(epe[val].mean())
        image_out = float((epe[val] > 1.0).mean())
        logger.info("ETH3D %d/%d EPE %.4f D1 %.4f", i + 1, len(dataset),
                    image_epe, image_out)
        epe_list.append(image_epe)
        out_list.append(image_out)
    return {"eth3d-epe": float(np.mean(epe_list)),
            "eth3d-d1": 100 * float(np.mean(out_list))}


def validate_kitti(model, variables, iters: int = 32,
                   dataset=None, root: Optional[str] = None,
                   evaluator: Optional[Evaluator] = None,
                   warmup: int = 50) -> Dict[str, float]:
    """KITTI-2015 training split (reference: evaluate_stereo.py:60-108)."""
    if dataset is None:
        dataset = ds.KITTI(aug_params=None, image_set="training",
                           **({"root": root} if root else {}))
    run = evaluator or Evaluator(model, variables, iters=iters)
    epe_list, out_list, elapsed = [], [], []
    for i in range(len(dataset)):
        image1, image2, flow_gt, valid_gt = _unpack(dataset[i])
        pred = run(image1, image2)
        # The reference warms up by image count only (evaluate_stereo.py:81);
        # with XLA a NEW padded shape after the warmup still pays a compile,
        # so compile-tainted samples are excluded explicitly.
        if i > warmup and not run.last_included_compile:
            elapsed.append(run.last_runtime)
        epe = _epe_map(pred, flow_gt).ravel()
        val = valid_gt.ravel() >= 0.5
        image_epe = float(epe[val].mean())
        if i < 9 or (i + 1) % 10 == 0:
            logger.info("KITTI %d/%d EPE %.4f D1 %.4f (%.3fs)", i + 1,
                        len(dataset), image_epe,
                        float((epe[val] > 3.0).mean()), run.last_runtime)
        epe_list.append(image_epe)
        out_list.append(epe[val] > 3.0)
    result = {"kitti-epe": float(np.mean(epe_list)),
              "kitti-d1": 100 * float(np.mean(np.concatenate(out_list)))}
    if elapsed:
        result["kitti-fps"] = 1.0 / float(np.mean(elapsed))
    return result


def validate_things(model, variables, iters: int = 32,
                    dataset=None, root: Optional[str] = None,
                    evaluator: Optional[Evaluator] = None,
                    max_images: Optional[int] = None) -> Dict[str, float]:
    """FlyingThings3D TEST split, finalpass; the in-training regression
    check (reference: evaluate_stereo.py:112-146; train_stereo.py:189)."""
    if dataset is None:
        dataset = ds.SceneFlowDatasets(dstype="frames_finalpass",
                                       things_test=True,
                                       **({"root": root} if root else {}))
    run = evaluator or Evaluator(model, variables, iters=iters)
    n = len(dataset) if max_images is None else min(max_images, len(dataset))
    epe_list, out_list = [], []
    for i in range(n):
        image1, image2, flow_gt, valid_gt = _unpack(dataset[i])
        pred = run(image1, image2)
        epe = _epe_map(pred, flow_gt).ravel()
        val = ((valid_gt.ravel() >= 0.5)
               & (np.abs(flow_gt[..., 0]).ravel() < 192))
        epe_list.append(float(epe[val].mean()))
        out_list.append(epe[val] > 1.0)
    return {"things-epe": float(np.mean(epe_list)),
            "things-d1": 100 * float(np.mean(np.concatenate(out_list)))}


def validate_middlebury(model, variables, iters: int = 32, split: str = "F",
                        dataset=None, root: Optional[str] = None,
                        evaluator: Optional[Evaluator] = None) -> Dict[str, float]:
    """Middlebury-V3 training split (reference: evaluate_stereo.py:150-189).

    Validity mirrors the reference's quirk exactly: ``valid >= -0.5`` is
    always true for the reader's 0/1 nocc mask, so only ``gt x-flow > -1000``
    actually filters — occluded pixels with finite ground truth are scored
    (reference: evaluate_stereo.py:173).
    """
    if dataset is None:
        dataset = ds.Middlebury(aug_params=None, split=split,
                                **({"root": root} if root else {}))
    run = evaluator or Evaluator(model, variables, iters=iters)
    epe_list, out_list = [], []
    for i in range(len(dataset)):
        image1, image2, flow_gt, valid_gt = _unpack(dataset[i])
        pred = run(image1, image2)
        epe = _epe_map(pred, flow_gt).ravel()
        val = (valid_gt.ravel() >= -0.5) & (flow_gt[..., 0].ravel() > -1000)
        image_epe = float(epe[val].mean())
        image_out = float((epe[val] > 2.0).mean())
        logger.info("Middlebury %d/%d EPE %.4f D1 %.4f", i + 1, len(dataset),
                    image_epe, image_out)
        epe_list.append(image_epe)
        out_list.append(image_out)
    return {f"middlebury{split}-epe": float(np.mean(epe_list)),
            f"middlebury{split}-d1": 100 * float(np.mean(out_list))}


def validate_sl(model, variables, iters: int = 32,
                dataset=None, root: Optional[str] = None,
                evaluator: Optional[Evaluator] = None,
                max_images: Optional[int] = None) -> Dict[str, float]:
    """Structured-light validation: masked EPE / bad-1px over the
    valid-modulation region (docs/structured_light.md).

    Items follow the SL train protocol — 12-channel stacks with the
    modulation gate folded into ``valid`` — from ``sl.SLTrainView`` over a
    real capture tree (``root``) or the in-memory exact-GT synthetic set
    when neither ``dataset`` nor ``root`` is given.  Unlike the passive
    validators there is no unmasked variant: the projector-shadow region
    carries no signal by construction (sl/synthetic.py).
    """
    # Lazy import: eval is imported by sl.evaluate, so a module-level
    # import here would cycle.
    from ..sl import SLShiftStereoDataset, SLTrainView
    if dataset is None:
        if root is not None:
            from ..data.sl import StructuredLightDataset
            dataset = SLTrainView(StructuredLightDataset(
                root, split="validation", scale=1.0, with_depth=True))
        else:
            dataset = SLShiftStereoDataset()
    run = evaluator or Evaluator(model, variables, iters=iters)
    n = len(dataset) if max_images is None else min(max_images, len(dataset))
    epe_list, out_list = [], []
    for i in range(n):
        image1, image2, flow_gt, valid_gt = _unpack(dataset[i])
        pred = run(image1, image2)
        epe = _epe_map(pred, flow_gt).ravel()
        val = valid_gt.ravel() >= 0.5
        epe_list.append(float(epe[val].mean()))
        out_list.append(epe[val] > 1.0)
    return {"sl-epe": float(np.mean(epe_list)),
            "sl-d1": 100 * float(np.mean(np.concatenate(out_list)))}


VALIDATORS = {
    "eth3d": validate_eth3d,
    "sl": validate_sl,
    "kitti": validate_kitti,
    "things": validate_things,
    "middlebury_F": lambda *a, **k: validate_middlebury(*a, split="F", **k),
    "middlebury_H": lambda *a, **k: validate_middlebury(*a, split="H", **k),
    "middlebury_Q": lambda *a, **k: validate_middlebury(*a, split="Q", **k),
}


def validate(name: str, model, variables, **kwargs) -> Dict[str, float]:
    """Dispatch by dataset name (reference: evaluate_stereo.py:232-242)."""
    if name not in VALIDATORS:
        raise ValueError(f"unknown benchmark {name!r}; "
                         f"choices: {sorted(VALIDATORS)}")
    return VALIDATORS[name](model, variables, **kwargs)
