"""Accuracy-tier certification: measured EPE deltas vs the fp32 reference.

The serving layer's accuracy tiers (ops/quant.py, docs/serving.md
"Accuracy tiers") trade numerics for throughput — ``fast`` runs bf16,
``turbo`` adds the int8-quantized correlation volume.  A tier is only
worth offering if its accuracy cost is KNOWN and BOUNDED, so this module
is the gate between "implemented" and "advertised":

* :func:`certify_tiers` runs synthetic stereo pairs with exact ground
  truth (data/synthetic.ShiftStereoDataset — matched textures, so the
  correlation volume is genuinely informative) through the fp32
  reference forward and through each tier's model (same weights, only
  the numeric-policy config fields swapped), and records each tier's
  mean-EPE delta against its bound;
* the resulting **certification manifest** (JSON, written by
  ``python -m raftstereo_tpu.cli.certify``) travels with the checkpoint;
* :func:`resolve_tiers` is what the server calls at startup
  (serve/server.build_server, serve/cluster/replica.py): a tier is
  advertised on ``/predict`` only when the manifest certifies it for
  this model — over-bound, missing, stale-architecture or unreadable
  manifests all refuse the tier with a recorded reason (a request for it
  is a clean 400, never a silently-degraded answer).

The deltas are measured on synthetic data — they certify the numeric
envelope of the tier's kernels, not benchmark leaderboard deltas; the
bounds are deliberately loose screens against implementation regressions
(a broken dequant shows up px-large), not sub-pixel accuracy claims.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..ops.quant import (TIER_MODES, TIERS, config_for_mode,
                         mode_for_accuracy)

logger = logging.getLogger(__name__)

__all__ = ["DEFAULT_BOUNDS", "certify_tiers", "load_manifest",
           "resolve_tiers", "tier_ok", "write_manifest"]

MANIFEST_VERSION = 1

# Default mean-EPE-delta bounds (px) per tier on the synthetic
# certification set.  Loose by design: they catch implementation breakage
# (a wrong dequant scale or a mis-keyed executable is pixels-large), while
# the measured delta itself is recorded in the manifest for operators who
# want tighter SLOs.
DEFAULT_BOUNDS = {"fast": 0.5, "turbo": 1.0}

# Model-config fields that must match between certification time and
# serving time for the certificate to transfer: everything that changes
# the traced program or its numerics APART from the three fields the tier
# itself swaps (compute_dtype/corr_dtype/corr_quant — config_for_mode
# overrides those identically on both sides, so base-config differences
# there are irrelevant to the tier programs).  Backend selectors with
# "auto" resolution (corr_implementation, gru_backend, fused_encoder)
# are fingerprinted as the RAW config strings; their platform-dependent
# resolution is covered by the separate platform check in tier_ok.
ARCH_FIELDS = ("corr_levels", "corr_radius", "n_downsample", "n_gru_layers",
               "hidden_dims", "slow_fast_gru", "shared_backbone",
               "context_norm", "corr_implementation", "corr_precision",
               "fused_encoder", "gru_backend", "input_mode")


def _arch_of(config) -> Dict[str, object]:
    d = dataclasses.asdict(config)
    return {k: (list(v) if isinstance(v, tuple) else v)
            for k, v in d.items() if k in ARCH_FIELDS}


def certify_tiers(config, variables, tiers: Sequence[str] = ("fast",
                                                             "turbo"), *,
                  hw: Tuple[int, int] = (64, 96), n_pairs: int = 4,
                  iters: int = 12, seed: int = 0,
                  bounds: Optional[Dict[str, float]] = None) -> Dict:
    """Measure per-tier EPE deltas vs the fp32 reference and build the
    certification manifest.

    One batched test-mode forward per tier (fp32 reference included), all
    at the same program shape so the comparison is apples-to-apples.
    ``bounds`` overrides :data:`DEFAULT_BOUNDS` per tier.  The returned
    manifest is self-contained: measured EPEs, deltas, bounds, the
    certified verdicts, and the model-architecture fingerprint
    :func:`tier_ok` later checks it against.
    """
    import jax
    import jax.numpy as jnp

    from ..data.synthetic import ShiftStereoDataset
    from ..models.raft_stereo import RAFTStereo

    bad = [t for t in tiers if t not in TIERS or t == "certified"]
    assert not bad, (f"cannot certify tiers {bad}: choose from "
                     f"{[t for t in TIERS if t != 'certified']}")
    bounds = {**DEFAULT_BOUNDS, **(bounds or {})}
    if config.input_mode == "sl":
        # SL models certify on SL data: the exact-GT synthetic twin with
        # 12-channel pattern-conditioned inputs (sl/synthetic.py).  A
        # passive certification set cannot even be fed to an SL model —
        # and the fingerprint (ARCH_FIELDS) keys the manifest to the
        # input mode, so certificates never transfer across modes.
        from ..sl import SLShiftStereoDataset
        ds = SLShiftStereoDataset(n=n_pairs, hw=hw, seed=seed)
        data_desc = "synthetic SLShiftStereoDataset (exact GT, masked)"
    else:
        ds = ShiftStereoDataset(n=n_pairs, hw=hw, seed=seed)
        data_desc = "synthetic ShiftStereoDataset (exact GT)"
    lefts = np.stack([ds[i][1] for i in range(n_pairs)])
    rights = np.stack([ds[i][2] for i in range(n_pairs)])
    gts = np.stack([ds[i][3] for i in range(n_pairs)])   # (N, H, W, 1)
    # Passive synthetic pairs are valid everywhere; SL pairs carry a
    # projector-shadow band that the EPE must skip (masked semantics).
    valid = np.stack([np.asarray(ds[i][4], np.float32)[..., None]
                      for i in range(n_pairs)])
    n_valid = max(float(valid.sum()), 1.0)

    def _epe(pred: np.ndarray) -> float:
        return float((np.abs(pred - gts) * valid).sum() / n_valid)

    def run(mode: str) -> np.ndarray:
        model = RAFTStereo(config_for_mode(config, mode))
        fn = jax.jit(lambda v, a, b, m=model: m.forward(
            v, a, b, iters=iters, test_mode=True)[1])
        up = fn(variables, jnp.asarray(lefts), jnp.asarray(rights))
        return np.asarray(up, np.float32)

    ref = run("fp32")
    epe_ref = _epe(ref)
    entries: Dict[str, Dict] = {}
    for tier in tiers:
        pred = run(TIER_MODES[tier])
        epe = _epe(pred)
        delta = epe - epe_ref
        bound = float(bounds[tier])
        entries[tier] = {
            "mode": TIER_MODES[tier],
            "epe": round(epe, 6),
            "epe_delta": round(delta, 6),
            "bound": bound,
            "max_abs_disp_diff": round(
                float((np.abs(pred - ref) * valid).max()), 6),
            "certified": bool(delta <= bound),
        }
        logger.info("certify %s: epe %.4f (ref %.4f, delta %+.4f, bound "
                    "%.3f) -> %s", tier, epe, epe_ref, delta, bound,
                    "CERTIFIED" if entries[tier]["certified"]
                    else "OVER BOUND")
    return {
        "version": MANIFEST_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
        # The platform the deltas were MEASURED on: "auto" backends and
        # kernel selection resolve per platform, so a CPU-measured
        # manifest must not certify the TPU kernels (tier_ok refuses).
        "platform": jax.default_backend(),
        "model": _arch_of(config),
        "eval": {"hw": list(hw), "n_pairs": n_pairs, "iters": iters,
                 "seed": seed, "epe_ref": round(epe_ref, 6),
                 "data": data_desc},
        "tiers": entries,
    }


def write_manifest(manifest: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")


def load_manifest(path: str) -> Dict:
    """Parse + shape-check a manifest; raises ``ValueError`` on anything
    that should refuse certification loudly (bad JSON, wrong version,
    missing sections) rather than half-working."""
    with open(path) as f:
        try:
            manifest = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"certification manifest {path!r} is not "
                             f"valid JSON: {e}") from e
    if not isinstance(manifest, dict) \
            or manifest.get("version") != MANIFEST_VERSION \
            or not isinstance(manifest.get("tiers"), dict):
        raise ValueError(
            f"certification manifest {path!r} has unsupported layout/"
            f"version (want version {MANIFEST_VERSION} with a 'tiers' "
            f"table)")
    return manifest


def tier_ok(manifest: Optional[Dict], tier: str,
            model_config=None) -> Tuple[bool, str]:
    """Whether ``manifest`` certifies ``tier`` (optionally for
    ``model_config``'s architecture).  Returns ``(ok, reason)`` — the
    reason is what the server records and returns in the 400."""
    if tier not in TIER_MODES:
        return False, f"unknown tier {tier!r}"
    if manifest is None:
        return False, "no certification manifest"
    entry = manifest["tiers"].get(tier)
    if entry is None:
        return False, "tier not present in the certification manifest"
    if not entry.get("certified"):
        return False, (f"tier measured over bound (epe_delta "
                       f"{entry.get('epe_delta')} > bound "
                       f"{entry.get('bound')})")
    delta, bound = entry.get("epe_delta"), entry.get("bound")
    if not (isinstance(delta, (int, float)) and isinstance(bound,
                                                           (int, float))
            and delta <= bound):
        # Belt-and-braces: a hand-edited certified=true with an
        # over-bound delta must not advertise.
        return False, (f"manifest inconsistent: epe_delta {delta!r} vs "
                       f"bound {bound!r}")
    plat = manifest.get("platform")
    if plat is not None:
        import jax

        if plat != jax.default_backend():
            # "auto" backends resolve per platform: deltas measured on
            # CPU kernels say nothing about the TPU kernels /predict
            # would actually run.
            return False, (f"manifest measured on platform {plat!r}, "
                           f"serving on {jax.default_backend()!r} — "
                           f"re-certify on this platform")
    if model_config is not None:
        want = _arch_of(model_config)
        have = manifest.get("model", {})
        if have != want:
            diff = sorted(k for k in want
                          if have.get(k) != want[k])
            return False, (f"manifest certifies a different model "
                           f"architecture (mismatched: {diff})")
    return True, "certified"


def resolve_tiers(serve_cfg, model_config=None
                  ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """The server's startup gate: which requested tiers may be advertised.

    Returns ``(advertised, refused)``: ``advertised`` maps tier name ->
    precision mode (what /predict accepts and warmup compiles),
    ``refused`` maps tier name -> the human-readable reason (what the
    400 carries and /healthz reports).  ``certified`` needs no manifest —
    it IS the fp32 reference the others are certified against."""
    advertised: Dict[str, str] = {}
    refused: Dict[str, str] = {}
    if not serve_cfg.tiers:
        return advertised, refused
    manifest = None
    manifest_err = None
    if serve_cfg.cert_manifest:
        try:
            manifest = load_manifest(serve_cfg.cert_manifest)
        except (OSError, ValueError) as e:
            manifest_err = str(e)
    for tier in serve_cfg.tiers:
        if tier == "certified":
            advertised[tier] = mode_for_accuracy(tier)
            continue
        if manifest is None:
            refused[tier] = manifest_err or "no certification manifest " \
                "(--cert_manifest; python -m raftstereo_tpu.cli.certify)"
            continue
        ok, reason = tier_ok(manifest, tier, model_config)
        if ok:
            advertised[tier] = mode_for_accuracy(tier)
        else:
            refused[tier] = reason
    for tier, reason in refused.items():
        logger.warning("accuracy tier %r NOT advertised: %s", tier, reason)
    return advertised, refused
