"""Accuracy-tier certification: measured EPE deltas vs the fp32 reference.

The serving layer's accuracy tiers (ops/quant.py, docs/serving.md
"Accuracy tiers") trade numerics for throughput — ``fast`` runs bf16,
``turbo`` adds the int8-quantized correlation volume.  A tier is only
worth offering if its accuracy cost is KNOWN and BOUNDED, so this module
is the gate between "implemented" and "advertised":

* :func:`certify_tiers` runs synthetic stereo pairs with exact ground
  truth (data/synthetic.ShiftStereoDataset — matched textures, so the
  correlation volume is genuinely informative) through the fp32
  reference forward and through each tier's model (same weights, only
  the numeric-policy config fields swapped), and records each tier's
  mean-EPE delta against its bound;
* the resulting **certification manifest** (JSON, written by
  ``python -m raftstereo_tpu.cli.certify``) travels with the checkpoint;
* :func:`resolve_tiers` is what the server calls at startup
  (serve/server.build_server, serve/cluster/replica.py): a tier is
  advertised on ``/predict`` only when the manifest certifies it for
  this model — over-bound, missing, stale-architecture or unreadable
  manifests all refuse the tier with a recorded reason (a request for it
  is a clean 400, never a silently-degraded answer).

The deltas are measured on synthetic data — they certify the numeric
envelope of the tier's kernels, not benchmark leaderboard deltas; the
bounds are deliberately loose screens against implementation regressions
(a broken dequant shows up px-large), not sub-pixel accuracy claims.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..ops.quant import (TIER_MODES, TIERS, config_for_mode,
                         mode_for_accuracy)

logger = logging.getLogger(__name__)

__all__ = ["DEFAULT_BOUNDS", "DEFAULT_CASCADE_BOUND", "cascade_ok",
           "certify_cascades", "certify_tiers", "load_manifest",
           "resolve_cascades", "resolve_tiers", "tier_ok",
           "write_manifest"]

MANIFEST_VERSION = 1

# Default mean-EPE-delta bounds (px) per tier on the synthetic
# certification set.  Loose by design: they catch implementation breakage
# (a wrong dequant scale or a mis-keyed executable is pixels-large), while
# the measured delta itself is recorded in the manifest for operators who
# want tighter SLOs.
DEFAULT_BOUNDS = {"fast": 0.5, "turbo": 1.0}

# Default mean-EPE-delta bound (px) for a CASCADE schedule vs the fp32
# monolithic reference at EQUAL TOTAL iteration count.  Tighter than the
# all-cheap tier bounds because the certifying fp32 leg pulls the
# estimate back toward the reference fixed point — a cascade that cannot
# beat its cheap tier's bound is pointless.
DEFAULT_CASCADE_BOUND = 0.5

# Model-config fields that must match between certification time and
# serving time for the certificate to transfer: everything that changes
# the traced program or its numerics APART from the three fields the tier
# itself swaps (compute_dtype/corr_dtype/corr_quant — config_for_mode
# overrides those identically on both sides, so base-config differences
# there are irrelevant to the tier programs).  Backend selectors with
# "auto" resolution (corr_implementation, gru_backend, fused_encoder)
# are fingerprinted as the RAW config strings; their platform-dependent
# resolution is covered by the separate platform check in tier_ok.
ARCH_FIELDS = ("corr_levels", "corr_radius", "n_downsample", "n_gru_layers",
               "hidden_dims", "slow_fast_gru", "shared_backbone",
               "context_norm", "corr_implementation", "corr_precision",
               "fused_encoder", "gru_backend", "input_mode")


def _arch_of(config) -> Dict[str, object]:
    d = dataclasses.asdict(config)
    return {k: (list(v) if isinstance(v, tuple) else v)
            for k, v in d.items() if k in ARCH_FIELDS}


def _cert_data(config, hw: Tuple[int, int], n_pairs: int, seed: int):
    """The certification set, stacked: ``(lefts, rights, gts, valid,
    n_valid, description)`` — shared by tier and cascade certification so
    both measure against the same pairs."""
    if config.input_mode == "sl":
        # SL models certify on SL data: the exact-GT synthetic twin with
        # 12-channel pattern-conditioned inputs (sl/synthetic.py).  A
        # passive certification set cannot even be fed to an SL model —
        # and the fingerprint (ARCH_FIELDS) keys the manifest to the
        # input mode, so certificates never transfer across modes.
        from ..sl import SLShiftStereoDataset
        ds = SLShiftStereoDataset(n=n_pairs, hw=hw, seed=seed)
        data_desc = "synthetic SLShiftStereoDataset (exact GT, masked)"
    else:
        from ..data.synthetic import ShiftStereoDataset
        ds = ShiftStereoDataset(n=n_pairs, hw=hw, seed=seed)
        data_desc = "synthetic ShiftStereoDataset (exact GT)"
    lefts = np.stack([ds[i][1] for i in range(n_pairs)])
    rights = np.stack([ds[i][2] for i in range(n_pairs)])
    gts = np.stack([ds[i][3] for i in range(n_pairs)])   # (N, H, W, 1)
    # Passive synthetic pairs are valid everywhere; SL pairs carry a
    # projector-shadow band that the EPE must skip (masked semantics).
    valid = np.stack([np.asarray(ds[i][4], np.float32)[..., None]
                      for i in range(n_pairs)])
    n_valid = max(float(valid.sum()), 1.0)
    return lefts, rights, gts, valid, n_valid, data_desc


def certify_tiers(config, variables, tiers: Sequence[str] = ("fast",
                                                             "turbo"), *,
                  hw: Tuple[int, int] = (64, 96), n_pairs: int = 4,
                  iters: int = 12, seed: int = 0,
                  bounds: Optional[Dict[str, float]] = None) -> Dict:
    """Measure per-tier EPE deltas vs the fp32 reference and build the
    certification manifest.

    One batched test-mode forward per tier (fp32 reference included), all
    at the same program shape so the comparison is apples-to-apples.
    ``bounds`` overrides :data:`DEFAULT_BOUNDS` per tier.  The returned
    manifest is self-contained: measured EPEs, deltas, bounds, the
    certified verdicts, and the model-architecture fingerprint
    :func:`tier_ok` later checks it against.
    """
    import jax
    import jax.numpy as jnp

    from ..models.raft_stereo import RAFTStereo

    bad = [t for t in tiers if t not in TIERS or t == "certified"]
    assert not bad, (f"cannot certify tiers {bad}: choose from "
                     f"{[t for t in TIERS if t != 'certified']}")
    bounds = {**DEFAULT_BOUNDS, **(bounds or {})}
    lefts, rights, gts, valid, n_valid, data_desc = _cert_data(
        config, hw, n_pairs, seed)

    def _epe(pred: np.ndarray) -> float:
        return float((np.abs(pred - gts) * valid).sum() / n_valid)

    def run(mode: str) -> np.ndarray:
        model = RAFTStereo(config_for_mode(config, mode))
        fn = jax.jit(lambda v, a, b, m=model: m.forward(
            v, a, b, iters=iters, test_mode=True)[1])
        up = fn(variables, jnp.asarray(lefts), jnp.asarray(rights))
        return np.asarray(up, np.float32)

    ref = run("fp32")
    epe_ref = _epe(ref)
    entries: Dict[str, Dict] = {}
    for tier in tiers:
        pred = run(TIER_MODES[tier])
        epe = _epe(pred)
        delta = epe - epe_ref
        bound = float(bounds[tier])
        entries[tier] = {
            "mode": TIER_MODES[tier],
            "epe": round(epe, 6),
            "epe_delta": round(delta, 6),
            "bound": bound,
            "max_abs_disp_diff": round(
                float((np.abs(pred - ref) * valid).max()), 6),
            "certified": bool(delta <= bound),
        }
        logger.info("certify %s: epe %.4f (ref %.4f, delta %+.4f, bound "
                    "%.3f) -> %s", tier, epe, epe_ref, delta, bound,
                    "CERTIFIED" if entries[tier]["certified"]
                    else "OVER BOUND")
    return {
        "version": MANIFEST_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
        # The platform the deltas were MEASURED on: "auto" backends and
        # kernel selection resolve per platform, so a CPU-measured
        # manifest must not certify the TPU kernels (tier_ok refuses).
        "platform": jax.default_backend(),
        "model": _arch_of(config),
        "eval": {"hw": list(hw), "n_pairs": n_pairs, "iters": iters,
                 "seed": seed, "epe_ref": round(epe_ref, 6),
                 "data": data_desc},
        "tiers": entries,
    }


def write_manifest(manifest: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")


def load_manifest(path: str) -> Dict:
    """Parse + shape-check a manifest; raises ``ValueError`` on anything
    that should refuse certification loudly (bad JSON, wrong version,
    missing sections) rather than half-working."""
    with open(path) as f:
        try:
            manifest = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"certification manifest {path!r} is not "
                             f"valid JSON: {e}") from e
    if not isinstance(manifest, dict) \
            or manifest.get("version") != MANIFEST_VERSION \
            or not isinstance(manifest.get("tiers"), dict):
        raise ValueError(
            f"certification manifest {path!r} has unsupported layout/"
            f"version (want version {MANIFEST_VERSION} with a 'tiers' "
            f"table)")
    return manifest


def tier_ok(manifest: Optional[Dict], tier: str,
            model_config=None) -> Tuple[bool, str]:
    """Whether ``manifest`` certifies ``tier`` (optionally for
    ``model_config``'s architecture).  Returns ``(ok, reason)`` — the
    reason is what the server records and returns in the 400."""
    if tier not in TIER_MODES:
        return False, f"unknown tier {tier!r}"
    if manifest is None:
        return False, "no certification manifest"
    entry = manifest["tiers"].get(tier)
    if entry is None:
        return False, "tier not present in the certification manifest"
    if not entry.get("certified"):
        return False, (f"tier measured over bound (epe_delta "
                       f"{entry.get('epe_delta')} > bound "
                       f"{entry.get('bound')})")
    delta, bound = entry.get("epe_delta"), entry.get("bound")
    if not (isinstance(delta, (int, float)) and isinstance(bound,
                                                           (int, float))
            and delta <= bound):
        # Belt-and-braces: a hand-edited certified=true with an
        # over-bound delta must not advertise.
        return False, (f"manifest inconsistent: epe_delta {delta!r} vs "
                       f"bound {bound!r}")
    plat = manifest.get("platform")
    if plat is not None:
        import jax

        if plat != jax.default_backend():
            # "auto" backends resolve per platform: deltas measured on
            # CPU kernels say nothing about the TPU kernels /predict
            # would actually run.
            return False, (f"manifest measured on platform {plat!r}, "
                           f"serving on {jax.default_backend()!r} — "
                           f"re-certify on this platform")
    if model_config is not None:
        want = _arch_of(model_config)
        have = manifest.get("model", {})
        if have != want:
            diff = sorted(k for k in want
                          if have.get(k) != want[k])
            return False, (f"manifest certifies a different model "
                           f"architecture (mismatched: {diff})")
    return True, "certified"


def resolve_tiers(serve_cfg, model_config=None
                  ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """The server's startup gate: which requested tiers may be advertised.

    Returns ``(advertised, refused)``: ``advertised`` maps tier name ->
    precision mode (what /predict accepts and warmup compiles),
    ``refused`` maps tier name -> the human-readable reason (what the
    400 carries and /healthz reports).  ``certified`` needs no manifest —
    it IS the fp32 reference the others are certified against."""
    advertised: Dict[str, str] = {}
    refused: Dict[str, str] = {}
    if not serve_cfg.tiers:
        return advertised, refused
    manifest = None
    manifest_err = None
    if serve_cfg.cert_manifest:
        try:
            manifest = load_manifest(serve_cfg.cert_manifest)
        except (OSError, ValueError) as e:
            manifest_err = str(e)
    for tier in serve_cfg.tiers:
        if tier == "certified":
            advertised[tier] = mode_for_accuracy(tier)
            continue
        if manifest is None:
            refused[tier] = manifest_err or "no certification manifest " \
                "(--cert_manifest; python -m raftstereo_tpu.cli.certify)"
            continue
        ok, reason = tier_ok(manifest, tier, model_config)
        if ok:
            advertised[tier] = mode_for_accuracy(tier)
        else:
            refused[tier] = reason
    for tier, reason in refused.items():
        logger.warning("accuracy tier %r NOT advertised: %s", tier, reason)
    return advertised, refused


# --------------------------------------------------------------- cascades


def certify_cascades(config, variables, schedules: Sequence[str], *,
                     hw: Tuple[int, int] = (64, 96), n_pairs: int = 4,
                     seed: int = 0,
                     bounds: Optional[Dict[str, float]] = None,
                     base: Optional[Dict] = None) -> Dict:
    """Certify speculative tier-cascade schedules (serve/cascade/,
    docs/serving.md "Tier cascade") exactly like single tiers: masked
    mean-EPE delta vs the fp32 MONOLITHIC reference at EQUAL TOTAL
    iteration count, entries keyed by the canonical schedule string.

    What is measured is what serves: each schedule runs the model-level
    phase chain the engine's cascade executables trace — cheap-tier
    prologue + steps, the ``handoff_state`` cast/corr-swap, certified
    steps + epilogue — so the certificate covers the handoff itself, not
    just the tiers it connects.

    ``bounds`` maps canonical schedule string -> EPE-delta bound (px),
    defaulting to :data:`DEFAULT_CASCADE_BOUND`.  ``base`` merges the
    cascades table into an existing manifest (same architecture +
    platform required — a certificate never transfers); None builds a
    standalone manifest with an empty tiers table.
    """
    import jax
    import jax.numpy as jnp

    from ..models.raft_stereo import RAFTStereo
    from ..serve.cascade.handoff import handoff_state
    from ..serve.cascade.schedule import parse_schedule

    parsed = [parse_schedule(s) for s in schedules]
    assert parsed, "no cascade schedules to certify"
    bounds = dict(bounds or {})
    lefts, rights, gts, valid, n_valid, data_desc = _cert_data(
        config, hw, n_pairs, seed)

    def _epe(pred: np.ndarray) -> float:
        return float((np.abs(pred - gts) * valid).sum() / n_valid)

    def run_mono(mode: str, iters: int) -> np.ndarray:
        model = RAFTStereo(config_for_mode(config, mode))
        fn = jax.jit(lambda v, a, b, m=model: m.forward(
            v, a, b, iters=iters, test_mode=True)[1])
        return np.asarray(fn(variables, jnp.asarray(lefts),
                             jnp.asarray(rights)), np.float32)

    def run_cascade(s) -> np.ndarray:
        m_cheap = RAFTStereo(config_for_mode(config, s.cheap_mode))
        m_cert = RAFTStereo(config_for_mode(config, s.cert_mode))

        def fn(v, a, b):
            st = m_cheap.forward_prologue(v, a, b)
            st = m_cheap.forward_step(v, st, iters=s.cheap_iters)
            stage = m_cert.forward_prologue(v, a, b)
            st = handoff_state(st, stage)
            st = m_cert.forward_step(v, st, iters=s.cert_iters)
            return m_cert.forward_epilogue(v, st)[1]

        jitted = jax.jit(fn)
        return np.asarray(jitted(variables, jnp.asarray(lefts),
                                 jnp.asarray(rights)), np.float32)

    # One fp32 reference per distinct total iteration count (schedules
    # with different budgets certify against different references).
    refs = {total: run_mono("fp32", total)
            for total in sorted({s.total_iters for s in parsed})}
    entries: Dict[str, Dict] = {}
    for s in parsed:
        ref = refs[s.total_iters]
        epe_ref = _epe(ref)
        pred = run_cascade(s)
        epe = _epe(pred)
        delta = epe - epe_ref
        bound = float(bounds.get(s.schedule, DEFAULT_CASCADE_BOUND))
        entries[s.schedule] = {
            "cheap_mode": s.cheap_mode,
            "cert_mode": s.cert_mode,
            "total_iters": s.total_iters,
            "fp32_fraction": round(s.fp32_fraction, 6),
            "epe": round(epe, 6),
            "epe_ref": round(epe_ref, 6),
            "epe_delta": round(delta, 6),
            "bound": bound,
            "max_abs_disp_diff": round(
                float((np.abs(pred - ref) * valid).max()), 6),
            "certified": bool(delta <= bound),
        }
        logger.info(
            "certify cascade %s: epe %.4f (ref %.4f at %d iters, delta "
            "%+.4f, bound %.3f) -> %s", s, epe, epe_ref, s.total_iters,
            delta, bound,
            "CERTIFIED" if entries[s.schedule]["certified"]
            else "OVER BOUND")
    if base is not None:
        want = _arch_of(config)
        assert base.get("model") == want, (
            "cannot merge cascade certificates into a manifest for a "
            "different model architecture")
        assert base.get("platform") == jax.default_backend(), (
            f"cannot merge cascade certificates measured on "
            f"{jax.default_backend()!r} into a manifest from "
            f"{base.get('platform')!r}")
        merged = dict(base)
        merged["cascades"] = {**base.get("cascades", {}), **entries}
        return merged
    return {
        "version": MANIFEST_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
        "platform": jax.default_backend(),
        "model": _arch_of(config),
        "eval": {"hw": list(hw), "n_pairs": n_pairs, "seed": seed,
                 "data": data_desc},
        "tiers": {},
        "cascades": entries,
    }


def cascade_ok(manifest: Optional[Dict], schedule: str,
               model_config=None) -> Tuple[bool, str]:
    """Whether ``manifest`` certifies cascade ``schedule`` (canonical
    string) — the cascade twin of :func:`tier_ok`, sharing its platform
    and architecture-fingerprint gates."""
    if manifest is None:
        return False, "no certification manifest"
    entry = manifest.get("cascades", {}).get(schedule)
    if entry is None:
        return False, ("cascade schedule not present in the "
                       "certification manifest (run 'python -m "
                       "raftstereo_tpu.cli.certify cascade')")
    if not entry.get("certified"):
        return False, (f"cascade measured over bound (epe_delta "
                       f"{entry.get('epe_delta')} > bound "
                       f"{entry.get('bound')})")
    delta, bound = entry.get("epe_delta"), entry.get("bound")
    if not (isinstance(delta, (int, float))
            and isinstance(bound, (int, float)) and delta <= bound):
        return False, (f"manifest inconsistent: epe_delta {delta!r} vs "
                       f"bound {bound!r}")
    plat = manifest.get("platform")
    if plat is not None:
        import jax

        if plat != jax.default_backend():
            return False, (f"manifest measured on platform {plat!r}, "
                           f"serving on {jax.default_backend()!r} — "
                           f"re-certify on this platform")
    if model_config is not None:
        want = _arch_of(model_config)
        have = manifest.get("model", {})
        if have != want:
            diff = sorted(k for k in want if have.get(k) != want[k])
            return False, (f"manifest certifies a different model "
                           f"architecture (mismatched: {diff})")
    return True, "certified"


def resolve_cascades(serve_cfg, model_config=None
                     ) -> Tuple[Dict[str, object], Dict[str, str]]:
    """The startup gate for cascade schedules, mirroring
    :func:`resolve_tiers`: returns ``(advertised, refused)`` where
    ``advertised`` maps canonical schedule string -> parsed
    ``CascadeSchedule`` (what /predict accepts and warmup compiles) and
    ``refused`` maps schedule -> reason (the 400 payload and the
    /healthz report).  Unlike single tiers there is no manifest-free
    member: EVERY cascade must certify — its answer leaves fp32
    executables, but from a speculatively drafted state."""
    from ..serve.cascade.schedule import parse_schedule

    advertised: Dict[str, object] = {}
    refused: Dict[str, str] = {}
    if not getattr(serve_cfg, "cascades", ()):
        return advertised, refused
    manifest = None
    manifest_err = None
    if serve_cfg.cert_manifest:
        try:
            manifest = load_manifest(serve_cfg.cert_manifest)
        except (OSError, ValueError) as e:
            manifest_err = str(e)
    for text in serve_cfg.cascades:
        s = parse_schedule(text)  # canonical already (ServeConfig)
        if manifest is None:
            refused[s.schedule] = manifest_err or (
                "no certification manifest (--cert_manifest; python -m "
                "raftstereo_tpu.cli.certify cascade)")
            continue
        ok, reason = cascade_ok(manifest, s.schedule, model_config)
        if ok:
            advertised[s.schedule] = s
        else:
            refused[s.schedule] = reason
    for sched_str, reason in refused.items():
        logger.warning("cascade %r NOT advertised: %s", sched_str, reason)
    return advertised, refused
