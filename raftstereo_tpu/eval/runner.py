"""Compiled inference runner for evaluation and demo.

Wraps the model's test-mode forward behind the shared pad-and-bucket shape
policy (``ops/image.BucketPadder``); ``jax.jit`` caches one executable per
distinct padded shape, so a dataset with varying image sizes (e.g. ETH3D)
compiles once per shape instead of per image (SURVEY.md §7 hard-part 4:
dynamic shapes vs XLA recompilation).  ``bucket_multiple`` optionally rounds
the padded shape up to a coarser grid to share compiles across
near-identical sizes — the same policy the serving engine
(serve/engine.py) uses, so their outputs agree bitwise.

Replaces the per-image boilerplate of the reference evaluators
(reference: evaluate_stereo.py:28-36,70-83): pad -> forward(test_mode) ->
unpad, plus wall-clock timing of the compiled step.  Timing spans the host
fetch of the output: under a remote-device tunnel ``block_until_ready``
returns at enqueue time, and only a host fetch proves execution finished
(same protocol as bench.py).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.image import BucketPadder
from ..utils.profiling import LatencyHistogram


class Evaluator:
    """Stateful wrapper: (H, W, 3) numpy image pair -> (H, W) x-flow field.

    Predictions follow the dataset sign convention (negative disparity,
    reference: core/stereo_datasets.py:77), so they compare directly against
    the ``flow`` channel produced by the data layer.

    ``last_runtime`` is the wall-clock of the latest call (forward + host
    fetch); ``last_included_compile`` flags calls whose padded shape had not
    been executed before, i.e. whose runtime contains an XLA compile — FPS
    protocols should drop those samples.  ``cache_stats`` aggregates the
    same signal (compile-cache hits/misses over the Evaluator's lifetime),
    and ``latency`` accumulates per-call runtimes in a fixed-bucket
    histogram with p50/p90/p99 summaries.
    """

    def __init__(self, model, variables, iters: int = 32,
                 divis_by: int = 32, bucket_multiple: Optional[int] = None,
                 batch_pad: Optional[int] = None, mesh=None):
        self.model = model
        self.variables = variables
        self.iters = iters
        self.divis_by = divis_by
        self.bucket_multiple = bucket_multiple
        # Serving-parity mode: zero-pad the batch axis to this size so the
        # pair executes at the serving engine's padded-batch program shape
        # (serve/engine.py pads every batch to max_batch_size).  XLA tiles
        # reductions differently per program shape, so only identical
        # shapes guarantee bitwise-identical per-sample results.
        self.batch_pad = batch_pad
        self._fn = model.jitted_infer(iters=iters)
        # Optional multi-chip spatial parallelism: shard image height over
        # the mesh's 'space' axis so ONE pair uses several chips' HBM/FLOPs
        # (XLA inserts the conv halo exchanges; the 1-D correlation is along
        # W, so each height shard's epipolar lines are self-contained —
        # numerically transparent, tests/test_parallel.py).
        self._in_sharding = None
        self._mesh = mesh
        if mesh is not None:
            from ..parallel import SPACE_AXIS, replicated, spatial_sharded
            space = mesh.shape.get(SPACE_AXIS, 1)
            # The final padded height is a multiple of bucket_multiple when
            # set, else of divis_by; sharding H over 'space' needs that to be
            # divisible, so fail fast with the fix.
            governing = ("bucket_multiple", self.bucket_multiple) \
                if self.bucket_multiple else ("divis_by", self.divis_by)
            if governing[1] % space:
                raise ValueError(
                    f"mesh '{SPACE_AXIS}' extent {space} must divide "
                    f"{governing[0]}={governing[1]}; pass {governing[0]}="
                    f"{governing[1] * space} (or a multiple of {space})")
            self._in_sharding = spatial_sharded(mesh)
            # Weights restored from a checkpoint arrive committed to one
            # device; jit refuses mixed device sets, so replicate them onto
            # the mesh explicitly.
            self.variables = jax.device_put(self.variables, replicated(mesh))
        self.compiled_shapes: Set[Tuple[int, int]] = set()
        self.cache_hits: int = 0
        self.cache_misses: int = 0
        self.latency = LatencyHistogram()
        self.last_runtime: float = float("nan")
        self.last_included_compile: bool = True

    @property
    def cache_stats(self) -> Dict[str, int]:
        """Compile-cache counters: one miss per padded shape ever executed."""
        return {"hits": self.cache_hits, "misses": self.cache_misses,
                "shapes": len(self.compiled_shapes)}

    def __call__(self, image1: np.ndarray, image2: np.ndarray) -> np.ndarray:
        if image1.ndim == 3:
            image1, image2 = image1[None], image2[None]
        assert image1.shape[0] == 1, (
            f"Evaluator is single-pair; got batch {image1.shape[0]}")
        padder = BucketPadder(image1.shape, divis_by=self.divis_by,
                              bucket_multiple=self.bucket_multiple)
        i1, i2 = padder.pad(jnp.asarray(image1), jnp.asarray(image2))
        if self.batch_pad and self.batch_pad > 1:
            rows = ((0, self.batch_pad - 1), (0, 0), (0, 0), (0, 0))
            i1, i2 = jnp.pad(i1, rows), jnp.pad(i2, rows)
        if self._in_sharding is not None:
            i1 = jax.device_put(i1, self._in_sharding)
            i2 = jax.device_put(i2, self._in_sharding)
        shape = tuple(i1.shape[1:3])
        self.last_included_compile = shape not in self.compiled_shapes
        if self.last_included_compile:
            self.cache_misses += 1
        else:
            self.cache_hits += 1
        start = time.perf_counter()
        from ..parallel.context import use_corr_mesh
        with use_corr_mesh(self._mesh):  # lets Pallas backends shard_map
            _, flow_up = self._fn(self.variables, i1, i2)
        flow_up = np.asarray(flow_up, np.float32)  # host fetch = completion
        self.last_runtime = time.perf_counter() - start
        self.latency.observe(self.last_runtime)
        self.compiled_shapes.add(shape)
        return padder.unpad(flow_up)[0, ..., 0]
