"""Tracing + telemetry subsystem (docs/observability.md).

Dependency-free observability shared by all three workloads:

* ``trace``    — nested, thread-safe spans in a bounded ring buffer,
                 exportable as Chrome trace-event / Perfetto JSON.  The
                 serve path traces every request (admission → queue wait →
                 dispatch → host fetch, keyed by ``X-Request-Id``), the
                 stream path traces warp → forward per frame, the train
                 loop traces data-wait / step / checkpoint phases.
* ``prom``     — Prometheus text-exposition validator + metric-name lint
                 (``scripts/check_metrics.py``), keeping the hand-rolled
                 render scrapeable.
* ``exporter`` — the train-side ``--metrics_port`` HTTP exporter and the
                 debug-endpoint helpers (thread dump, build info, trace
                 download) the serving front-end shares.

The instruments themselves (Counter/Gauge/label families/histograms) live
in ``serve/metrics.py``; this package is everything around them.
"""

from .alerts import AlertClass, BurnRateAlerts  # noqa: F401
from .exporter import (  # noqa: F401
    TelemetryServer,
    build_info,
    dump_threads,
    trace_response,
)
from .fleet import FleetFederator, FleetScrape  # noqa: F401
from .prom import (  # noqa: F401
    Scrape,
    lint_registry,
    parse_sample,
    parse_text,
    validate_prometheus,
)
from .stitch import (  # noqa: F401
    TailSampler,
    spans_from_chrome,
    stitch_sources,
    stitch_tree,
)
from .trace import Span, Tracer, to_chrome_trace  # noqa: F401
