"""In-process span tracer with Chrome trace-event (Perfetto) export.

The XLA profiler (utils/profiling.py) answers "what did the device do" for
a pre-scheduled window; this module answers "where did THIS request/step
go" continuously: lightweight host-side spans (trace id, parent id, name,
attrs, wall-time) recorded into a bounded ring buffer, always on, cheap
enough for every request (one dict + one deque append per span, a few
microseconds — measured in tests/test_obs.py).

Spans are exportable as Chrome trace-event JSON — the format Perfetto and
``chrome://tracing`` open directly, and the same family of viewers the XLA
trace lands in, so a request trace and a ``jax.profiler`` capture can be
eyeballed side by side.  ``GET /debug/trace`` on the serving front-end and
the train-side telemetry exporter both serve this export
(docs/observability.md).

Two recording styles:

* ``with tracer.span("admission", trace_id=rid):`` — live nesting via a
  thread-local stack (children inherit trace/parent ids automatically);
* ``tracer.record("queue_wait", t0, t1, rid)`` — after-the-fact, for
  phases measured by another component (the batcher reconstructs each
  request's queue-wait/dispatch/host-fetch from the dispatch worker).

Timestamps are ``time.perf_counter`` values (monotonic, ns-resolution);
the export converts them to epoch microseconds with one process-wide
offset so spans from every thread share a clock.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["Span", "Tracer", "to_chrome_trace"]

# perf_counter -> unix epoch seconds, fixed at import so every span (and
# every thread) converts identically.
_EPOCH_OFFSET = time.time() - time.perf_counter()


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed span (immutable once recorded)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    t0: float  # time.perf_counter at start
    t1: float  # time.perf_counter at end
    thread: str
    attrs: Dict[str, object]

    @property
    def duration_s(self) -> float:
        return self.t1 - self.t0

    @property
    def wall_t0(self) -> float:
        """Start as unix epoch seconds."""
        return self.t0 + _EPOCH_OFFSET


class _Live:
    """Handle yielded by ``Tracer.span`` — mutate ``attrs`` mid-span."""

    __slots__ = ("trace_id", "span_id", "attrs")

    def __init__(self, trace_id: str, span_id: str, attrs: Dict):
        self.trace_id = trace_id
        self.span_id = span_id
        self.attrs = attrs


class Tracer:
    """Thread-safe bounded span recorder.

    ``capacity`` bounds memory: the ring keeps the most recent spans and
    silently drops the oldest — telemetry must never be the thing that
    OOMs the server.  Dropped spans are counted (``dropped``).
    """

    def __init__(self, capacity: int = 4096):
        assert capacity >= 1, capacity
        self.capacity = capacity
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)  # guarded_by: _lock
        self._recorded = 0  # guarded_by: _lock
        self._tls = threading.local()

    # ------------------------------------------------------------------ ids

    @staticmethod
    def new_trace_id() -> str:
        return uuid.uuid4().hex

    @staticmethod
    def new_span_id() -> str:
        """Public span-id mint: callers that must know a span's id BEFORE
        the span completes (the router emits the hop span's id in the
        outbound ``X-Trace-Context`` header, then records the span after
        the forward returns) mint here and pass it to ``record``."""
        return uuid.uuid4().hex[:16]

    # internal alias kept for the pre-PR 20 private callers
    _new_span_id = new_span_id

    def current(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) of this thread's innermost open span."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # ------------------------------------------------------------ recording

    def record(self, name: str, t0: float, t1: float,
               trace_id: Optional[str],
               parent_id: Optional[str] = None,
               attrs: Optional[Dict] = None,
               span_id: Optional[str] = None) -> str:
        """Record a span measured elsewhere (``t0``/``t1`` are
        ``time.perf_counter`` values).  Returns the span id so callers can
        parent further spans under it.

        A falsy ``trace_id`` records NOTHING and returns "" — this is the
        central ``sampled=0`` guard: hops that continue an unsampled
        trace context pass ``trace_id=None`` downstream (batcher,
        scheduler, stream) and every span silently vanishes without
        per-component flag plumbing.  ``span_id`` lets the caller use a
        pre-minted id (``new_span_id``) that already left the process in
        a trace-context header."""
        if not trace_id:
            return ""
        sid = span_id or self.new_span_id()
        span = Span(trace_id=trace_id, span_id=sid, parent_id=parent_id,
                    name=name, t0=t0, t1=t1,
                    thread=threading.current_thread().name,
                    attrs=dict(attrs or {}))
        with self._lock:
            self._recorded += 1
            self._spans.append(span)
        return sid

    @contextlib.contextmanager
    def span(self, name: str, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> Iterator[_Live]:
        """Context-managed span; nests via a thread-local stack.

        With no explicit ``trace_id`` the span joins this thread's current
        trace (becoming a child of the innermost open span) or starts a
        fresh trace when there is none.
        """
        cur = self.current()
        if trace_id is None:
            if cur is not None:
                trace_id = cur[0]
                if parent_id is None:
                    parent_id = cur[1]
            else:
                trace_id = self.new_trace_id()
        elif parent_id is None and cur is not None and cur[0] == trace_id:
            parent_id = cur[1]
        sid = self._new_span_id()
        live = _Live(trace_id, sid, dict(attrs))
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append((trace_id, sid))
        t0 = time.perf_counter()
        try:
            yield live
        finally:
            t1 = time.perf_counter()
            stack.pop()
            span = Span(trace_id=trace_id, span_id=sid, parent_id=parent_id,
                        name=name, t0=t0, t1=t1,
                        thread=threading.current_thread().name,
                        attrs=live.attrs)
            with self._lock:
                self._recorded += 1
                self._spans.append(span)

    # -------------------------------------------------------------- reading

    @property
    def recorded(self) -> int:
        """Spans ever recorded (including ones the ring has dropped)."""
        with self._lock:  # vs a concurrent record() increment
            return self._recorded

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._spans)

    def spans(self, last: Optional[int] = None,
              trace_id: Optional[str] = None) -> List[Span]:
        """Most recent spans, oldest first; optionally the last ``last``
        and/or only one trace."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if last is not None:
            out = out[-max(int(last), 0):]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def to_chrome(self, last: Optional[int] = None,
                  trace_id: Optional[str] = None) -> Dict:
        return to_chrome_trace(self.spans(last=last, trace_id=trace_id))

    def export_json(self, last: Optional[int] = None,
                    trace_id: Optional[str] = None) -> str:
        return json.dumps(self.to_chrome(last=last, trace_id=trace_id))


def to_chrome_trace(spans: List[Span]) -> Dict:
    """Chrome trace-event JSON (the ``traceEvents`` array form).

    Every span becomes one complete ("ph": "X") event; trace/span/parent
    ids and attrs ride in ``args`` so Perfetto's query/filter UI can slice
    by request id.  Open at https://ui.perfetto.dev or chrome://tracing.
    """
    pid = os.getpid()
    threads = {}  # name -> stable synthetic tid (Perfetto wants ints)
    events = []
    for s in spans:
        tid = threads.setdefault(s.thread, len(threads) + 1)
        events.append({
            "ph": "X",
            "name": s.name,
            "cat": "obs",
            "ts": round(s.wall_t0 * 1e6, 3),
            "dur": round(s.duration_s * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                     "parent_id": s.parent_id, **s.attrs},
        })
    for name, tid in threads.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
