"""Federated fleet metrics: one scrape for the whole cluster
(docs/observability.md "Federated metrics").

The router, every backend, and the session tier each serve their own
``/metrics``; capacity planning and the live burn-rate alerts
(obs/alerts.py) need the UNION.  ``FleetFederator`` scrapes each
registered target, re-labels every foreign series with ``backend=``,
merges them with the router's own registry render, and returns one
Prometheus 0.0.4 exposition — served by the router at
``GET /metrics/fleet``.

Validator-clean by construction: every source text is round-tripped
through ``obs/prom.parse_text`` (which itself runs the validator), label
values and HELP text are re-emitted in their already-escaped wire form,
and the merged text is parsed ONCE MORE before it leaves — a federated
scrape that fails its own validator is a bug here, not in a source.

Scrape failures are surfaced, not swallowed: an unreachable target
increments ``fleet_scrape_failures_total{backend=}`` and its series are
simply absent from that render — the fleet view degrades per-hop, the
endpoint never 500s because one backend is down (that is precisely when
the fleet view is needed).

Stdlib-only: the router imports this and the router is model-free.
"""

from __future__ import annotations

import http.client
import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .prom import parse_text

__all__ = ["FleetFederator", "FleetScrape", "fetch_metrics_text"]

Target = Tuple[str, str, int]  # (label, host, port)


def fetch_metrics_text(host: str, port: int, timeout_s: float = 2.0,
                       path: str = "/metrics") -> str:
    """GET one target's text exposition (raises on any failure)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"{host}:{port}{path} -> {resp.status}")
        return body.decode("utf-8", "replace")
    finally:
        conn.close()


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    if float(v).is_integer():
        return str(int(v))
    return format(v, ".9g")


class FleetScrape:
    """One federated render: the merged text plus its parsed form and
    the per-source outcome (``sources`` scraped, ``gaps`` not)."""

    def __init__(self, text: str, sources: List[str], gaps: List[str]):
        self.text = text
        self.sources = sources
        self.gaps = gaps
        self.scrape = parse_text(text)  # self-validating by construction


class FleetFederator:
    """Scrape-and-merge across the fleet.

    ``targets_fn`` returns the live ``(label, host, port)`` list at call
    time (the router's backend set changes under drain/rejoin, so it is
    a callable, not a snapshot).  ``fetch_fn`` is injectable for tests.
    """

    def __init__(self, registry, targets_fn: Optional[
                     Callable[[], Sequence[Target]]] = None,
                 timeout_s: float = 2.0,
                 fetch_fn: Optional[Callable[[str, int, float],
                                             str]] = None):
        self.registry = registry
        self._targets_fn = targets_fn or (lambda: ())
        self.timeout_s = timeout_s
        self._fetch = fetch_fn or (
            lambda host, port, t: fetch_metrics_text(host, port, t))
        self.scrapes = registry.counter(
            "fleet_scrapes_total",
            "federation scrape attempts per target, successful or not "
            "(obs/fleet.py; GET /metrics/fleet)",
            labels=("backend",))
        self.scrape_failures = registry.counter(
            "fleet_scrape_failures_total",
            "federation scrapes that failed (target unreachable, "
            "non-200, or invalid exposition) — the target's series are "
            "absent from that /metrics/fleet render, never silently "
            "stale",
            labels=("backend",))

    # ------------------------------------------------------------- merge

    def federate(self, local_text_fn: Optional[
            Callable[[], str]] = None) -> FleetScrape:
        """One federated render.  ``local_text_fn`` produces the
        router's own freshly refreshed render (defaults to
        ``registry.render`` — callers that must refresh gauges first
        pass their own).  It is a CALLABLE invoked AFTER the foreign
        scrapes so this very render's ``fleet_scrape_failures_total``
        increments are already in it — a failed scrape is visible in
        the same exposition that carries its gap, never one render
        late.

        Merge rules: the router's series pass through unlabeled; every
        foreign series gains ``backend=<label>`` (histogram
        ``_bucket``/``_sum``/``_count`` included, so per-backend bucket
        ladders stay independently cumulative — the validator checks
        coherence per label set).  First-seen HELP/TYPE wins for a
        family name; duplicate series keep the first occurrence."""
        # families: name -> (kind, help, rows); rows keep source order.
        families: "Dict[str, List]" = {}
        order: List[str] = []
        seen_series = set()

        def add(name, kind, help_, sample_name, labels, value):
            fam = families.get(name)
            if fam is None:
                fam = families[name] = [kind, help_, []]
                order.append(name)
            elif fam[0] != kind:
                return  # TYPE conflict across sources: first wins
            key = (sample_name, labels)
            if key in seen_series:
                return
            seen_series.add(key)
            fam[2].append((sample_name, labels, value))

        def merge(scrape, backend: Optional[str]):
            for name, metric in scrape.metrics.items():
                for (sname, litems), value in metric.samples.items():
                    labels = litems
                    if backend is not None:
                        labels = (("backend", backend),) + tuple(
                            kv for kv in litems if kv[0] != "backend")
                    add(name, metric.kind, metric.help, sname,
                        tuple(labels), value)
                if not metric.samples:  # declared-but-empty family
                    add(name, metric.kind, metric.help, None, None, None)

        sources, gaps = [], []
        foreign: List[Tuple[str, object]] = []
        for label, host, port in self._targets_fn():
            self.scrapes.labels(backend=label).inc()
            try:
                text = self._fetch(host, port, self.timeout_s)
                foreign.append((label, parse_text(text)))
            except Exception:
                self.scrape_failures.labels(backend=label).inc()
                gaps.append(label)
                continue
            sources.append(label)
        merge(parse_text(local_text_fn() if local_text_fn is not None
                         else self.registry.render()), None)
        for label, scrape in foreign:
            merge(scrape, label)
        lines: List[str] = []
        for name in order:
            kind, help_, rows = families[name]
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for sname, labels, value in rows:
                if sname is None:
                    continue  # family with no series yet
                if labels:
                    labelset = ",".join(f'{k}="{v}"' for k, v in labels)
                    lines.append(f"{sname}{{{labelset}}} "
                                 f"{_fmt_value(value)}")
                else:
                    lines.append(f"{sname} {_fmt_value(value)}")
        text = "\n".join(lines) + "\n"
        return FleetScrape(text, sources, gaps)
