"""Prometheus text-exposition (0.0.4) format validator + metric-name lint.

The registry's ``render`` is hand-rolled (no client library), so nothing
upstream guarantees the output actually parses — and a scrape that 400s in
production is an outage of exactly the signal needed to debug it.  This
module is the compensating control: a strict line-by-line validator run
over fully populated renders in the tier-1 tests (tests/test_obs.py) and
over the live ``/metrics`` endpoint in the serving e2e test, plus the
naming lint behind ``scripts/check_metrics.py``.

Dependency-free on purpose, like the metrics code it validates.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["validate_prometheus", "parse_sample", "parse_text",
           "lint_registry"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*)(?: (.*))?$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|histogram|summary|untyped)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|'
                       r'\\n)*)"')
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def parse_sample(line: str) -> Tuple[str, Tuple[Tuple[str, str], ...], float]:
    """Parse one sample line into (name, ((label, raw_value), ...), value).

    Raises ``ValueError`` with a specific message on any malformation —
    the validator surfaces these per line.
    """
    brace = line.find("{")
    if brace == -1:
        parts = line.split(" ")
        if len(parts) not in (2, 3):  # optional trailing timestamp
            raise ValueError(f"expected 'name value [timestamp]': {line!r}")
        name, labels, rest = parts[0], (), parts[1:]
    else:
        name = line[:brace]
        close = line.rfind("}")
        if close == -1:
            raise ValueError(f"unterminated label set: {line!r}")
        body = line[brace + 1:close]
        labels = []
        pos = 0
        while pos < len(body):
            m = _LABEL_RE.match(body, pos)
            if not m:
                raise ValueError(
                    f"bad label pair at {body[pos:]!r} in {line!r}")
            labels.append((m.group(1), m.group(2)))
            pos = m.end()
            if pos < len(body):
                if body[pos] != ",":
                    raise ValueError(
                        f"expected ',' between labels in {line!r}")
                pos += 1
        labels = tuple(labels)
        rest = line[close + 1:].split()
        if len(rest) not in (1, 2):
            raise ValueError(f"expected 'value [timestamp]' after labels: "
                             f"{line!r}")
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    seen = set()
    for k, _ in labels:
        if k in seen:
            raise ValueError(f"duplicate label {k!r} in {line!r}")
        seen.add(k)
    try:
        value = _parse_value(rest[0])
    except ValueError:
        raise ValueError(f"unparseable sample value {rest[0]!r} in {line!r}")
    if len(rest) == 2 and not re.match(r"^-?[0-9]+$", rest[1]):
        raise ValueError(f"bad timestamp {rest[1]!r} in {line!r}")
    return name, labels, value


def _base_of(name: str, types: Dict[str, str]) -> Optional[str]:
    """The declared metric a sample line belongs to (histograms own their
    ``_bucket``/``_sum``/``_count`` series)."""
    if name in types:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if types.get(base) in ("histogram", "summary"):
                return base
    return None


def validate_prometheus(text: str) -> List[str]:
    """Validate a text exposition; returns a list of error strings
    (empty = valid).  Checks, beyond per-line syntax:

    * HELP/TYPE comments well-formed, at most one of each per metric,
      TYPE declared before the metric's samples;
    * HELP text uses only the legal escapes (``\\\\`` and ``\\n``);
    * every sample belongs to a declared metric (histogram ``_bucket`` /
      ``_sum`` / ``_count`` included), no duplicate series;
    * histogram ``le`` labels parse as numbers, cumulative counts are
      monotone, and the ``+Inf`` bucket equals ``_count``.
    """
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Set[str] = set()
    sampled: Set[str] = set()
    series_seen: Set[Tuple] = set()
    hist: Dict[str, Dict] = {}  # base -> {"buckets": [...], "count": float}
    if text and not text.endswith("\n"):
        errors.append("exposition must end with a newline")
    for n, line in enumerate(text.splitlines(), 1):
        where = f"line {n}"
        if line == "":
            continue
        if line.startswith("#"):
            if line.startswith("# HELP "):
                m = _HELP_RE.match(line)
                if not m:
                    errors.append(f"{where}: malformed HELP: {line!r}")
                    continue
                name, help_ = m.group(1), m.group(2) or ""
                if name in helps:
                    errors.append(f"{where}: duplicate HELP for {name}")
                helps.add(name)
                bad = re.search(r"\\(?![\\n])", help_)
                if bad:
                    errors.append(
                        f"{where}: illegal escape in HELP text for {name} "
                        f"(only \\\\ and \\n are allowed)")
            elif line.startswith("# TYPE "):
                m = _TYPE_RE.match(line)
                if not m:
                    errors.append(f"{where}: malformed TYPE: {line!r}")
                    continue
                name = m.group(1)
                if name in types:
                    errors.append(f"{where}: duplicate TYPE for {name}")
                if name in sampled:
                    errors.append(
                        f"{where}: TYPE for {name} after its samples")
                types[name] = m.group(2)
            # other comments are legal and ignored
            continue
        try:
            name, labels, value = parse_sample(line)
        except ValueError as e:
            errors.append(f"{where}: {e}")
            continue
        base = _base_of(name, types)
        if base is None:
            errors.append(f"{where}: sample {name} has no TYPE declaration")
            continue
        sampled.add(base)
        key = (name, labels)
        if key in series_seen:
            errors.append(f"{where}: duplicate series {line.split(' ')[0]}")
        series_seen.add(key)
        if types[base] == "histogram":
            # Coherence is per label SET (minus ``le``): a federated
            # exposition (obs/fleet.py) carries one bucket ladder per
            # ``backend=`` label, each independently cumulative.
            group = tuple(sorted((k, v) for k, v in labels if k != "le"))
            h = hist.setdefault((base, group),
                                {"buckets": [], "count": None})
            if name == base + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    errors.append(f"{where}: histogram bucket without le")
                    continue
                try:
                    bound = _parse_value(le)
                except ValueError:
                    errors.append(f"{where}: unparseable le={le!r}")
                    continue
                h["buckets"].append((bound, value))
            elif name == base + "_count":
                h["count"] = value
    for (base, group), h in hist.items():
        label = base if not group else \
            base + "{" + ",".join(f'{k}="{v}"' for k, v in group) + "}"
        buckets = h["buckets"]
        if not buckets:
            errors.append(f"histogram {label} has no _bucket series")
            continue
        bounds = [b for b, _ in buckets]
        cums = [c for _, c in buckets]
        if bounds != sorted(bounds):
            errors.append(f"histogram {label} buckets out of order")
        if any(a > b for a, b in zip(cums, cums[1:])):
            errors.append(
                f"histogram {label} cumulative counts not monotone")
        if bounds[-1] != math.inf:
            errors.append(f"histogram {label} missing le=\"+Inf\" bucket")
        elif h["count"] is None:
            errors.append(f"histogram {label} missing _count")
        elif cums[-1] != h["count"]:
            errors.append(
                f"histogram {label} +Inf bucket {cums[-1]} != _count "
                f"{h['count']}")
    return errors


class Scrape:
    """A parsed exposition: ``{name: {"type", "help", "samples"}}`` plus
    point lookups and scrape-to-scrape deltas.

    ``samples`` maps the label set (a tuple of ``(label, raw_value)``
    pairs, as ``parse_sample`` returns them — ``()`` for unlabeled) to
    the sample value.  Histogram ``_bucket``/``_sum``/``_count`` series
    stay under their own sample names inside the BASE metric's entry, so
    ``scrape["serve_request_latency_seconds"].samples`` holds the whole
    histogram.
    """

    def __init__(self, metrics: Dict[str, "ScrapedMetric"]):
        self.metrics = metrics

    def __getitem__(self, name: str) -> "ScrapedMetric":
        return self.metrics[name]

    def __contains__(self, name: str) -> bool:
        return name in self.metrics

    def get(self, name: str, default=None):
        return self.metrics.get(name, default)

    def value(self, name: str, **labels) -> float:
        """The sample value for one series (0.0 when the series — or
        the whole metric — has not rendered yet; absent and zero are
        the same thing to a delta assertion)."""
        m = self.metrics.get(name)
        if m is None:
            return 0.0
        return m.value(name, **labels)

    def total(self, name: str) -> float:
        """Label-blind sum over a family's series (counters/gauges)."""
        m = self.metrics.get(name)
        if m is None:
            return 0.0
        return sum(v for (sname, _), v in m.samples.items()
                   if sname == name)

    def delta(self, before: "Scrape", name: str, **labels) -> float:
        """This scrape's series value minus ``before``'s — the
        metric-delta primitive the SLO harness asserts on."""
        return self.value(name, **labels) - before.value(name, **labels)


class ScrapedMetric:
    """One declared metric from a scrape (see ``Scrape``)."""

    def __init__(self, kind: str, help_: str):
        self.kind = kind
        self.help = help_
        # (sample_name, label_items) -> value; sample_name differs from
        # the base only for histogram _bucket/_sum/_count series.
        self.samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           float] = {}

    def value(self, sample_name: str, **labels) -> float:
        key = (sample_name,
               tuple(sorted((k, str(v)) for k, v in labels.items())))
        for (sname, litems), v in self.samples.items():
            if sname == sample_name and tuple(sorted(litems)) == key[1]:
                return v
        return 0.0

    def series(self, sample_name: Optional[str] = None):
        """[(label_items, value)] for one sample name (default: all)."""
        return [(litems, v) for (sname, litems), v in self.samples.items()
                if sample_name is None or sname == sample_name]


def parse_text(text: str) -> Scrape:
    """Parse a Prometheus 0.0.4 exposition into a ``Scrape`` — the
    inverse of ``MetricsRegistry.render``, labels included.

    Strict: raises ``ValueError`` listing the problems if the text
    fails ``validate_prometheus`` — a harness asserting metric deltas
    on a malformed scrape would certify garbage.  Tests that previously
    regexed ``/metrics`` by hand get structured lookups instead:

        scrape = parse_text(client.metrics_text())
        scrape.value("serve_requests_total",
                     endpoint="predict", outcome="ok")
        scrape.delta(before, "serve_shed_total")
    """
    errors = validate_prometheus(text)
    if errors:
        raise ValueError("malformed exposition: " + "; ".join(errors))
    metrics: Dict[str, ScrapedMetric] = {}
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                helps[m.group(1)] = m.group(2) or ""
                continue
            m = _TYPE_RE.match(line)
            if m:
                types[m.group(1)] = m.group(2)
                metrics[m.group(1)] = ScrapedMetric(
                    m.group(2), helps.get(m.group(1), ""))
            continue
        name, labels, value = parse_sample(line)
        base = _base_of(name, types)
        # The validator guaranteed base is not None.
        metrics[base].samples[(name, labels)] = value
    for name, m in metrics.items():  # HELP-after-TYPE is legal format
        m.help = helps.get(name, m.help)
    return Scrape(metrics)


# ------------------------------------------------------------------- lint

# Histogram names that measure a duration must carry the unit; these
# tokens flag a time-ish histogram whose name forgot it.
_TIME_TOKENS = ("latency", "duration", "wait", "runtime", "elapsed")


def lint_registry(entries) -> List[str]:
    """Metric-name lint over ``MetricsRegistry.entries()`` tuples
    ``(kind, name, help, obj)``:

    * counters end ``_total``; gauges and histograms do NOT;
    * histograms measuring time end ``_seconds`` (detected by name
      tokens: latency/duration/wait/runtime/elapsed);
    * every metric has non-empty HELP and a valid name.
    """
    errors = []
    for kind, name, help_, _ in entries:
        if not _NAME_RE.match(name):
            errors.append(f"{name}: invalid metric name")
        if not help_ or not help_.strip():
            errors.append(f"{name}: empty HELP text")
        if kind == "counter" and not name.endswith("_total"):
            errors.append(f"{name}: counter names must end in _total")
        if kind != "counter" and name.endswith("_total"):
            errors.append(f"{name}: _total suffix is reserved for counters")
        if kind == "histogram" and not name.endswith("_seconds") \
                and any(tok in name for tok in _TIME_TOKENS):
            errors.append(
                f"{name}: time histogram names must end in _seconds")
    return errors
