"""Telemetry HTTP exporter + shared debug-endpoint plumbing.

``TelemetryServer`` is the small stdlib HTTP server the train CLI mounts
with ``--metrics_port``: long TPU runs expose the same ``MetricsRegistry``
render a scraper expects (``/metrics``), the span ring as a Perfetto
download (``/debug/trace``), an all-thread stack dump (``/debug/threads``)
and resolved config + build info (``/debug/vars``) — instead of being
observable only through the JSONL log on disk.

The serving front-end (serve/server.py) mounts the SAME debug surface on
its own handler; the formatting helpers here (``dump_threads``,
``build_info``, ``trace_response``) are shared so both speak one format.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)

__all__ = ["TelemetryServer", "build_info", "dump_threads",
           "trace_response"]

_STARTED_AT = time.time()


def build_info() -> Dict:
    """Process/build identification for ``/debug/vars``."""
    info = {
        "python": sys.version.split()[0],
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "started_unix": round(_STARTED_AT, 3),
        "uptime_s": round(time.time() - _STARTED_AT, 3),
    }
    for mod in ("jax", "jaxlib", "numpy", "flax", "optax"):
        m = sys.modules.get(mod)
        if m is not None:
            info[f"{mod}_version"] = getattr(m, "__version__", "?")
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            info["jax_backend"] = jax.default_backend()
            info["jax_device_count"] = jax.device_count()
        except Exception:  # backend not initialized yet — fine
            pass
    return info


def dump_threads() -> str:
    """Stack dump of every live thread (``/debug/threads``) — the
    post-mortem for 'the server stopped answering': which thread holds
    which lock, where the batcher worker is parked."""
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    parts = []
    for ident, frame in sorted(frames.items()):
        t = names.get(ident)
        label = t.name if t is not None else "?"
        daemon = " daemon" if (t is not None and t.daemon) else ""
        parts.append(f"--- thread {label} (ident {ident}{daemon}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
        parts.append("")
    return "\n".join(parts) + "\n"


def trace_response(tracer, query: str) -> Tuple[bytes, Dict[str, str]]:
    """Body + headers for ``GET /debug/trace[?last=N]``: Chrome
    trace-event JSON served as a download Perfetto opens directly."""
    qs = parse_qs(query or "")
    last = None
    if "last" in qs:
        last = max(int(qs["last"][0]), 0)
    trace_id = qs.get("trace_id", [None])[0]
    body = tracer.export_json(last=last, trace_id=trace_id).encode()
    return body, {"Content-Disposition":
                  'attachment; filename="trace.json"'}


class _Handler(BaseHTTPRequestHandler):
    server_version = "raftstereo-telemetry/1.0"

    def log_message(self, fmt, *args):
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code: int, body: bytes, ctype: str,
              extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: "TelemetryServer" = self.server
        url = urlparse(self.path)
        try:
            if url.path == "/metrics":
                self._send(200, srv.registry.render().encode(),
                           "text/plain; version=0.0.4")
            elif url.path == "/debug/trace" and srv.tracer is not None:
                try:
                    body, extra = trace_response(srv.tracer, url.query)
                except ValueError as e:  # e.g. ?last=abc — client error,
                    # same mapping as the serving front-end
                    self._send(400, json.dumps(
                        {"error": f"bad query: {e}"}).encode(),
                        "application/json")
                    return
                self._send(200, body, "application/json", extra)
            elif url.path == "/debug/threads":
                self._send(200, dump_threads().encode(), "text/plain")
            elif url.path == "/debug/vars":
                out = {"build": build_info()}
                if srv.vars_fn is not None:
                    out.update(srv.vars_fn())
                self._send(200, json.dumps(out, default=str).encode(),
                           "application/json")
            elif url.path == "/healthz":
                self._send(200, b'{"status": "ok"}', "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": f"no such path {url.path!r}"}).encode(),
                    "application/json")
        except Exception as e:  # never die on a debug scrape
            self._send(500, json.dumps({"error": str(e)}).encode(),
                       "application/json")


class TelemetryServer(ThreadingHTTPServer):
    """Read-only telemetry server: metrics + debug endpoints, no inference.

    ``port=0`` binds an ephemeral port (read it from ``.port``).  Use as::

        srv = TelemetryServer(registry, tracer, port=9100).start()
        ...
        srv.close()
    """

    daemon_threads = True

    def __init__(self, registry, tracer=None,
                 vars_fn: Optional[Callable[[], Dict]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        # Loopback by default: /debug/threads and /debug/vars expose
        # stacks, argv and resolved paths — exporting beyond the host is
        # an explicit choice (cli.train --metrics_host).
        self.registry = registry
        self.tracer = tracer
        self.vars_fn = vars_fn
        self._thread: Optional[threading.Thread] = None
        super().__init__((host, port), _Handler)

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "TelemetryServer":
        assert self._thread is None, "telemetry server already started"
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True, name="telemetry-http")
        self._thread.start()
        logger.info("telemetry exporter on :%d (/metrics, /debug/trace, "
                    "/debug/threads, /debug/vars)", self.port)
        return self

    def close(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None
