"""Cross-hop trace stitching + tail-based retention (docs/observability.md).

One request now crosses up to three processes — router, backend, session
tier — and each hop records spans into its OWN bounded ring
(``obs/trace.py``).  The trace-context header (``serve/httpbase.py``)
makes every hop agree on the trace id; this module puts the pieces back
together: the router's ``GET /debug/trace?trace_id=`` fans out to each
hop's trace endpoint, parses the Chrome trace-event exports back into
spans, and returns ONE stitched span tree in which the router's hop span
is an ancestor of the backend's admission → queue_wait → dispatch →
host_fetch spans.

Two stitching rules, applied in order:

1. **Explicit parentage** — a span whose ``parent_id`` resolves to
   another collected span attaches there.  The router emits its hop
   span's id in the outbound header, so the backend's root "request"
   span links across the process boundary by id.
2. **Wall-time containment** — spans recorded without a parent (the
   batcher's queue_wait/dispatch/host_fetch are measured after the fact
   by the dispatch worker, which has no span stack) attach to the
   SMALLEST span whose wall-time interval encloses theirs, within a
   small jitter allowance: every process computes wall time from one
   import-time ``time.time() - time.perf_counter()`` offset, so
   same-host hops agree to well under the allowance.

Everything else is a root.  The stitched document stays a valid Chrome
trace (top-level ``traceEvents``) so Perfetto opens it unchanged, with
the tree + per-source gap report riding alongside.

``TailSampler`` is the retention policy that makes the ring buffers
useful at fleet rates: sampling decided at request END (tail-based),
when the outcome is known — error traces are ALWAYS kept, traces slower
than the caller's live p99 threshold are kept, and the boring middle is
dropped deterministically (the decision is a pure function of
(status, duration, threshold), so replaying the same traffic retains
the same traces).

Stdlib-only: the router imports this and the router is model-free.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["spans_from_chrome", "stitch_tree", "stitch_sources",
           "TailSampler"]

#: wall-clock jitter allowance (µs) for cross-process containment —
#: generous against import-time offset skew, far below span durations.
CLOCK_SLACK_US = 5000.0


def spans_from_chrome(doc: Optional[Dict], source: str) -> List[Dict]:
    """Parse a Chrome trace-event export (``to_chrome_trace`` form) back
    into plain span dicts, each tagged with the ``source`` hop it came
    from.  Tolerant: events without span/trace ids (foreign exports,
    metadata events) are skipped, never raised on."""
    out: List[Dict] = []
    for ev in (doc or {}).get("traceEvents", []):
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        args = ev.get("args")
        args = dict(args) if isinstance(args, dict) else {}
        span_id = args.pop("span_id", None)
        trace_id = args.pop("trace_id", None)
        parent_id = args.pop("parent_id", None)
        if not span_id or not trace_id:
            continue
        try:
            t0_us = float(ev.get("ts", 0.0))
            dur_us = max(float(ev.get("dur", 0.0)), 0.0)
        except (TypeError, ValueError):
            continue
        out.append({"name": str(ev.get("name", "?")), "source": source,
                    "trace_id": str(trace_id), "span_id": str(span_id),
                    "parent_id": (str(parent_id) if parent_id else None),
                    "t0_us": t0_us, "dur_us": dur_us, "attrs": args})
    return out


def _order_key(span: Dict) -> Tuple[float, str]:
    """Strict ordering that makes containment attachment acyclic: a span
    may only attach under a span with a GREATER key, so the child→parent
    walk strictly increases and can never loop even when clock slack
    makes two near-identical intervals mutually 'enclosing'."""
    return (span["dur_us"], span["span_id"])


def stitch_tree(spans: Sequence[Dict]) -> List[Dict]:
    """Build the stitched tree: ``[{"span": ..., "children": [...]}]``
    roots, children sorted by start time.  See the module doc for the
    two attachment rules."""
    by_id = {s["span_id"]: s for s in spans}
    parent_of: Dict[str, Optional[str]] = {}
    for s in spans:
        pid = s.get("parent_id")
        if pid and pid in by_id and pid != s["span_id"]:
            parent_of[s["span_id"]] = pid
            continue
        # Orphan: smallest enclosing wall-time interval, slack-tolerant.
        s0, s1 = s["t0_us"], s["t0_us"] + s["dur_us"]
        best = None
        for cand in spans:
            if cand["span_id"] == s["span_id"]:
                continue
            if _order_key(cand) <= _order_key(s):
                continue  # acyclicity: parents are strictly "bigger"
            c0 = cand["t0_us"] - CLOCK_SLACK_US
            c1 = cand["t0_us"] + cand["dur_us"] + CLOCK_SLACK_US
            if c0 <= s0 and s1 <= c1:
                if best is None or _order_key(cand) < _order_key(best):
                    best = cand
        parent_of[s["span_id"]] = best["span_id"] if best else None
    nodes = {s["span_id"]: {"span": s, "children": []} for s in spans}
    roots: List[Dict] = []
    for s in spans:
        pid = parent_of.get(s["span_id"])
        if pid is not None:
            nodes[pid]["children"].append(nodes[s["span_id"]])
        else:
            roots.append(nodes[s["span_id"]])
    for node in nodes.values():
        node["children"].sort(key=lambda n: n["span"]["t0_us"])
    roots.sort(key=lambda n: n["span"]["t0_us"])
    return roots


def stitch_sources(trace_id: str,
                   sources: Sequence[Tuple[str, Optional[Dict]]]) -> Dict:
    """Stitch one trace from per-hop Chrome exports.

    ``sources`` is ``[(name, chrome_doc_or_None), ...]`` — None marks a
    hop that could not be scraped; it becomes an entry in
    ``stitch.gaps`` and the tree is returned PARTIAL rather than the
    whole request 500ing (the observable part of a degraded fleet is
    exactly what an operator needs while it is degraded).

    The result is simultaneously a valid Chrome trace (``traceEvents``
    rebuilt with one synthetic pid per source hop + process_name
    metadata, so Perfetto shows router/backend/tier as separate process
    tracks) and the structured form (``tree``, ``stitch``)."""
    spans: List[Dict] = []
    used: List[str] = []
    gaps: List[str] = []
    for name, doc in sources:
        if doc is None:
            gaps.append(name)
            continue
        used.append(name)
        spans.extend(s for s in spans_from_chrome(doc, name)
                     if s["trace_id"] == trace_id)
    events: List[Dict] = []
    pids = {name: i + 1 for i, name in enumerate(used)}
    for s in spans:
        events.append({
            "ph": "X", "name": s["name"], "cat": "obs",
            "ts": s["t0_us"], "dur": s["dur_us"],
            "pid": pids[s["source"]], "tid": 1,
            "args": {"trace_id": s["trace_id"], "span_id": s["span_id"],
                     "parent_id": s["parent_id"], "source": s["source"],
                     **s["attrs"]},
        })
    for name, pid in pids.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 1, "args": {"name": name}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "trace_id": trace_id,
        "tree": stitch_tree(spans),
        "stitch": {"sources": used, "gaps": gaps, "n_spans": len(spans)},
    }


class TailSampler:
    """Bounded tail-based trace retention ring.

    ``offer`` is called once per finished request with the outcome in
    hand; it KEEPS the trace id when the request errored (status >= 500)
    or ran slower than the live threshold the caller passes (the
    router's hop p99), and counts a deterministic drop otherwise.  The
    ring is bounded (LRU on insertion order) so retention can never be
    the thing that OOMs the router.
    """

    def __init__(self, capacity: int = 256):
        assert capacity >= 1, capacity
        self.capacity = capacity
        self._lock = threading.Lock()
        self._kept: "OrderedDict[str, Dict]" = OrderedDict()  # guarded_by: _lock
        self._dropped = 0  # guarded_by: _lock
        self._kept_error = 0  # guarded_by: _lock
        self._kept_slow = 0  # guarded_by: _lock
        self._evicted = 0  # guarded_by: _lock

    def offer(self, trace_id: Optional[str], duration_s: float,
              status: int, threshold_s: Optional[float] = None) -> bool:
        """Decide retention for one finished request; returns True when
        the trace was kept.  Pure function of the arguments — replaying
        identical traffic retains identical traces."""
        if not trace_id:
            return False  # unsampled: there are no spans to retain
        error = status >= 500
        slow = threshold_s is not None and duration_s > threshold_s
        if not (error or slow):
            with self._lock:
                self._dropped += 1
            return False
        record = {"trace_id": trace_id,
                  "duration_ms": round(duration_s * 1e3, 3),
                  "status": int(status),
                  "why": "error" if error else "slow"}
        with self._lock:
            if error:
                self._kept_error += 1
            else:
                self._kept_slow += 1
            self._kept[trace_id] = record
            self._kept.move_to_end(trace_id)
            while len(self._kept) > self.capacity:
                self._kept.popitem(last=False)
                self._evicted += 1
        return True

    def __contains__(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._kept

    def retained(self) -> List[Dict]:
        """Kept-trace records, oldest first (snapshot)."""
        with self._lock:
            return list(self._kept.values())

    def stats(self) -> Dict:
        with self._lock:
            return {"capacity": self.capacity, "kept": len(self._kept),
                    "dropped": self._dropped,
                    "kept_error": self._kept_error,
                    "kept_slow": self._kept_slow,
                    "evicted": self._evicted}
