"""Live SLO burn-rate alerting over the federated scrape
(docs/observability.md "Burn-rate alerts").

The SLO harness (loadgen/slo.py) renders VERDICTS after a replay ends;
this module answers "is the error budget burning RIGHT NOW" while
traffic is live.  It keeps a short history of federated counter
snapshots (obs/fleet.py), computes windowed error/shed rates and the
live hop p99, and turns them into multi-window BURN RATES: a burn of
1.0 means the class bound is being consumed exactly at its limit; N
means N times faster.

The class vocabulary deliberately mirrors ``loadgen.slo.SLOClass``
(same field names, same ``selector()`` string — a parity test pins
this), but it is re-declared here rather than imported: ``loadgen``
pulls in the serve client stack and the router is model-free, exactly
the reason ``ops/autoscale.py`` re-implements its capacity-model
loading (see that module's doc).

Multi-window rule (the standard fast+slow burn-rate pattern): PAGE
(state 2) only when BOTH the fast and the slow window burn at
``page_burn`` or faster — fast-only spikes are noise, slow-only means
the incident is already old news; WARN (state 1) when either window
burns at >= 1.0; OK (state 0) otherwise.  States are exported as
``fleet_alert_state{class=}`` and the page-qualified burn
(min(fast, slow), the quantity the page rule thresholds) feeds
``ops/autoscale.Autoscaler`` as a scale-up signal.

Stdlib-only: the router imports this and the router is model-free.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["AlertClass", "BurnRateAlerts", "ERROR_OUTCOMES"]

#: ``serve_requests_total{outcome=}`` values that consume error budget.
#: ``shed`` is budgeted separately (``max_shed_rate``) — load shedding
#: is a policy outcome, not a failure (docs/slo_harness.md).
ERROR_OUTCOMES = ("error", "timeout", "unavailable")


@dataclasses.dataclass(frozen=True)
class AlertClass:
    """One alerted traffic class — the live-alerting subset of
    ``loadgen.slo.SLOClass``'s vocabulary (field names and ``selector``
    format are identical by test-pinned contract; tests/test_fleet.py).

    Bounds ARE budgets: ``max_error_rate=0.01`` means 1% of requests
    may fail; an observed 2% error rate is a burn of 2.0.  Unset bounds
    (the defaults) never contribute burn."""

    tier: str = "*"
    priority: str = "*"
    p99_ms: float = math.inf
    max_shed_rate: float = 1.0
    max_error_rate: float = 1.0

    def __post_init__(self):
        assert self.p99_ms > 0, self.p99_ms
        assert 0 < self.max_shed_rate <= 1.0, self.max_shed_rate
        assert 0 < self.max_error_rate <= 1.0, self.max_error_rate

    def selector(self) -> str:
        return f"tier={self.tier},priority={self.priority}"


_STATE_NAMES = {0: "ok", 1: "warn", 2: "page"}


class BurnRateAlerts:
    """Rolling burn-rate evaluation over successive federated scrapes.

    ``observe(fleet_scrape, p99_s=...)`` is called on each evaluation
    (the router's ``GET /debug/alerts`` triggers one); it appends a
    counter snapshot, evaluates every class over the fast and slow
    windows, updates the ``fleet_alert_state{class=}`` /
    ``fleet_alert_burn`` gauges, and returns the full evaluation dict.
    """

    def __init__(self, registry, classes: Sequence[AlertClass] = (),
                 fast_window_s: float = 30.0,
                 slow_window_s: Optional[float] = None,
                 page_burn: float = 2.0):
        assert fast_window_s > 0, fast_window_s
        if slow_window_s is None:
            slow_window_s = 5.0 * fast_window_s
        assert slow_window_s >= fast_window_s, (slow_window_s,
                                                fast_window_s)
        assert page_burn >= 1.0, page_burn
        self.classes = tuple(classes) or (AlertClass(),)
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.page_burn = page_burn
        self._lock = threading.Lock()
        # (t, requests, errors, sheds) snapshots, oldest first
        self._snaps: deque = deque()  # guarded_by: _lock
        self._last: Optional[Dict] = None  # guarded_by: _lock
        self.alert_state = registry.gauge(
            "fleet_alert_state",
            "live burn-rate alert state per SLO class "
            "(0 = ok, 1 = warn, 2 = page; obs/alerts.py)",
            labels=("class",))
        self.alert_burn = registry.gauge(
            "fleet_alert_burn",
            "page-qualified error-budget burn rate per SLO class — "
            "min(fast, slow) window burn, 1.0 = budget consumed exactly "
            "at its limit",
            labels=("class",))

    # ------------------------------------------------------------ counts

    @staticmethod
    def _counts(scrape) -> Tuple[float, float, float]:
        """(requests, errors, sheds) fleet-wide from one parsed scrape.
        ``serve_requests_total`` is summed across every ``backend=``
        label the federator added — absent metric reads as 0."""
        m = scrape.get("serve_requests_total")
        requests = errors = sheds = 0.0
        if m is None:
            return requests, errors, sheds
        for litems, value in m.series("serve_requests_total"):
            labels = dict(litems)
            requests += value
            outcome = labels.get("outcome")
            if outcome in ERROR_OUTCOMES:
                errors += value
            elif outcome == "shed":
                sheds += value
        return requests, errors, sheds

    def _window_delta(self, now: float, window_s: float  # guarded_by: _lock
                      ) -> Tuple[float, float, float]:
        """Counter deltas over the trailing window: current snapshot
        minus the most recent snapshot at least ``window_s`` old (or
        the oldest held — a young history under-reports the window,
        which biases burn DOWN, never a false page)."""
        cur = self._snaps[-1]
        base = self._snaps[0]
        for snap in self._snaps:
            if snap[0] <= now - window_s:
                base = snap
            else:
                break
        return (cur[1] - base[1], cur[2] - base[2], cur[3] - base[3])

    # ---------------------------------------------------------- evaluate

    def observe(self, fleet_scrape, p99_s: Optional[float] = None,
                now: Optional[float] = None) -> Dict:
        """Snapshot + evaluate.  ``fleet_scrape`` is a
        ``fleet.FleetScrape`` (or anything with ``.scrape``);
        ``p99_s`` is the live hop p99 the caller reads from its
        latency histogram (``LatencyHistogram.quantile(0.99)``)."""
        t = time.time() if now is None else now
        scrape = getattr(fleet_scrape, "scrape", fleet_scrape)
        requests, errors, sheds = self._counts(scrape)
        with self._lock:
            self._snaps.append((t, requests, errors, sheds))
            horizon = t - 2.0 * self.slow_window_s
            while len(self._snaps) > 2 and self._snaps[1][0] < horizon:
                self._snaps.popleft()
            windows = {}
            for name, window_s in (("fast", self.fast_window_s),
                                   ("slow", self.slow_window_s)):
                dr, de, ds = self._window_delta(t, window_s)
                windows[name] = {
                    "window_s": window_s, "requests": dr,
                    "error_rate": (de / dr) if dr > 0 else 0.0,
                    "shed_rate": (ds / dr) if dr > 0 else 0.0,
                }
            evaluated: List[Dict] = []
            for cls in self.classes:
                burns = {}
                for name, w in windows.items():
                    burn = max(w["error_rate"] / cls.max_error_rate,
                               w["shed_rate"] / cls.max_shed_rate)
                    if p99_s is not None and math.isfinite(cls.p99_ms):
                        burn = max(burn, p99_s * 1e3 / cls.p99_ms)
                    burns[name] = burn
                paged = min(burns["fast"], burns["slow"])
                if paged >= self.page_burn:
                    state = 2
                elif max(burns["fast"], burns["slow"]) >= 1.0:
                    state = 1
                else:
                    state = 0
                sel = cls.selector()
                self.alert_state.labels(**{"class": sel}).set(state)
                self.alert_burn.labels(**{"class": sel}).set(
                    round(paged, 6))
                evaluated.append({
                    "class": sel, "state": state,
                    "state_name": _STATE_NAMES[state],
                    "burn_fast": round(burns["fast"], 6),
                    "burn_slow": round(burns["slow"], 6),
                    "burn": round(paged, 6),
                    "bounds": {"p99_ms": cls.p99_ms,
                               "max_error_rate": cls.max_error_rate,
                               "max_shed_rate": cls.max_shed_rate},
                })
            self._last = {
                "now_unix": round(t, 3),
                "page_burn": self.page_burn,
                "p99_ms": (round(p99_s * 1e3, 3)
                           if p99_s is not None else None),
                "windows": windows,
                "classes": evaluated,
                "scrape": {"sources": getattr(fleet_scrape, "sources",
                                              None),
                           "gaps": getattr(fleet_scrape, "gaps", None)},
            }
            return self._last

    def last(self) -> Optional[Dict]:
        """Most recent evaluation (None before the first observe)."""
        with self._lock:
            return self._last

    def max_burn(self) -> float:
        """Max page-qualified burn across classes from the LAST
        evaluation — the autoscaler's scale-up signal; 0.0 before any
        evaluation (never triggers a fresh fleet scrape: the gauge
        refresh path must stay cheap)."""
        with self._lock:
            if self._last is None:
                return 0.0
            return max((c["burn"] for c in self._last["classes"]),
                       default=0.0)
