"""Open-loop trace replay against a live server or router.

Drives a real ``/predict`` endpoint (single server or the model-free
``cli.router`` front-end — the client is the same) through
``ServeClient`` on the TRACE'S schedule, not the server's: each event
fires at its ``t_ms`` offset from replay start regardless of earlier
completions.  When the workers fall behind, the send still happens
immediately and the lag is RECORDED (``send_lag_ms`` on the row, late
count in the summary) — never silently rescheduled, because a harness
that quietly reshapes its offered load can't certify an SLO.

Session frames are the one ordering constraint: a stream's frames must
reach the server in seq_no order (out-of-order = documented cold
frame), so a worker holding frame k of a session blocks until frame
k-1's worker has finished sending.  Claims are handed out in event
order, so the wait chain always bottoms out at a frame that is actively
being sent — no deadlock (see ``_SessionGate``).

The pair content for event i is deterministic in (pair_seed, height,
width, index): replaying the same trace twice offers bitwise-identical
request bodies, which is what makes the double-replay determinism
assertion in tests/test_loadgen.py meaningful.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..serve.client import ServeClient, ServeError
from .records import Recorder, RequestRow
from .trace import TraceEvent

__all__ = ["ReplayConfig", "pair_provider", "replay"]


@dataclasses.dataclass(frozen=True)
class ReplayConfig:
    """How to drive the endpoint (the WHAT lives in the trace)."""

    host: str = "127.0.0.1"
    port: int = 8080
    concurrency: int = 4
    timeout_s: float = 120.0
    retries: int = 0
    pair_seed: int = 0
    pool_size: int = 4      # distinct pairs per resolution
    speed: float = 1.0      # >1 replays the trace faster than recorded
    # /predict dialect (docs/wire_format.md): "binary" wire frames or
    # the legacy base64 "json" — replaying the SAME trace under both is
    # how the SLO harness states the wire-bytes/pair reduction.
    wire_format: str = "binary"
    response_encoding: str = "f32"  # binary replies: bitwise | int16
    # Upper bound on waiting for a session predecessor before the frame
    # is recorded as an error (a crashed predecessor worker must not
    # hang the replay).
    gate_timeout_s: float = 300.0


def pair_provider(seed: int, pool_size: int = 4
                  ) -> Callable[[TraceEvent], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic ``make_pair(event)``: a lazily-built pool of
    ``pool_size`` pairs per resolution, seeded by (seed, h, w) only —
    event i draws pool entry ``i % pool_size``, so the i-th request's
    bytes are a pure function of the trace and the seed."""
    pools: Dict[Tuple[int, int], List] = {}
    lock = threading.Lock()

    def make_pair(ev: TraceEvent) -> Tuple[np.ndarray, np.ndarray]:
        key = (ev.height, ev.width)
        with lock:
            pool = pools.get(key)
            if pool is None:
                rng = np.random.default_rng((seed, ev.height, ev.width))
                pool = pools[key] = [
                    (rng.integers(0, 255, (*key, 3)).astype(np.float32),
                     rng.integers(0, 255, (*key, 3)).astype(np.float32))
                    for _ in range(max(1, pool_size))]
        return pool[ev.index % len(pool)]

    return make_pair


class _SessionGate:
    """Per-session frame ordering: ``wait(session, k)`` blocks until
    k frames of that session have been RELEASED (sent or failed).

    Safety: claims are issued in event-index order and a session's
    frames are index-ordered in the trace, so frame k-1 is always
    claimed before frame k — the blocked worker's predecessor is either
    mid-send (progress) or waiting on ITS predecessor, and the chain
    terminates at seq 0, which never waits.  A failed send still
    releases (the successor then becomes a genuine out_of_order cold
    frame at the server — the harness observes it, it doesn't hide it).
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._done: Dict[str, int] = {}  # guarded_by: _cond

    def wait(self, session: str, k: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._done.get(session, 0) < k:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def release(self, session: str) -> None:
        with self._cond:
            self._done[session] = self._done.get(session, 0) + 1
            self._cond.notify_all()


def replay(events: Sequence[TraceEvent], cfg: ReplayConfig,
           make_pair: Optional[Callable] = None,
           on_result: Optional[Callable] = None,
           chaos=None) -> Recorder:
    """Replay ``events`` against ``cfg.host:cfg.port``; returns the
    recorder holding one ``RequestRow`` per event.

    ``on_result(event, disparity, meta)`` runs (serialised under a
    lock) for every 200 reply — the hook the determinism test uses to
    capture disparities without the replay path knowing about it.

    ``chaos`` (a ``loadgen.chaos.ChaosController``) is started on the
    SAME clock base and speed as the sends, so its fault armings land
    at their declared trace offsets relative to the offered load —
    that alignment is what makes degraded-window SLO verdicts
    meaningful (docs/slo_harness.md "Chaos mode").
    """
    events = sorted(events, key=lambda e: (e.t_ms, e.index))
    make_pair = make_pair or pair_provider(cfg.pair_seed, cfg.pool_size)
    recorder = Recorder()
    gate = _SessionGate()
    result_lock = threading.Lock()
    claim_lock = threading.Lock()
    next_slot = [0]
    # Per-session ordinal of each frame (position within the session's
    # frame list, which seq_no need not equal if a trace hand-skips).
    ordinal: Dict[int, int] = {}
    seen: Dict[str, int] = {}
    for ev in events:
        if ev.session is not None:
            ordinal[ev.index] = seen.get(ev.session, 0)
            seen[ev.session] = ordinal[ev.index] + 1

    t_start = time.perf_counter()
    if chaos is not None:
        chaos.start(t_start, speed=cfg.speed)

    def claim() -> Optional[TraceEvent]:
        with claim_lock:
            slot = next_slot[0]
            if slot >= len(events):
                return None
            next_slot[0] += 1
            return events[slot]

    def run_one(client: ServeClient, ev: TraceEvent) -> None:
        sched_ms = ev.t_ms / cfg.speed
        gated = True
        if ev.session is not None and ordinal[ev.index] > 0:
            gated = gate.wait(ev.session, ordinal[ev.index],
                              cfg.gate_timeout_s)
        delay = t_start + sched_ms / 1e3 - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        send_ms = (time.perf_counter() - t_start) * 1e3
        lag_ms = max(0.0, send_ms - sched_ms)
        row = dict(index=ev.index, t_sched_ms=sched_ms, t_send_ms=send_ms,
                   send_lag_ms=lag_ms,
                   tier=ev.tier or "default", priority=ev.priority or "",
                   deadline_ms=ev.deadline_ms, iters=ev.iters,
                   height=ev.height, width=ev.width,
                   session=ev.session or "", seq_no=ev.seq_no,
                   wire=client.wire_format)
        sent0, recv0 = client.bytes_sent, client.bytes_received

        def used() -> Dict[str, int]:
            # Byte deltas are per-request because each worker owns its
            # client (the counters are never shared across threads).
            return dict(bytes_sent=client.bytes_sent - sent0,
                        bytes_received=client.bytes_received - recv0)

        if not gated:
            recorder.add(RequestRow(outcome="error", latency_ms=math.nan,
                                    **row))
            return
        left, right = make_pair(ev)
        t0 = time.perf_counter()
        try:
            disparity, meta = client.predict(
                left, right, iters=ev.iters, session_id=ev.session,
                seq_no=ev.seq_no, deadline_ms=ev.deadline_ms,
                priority=ev.priority, accuracy=ev.tier,
                spatial=ev.spatial)
        except ServeError as e:
            outcome = {503: "shed", 504: "timeout"}.get(e.status, "error")
            recorder.add(RequestRow(
                outcome=outcome, latency_ms=(time.perf_counter() - t0) * 1e3,
                status=e.status, request_id=e.request_id or "",
                **used(), **row))
        except Exception:
            recorder.add(RequestRow(outcome="error", latency_ms=math.nan,
                                    **used(), **row))
        else:
            latency_ms = (time.perf_counter() - t0) * 1e3
            hit = None
            if ev.deadline_ms is not None:
                hit = latency_ms <= ev.deadline_ms
            recorder.add(RequestRow(
                outcome="ok", latency_ms=latency_ms, status=200,
                deadline_hit=hit, iters_done=meta.get("iters"),
                warm=meta.get("warm"),
                cascade=meta.get("cascade") or "",
                promoted_early=meta.get("promoted_early"),
                degraded=bool(meta.get("degraded", False)),
                backend=meta.get("backend", ""),
                request_id=meta.get("request_id") or "",
                **used(), **row))
            if on_result is not None:
                with result_lock:
                    on_result(ev, disparity, meta)

    def worker():
        client = ServeClient(cfg.host, cfg.port, timeout=cfg.timeout_s,
                             retries=cfg.retries,
                             wire_format=cfg.wire_format,
                             response_encoding=cfg.response_encoding)
        try:
            while True:
                ev = claim()
                if ev is None:
                    return
                try:
                    run_one(client, ev)
                finally:
                    if ev.session is not None:
                        gate.release(ev.session)
        finally:
            client.close()

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"replay-{i}")
               for i in range(cfg.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if chaos is not None:
        # All sends are done; any not-yet-due action would land after
        # the traffic it was meant to shape — stop instead of arming
        # faults into an idle cluster.
        chaos.stop()
    return recorder
