"""Versioned chaos plans scheduled against trace time during replay.

A :class:`ChaosPlan` is the fault-side twin of a loadgen trace: a list
of :class:`ChaosAction` rows, each "at trace offset ``t_ms``, arm this
``utils/faults.py`` spec on this named target".  The
:class:`ChaosController` runs the plan on the REPLAY'S clock — the same
``t_start`` and ``speed`` the open-loop sender uses — by POSTing each
action's spec to the target's ``/debug/faults`` arming endpoint
(serve/server.py and serve/cluster/router.py both expose it).  Faults
are therefore injected at declared trace offsets, which is what lets
``loadgen/slo.py``'s :class:`~raftstereo_tpu.loadgen.slo.DegradedWindow`
bounds line up with the fault windows: the plan DECLARES when service is
allowed to degrade, the verdict checks that it degraded no further and
recovered on time.

Plans are JSON on disk (``save``/``load``) with an explicit format tag +
version, like traces and capacity models — a chaos certification is only
reproducible if the fault schedule is an artifact, not a shell script.

Every fault spec is validated against the fault grammar at plan
construction (``FaultPlan.parse``), so a typo fails when the plan is
BUILT, not minutes into a replay.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.faults import FaultPlan
from .slo import DegradedWindow

__all__ = ["ChaosAction", "ChaosPlan", "ChaosController",
           "CHAOS_FORMAT", "CHAOS_VERSION"]

logger = logging.getLogger(__name__)

CHAOS_FORMAT = "raftstereo_tpu.chaos"
CHAOS_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ChaosAction:
    """One arming event: at trace offset ``t_ms``, POST ``faults`` (a
    ``utils/faults.py`` spec string) to the target named ``target``.

    Targets are LOGICAL names ("router", "b0", ...) resolved to
    host:port at replay time — the plan artifact stays portable across
    port assignments.  Timed faults (``@t_ms=OFFSET``) measure their
    offset from ARMING, so an action's effective window is
    ``t_ms + offset`` in trace time."""

    t_ms: float
    target: str
    faults: str

    def __post_init__(self):
        if self.t_ms < 0:
            raise ValueError(f"chaos action t_ms must be >= 0: {self.t_ms}")
        if not self.target:
            raise ValueError("chaos action needs a target name")
        # Validate the spec against the grammar now, not mid-replay.
        FaultPlan.parse(self.faults)


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """The whole schedule plus the degraded-mode bounds it justifies."""

    actions: Tuple[ChaosAction, ...] = ()
    windows: Tuple[DegradedWindow, ...] = ()

    def degraded_windows(self) -> Tuple[DegradedWindow, ...]:
        """The declared degraded-mode windows, for ``SLOSpec.windows``."""
        return self.windows

    def to_json(self) -> Dict:
        return {
            "chaos_plan": CHAOS_FORMAT,
            "version": CHAOS_VERSION,
            "actions": [dataclasses.asdict(a) for a in
                        sorted(self.actions, key=lambda a: a.t_ms)],
            "windows": [dataclasses.asdict(w) for w in self.windows],
        }

    @classmethod
    def from_json(cls, data: Dict) -> "ChaosPlan":
        if data.get("chaos_plan") != CHAOS_FORMAT:
            raise ValueError(
                f"not a chaos plan (chaos_plan={data.get('chaos_plan')!r})")
        version = data.get("version")
        if version != CHAOS_VERSION:
            raise ValueError(
                f"chaos plan version {version!r} not supported "
                f"(this build reads version {CHAOS_VERSION})")
        actions = tuple(ChaosAction(**a) for a in data.get("actions", ()))
        windows = tuple(DegradedWindow(**w) for w in data.get("windows", ()))
        return cls(actions=actions, windows=windows)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "ChaosPlan":
        with open(path) as f:
            return cls.from_json(json.load(f))


def _arm(host: str, port: int, spec: str, timeout_s: float) -> Dict:
    """POST one spec to ``/debug/faults``; raises on refusal."""
    body = json.dumps({"faults": spec}).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("POST", "/debug/faults", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(
                f"/debug/faults on {host}:{port} refused {spec!r}: "
                f"{resp.status} {data[:200]!r}")
        return json.loads(data)
    finally:
        conn.close()


class ChaosController:
    """Runs a plan's actions on the replay clock, in its own thread.

    ``targets`` maps each action's logical target name to ``(host,
    port)``.  The controller is handed the replay's ``t_start`` (a
    ``time.perf_counter()`` stamp) and ``speed`` by ``replay()`` so
    action offsets land on the same compressed timeline as the sends.
    Arming failures are COUNTED (``chaos_actions_total{outcome=
    "failed"}``) and logged, never raised — a chaos harness that dies
    when its fault landed on an already-dead backend certifies nothing.
    """

    def __init__(self, plan: ChaosPlan,
                 targets: Dict[str, Tuple[str, int]],
                 timeout_s: float = 10.0, metrics=None):
        missing = sorted({a.target for a in plan.actions} - set(targets))
        if missing:
            raise ValueError(
                f"chaos plan targets not mapped: {missing} "
                f"(known: {sorted(targets)})")
        self.plan = plan
        self.targets = dict(targets)
        self.timeout_s = timeout_s
        self.metrics = metrics  # LoadgenMetrics or None
        self.results: List[Dict] = []  # guarded_by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, t_start: float, speed: float = 1.0) -> "ChaosController":
        self._thread = threading.Thread(
            target=self._run, args=(t_start, max(speed, 1e-9)),
            name="chaos-controller", daemon=True)
        self._thread.start()
        return self

    def join(self, timeout_s: float = 60.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)

    def stop(self) -> None:
        self._stop.set()
        self.join()

    # ------------------------------------------------------------------

    def _count(self, spec: str, outcome: str) -> None:
        if self.metrics is None:
            return
        # One count per fault KIND in the spec: the metric answers "how
        # many slow_replica armings failed", not "how many POSTs".
        for fault in FaultPlan.parse(spec).faults:
            self.metrics.chaos_actions.labels(
                kind=fault.kind, outcome=outcome).inc()

    def _run(self, t_start: float, speed: float) -> None:
        for action in sorted(self.plan.actions, key=lambda a: a.t_ms):
            due = t_start + action.t_ms / 1e3 / speed
            while True:
                delay = due - time.perf_counter()
                if delay <= 0:
                    break
                if self._stop.wait(min(delay, 0.05)):
                    return
            host, port = self.targets[action.target]
            record = {"t_ms": action.t_ms, "target": action.target,
                      "faults": action.faults}
            try:
                reply = _arm(host, port, action.faults, self.timeout_s)
            except Exception as e:
                logger.error("chaos: arming %r on %s (%s:%d) failed: %s",
                             action.faults, action.target, host, port, e)
                record.update(outcome="failed", error=str(e))
                self._count(action.faults, "failed")
            else:
                logger.info("chaos: armed %r on %s (%s:%d)",
                            action.faults, action.target, host, port)
                record.update(outcome="armed", armed=reply.get("armed"))
                self._count(action.faults, "armed")
            with self._lock:
                self.results.append(record)

    def summary(self) -> Dict:
        with self._lock:
            results = list(self.results)
        return {"actions": len(self.plan.actions),
                "armed": sum(1 for r in results if r["outcome"] == "armed"),
                "failed": sum(1 for r in results
                              if r["outcome"] == "failed"),
                "results": results}
