"""Per-request result rows + the thread-safe recorder they land in.

One ``RequestRow`` per request is the harness's unit of truth: the SLO
report (slo.py), the capacity fit (capacity.py) and ``run_load``'s
legacy summary are all pure functions over the recorded rows — no
aggregate is maintained anywhere else, so every number in a verdict can
be re-derived from the rows it cites.

Deliberately stdlib-only (dataclasses + threading + math): the recorder
is imported by ``serve/client.py`` (whose ``run_load`` summarises
through it) and must not drag the rest of the harness — let alone the
model stack — into client-side tooling.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Recorder", "RequestRow", "percentile", "summarize",
           "wire_bytes"]

#: Row outcomes, in the order the legacy ``run_load`` counted them.
OUTCOMES = ("ok", "shed", "timeout", "error")


@dataclasses.dataclass(frozen=True)
class RequestRow:
    """One replayed request, fully described.

    Times are milliseconds.  ``t_sched_ms``/``t_send_ms`` are offsets
    from the replay's t=0 (``nan`` for closed-loop traffic, which has no
    schedule); ``send_lag_ms`` is how late the send left relative to the
    schedule (0.0 = on time or early).  ``latency_ms`` is send-to-reply
    wall clock (``nan`` when no reply arrived).  ``deadline_hit`` is
    None when the request carried no deadline.
    """

    index: int
    outcome: str                      # ok | shed | timeout | error
    latency_ms: float
    t_sched_ms: float = math.nan
    t_send_ms: float = math.nan
    send_lag_ms: float = 0.0
    status: int = 0                   # HTTP status (0 = transport error)
    tier: str = "default"
    priority: str = ""
    deadline_ms: Optional[float] = None
    deadline_hit: Optional[bool] = None
    iters: Optional[int] = None       # requested target (None = default)
    iters_done: Optional[int] = None  # from response meta
    height: int = 0
    width: int = 0
    session: str = ""
    seq_no: Optional[int] = None
    warm: Optional[bool] = None       # session frames: warm-start engaged
    # Speculative tier cascade (serve/cascade/): the canonical schedule
    # that served this request ("" = single-tier path) and whether the
    # divergence trigger promoted it to the certified tier early.
    cascade: str = ""
    promoted_early: Optional[bool] = None
    degraded: bool = False
    backend: str = ""                 # X-Backend via the router
    request_id: str = ""
    wire: str = "json"                # request dialect: json | binary
    bytes_sent: int = 0               # request body bytes on the wire
    bytes_received: int = 0           # response body bytes on the wire

    def bucket(self) -> str:
        """Capacity-model bucket key: tier|iters|HxW (docs/slo_harness.md)."""
        iters = "auto" if self.iters is None else str(self.iters)
        return f"{self.tier}|{iters}|{self.height}x{self.width}"


class Recorder:
    """Append-only, thread-safe row store (load-gen workers share one)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: List[RequestRow] = []  # guarded_by: _lock

    def add(self, row: RequestRow) -> None:
        with self._lock:
            self._rows.append(row)

    def rows(self) -> Tuple[RequestRow, ...]:
        """Snapshot in append order (NOT request-index order under
        concurrency — sort by ``index`` for stream comparisons)."""
        with self._lock:
            return tuple(self._rows)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


def percentile(values: Sequence[float], q: float) -> float:
    """Exact q-th percentile (q in [0, 100], linear interpolation
    between order statistics — numpy's default, without numpy)."""
    assert 0.0 <= q <= 100.0, q
    vs = sorted(values)
    if not vs:
        return math.nan
    if len(vs) == 1:
        return vs[0]
    pos = (q / 100.0) * (len(vs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vs) - 1)
    frac = pos - lo
    return vs[lo] * (1.0 - frac) + vs[hi] * frac


def outcome_counts(rows: Sequence[RequestRow]) -> Dict[str, int]:
    counts = {k: 0 for k in OUTCOMES}
    for r in rows:
        counts[r.outcome] = counts.get(r.outcome, 0) + 1
    return counts


def backend_split(rows: Sequence[RequestRow]) -> Dict[str, int]:
    """ok rows per answering backend (empty when not behind a router)."""
    split: Dict[str, int] = {}
    for r in rows:
        if r.outcome == "ok" and r.backend:
            split[r.backend] = split.get(r.backend, 0) + 1
    return split


def summarize(rows: Sequence[RequestRow], *, mode: str, requests: int,
              concurrency: int, wall_s: float,
              rate: Optional[float] = None,
              sequence_len: Optional[int] = None) -> Dict:
    """The legacy ``run_load`` stats dict, computed from rows.

    Key set and presence conditions are the historical contract
    (bench.py, cli.serve --loadgen and their tests consume it):
    percentiles only when ok rows exist; ``late_sends``/
    ``send_lag_p99_ms`` only for rate-driven traffic; ``warm_frames``/
    ``cold_frames``/``sequence_len`` only under sequence replay.
    Percentiles are exact over the rows (the old path interpolated
    histogram buckets — same keys, sharper values).
    """
    counts = outcome_counts(rows)
    stats = {
        "mode": mode, "requests": requests, "concurrency": concurrency,
        "wall_s": round(wall_s, 3),
        "pairs_per_sec": (round(counts["ok"] / wall_s, 4)
                          if wall_s else 0.0),
        **counts,
    }
    if sequence_len is not None:
        stats["warm_frames"] = sum(1 for r in rows
                                   if r.outcome == "ok" and r.warm)
        stats["cold_frames"] = sum(1 for r in rows
                                   if r.outcome == "ok" and not r.warm)
        stats["sequence_len"] = sequence_len
    if rate:
        late = [r.send_lag_ms for r in rows if r.send_lag_ms > 0.0]
        stats["offered_rate"] = rate
        # How far behind schedule sends fell (0 = on time): large values
        # mean concurrency was too low for the offered rate and the run
        # degraded toward closed-loop.
        stats["late_sends"] = len(late)
        stats["send_lag_p99_ms"] = (round(percentile(late, 99), 2)
                                    if late else 0.0)
    lats = [r.latency_ms for r in rows if r.outcome == "ok"
            and not math.isnan(r.latency_ms)]
    if lats:
        stats.update(p50_ms=round(percentile(lats, 50), 2),
                     p90_ms=round(percentile(lats, 90), 2),
                     p99_ms=round(percentile(lats, 99), 2))
    wb = wire_bytes(rows)
    if wb is not None:
        stats.update(wb)
    split = backend_split(rows)
    if split:
        stats["backends"] = dict(sorted(split.items()))
    return stats


def wire_bytes(rows: Sequence[RequestRow]) -> Optional[Dict]:
    """Wire-byte summary over ok rows (None when nothing was counted —
    rows recorded by a pre-wire client).  ``wire_bytes_per_pair`` is the
    round-trip mean (request body + response body), the number the SLO
    verdict states alongside latency (docs/wire_format.md)."""
    ok = [r for r in rows
          if r.outcome == "ok" and (r.bytes_sent or r.bytes_received)]
    if not ok:
        return None
    total = sum(r.bytes_sent + r.bytes_received for r in ok)
    return {
        "wire_format": ok[0].wire,
        "wire_bytes_per_pair": round(total / len(ok), 1),
        "wire_mb_sent": round(sum(r.bytes_sent for r in ok) / 2 ** 20, 3),
        "wire_mb_received": round(sum(r.bytes_received for r in ok)
                                  / 2 ** 20, 3),
    }
