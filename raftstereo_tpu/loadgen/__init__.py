"""Trace-driven SLO harness + capacity model (docs/slo_harness.md).

The bridge from "pairs/s on this box" to "N chips serve M users at
SLO", in four pieces:

* ``trace``    — versioned JSONL trace grammar (bursty arrivals,
                 session create/churn/close, tiers, priorities,
                 deadlines, iteration targets, resolution mix, spatial
                 pairs) + seeded deterministic generators
                 (poisson/burst/diurnal).
* ``replay``   — open-loop replay engine: drives a real server or the
                 ``cli.router`` cluster through ``ServeClient`` on the
                 trace's schedule (late sends counted, never silently
                 rescheduled), one ``records.RequestRow`` per request.
* ``slo``      — SLO spec + assertion report: per-(tier, priority)
                 p50/p99, shed/deadline-hit/cold-frame rates,
                 validator-clean ``/metrics`` deltas, retrace budget —
                 one machine-readable JSON verdict.
* ``capacity`` — requests/s/chip as f(tier, iters, resolution), fit
                 from a replay; feeds ``ops/autoscale.Autoscaler`` and
                 answers what-ifs via ``cli.loadgen`` / ``bench.py
                 --slo``.

``records`` (the row store) and ``capacity`` are stdlib-only — they
are imported by client tooling and the model-free router's autoscaler.
"""

import importlib

# Lazy (PEP 562) exports, same contract as raftstereo_tpu.serve:
# importing the package must stay cheap — ``records``/``capacity`` are
# stdlib, but ``replay`` pulls ServeClient (numpy + the serve package)
# which the router-side capacity consumer has no use for.
_EXPORTS = {
    "Recorder": ".records",
    "RequestRow": ".records",
    "summarize": ".records",
    "TraceEvent": ".trace",
    "TraceSpec": ".trace",
    "generate": ".trace",
    "read_trace": ".trace",
    "write_trace": ".trace",
    # The replay() FUNCTION is deliberately NOT exported: it shares its
    # name with the submodule, and `from raftstereo_tpu.loadgen import
    # replay` would resolve to the function or the module depending on
    # import order.  Call sites import it from the submodule:
    # `from raftstereo_tpu.loadgen.replay import replay`.
    "ReplayConfig": ".replay",
    "pair_provider": ".replay",
    "SLOClass": ".slo",
    "SLOSpec": ".slo",
    "evaluate": ".slo",
    "fit": ".capacity",
    "load_model": ".capacity",
    "save_model": ".capacity",
    "sustainable_rps": ".capacity",
    "whatif": ".capacity",
    "LoadgenMetrics": ".metrics",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        rel = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(rel, __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
