"""Harness-side metrics: what the LOAD GENERATOR itself observed.

The server has its own story under ``/metrics``; this bundle is the
client-side counterpart so a long-running replay (cli.loadgen, soak
rigs) can expose its offered load and verdict history through the same
registry/exposition machinery — and so the ``loadgen_*``/``slo_*``
naming is enforced by the RSA50x metric lint like every other family
(analysis/metrics_lint.py instantiates + renders this bundle).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..serve.metrics import MetricsRegistry
from .records import RequestRow

__all__ = ["LoadgenMetrics"]


class LoadgenMetrics:
    """Every instrument the replay harness records, in one bundle."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "loadgen_requests_total",
            "replayed requests by client-observed outcome "
            "(ok/shed/timeout/error) and accuracy tier "
            "('default' = no tier requested)",
            labels=("outcome", "tier"))
        self.late_sends = r.counter(
            "loadgen_late_sends_total",
            "sends that left after their trace-scheduled time (the lag "
            "is recorded, the send is never rescheduled — "
            "docs/slo_harness.md)")
        self.send_lag = r.histogram(
            "loadgen_send_lag_seconds",
            "scheduled-vs-actual send lag for late sends (0 "
            "observations = the replay held the trace's schedule)")
        self.latency = r.histogram(
            "loadgen_request_latency_seconds",
            "client-observed send-to-reply latency per ok request "
            "(includes network + router hop, unlike the server's own "
            "serve_request_latency_seconds)")
        self.chaos_actions = r.counter(
            "chaos_actions_total",
            "chaos-plan fault armings POSTed to /debug/faults during "
            "replay, by fault kind and outcome (armed/failed) — "
            "loadgen/chaos.py, docs/fault_tolerance.md",
            labels=("kind", "outcome"))
        self.slo_checks = r.counter(
            "slo_checks_total",
            "individual SLO checks evaluated, by status (pass/fail)",
            labels=("status",))
        self.slo_pass = r.gauge(
            "slo_pass",
            "1 when the most recent SLO verdict passed every check, "
            "else 0")

    def observe_rows(self, rows: Sequence[RequestRow]) -> None:
        for row in rows:
            self.requests.labels(outcome=row.outcome, tier=row.tier).inc()
            if row.send_lag_ms > 0.0:
                self.late_sends.inc()
                self.send_lag.observe(row.send_lag_ms / 1e3)
            if row.outcome == "ok":
                self.latency.observe(row.latency_ms / 1e3)

    def observe_verdict(self, verdict: Dict) -> None:
        for c in verdict.get("checks", ()):
            status = "pass" if c.get("pass") else "fail"
            self.slo_checks.labels(status=status).inc()
        self.slo_pass.set(1.0 if verdict.get("pass") else 0.0)

    def render(self) -> str:
        return self.registry.render()
