"""Capacity model: requests/s/chip as f(tier, iters, resolution).

Fit from replayed rows, saved as versioned JSON, consumed by
``ops/autoscale.Autoscaler`` and the ``cli.loadgen whatif`` verb — the
bridge from "pairs/s on this box" to "N chips serve M users at SLO".

The fit is THROUGHPUT ACCOUNTING, not queueing theory: client-observed
latency mass allocates the measured busy chip-seconds across
(tier, iters, resolution) buckets, giving a per-bucket service-time
estimate ``service_s`` (chip-seconds per request) and its reciprocal
``rps_per_chip``.  Utilisation is estimated from the same rows
(Little's law: mean concurrency-in-service over the wall), so a fit
taken at saturation — the only regime where "sustainable rate" is even
observable — predicts the observed rate by construction, and what-ifs
interpolate between buckets by traffic mix:

    sustainable_rps(model, chips=N, mix={bucket: weight})
        = N / Σ mix_b · service_s_b

Deliberately stdlib-only: the saved JSON feeds the model-free router's
autoscaler, and the maths is a few sums.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Sequence

from .records import RequestRow

__all__ = ["chips_for", "fit", "load_model", "save_model",
           "sustainable_rps", "users_served", "whatif"]

CAPACITY_FORMAT = "raftstereo_tpu.loadgen.capacity"
CAPACITY_VERSION = 1


def fit(rows: Sequence[RequestRow], *, chips: int, wall_s: float) -> Dict:
    """Fit the model from one replay's rows against ``chips`` chips.

    Only ok rows carry service information; shed/timeout/error rows are
    counted but allocate no chip time.  ``utilization`` is
    Σ latency / (wall × chips) clamped to 1 — at saturation the clamp
    makes the accounting exact; below saturation latency ≈ service time
    and the estimate is simply conservative (queue wait inflates it).
    """
    assert chips >= 1, chips
    assert wall_s > 0, wall_s
    ok = [r for r in rows if r.outcome == "ok"
          and not math.isnan(r.latency_ms)]
    n_ok = len(ok)
    lat_mass_s = sum(r.latency_ms for r in ok) / 1e3
    utilization = min(1.0, lat_mass_s / (wall_s * chips)) if n_ok else 0.0
    busy_chip_s = wall_s * chips * utilization
    per_chip_rps = (n_ok / busy_chip_s) if busy_chip_s > 0 else 0.0

    buckets: Dict[str, Dict] = {}
    for r in ok:
        b = buckets.setdefault(r.bucket(), {"count": 0, "lat_s": 0.0})
        b["count"] += 1
        b["lat_s"] += r.latency_ms / 1e3
    out_buckets: Dict[str, Dict] = {}
    for key, b in sorted(buckets.items()):
        # Allocate busy chip-seconds proportional to latency mass: a
        # bucket whose requests spend 2x longer in the system gets 2x
        # the service-time estimate, independent of queue-wait skew
        # between buckets at similar depth.
        share = (b["lat_s"] / lat_mass_s) if lat_mass_s > 0 else 0.0
        service_s = (share * busy_chip_s / b["count"]) if b["count"] \
            else math.inf
        out_buckets[key] = {
            "count": b["count"],
            "mean_latency_ms": round(b["lat_s"] / b["count"] * 1e3, 3),
            "service_s": round(service_s, 6),
            "rps_per_chip": (round(1.0 / service_s, 4)
                             if service_s > 0 else 0.0),
        }
    return {
        "capacity_model": CAPACITY_FORMAT,
        "version": CAPACITY_VERSION,
        "chips": chips,
        "wall_s": round(wall_s, 3),
        "requests": len(rows),
        "ok": n_ok,
        "utilization": round(utilization, 4),
        "per_chip_rps": round(per_chip_rps, 4),
        "buckets": out_buckets,
    }


def _mix(model: Dict, mix: Optional[Dict[str, float]]) -> Dict[str, float]:
    """Normalised traffic mix; default = the fit's observed mix."""
    buckets = model["buckets"]
    if mix is None:
        mix = {k: float(b["count"]) for k, b in buckets.items()}
    unknown = sorted(set(mix) - set(buckets))
    if unknown:
        raise ValueError(f"mix buckets not in model: {unknown} "
                         f"(known: {sorted(buckets)})")
    total = sum(mix.values())
    if total <= 0:
        raise ValueError("traffic mix has no mass")
    return {k: v / total for k, v in mix.items() if v > 0}


def sustainable_rps(model: Dict, *, chips: Optional[int] = None,
                    mix: Optional[Dict[str, float]] = None) -> float:
    """Aggregate requests/s ``chips`` can sustain for a traffic mix."""
    chips = model["chips"] if chips is None else chips
    weights = _mix(model, mix)
    mean_service = sum(w * model["buckets"][k]["service_s"]
                       for k, w in weights.items())
    return chips / mean_service if mean_service > 0 else 0.0


def chips_for(model: Dict, target_rps: float, *,
              mix: Optional[Dict[str, float]] = None,
              headroom: float = 0.0) -> int:
    """Minimum chips for ``target_rps`` with ``headroom`` (0.2 = plan
    at 80% of fitted capacity)."""
    assert 0.0 <= headroom < 1.0, headroom
    if target_rps <= 0:
        return 0
    per_chip = sustainable_rps(model, chips=1, mix=mix) * (1.0 - headroom)
    if per_chip <= 0:
        raise ValueError("model has zero per-chip capacity")
    return max(1, math.ceil(target_rps / per_chip))


def users_served(model: Dict, *, chips: Optional[int] = None,
                 rps_per_user: float = 1.0,
                 mix: Optional[Dict[str, float]] = None,
                 headroom: float = 0.0) -> int:
    """The headline number: M users at ``rps_per_user`` each."""
    assert rps_per_user > 0, rps_per_user
    rate = sustainable_rps(model, chips=chips, mix=mix) * (1.0 - headroom)
    return int(rate / rps_per_user)


def whatif(model: Dict, *, chips: Optional[int] = None,
           target_rps: Optional[float] = None,
           rps_per_user: float = 1.0, headroom: float = 0.1,
           mix: Optional[Dict[str, float]] = None) -> Dict:
    """One JSON answer for the cli verb: capacity at N chips and/or
    chips needed for a target rate."""
    out: Dict = {"model_chips": model["chips"],
                 "per_chip_rps": model["per_chip_rps"],
                 "headroom": headroom}
    n = model["chips"] if chips is None else chips
    rate = sustainable_rps(model, chips=n, mix=mix)
    out["chips"] = n
    out["sustainable_rps"] = round(rate, 4)
    out["planned_rps"] = round(rate * (1.0 - headroom), 4)
    out["users_served"] = users_served(model, chips=n,
                                       rps_per_user=rps_per_user,
                                       mix=mix, headroom=headroom)
    out["rps_per_user"] = rps_per_user
    if target_rps is not None:
        out["target_rps"] = target_rps
        out["chips_for_target"] = chips_for(model, target_rps, mix=mix,
                                            headroom=headroom)
    return out


def save_model(model: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(model, f, indent=2, sort_keys=True)
        f.write("\n")


def load_model(path: str) -> Dict:
    with open(path) as f:
        model = json.load(f)
    if model.get("capacity_model") != CAPACITY_FORMAT:
        raise ValueError(f"{path}: not a {CAPACITY_FORMAT} file")
    if model.get("version") != CAPACITY_VERSION:
        raise ValueError(f"{path}: capacity model version "
                         f"{model.get('version')} != supported "
                         f"{CAPACITY_VERSION}")
    return model
