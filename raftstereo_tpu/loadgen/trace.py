"""Versioned JSONL trace grammar + seeded synthetic-trace generators.

A trace is the unit of reproducible load: one header line followed by
one JSON object per request event, arrival-ordered.  The grammar covers
everything the serving stack can be asked to do — bursty arrival
timestamps, session create/churn/close, accuracy tier, priority,
deadline_ms, explicit iteration targets, a resolution mix, and
oversized pairs for the spatial path:

    {"trace": "raftstereo_tpu.loadgen", "version": 1, "seed": 7, ...}
    {"i": 0, "t_ms": 12.4, "h": 60, "w": 90, "tier": "fast",
     "priority": "high", "deadline_ms": 2000.0}
    {"i": 1, "t_ms": 31.0, "h": 60, "w": 90, "session": "s0",
     "seq_no": 0}
    ...

Omitted fields mean "server default" (no tier, no priority, no
deadline, controller-owned iterations).  Session frames never carry
priority/deadline/iters — the server rejects that combination (400,
docs/serving.md "Scheduling") and the generator respects the contract.

``tier`` also accepts ``cascade:<schedule>`` (e.g.
``cascade:int8:24+fp32:8``) — the speculative-tier-cascade request form
(serve/cascade/, docs/serving.md "Tier cascade").  Cascade events never
carry explicit ``iters`` (the schedule fixes the budget; the server
rejects the combination) and the schedule grammar is validated at trace
read/generate time so a typo fails before any traffic is offered.  The
plain ``certified`` tier stays valid as ever — against a
cascade-serving deployment it resolves server-side to the cheapest
certified cascade.

Generators are DETERMINISTIC: same ``TraceSpec`` (seed included) ⇒
byte-identical JSONL.  That is what makes "replay the same trace twice,
demand identical request streams" an assertable property
(tests/test_loadgen.py) rather than a hope.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TraceEvent", "TraceSpec", "generate", "read_trace",
           "write_trace"]

TRACE_FORMAT = "raftstereo_tpu.loadgen"
TRACE_VERSION = 1

_PRIORITIES = ("high", "normal", "low")
_SHAPES = ("poisson", "burst", "diurnal")


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One request in a trace (see module docstring for the JSON form)."""

    index: int
    t_ms: float                        # arrival offset from trace start
    height: int
    width: int
    tier: Optional[str] = None         # None = server default precision
    priority: Optional[str] = None     # high | normal | low (unary only)
    deadline_ms: Optional[float] = None
    iters: Optional[int] = None        # explicit target (unary only)
    session: Optional[str] = None      # set ⇒ this is a stream frame
    seq_no: Optional[int] = None
    close: bool = False                # last frame of its session
    spatial: Optional[bool] = None     # True demands the sharded path

    def to_json(self) -> Dict:
        d: Dict = {"i": self.index, "t_ms": round(self.t_ms, 3),
                   "h": self.height, "w": self.width}
        for key, val in (("tier", self.tier), ("priority", self.priority),
                         ("deadline_ms", self.deadline_ms),
                         ("iters", self.iters), ("session", self.session),
                         ("seq_no", self.seq_no),
                         ("spatial", self.spatial)):
            if val is not None:
                d[key] = val
        if self.close:
            d["close"] = True
        return d

    @classmethod
    def from_json(cls, d: Dict) -> "TraceEvent":
        return cls(index=int(d["i"]), t_ms=float(d["t_ms"]),
                   height=int(d["h"]), width=int(d["w"]),
                   tier=d.get("tier"), priority=d.get("priority"),
                   deadline_ms=d.get("deadline_ms"), iters=d.get("iters"),
                   session=d.get("session"), seq_no=d.get("seq_no"),
                   close=bool(d.get("close", False)),
                   spatial=d.get("spatial"))

    def validate(self) -> None:
        if self.priority is not None and self.priority not in _PRIORITIES:
            raise ValueError(f"event {self.index}: bad priority "
                             f"{self.priority!r}")
        if self.tier is not None and self.tier.startswith("cascade:"):
            # Cascade requests (serve/cascade/): fail a schedule typo at
            # trace time, not as N replayed 400s.  The schedule module
            # is deliberately jax-free, so this stays client-weight.
            from ..serve.cascade.schedule import parse_schedule
            try:
                parse_schedule(self.tier[len("cascade:"):])
            except (ValueError, AssertionError) as e:
                raise ValueError(f"event {self.index}: bad cascade "
                                 f"schedule {self.tier!r}: {e}")
            if self.iters is not None:
                # Mirrors the server's 400: the schedule fixes the
                # iteration budget, an explicit target contradicts it.
                raise ValueError(
                    f"event {self.index}: cascade events cannot carry "
                    f"iters (the schedule fixes the budget)")
        if self.session is not None:
            if self.priority is not None or self.deadline_ms is not None \
                    or self.iters is not None:
                # Mirrors the server's 400: session frames ride the
                # scheduler as high-priority short jobs; per-frame
                # deadline/priority/iters are not part of the contract.
                raise ValueError(
                    f"event {self.index}: session frames cannot carry "
                    f"priority/deadline_ms/iters")
            if self.seq_no is None:
                raise ValueError(f"event {self.index}: session frame "
                                 f"without seq_no")


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Parameters of a synthetic trace; the header line is this, dumped.

    ``shape`` picks the arrival process over ``duration_s``:

    * ``poisson`` — homogeneous Poisson (exponential gaps, normalised);
    * ``burst``   — Poisson baseline with a ``burst_factor``× intensity
      window covering ``burst_fraction`` of the duration (starts at 40%
      in — mid-run, after any warmup traffic);
    * ``diurnal`` — sinusoidal intensity (one full period), the
      classic day/night load curve compressed into the trace.

    ``session_fraction`` of events become stream frames, grouped into
    interleaved sessions of ``sequence_len`` frames each (created,
    churned against each other, closed).  ``tier_mix``/``priority_mix``
    are (value, weight) tables sampled per unary event; ``deadlines``
    maps a priority to its deadline_ms.  ``iters_choices`` (when
    non-empty) gives ``iters_fraction`` of unary events an explicit
    iteration target.  ``spatial_fraction`` of unary events demand the
    multi-chip path at ``spatial_resolution``.
    """

    seed: int = 0
    requests: int = 64
    duration_s: float = 4.0
    shape: str = "burst"
    burst_factor: float = 4.0
    burst_fraction: float = 0.25
    resolutions: Tuple[Tuple[int, int], ...] = ((60, 90),)
    session_fraction: float = 0.0
    sequence_len: int = 4
    tier_mix: Tuple[Tuple[str, float], ...] = (("default", 1.0),)
    priority_mix: Tuple[Tuple[str, float], ...] = (("normal", 1.0),)
    deadlines: Tuple[Tuple[str, float], ...] = ()
    iters_choices: Tuple[int, ...] = ()
    iters_fraction: float = 0.5
    spatial_fraction: float = 0.0
    spatial_resolution: Optional[Tuple[int, int]] = None

    def __post_init__(self):
        assert self.shape in _SHAPES, self.shape
        assert self.requests >= 1, self.requests
        assert self.duration_s > 0, self.duration_s
        assert 0.0 <= self.session_fraction <= 1.0, self.session_fraction
        assert self.sequence_len >= 2, self.sequence_len
        for p, _ in self.priority_mix:
            assert p in _PRIORITIES, p

    def header(self) -> Dict:
        d = dataclasses.asdict(self)
        # Tuples JSON-ify as lists; normalise for byte-stable round trips.
        return {"trace": TRACE_FORMAT, "version": TRACE_VERSION,
                **json.loads(json.dumps(d))}


def _pick(rng: np.random.Generator,
          mix: Sequence[Tuple[str, float]]) -> str:
    values = [v for v, _ in mix]
    weights = np.asarray([w for _, w in mix], dtype=np.float64)
    total = float(weights.sum())
    assert total > 0, mix
    return values[int(rng.choice(len(values), p=weights / total))]


def _arrival_times_ms(rng: np.random.Generator,
                      spec: TraceSpec) -> List[float]:
    """``requests`` arrival offsets in ms, normalised to ``duration_s``.

    Inverse-CDF over the shape's intensity profile: gap noise comes from
    a homogeneous exponential draw, which is then warped through the
    cumulative intensity so bursts compress arrivals without changing
    their count — the trace always offers exactly ``requests`` events.
    """
    n = spec.requests
    gaps = rng.exponential(1.0, size=n)
    uniform = np.cumsum(gaps)
    uniform /= uniform[-1]             # sorted points in (0, 1]
    if spec.shape == "poisson":
        warped = uniform
    else:
        grid = np.linspace(0.0, 1.0, 2049)
        if spec.shape == "burst":
            b0 = 0.4
            b1 = min(1.0, b0 + spec.burst_fraction)
            intensity = np.where((grid >= b0) & (grid < b1),
                                 spec.burst_factor, 1.0)
        else:                          # diurnal: one sinusoidal period
            intensity = 1.0 + 0.8 * np.sin(2.0 * math.pi * grid)
            intensity = np.maximum(intensity, 0.05)
        cdf = np.cumsum(intensity)
        cdf /= cdf[-1]
        warped = np.interp(uniform, cdf, grid)
    return [float(t) for t in warped * spec.duration_s * 1e3]


def generate(spec: TraceSpec) -> List[TraceEvent]:
    """Deterministic synthetic trace from ``spec`` (seeded rng only)."""
    rng = np.random.default_rng(spec.seed)
    times = _arrival_times_ms(rng, spec)
    n = spec.requests

    # Which arrival slots are stream frames: sessions of sequence_len
    # frames, interleaved round-robin so they overlap (create/churn/
    # close) instead of running back to back.
    n_sessions = int(round(n * spec.session_fraction / spec.sequence_len))
    n_frames = min(n, n_sessions * spec.sequence_len)
    n_sessions = n_frames // spec.sequence_len
    n_frames = n_sessions * spec.sequence_len
    frame_slots = (sorted(int(i) for i in
                          rng.choice(n, size=n_frames, replace=False))
                   if n_frames else [])
    frame_of = {}                      # slot -> (session, seq_no, close)
    for rank, slot in enumerate(frame_slots):
        s = rank % n_sessions
        k = rank // n_sessions
        frame_of[slot] = (f"s{s}", k, k == spec.sequence_len - 1)

    deadlines = dict(spec.deadlines)
    events: List[TraceEvent] = []
    for i in range(n):
        h, w = spec.resolutions[
            int(rng.integers(0, len(spec.resolutions)))]
        if i in frame_of:
            session, seq, close = frame_of[i]
            ev = TraceEvent(index=i, t_ms=times[i], height=h, width=w,
                            session=session, seq_no=seq, close=close,
                            tier=None)
        else:
            tier = _pick(rng, spec.tier_mix)
            priority = _pick(rng, spec.priority_mix)
            iters = None
            if spec.iters_choices and \
                    rng.random() < spec.iters_fraction:
                iters = int(spec.iters_choices[
                    int(rng.integers(0, len(spec.iters_choices)))])
            if tier.startswith("cascade:"):
                # The schedule fixes the budget; drawing THEN dropping
                # keeps rng consumption identical across tier choices,
                # so adding a cascade to the mix never reshuffles the
                # other events' draws.
                iters = None
            spatial = None
            if spec.spatial_fraction and \
                    rng.random() < spec.spatial_fraction:
                spatial = True
                if spec.spatial_resolution is not None:
                    h, w = spec.spatial_resolution
            ev = TraceEvent(
                index=i, t_ms=times[i], height=h, width=w,
                tier=None if tier == "default" else tier,
                priority=None if priority == "normal" else priority,
                deadline_ms=deadlines.get(priority), iters=iters,
                spatial=spatial)
        ev.validate()
        events.append(ev)
    return events


def write_trace(path: str, events: Sequence[TraceEvent],
                header: Optional[Dict] = None) -> None:
    """JSONL: one header line, then one event per line (byte-stable —
    ``sort_keys`` + fixed float rounding in ``to_json``)."""
    head = dict(header or {})
    head.setdefault("trace", TRACE_FORMAT)
    head.setdefault("version", TRACE_VERSION)
    head["events"] = len(events)
    with open(path, "w") as f:
        f.write(json.dumps(head, sort_keys=True) + "\n")
        for ev in events:
            f.write(json.dumps(ev.to_json(), sort_keys=True) + "\n")


def read_trace(path: str) -> Tuple[Dict, List[TraceEvent]]:
    """Parse + validate a JSONL trace; returns (header, events)."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty trace")
    header = json.loads(lines[0])
    if header.get("trace") != TRACE_FORMAT:
        raise ValueError(f"{path}: not a {TRACE_FORMAT} trace")
    if header.get("version") != TRACE_VERSION:
        raise ValueError(f"{path}: trace version {header.get('version')} "
                         f"!= supported {TRACE_VERSION}")
    events = [TraceEvent.from_json(json.loads(ln)) for ln in lines[1:]]
    for ev in events:
        ev.validate()
    if [e.index for e in events] != list(range(len(events))):
        raise ValueError(f"{path}: event indices not dense/ordered")
    if any(b.t_ms < a.t_ms for a, b in zip(events, events[1:])):
        raise ValueError(f"{path}: arrival times not monotone")
    return header, events
