"""SLO spec + machine-readable assertion report over replayed rows.

An ``SLOSpec`` is a list of ``SLOClass`` bounds, each scoped to a
(tier, priority) selector (``"*"`` matches anything).  ``evaluate``
partitions the recorder's rows into the spec's classes and emits one
JSON-able verdict:

    {"slo_report": "raftstereo_tpu.loadgen", "version": 1,
     "pass": true,
     "checks": [{"cls": "tier=*,priority=high", "metric": "p99_ms",
                 "value": 812.4, "bound": 2000.0, "pass": true}, ...],
     "groups": {"default|high": {"count": 9, "ok": 9, "p50_ms": ...}},
     "metrics": {"validator_errors": [], "deltas": {...}},
     "retraces": 0}

Every check is (value, bound, pass) — the verdict is self-auditing, no
re-running needed to see WHY it failed.  ``/metrics`` scrapes taken
around the replay feed two further gates: the after-scrape must pass
the exposition validator (a harness certifying SLOs off a malformed
scrape would certify garbage) and selected counter deltas are reported
so shed/cold-frame rates cross-check the client-observed rows.
Zero-compile steady state is asserted OUTSIDE this module by running
the replay under ``analysis.retrace_guard`` and passing the observed
count in as ``retraces``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs.prom import parse_text
from .records import RequestRow, percentile, wire_bytes

__all__ = ["SLOClass", "SLOSpec", "DegradedWindow", "evaluate"]

SLO_FORMAT = "raftstereo_tpu.loadgen"
SLO_VERSION = 1


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """Bounds for one (tier, priority) slice; ``inf``/0-rate defaults
    make every bound opt-in."""

    tier: str = "*"
    priority: str = "*"
    p50_ms: float = math.inf
    p99_ms: float = math.inf
    max_shed_rate: float = 1.0
    max_error_rate: float = 1.0
    min_deadline_hit_rate: float = 0.0
    max_cold_frame_rate: float = 1.0   # over frames past each stream's first

    def selector(self) -> str:
        return f"tier={self.tier},priority={self.priority}"

    def matches(self, row: RequestRow) -> bool:
        if self.tier != "*" and row.tier != self.tier:
            return False
        if self.priority != "*" and (row.priority or "normal") \
                != self.priority:
            return False
        return True


@dataclasses.dataclass(frozen=True)
class DegradedWindow:
    """A declared fault window with its own (relaxed) bounds.

    While a chaos plan (loadgen/chaos.py) holds a fault open, the
    steady-state bounds are the wrong contract — the whole point of
    graceful degradation is that service gets WORSE, boundedly.  A
    window scopes ``[t_start_ms, t_end_ms)`` of trace time (rows
    partition on ``t_send_ms``): rows inside it are judged against the
    degraded bounds here instead of the class bounds, and rows at or
    after ``t_end_ms + recover_by_ms`` form the RECOVERY slice that
    must be back within the recovery bounds — a breaker that opens and
    never half-open-recovers fails the verdict even if the window
    itself looked fine.

    Both the window and (when any recovery bound is set) the recovery
    slice fail loudly on zero traffic: a chaos verdict whose fault
    window saw no requests certified nothing.
    """

    t_start_ms: float
    t_end_ms: float
    label: str = "degraded"
    # Degraded-mode bounds over rows INSIDE the window (opt-in, like
    # SLOClass bounds).
    p99_ms: float = math.inf
    max_shed_rate: float = 1.0
    max_error_rate: float = 1.0
    # Recovery: rows with t_send_ms >= t_end_ms + recover_by_ms must be
    # back within these bounds.
    recover_by_ms: float = 0.0
    recovery_p99_ms: float = math.inf
    recovery_max_error_rate: float = 1.0
    recovery_max_cold_frame_rate: float = 1.0

    def __post_init__(self):
        if self.t_end_ms <= self.t_start_ms:
            raise ValueError(
                f"degraded window must have t_end_ms > t_start_ms "
                f"({self.t_start_ms} .. {self.t_end_ms})")
        if self.recover_by_ms < 0:
            raise ValueError("recover_by_ms must be >= 0")

    def contains(self, row: RequestRow) -> bool:
        return self.t_start_ms <= row.t_send_ms < self.t_end_ms

    def _has_recovery_bounds(self) -> bool:
        return (self.recovery_p99_ms < math.inf
                or self.recovery_max_error_rate < 1.0
                or self.recovery_max_cold_frame_rate < 1.0)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """The whole contract: per-class bounds + global gates."""

    classes: Tuple[SLOClass, ...] = (SLOClass(),)
    max_retraces: int = 0              # warm steady state compiles nothing
    require_clean_metrics: bool = True
    max_late_send_rate: float = 1.0    # harness health, not server SLO
    # Declared fault windows (chaos mode): class bounds then apply to
    # STEADY rows only (those inside no window), each window judges its
    # own slice against its degraded bounds, and recovery slices are
    # checked per window (see DegradedWindow).
    windows: Tuple[DegradedWindow, ...] = ()


def _group_stats(rows: Sequence[RequestRow]) -> Dict:
    ok = [r for r in rows if r.outcome == "ok"]
    lats = [r.latency_ms for r in ok if not math.isnan(r.latency_ms)]
    deadlined = [r for r in rows if r.deadline_ms is not None]
    frames = [r for r in rows if r.session and (r.seq_no or 0) > 0]
    stats = {
        "count": len(rows),
        "ok": len(ok),
        "shed": sum(1 for r in rows if r.outcome == "shed"),
        "timeout": sum(1 for r in rows if r.outcome == "timeout"),
        "error": sum(1 for r in rows if r.outcome == "error"),
        "degraded": sum(1 for r in ok if r.degraded),
    }
    if lats:
        stats["p50_ms"] = round(percentile(lats, 50), 2)
        stats["p99_ms"] = round(percentile(lats, 99), 2)
    if deadlined:
        hits = sum(1 for r in deadlined if r.deadline_hit)
        stats["deadline_hit_rate"] = round(hits / len(deadlined), 4)
    if frames:
        # First frame of a stream is cold by definition; the SLO is
        # about warmth HOLDING, so rate is over non-initial frames.
        cold = sum(1 for r in frames if r.outcome == "ok" and not r.warm)
        stats["cold_frame_rate"] = round(cold / len(frames), 4)
    cascaded = [r for r in ok if r.cascade]
    if cascaded:
        # Cascade-served answers (serve/cascade/) and how many the
        # divergence trigger promoted early — keyed only when the group
        # saw cascades, preserving the historical stats schema.
        stats["cascade"] = len(cascaded)
        stats["promoted_early"] = sum(1 for r in cascaded
                                      if r.promoted_early)
    return stats


# Counter families whose scrape deltas the verdict carries — the
# server-side cross-check of the client-observed outcome counts.
_DELTA_FAMILIES = (
    "serve_requests_total", "serve_shed_total", "serve_timeout_total",
    "serve_errors_total", "serve_tier_requests_total",
    "stream_warm_frames_total", "stream_cold_frames_total",
    "sched_early_exits_total", "cluster_dispatch_total",
    "loadgen_requests_total", "wire_bytes_total",
    "cluster_wire_stream_bytes_total",
    # Tier-cascade families (serve/cascade/): completed cascades,
    # promotions (scheduled + early) and per-phase iteration counts —
    # the server-side cross-check that cascade rows really drafted
    # their cheap iterations where the client thinks they did.
    "cascade_schedules_total", "cascade_promotions_total",
    "cascade_iterations_total",
)


def _metric_deltas(before_text: Optional[str],
                   after_text: Optional[str]) -> Tuple[Dict, List[str]]:
    if not after_text:
        return {}, []
    errors = []
    try:
        after = parse_text(after_text)
    except ValueError as e:
        return {}, [str(e)]
    try:
        before = parse_text(before_text) if before_text else None
    except ValueError as e:
        before, errors = None, [f"before-scrape: {e}"]
    deltas: Dict[str, float] = {}
    for fam in _DELTA_FAMILIES:
        if fam not in after:
            continue
        now = after.total(fam)
        prev = before.total(fam) if before else 0.0
        deltas[fam] = now - prev
    return deltas, errors


def evaluate(spec: SLOSpec, rows: Sequence[RequestRow], *,
             wall_s: float,
             metrics_before: Optional[str] = None,
             metrics_after: Optional[str] = None,
             retraces: Optional[int] = None) -> Dict:
    """Assert ``spec`` over ``rows``; returns the JSON-able verdict."""
    checks: List[Dict] = []

    def check(cls: str, metric: str, value: float, bound: float,
              ok: bool) -> None:
        checks.append({"cls": cls, "metric": metric,
                       "value": (None if value is None or
                                 (isinstance(value, float) and
                                  math.isnan(value)) else round(value, 4)),
                       "bound": (None if bound in (math.inf, -math.inf)
                                 else bound),
                       "pass": bool(ok)})

    groups: Dict[str, Dict] = {}
    for r in rows:
        key = f"{r.tier}|{r.priority or 'normal'}"
        groups.setdefault(key, [])
        groups[key].append(r)
    group_stats = {k: _group_stats(v) for k, v in sorted(groups.items())}

    # Chaos mode: class bounds judge STEADY rows only — the declared
    # windows carve their slices out and judge them against degraded
    # bounds below.  Without windows, steady is everything (unchanged
    # non-chaos behavior).
    steady = ([r for r in rows
               if not any(w.contains(r) for w in spec.windows)]
              if spec.windows else list(rows))

    for cls in spec.classes:
        sel = [r for r in steady if cls.matches(r)]
        name = cls.selector()
        if not sel:
            check(name, "count", 0, 1, False)
            continue
        g = _group_stats(sel)
        n = g["count"]
        if cls.p50_ms < math.inf:
            v = g.get("p50_ms", math.nan)
            check(name, "p50_ms", v, cls.p50_ms,
                  not math.isnan(v) and v <= cls.p50_ms)
        if cls.p99_ms < math.inf:
            v = g.get("p99_ms", math.nan)
            check(name, "p99_ms", v, cls.p99_ms,
                  not math.isnan(v) and v <= cls.p99_ms)
        if cls.max_shed_rate < 1.0:
            v = g["shed"] / n
            check(name, "shed_rate", v, cls.max_shed_rate,
                  v <= cls.max_shed_rate)
        if cls.max_error_rate < 1.0:
            v = (g["error"] + g["timeout"]) / n
            check(name, "error_rate", v, cls.max_error_rate,
                  v <= cls.max_error_rate)
        if cls.min_deadline_hit_rate > 0.0:
            v = g.get("deadline_hit_rate")
            check(name, "deadline_hit_rate",
                  math.nan if v is None else v,
                  cls.min_deadline_hit_rate,
                  v is not None and v >= cls.min_deadline_hit_rate)
        if cls.max_cold_frame_rate < 1.0:
            v = g.get("cold_frame_rate")
            check(name, "cold_frame_rate",
                  math.nan if v is None else v,
                  cls.max_cold_frame_rate,
                  v is not None and v <= cls.max_cold_frame_rate)

    window_stats: Dict[str, Dict] = {}
    for i, w in enumerate(spec.windows):
        name = f"window[{i}]:{w.label}"
        inside = [r for r in rows if w.contains(r)]
        window_stats[name] = _group_stats(inside)
        if not inside:
            # A fault window no request ever hit certifies nothing.
            check(name, "count", 0, 1, False)
            continue
        g = _group_stats(inside)
        n = g["count"]
        if w.p99_ms < math.inf:
            v = g.get("p99_ms", math.nan)
            check(name, "p99_ms", v, w.p99_ms,
                  not math.isnan(v) and v <= w.p99_ms)
        if w.max_shed_rate < 1.0:
            v = g["shed"] / n
            check(name, "shed_rate", v, w.max_shed_rate,
                  v <= w.max_shed_rate)
        if w.max_error_rate < 1.0:
            v = (g["error"] + g["timeout"]) / n
            check(name, "error_rate", v, w.max_error_rate,
                  v <= w.max_error_rate)
        if not w._has_recovery_bounds():
            continue
        rec = [r for r in rows
               if r.t_send_ms >= w.t_end_ms + w.recover_by_ms]
        window_stats[f"{name}:recovery"] = _group_stats(rec)
        if not rec:
            # Recovery bounds with no post-window traffic: the trace
            # ended inside the fault — the recovery claim is untested.
            check(name, "recovery_count", 0, 1, False)
            continue
        rg = _group_stats(rec)
        rn = rg["count"]
        if w.recovery_p99_ms < math.inf:
            v = rg.get("p99_ms", math.nan)
            check(name, "recovery_p99_ms", v, w.recovery_p99_ms,
                  not math.isnan(v) and v <= w.recovery_p99_ms)
        if w.recovery_max_error_rate < 1.0:
            v = (rg["error"] + rg["timeout"]) / rn
            check(name, "recovery_error_rate", v,
                  w.recovery_max_error_rate,
                  v <= w.recovery_max_error_rate)
        if w.recovery_max_cold_frame_rate < 1.0:
            # Vacuously green when the recovery slice has no stream
            # frames — cold-frame rate is a warmth property, and a
            # trace without sessions has no warmth to recover.
            v = rg.get("cold_frame_rate")
            check(name, "recovery_cold_frame_rate",
                  math.nan if v is None else v,
                  w.recovery_max_cold_frame_rate,
                  v is None or v <= w.recovery_max_cold_frame_rate)

    if spec.max_late_send_rate < 1.0 and rows:
        late = sum(1 for r in rows if r.send_lag_ms > 0.0)
        v = late / len(rows)
        check("harness", "late_send_rate", v, spec.max_late_send_rate,
              v <= spec.max_late_send_rate)

    deltas, scrape_errors = _metric_deltas(metrics_before, metrics_after)
    validator_errors: List[str] = list(scrape_errors)
    if spec.require_clean_metrics and metrics_after is not None:
        check("global", "metrics_validator_errors",
              len(validator_errors), 0, not validator_errors)

    if retraces is not None:
        check("global", "retraces", retraces, spec.max_retraces,
              retraces <= spec.max_retraces)

    verdict = {
        "slo_report": SLO_FORMAT,
        "version": SLO_VERSION,
        "pass": all(c["pass"] for c in checks),
        "wall_s": round(wall_s, 3),
        "requests": len(rows),
        "checks": checks,
        "groups": group_stats,
        "metrics": {"validator_errors": validator_errors,
                    "deltas": deltas},
    }
    if spec.windows:
        verdict["windows"] = window_stats
    # Wire-bytes/pair rides along whenever the client counted bytes:
    # the SLO statement is "N chips serve M users at SLO at B bytes/pair"
    # (docs/wire_format.md) — replaying the same trace under json vs
    # binary makes the reduction a verdict-level number, not a guess.
    wb = wire_bytes(rows)
    if wb is not None:
        verdict["wire"] = wb
    if retraces is not None:
        verdict["retraces"] = retraces
    return verdict
