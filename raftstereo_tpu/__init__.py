"""raftstereo_tpu — a TPU-native (JAX/XLA/Pallas) stereo-matching framework.

Capability-parity rebuild of RAFT-Stereo (arXiv 2109.07547; reference repo
xuhaozheng/RAFT-Stereo), designed TPU-first rather than ported:

* NHWC layout, flax.linen modules, explicit torch-compatible conv padding
* the full GRU refinement loop is a single ``jax.lax.scan`` -> one XLA program
* correlation volume as batched matmuls on the MXU; lookup via XLA gather or a
  gather-free Pallas kernel (the CUDA ``sampler/`` equivalent)
* data/model parallelism via ``jax.sharding`` meshes, bf16 via a dtype policy,
  Orbax checkpoints with full train state
"""

__version__ = "0.1.0"

from .config import RAFTStereoConfig, TrainConfig  # noqa: F401
