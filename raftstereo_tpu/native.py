"""Loader for the framework's native (C) components.

Native sources live in ``native/`` at the repo root; shared objects are built
on first use into ``native/build/`` with the system compiler and loaded via
ctypes (this image has no pybind11; ctypes keeps the binding dependency-free).
Every native component has a pure-python fallback, so the framework works —
slower — without a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC_DIR = os.path.join(_ROOT, "native")
_BUILD_DIR = os.path.join(_SRC_DIR, "build")
_LOCK = threading.Lock()
_CACHE: dict = {}


def _build(name: str) -> Optional[str]:
    src = os.path.join(_SRC_DIR, f"{name}.c")
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    if not os.path.exists(src):
        return None
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Compile to a process-unique temp file and atomically rename: several
    # loader worker processes may race to build the same library, and dlopen
    # of a partially-written .so can crash the worker.
    tmp = f"{out}.{os.getpid()}.tmp"
    for cc in ("cc", "gcc", "g++"):
        try:
            subprocess.run([cc, "-O3", "-shared", "-fPIC", src, "-o", tmp],
                           check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
            return out
        except (OSError, subprocess.SubprocessError):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """Build (if needed) and load native/<name>.c; None if unavailable."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        lib = None
        try:
            path = _build(name)
            if path is not None:
                lib = ctypes.CDLL(path)
        except OSError:
            lib = None
        _CACHE[name] = lib
        return lib
