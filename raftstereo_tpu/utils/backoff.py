"""Shared retry backoff policy (stdlib-only, importable from the
model-free router as well as the serving client).

One formula for both ends of the failover story — the cluster router's
backend failover (serve/cluster/router.py) and the client's
retry-with-backoff (serve/client.py) — so tuning the schedule (base,
growth, jitter range) cannot silently diverge between them.
"""

from __future__ import annotations

import random

__all__ = ["backoff_delay"]


def backoff_delay(base_ms: float, attempt: int) -> float:
    """Seconds to wait before retry ``attempt`` (0 = first retry):
    exponential from ``base_ms``, with +-50% jitter to decorrelate
    retry storms across concurrent callers."""
    return (base_ms / 1000.0) * (2 ** attempt) * (0.5 + random.random())
