"""JAX platform selection that works under eager-importing site hooks.

This image's site hook imports jax at interpreter startup, freezing the
``JAX_PLATFORMS`` env var before a shell-provided value (or one set by a
driver) can take effect.  ``jax.config`` still works until the first backend
initialization, so route the request through it.
"""

from __future__ import annotations

import os
from typing import Optional


def apply_env_platform(override: Optional[str] = None) -> Optional[str]:
    """Re-apply the requested JAX platform through ``jax.config``.

    ``override`` wins over the ``JAX_PLATFORMS`` env var.  Returns the
    platform applied (or None if nothing was requested).  A no-op when the
    backend is already initialized on some platform — callers get whatever
    that first initialization picked.
    """
    plat = override or os.environ.get("JAX_PLATFORMS")
    if not plat:
        return None
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except RuntimeError:
        return None  # backend already initialized; keep its choice
    return plat
