"""Utilities: checkpoint conversion, logging, misc."""

from .convert import convert_checkpoint, load_state_dict, torch_to_variables
from .platform import apply_env_platform

__all__ = ["apply_env_platform", "convert_checkpoint", "load_state_dict",
           "torch_to_variables"]
