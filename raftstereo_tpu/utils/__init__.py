"""Utilities: checkpoint conversion, fault injection, logging, misc."""

from .convert import convert_checkpoint, load_state_dict, torch_to_variables
from .faults import FaultPlan, InjectedCrash, InjectedFault, InjectedSampleError
from .platform import apply_env_platform

__all__ = ["apply_env_platform", "convert_checkpoint", "load_state_dict",
           "torch_to_variables", "FaultPlan", "InjectedFault",
           "InjectedCrash", "InjectedSampleError"]
