"""Utilities: checkpoint conversion, logging, misc."""

from .convert import convert_checkpoint, load_state_dict, torch_to_variables

__all__ = ["convert_checkpoint", "load_state_dict", "torch_to_variables"]
