"""Deterministic fault injection for the training stack.

A :class:`FaultPlan` is a small, declarative schedule of failures —
parsed from a spec string (usually the ``RAFTSTEREO_FAULTS`` env var, so
subprocess chaos tests and pod launchers can drive it without code
changes) — with injection hooks wired into the data loader
(``data/loader.py``), the train loop (``cli/train.py``) and the
checkpoint manager (``train/checkpoint.py``).  Every recovery mechanism
in the repo (preemption-safe checkpoints, checkpoint fallback, sample
quarantine, pool recycle, ``nan_policy``, ``max_restarts``) is proven by
injecting its failure on purpose (tests/test_faults.py), the way
frequent-checkpointing systems validate theirs (CheckFreq, FAST '21).

Grammar (comma-separated entries)::

    RAFTSTEREO_FAULTS="crash@step=7,corrupt@sample=3,hang@worker=1:10s,nan@step=5"

    entry := KIND "@" DIM "=" INT [":" SECONDS["s"|"ms"]]

    crash@step=N          raise InjectedCrash before executing step N
    preempt@step=N        deliver SIGTERM to self before executing step N
    nan@step=N            poison the batch of step N with a NaN
    slow@step=N:2s        sleep before step N (trips the step watchdog)
    corrupt@sample=I      dataset index I always raises (persistent)
    hang@sample=I:10s     sleep before loading index I (once)
    hang@worker=W:10s     worker W sleeps before its first load (once)
    corrupt_ckpt@step=N   scribble over the checkpoint saved at step N
    kill_backend@request=N  serving chaos trigger: ``on_request`` returns
                          True at the N-th request (1-based) — the test
                          harness kills its victim backend at exactly
                          that point, making the router kill/upgrade
                          chaos test deterministic instead of
                          SIGKILL-timing-dependent (tests/test_cluster.py)

All faults fire exactly once except ``corrupt@sample``, which models a
persistently bad shard and fires on every access.  Injection is fully
deterministic: no randomness, no timers beyond the explicit sleeps.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import List, Optional, Set

logger = logging.getLogger(__name__)

ENV_VAR = "RAFTSTEREO_FAULTS"

_KINDS = {
    # kind: (allowed dims, needs duration, persistent)
    "crash": (("step",), False, False),
    "preempt": (("step",), False, False),
    "nan": (("step",), False, False),
    "slow": (("step",), True, False),
    "corrupt": (("sample",), False, True),
    "hang": (("worker", "sample"), True, False),
    "corrupt_ckpt": (("step",), False, False),
    "kill_backend": (("request",), False, False),
}


class InjectedFault(RuntimeError):
    """Base of every deliberately injected failure."""


class InjectedCrash(InjectedFault):
    """Raised by ``crash@step=N`` — exercises elastic restart."""


class InjectedSampleError(InjectedFault):
    """Raised by ``corrupt@sample=I`` — exercises retry + quarantine."""


def _parse_seconds(text: str) -> float:
    text = text.strip().lower()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("s"):
        text = text[:-1]
    return float(text)


@dataclasses.dataclass
class Fault:
    kind: str
    dim: str
    value: int
    seconds: Optional[float] = None
    # -1 = unlimited (persistent faults); otherwise remaining fire count.
    remaining: int = 1

    def spec(self) -> str:
        dur = "" if self.seconds is None else f":{self.seconds:g}s"
        return f"{self.kind}@{self.dim}={self.value}{dur}"


@dataclasses.dataclass
class FaultPlan:
    """A parsed fault schedule.  Picklable (it crosses into spawned data
    workers); fired-state is per-process by design — a worker consuming
    its copy of a fault does not consume the parent's."""

    faults: List[Fault] = dataclasses.field(default_factory=list)

    # -- construction -------------------------------------------------------

    @classmethod
    def parse(cls, spec: Optional[str]) -> "FaultPlan":
        faults = []
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            try:
                kind, rest = entry.split("@", 1)
                dim, value = rest.split("=", 1)
                seconds = None
                if ":" in value:
                    value, dur = value.split(":", 1)
                    seconds = _parse_seconds(dur)
                kind, dim, value = kind.strip(), dim.strip(), int(value)
            except ValueError as e:
                raise ValueError(
                    f"bad fault entry {entry!r} (want KIND@DIM=INT[:SECS], "
                    f"e.g. crash@step=7 or hang@worker=1:10s): {e}") from e
            if kind not in _KINDS:
                raise ValueError(f"unknown fault kind {kind!r} in {entry!r}; "
                                 f"known: {sorted(_KINDS)}")
            dims, needs_dur, persistent = _KINDS[kind]
            if dim not in dims:
                raise ValueError(f"fault {kind!r} takes {dims}, got "
                                 f"{dim!r} in {entry!r}")
            if needs_dur and seconds is None:
                raise ValueError(f"fault {kind!r} needs a duration "
                                 f"(e.g. {kind}@{dim}={value}:10s)")
            faults.append(Fault(kind, dim, value, seconds,
                                remaining=-1 if persistent else 1))
        return cls(faults)

    @classmethod
    def from_env(cls, env_var: str = ENV_VAR) -> "FaultPlan":
        return cls.parse(os.environ.get(env_var))

    # -- matching -----------------------------------------------------------

    def __bool__(self) -> bool:
        return bool(self.faults)

    def peek(self, kind: str, dim: str, value: int) -> Optional[Fault]:
        for f in self.faults:
            if (f.kind == kind and f.dim == dim and f.value == value
                    and f.remaining != 0):
                return f
        return None

    def _take(self, kind: str, dim: str, value: int) -> Optional[Fault]:
        f = self.peek(kind, dim, value)
        if f is not None:
            if f.remaining > 0:
                f.remaining -= 1
            logger.warning("fault injection: firing %s", f.spec())
        return f

    # -- hooks --------------------------------------------------------------

    def at_step(self, step: int) -> Set[str]:
        """Train-loop hook, called before executing 1-based ``step``.

        Sleeps for ``slow``, self-delivers SIGTERM for ``preempt``, raises
        for ``crash``; returns the set of fired kinds (the loop poisons the
        batch itself when ``"nan"`` is in it).
        """
        fired = set()
        f = self._take("slow", "step", step)
        if f is not None:
            fired.add("slow")
            time.sleep(f.seconds)
        if self._take("nan", "step", step) is not None:
            fired.add("nan")
        if self._take("preempt", "step", step) is not None:
            fired.add("preempt")
            os.kill(os.getpid(), signal.SIGTERM)
        f = self._take("crash", "step", step)
        if f is not None:
            raise InjectedCrash(f"injected crash before step {step}")
        return fired

    def on_sample(self, index: int) -> None:
        """Loader hook, called before loading dataset ``index``."""
        f = self._take("hang", "sample", index)
        if f is not None:
            time.sleep(f.seconds)
        if self._take("corrupt", "sample", index) is not None:
            raise InjectedSampleError(f"injected corrupt sample {index}")

    def on_worker(self, worker_id: int) -> None:
        """Loader hook, called at the top of each worker load task."""
        f = self._take("hang", "worker", worker_id)
        if f is not None:
            time.sleep(f.seconds)

    def on_request(self, n: int) -> bool:
        """Serving hook, called with the 1-based count of each request a
        chaos harness issues; True exactly when ``kill_backend@request=N``
        fires — the harness then kills its victim backend, so the
        kill-mid-stream point is deterministic across runs."""
        return self._take("kill_backend", "request", n) is not None

    def on_checkpoint_saved(self, step: int, path: str) -> bool:
        """Checkpoint-manager hook: corrupt the just-saved step dir.
        Returns True if it fired (the caller must have waited for the
        async save to finish before calling)."""
        if self._take("corrupt_ckpt", "step", step) is None:
            return False
        corrupt_tree(path)
        return True


def corrupt_tree(path: str) -> int:
    """Overwrite every file under ``path`` with garbage (simulates torn
    writes / bit rot on the checkpoint volume).  Returns files touched."""
    n = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            with open(os.path.join(root, name), "wb") as f:
                f.write(b"\x00CORRUPTED-BY-FAULT-INJECTION\x00")
            n += 1
    logger.warning("fault injection: corrupted %d files under %s", n, path)
    return n
