"""Deterministic fault injection for the training stack.

A :class:`FaultPlan` is a small, declarative schedule of failures —
parsed from a spec string (usually the ``RAFTSTEREO_FAULTS`` env var, so
subprocess chaos tests and pod launchers can drive it without code
changes) — with injection hooks wired into the data loader
(``data/loader.py``), the train loop (``cli/train.py``) and the
checkpoint manager (``train/checkpoint.py``).  Every recovery mechanism
in the repo (preemption-safe checkpoints, checkpoint fallback, sample
quarantine, pool recycle, ``nan_policy``, ``max_restarts``) is proven by
injecting its failure on purpose (tests/test_faults.py), the way
frequent-checkpointing systems validate theirs (CheckFreq, FAST '21).

Grammar (comma-separated entries)::

    RAFTSTEREO_FAULTS="crash@step=7,corrupt@sample=3,hang@worker=1:10s,nan@step=5"

    entry := KIND "@" DIM "=" INT [":" SECONDS["s"|"ms"]]

    crash@step=N          raise InjectedCrash before executing step N
    preempt@step=N        deliver SIGTERM to self before executing step N
    nan@step=N            poison the batch of step N with a NaN
    slow@step=N:2s        sleep before step N (trips the step watchdog)
    corrupt@sample=I      dataset index I always raises (persistent)
    hang@sample=I:10s     sleep before loading index I (once)
    hang@worker=W:10s     worker W sleeps before its first load (once)
    corrupt_ckpt@step=N   scribble over the checkpoint saved at step N
    kill_backend@request=N  serving chaos trigger: ``on_request`` returns
                          True at the N-th request (1-based) — the test
                          harness kills its victim backend at exactly
                          that point, making the router kill/upgrade
                          chaos test deterministic instead of
                          SIGKILL-timing-dependent (tests/test_cluster.py)

Serving-plane kinds (docs/fault_tolerance.md "Serving-plane fault
grammar"; armed at runtime over ``POST /debug/faults`` by the chaos
controller in loadgen/chaos.py, or from the env at server start)::

    slow_replica@request=N:SECS   the next N engine dispatches each
                          sleep SECS before touching the device — a
                          replica that is alive but slow (the hedged-
                          request trigger).  Hook: ``dispatch_delay``
                          (serve/engine.py).
    blackhole_backend@t_ms=OFF:SECS  starting OFF ms after arming, for
                          SECS, the backend accepts connections but
                          does not respond until the window closes —
                          probes time out, the router's circuit breaker
                          opens.  Hooks: ``blackhole_until`` /
                          ``blackhole_hold`` (serve/httpbase.py).
    flap_probe@backend=N  the next N ``/healthz`` replies LIE
                          (``ready: false`` on a ready server) — probe
                          flapping without any real fault.  Hook:
                          ``healthz_lie`` (serve/server.py).
    corrupt_frame@request=N  the next N binary RSWF frames relayed by
                          the router get one payload byte bit-flipped
                          mid-stream — wire-plane corruption between
                          hops.  Hook: ``corrupt_stream``
                          (serve/cluster/router.py).
    evict_sessions@t_ms=OFF  OFF ms after arming, evict every live
                          streaming session (session-store pressure;
                          the next frame of each stream re-anchors
                          cold).  Hook: ``evict_due``
                          (serve/server.py -> dispatcher/runner).
    tier_outage@t_ms=OFF:SECS  starting OFF ms after arming, for SECS,
                          the session tier accepts connections but does
                          not respond until the window closes — backend
                          publishers time out and degrade to local-pin
                          behavior, re-attaching when the window ends.
                          Hooks: ``tier_outage_until`` /
                          ``tier_outage_hold`` (stream/tier.py).
    tier_slow@request=N:SECS  the next N session-tier requests each
                          sleep SECS before being served — a tier that
                          is alive but slow (the write-behind timeout /
                          degraded-mode trigger).  Hook:
                          ``tier_slow_delay`` (stream/tier.py).

Count-valued kinds (``slow_replica``/``flap_probe``/``corrupt_frame``/
``tier_slow``)
use the INT as a fire budget: the entry fires on each hook consult
until N firings are spent.  Time-valued kinds (``@t_ms=``) measure
offsets from ARMING (``FaultPlan.arm`` / ``extend``), so one plan
string can be scheduled against trace time by the chaos controller.

All training faults fire exactly once except ``corrupt@sample``, which
models a persistently bad shard and fires on every access.  Injection
is fully deterministic: no randomness, no timers beyond the explicit
sleeps and declared windows.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from typing import List, Optional, Set

logger = logging.getLogger(__name__)

ENV_VAR = "RAFTSTEREO_FAULTS"

_KINDS = {
    # kind: (allowed dims, needs duration, persistent)
    "crash": (("step",), False, False),
    "preempt": (("step",), False, False),
    "nan": (("step",), False, False),
    "slow": (("step",), True, False),
    "corrupt": (("sample",), False, True),
    "hang": (("worker", "sample"), True, False),
    "corrupt_ckpt": (("step",), False, False),
    "kill_backend": (("request",), False, False),
    "slow_replica": (("request",), True, False),
    "blackhole_backend": (("t_ms",), True, False),
    "flap_probe": (("backend",), False, False),
    "corrupt_frame": (("request",), False, False),
    "evict_sessions": (("t_ms",), False, False),
    "tier_outage": (("t_ms",), True, False),
    "tier_slow": (("request",), True, False),
}

# Kinds whose INT is a fire budget (remaining = value), not an index.
_COUNT_KINDS = frozenset(
    {"slow_replica", "flap_probe", "corrupt_frame", "tier_slow"})
# Kinds whose INT is a millisecond offset from arming.
_TIMED_KINDS = frozenset(
    {"blackhole_backend", "evict_sessions", "tier_outage"})

# Serving hooks fire from many handler threads at once; the training
# hooks are single-threaded by construction.  One coarse module lock
# keeps ``remaining`` decrements exact without making FaultPlan
# unpicklable (it crosses into spawned data workers).
_HOOK_LOCK = threading.Lock()


class InjectedFault(RuntimeError):
    """Base of every deliberately injected failure."""


class InjectedCrash(InjectedFault):
    """Raised by ``crash@step=N`` — exercises elastic restart."""


class InjectedSampleError(InjectedFault):
    """Raised by ``corrupt@sample=I`` — exercises retry + quarantine."""


def _parse_seconds(text: str) -> float:
    text = text.strip().lower()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("s"):
        text = text[:-1]
    return float(text)


@dataclasses.dataclass
class Fault:
    kind: str
    dim: str
    value: int
    seconds: Optional[float] = None
    # -1 = unlimited (persistent faults); otherwise remaining fire count.
    remaining: int = 1
    # Monotonic arming time (``FaultPlan.arm``) — the zero point for
    # ``@t_ms=`` offsets.  None until armed; time-windowed hooks are
    # inert while unarmed.
    armed_at: Optional[float] = None

    def spec(self) -> str:
        dur = "" if self.seconds is None else f":{self.seconds:g}s"
        return f"{self.kind}@{self.dim}={self.value}{dur}"


@dataclasses.dataclass
class FaultPlan:
    """A parsed fault schedule.  Picklable (it crosses into spawned data
    workers); fired-state is per-process by design — a worker consuming
    its copy of a fault does not consume the parent's."""

    faults: List[Fault] = dataclasses.field(default_factory=list)

    # -- construction -------------------------------------------------------

    @classmethod
    def parse(cls, spec: Optional[str]) -> "FaultPlan":
        faults = []
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            try:
                kind, rest = entry.split("@", 1)
                dim, value = rest.split("=", 1)
                seconds = None
                if ":" in value:
                    value, dur = value.split(":", 1)
                    seconds = _parse_seconds(dur)
                kind, dim, value = kind.strip(), dim.strip(), int(value)
            except ValueError as e:
                raise ValueError(
                    f"bad fault entry {entry!r} (want KIND@DIM=INT[:SECS], "
                    f"e.g. crash@step=7 or hang@worker=1:10s): {e}") from e
            if kind not in _KINDS:
                raise ValueError(f"unknown fault kind {kind!r} in {entry!r}; "
                                 f"known: {sorted(_KINDS)}")
            dims, needs_dur, persistent = _KINDS[kind]
            if dim not in dims:
                raise ValueError(f"fault {kind!r} takes {dims}, got "
                                 f"{dim!r} in {entry!r}")
            if needs_dur and seconds is None:
                raise ValueError(f"fault {kind!r} needs a duration "
                                 f"(e.g. {kind}@{dim}={value}:10s)")
            if kind in _COUNT_KINDS and value < 1:
                raise ValueError(f"fault {kind!r} wants a fire budget "
                                 f">= 1, got {value} in {entry!r}")
            if kind in _TIMED_KINDS and value < 0:
                raise ValueError(f"fault {kind!r} wants a millisecond "
                                 f"offset >= 0, got {value} in {entry!r}")
            remaining = (-1 if persistent
                         else value if kind in _COUNT_KINDS else 1)
            faults.append(Fault(kind, dim, value, seconds,
                                remaining=remaining))
        return cls(faults)

    @classmethod
    def from_env(cls, env_var: str = ENV_VAR) -> "FaultPlan":
        return cls.parse(os.environ.get(env_var))

    # -- arming (serving plans) ---------------------------------------------

    def arm(self, now: Optional[float] = None) -> "FaultPlan":
        """Stamp the arming time on every not-yet-armed fault: the zero
        point for ``@t_ms=`` offsets.  Idempotent per fault — re-arming
        a plan never rewinds a running window."""
        now = time.monotonic() if now is None else now
        with _HOOK_LOCK:
            for f in self.faults:
                if f.armed_at is None:
                    f.armed_at = now
        return self

    def extend(self, spec: str, now: Optional[float] = None
               ) -> List[Fault]:
        """Parse ``spec`` and append its faults, armed at ``now`` — the
        runtime arming seam behind ``POST /debug/faults`` (the chaos
        controller schedules plan entries against trace time with it).
        Raises ValueError on a bad spec without touching the plan."""
        new = FaultPlan.parse(spec).faults
        now = time.monotonic() if now is None else now
        with _HOOK_LOCK:
            for f in new:
                f.armed_at = now
                self.faults.append(f)
        if new:
            logger.warning("fault injection: armed %s",
                           ",".join(f.spec() for f in new))
        return new

    # -- matching -----------------------------------------------------------

    def __bool__(self) -> bool:
        return bool(self.faults)

    def peek(self, kind: str, dim: str, value: int) -> Optional[Fault]:
        for f in self.faults:
            if (f.kind == kind and f.dim == dim and f.value == value
                    and f.remaining != 0):
                return f
        return None

    def _take(self, kind: str, dim: str, value: int) -> Optional[Fault]:
        with _HOOK_LOCK:
            f = self.peek(kind, dim, value)
            if f is not None and f.remaining > 0:
                f.remaining -= 1
        if f is not None:
            logger.warning("fault injection: firing %s", f.spec())
        return f

    def _take_any(self, kind: str) -> Optional[Fault]:
        """Consume one firing of the first non-exhausted fault of
        ``kind`` regardless of its value — the consult path for
        count-budget kinds (``slow_replica@request=N`` means "the next
        N consults fire", not "the N-th consult")."""
        with _HOOK_LOCK:
            f = next((f for f in self.faults
                      if f.kind == kind and f.remaining != 0), None)
            if f is not None and f.remaining > 0:
                f.remaining -= 1
        if f is not None:
            logger.warning("fault injection: firing %s", f.spec())
        return f

    # -- hooks --------------------------------------------------------------

    def at_step(self, step: int) -> Set[str]:
        """Train-loop hook, called before executing 1-based ``step``.

        Sleeps for ``slow``, self-delivers SIGTERM for ``preempt``, raises
        for ``crash``; returns the set of fired kinds (the loop poisons the
        batch itself when ``"nan"`` is in it).
        """
        fired = set()
        f = self._take("slow", "step", step)
        if f is not None:
            fired.add("slow")
            time.sleep(f.seconds)
        if self._take("nan", "step", step) is not None:
            fired.add("nan")
        if self._take("preempt", "step", step) is not None:
            fired.add("preempt")
            os.kill(os.getpid(), signal.SIGTERM)
        f = self._take("crash", "step", step)
        if f is not None:
            raise InjectedCrash(f"injected crash before step {step}")
        return fired

    def on_sample(self, index: int) -> None:
        """Loader hook, called before loading dataset ``index``."""
        f = self._take("hang", "sample", index)
        if f is not None:
            time.sleep(f.seconds)
        if self._take("corrupt", "sample", index) is not None:
            raise InjectedSampleError(f"injected corrupt sample {index}")

    def on_worker(self, worker_id: int) -> None:
        """Loader hook, called at the top of each worker load task."""
        f = self._take("hang", "worker", worker_id)
        if f is not None:
            time.sleep(f.seconds)

    def on_request(self, n: int) -> bool:
        """Serving hook, called with the 1-based count of each request a
        chaos harness issues; True exactly when ``kill_backend@request=N``
        fires — the harness then kills its victim backend, so the
        kill-mid-stream point is deterministic across runs."""
        return self._take("kill_backend", "request", n) is not None

    def on_checkpoint_saved(self, step: int, path: str) -> bool:
        """Checkpoint-manager hook: corrupt the just-saved step dir.
        Returns True if it fired (the caller must have waited for the
        async save to finish before calling)."""
        if self._take("corrupt_ckpt", "step", step) is None:
            return False
        corrupt_tree(path)
        return True

    # -- serving hooks ------------------------------------------------------

    def dispatch_delay(self) -> float:
        """Engine hook (serve/engine.py ``_dispatch``): seconds to sleep
        before the next device dispatch, 0.0 when no ``slow_replica``
        fault has budget left."""
        f = self._take_any("slow_replica")
        return f.seconds if f is not None else 0.0

    def healthz_lie(self) -> bool:
        """Server hook (/healthz): True when this reply should LIE
        ``ready: false`` on a ready server (``flap_probe@backend=N``)."""
        return self._take_any("flap_probe") is not None

    def corrupt_stream(self) -> bool:
        """Router hook (route_predict_stream): True when the next
        relayed binary frame should get one payload byte bit-flipped
        mid-pump (``corrupt_frame@request=N``)."""
        return self._take_any("corrupt_frame") is not None

    def _window_until(self, kind: str, now: float) -> Optional[float]:
        """Monotonic end time of an ACTIVE armed ``KIND@t_ms=OFF:SECS``
        window (``armed+OFF <= now < armed+OFF+SECS``), else None."""
        with _HOOK_LOCK:
            for f in self.faults:
                if f.kind != kind or f.armed_at is None:
                    continue
                start = f.armed_at + f.value / 1e3
                end = start + f.seconds
                if start <= now < end:
                    return end
        return None

    def _window_hold(self, kind: str, clock, sleep) -> float:
        held = 0.0
        while True:
            now = clock()
            end = self._window_until(kind, now)
            if end is None:
                return held
            if held == 0.0:
                logger.warning(
                    "fault injection: %s holding request %.0f ms",
                    kind, (end - now) * 1e3)
            sleep(max(end - now, 0.0))
            held += max(end - now, 0.0)

    def blackhole_until(self, now: Optional[float] = None
                        ) -> Optional[float]:
        """Monotonic end time of an ACTIVE blackhole window (armed
        ``blackhole_backend@t_ms=OFF:SECS`` with
        ``armed+OFF <= now < armed+OFF+SECS``), else None."""
        now = time.monotonic() if now is None else now
        return self._window_until("blackhole_backend", now)

    def blackhole_hold(self, clock=time.monotonic,
                       sleep=time.sleep) -> float:
        """HTTP-handler hook (serve/httpbase.py): while a blackhole
        window is active, hold the request — the connection is accepted
        but nothing is answered until the window closes.  Returns the
        seconds held (0.0 outside any window).  Injected ``clock`` /
        ``sleep`` keep the unit tests wall-clock-free."""
        return self._window_hold("blackhole_backend", clock, sleep)

    def tier_outage_until(self, now: Optional[float] = None
                          ) -> Optional[float]:
        """Monotonic end time of an ACTIVE session-tier outage window
        (armed ``tier_outage@t_ms=OFF:SECS``), else None."""
        now = time.monotonic() if now is None else now
        return self._window_until("tier_outage", now)

    def tier_outage_hold(self, clock=time.monotonic,
                         sleep=time.sleep) -> float:
        """Session-tier handler hook (stream/tier.py): while an outage
        window is active, hold the request — the tier accepts the
        connection but answers nothing until the window closes, so
        backend publishers time out and degrade.  Returns the seconds
        held (0.0 outside any window)."""
        return self._window_hold("tier_outage", clock, sleep)

    def tier_slow_delay(self) -> float:
        """Session-tier handler hook (stream/tier.py): seconds to sleep
        before serving the next tier request, 0.0 when no ``tier_slow``
        fault has budget left."""
        f = self._take_any("tier_slow")
        return f.seconds if f is not None else 0.0

    def evict_due(self, now: Optional[float] = None) -> bool:
        """Server hook: True exactly once when an armed
        ``evict_sessions@t_ms=OFF`` offset has elapsed — the caller
        evicts every live streaming session."""
        now = time.monotonic() if now is None else now
        with _HOOK_LOCK:
            f = next((f for f in self.faults
                      if f.kind == "evict_sessions" and f.remaining != 0
                      and f.armed_at is not None
                      and now >= f.armed_at + f.value / 1e3), None)
            if f is not None and f.remaining > 0:
                f.remaining -= 1
        if f is not None:
            logger.warning("fault injection: firing %s", f.spec())
        return f is not None


def corrupt_tree(path: str) -> int:
    """Overwrite every file under ``path`` with garbage (simulates torn
    writes / bit rot on the checkpoint volume).  Returns files touched."""
    n = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            with open(os.path.join(root, name), "wb") as f:
                f.write(b"\x00CORRUPTED-BY-FAULT-INJECTION\x00")
            n += 1
    logger.warning("fault injection: corrupted %d files under %s", n, path)
    return n
