"""Tracing / profiling subsystem.

The reference's only observability is wall-clock FPS in the KITTI evaluator
(reference: evaluate_stereo.py:77-81,105-107).  The TPU-native equivalent is
the XLA profiler: device traces viewable in TensorBoard / Perfetto, plus
host-side step annotations that bracket each training step so device work
lines up with program phases.  This module wraps ``jax.profiler`` so the train
CLI (``--profile_steps``) and ad-hoc scripts never import it directly, and
adds a lightweight wall-clock ``Timer`` for the places where a full trace is
overkill.
"""

from __future__ import annotations

import bisect
import contextlib
import logging
import math
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

__all__ = ["trace", "step_annotation", "StepProfiler", "Timer",
           "LatencyHistogram"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA device+host trace into ``log_dir``.

    View with ``tensorboard --logdir <log_dir>`` (Profile tab) or open the
    generated ``.trace.json.gz`` in Perfetto.  Works on TPU and CPU backends.
    """
    import jax

    jax.profiler.start_trace(log_dir)
    logger.info("Profiler trace started -> %s", log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Profiler trace written to %s", log_dir)


def step_annotation(name: str, step: int):
    """Named host annotation that the trace viewer correlates with device ops
    launched inside it (use around one training step)."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class StepProfiler:
    """Trace a window of training steps [start, stop).

    Drives ``trace`` + ``step_annotation`` from a plain per-step ``step()``
    call so the train loop stays branch-free:

        prof = StepProfiler(log_dir, start=100, stop=105)
        for i in range(num_steps):
            with prof.step(i):
                train_step(...)
    """

    def __init__(self, log_dir: str, start: int = -1, stop: int = -1):
        self.log_dir = log_dir
        self.start, self.stop = start, stop
        self._active = False

    @property
    def enabled(self) -> bool:
        return 0 <= self.start < self.stop

    @contextlib.contextmanager
    def step(self, i: int) -> Iterator[None]:
        import jax

        if not self.enabled:
            yield
            return
        # >= not ==: a resumed run whose restored step is already inside (or
        # past the start of) the window must still trace the remainder.
        if self.start <= i < self.stop and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            logger.info("Profiling steps [%d, %d) -> %s",
                        self.start, self.stop, self.log_dir)
        try:
            if self._active:
                with step_annotation("train", i):
                    yield
            else:
                yield
        except BaseException:
            # Flush the trace even when the profiled step dies — the data is
            # most wanted exactly then.
            self.close()
            raise
        if self._active and i >= self.stop - 1:
            jax.profiler.stop_trace()
            self._active = False
            logger.info("Profiler trace written to %s", self.log_dir)

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


def _log_spaced_bounds(lo: float, hi: float,
                       per_decade: int) -> Tuple[float, ...]:
    """Ascending bucket upper bounds, ``per_decade`` per factor of 10."""
    n = int(math.ceil(math.log10(hi / lo) * per_decade)) + 1
    return tuple(lo * 10 ** (i / per_decade) for i in range(n))


class LatencyHistogram:
    """Fixed-bucket histogram with percentile summaries, O(1) per observation.

    Default buckets are log-spaced (5 per decade) from 100 us to 60 s — wide
    enough for a compiled TPU forward on one end and a compile-included
    first request on the other.  Pass explicit ``bounds`` for non-latency
    quantities (e.g. batch sizes).  Thread-safe: the serve layer observes
    from the batcher worker while the HTTP threads render ``/metrics``.

    Percentiles are estimated by linear interpolation inside the containing
    bucket (clamped to the observed min/max), the standard fixed-bucket
    estimate Prometheus applies server-side — exact at bucket edges, off by
    at most one bucket width inside.
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None,
                 lo: float = 1e-4, hi: float = 60.0, per_decade: int = 5):
        self.bounds: Tuple[float, ...] = (
            tuple(sorted(bounds)) if bounds is not None
            else _log_spaced_bounds(lo, hi, per_decade))
        self._lock = threading.Lock()
        # One count per bound plus the +Inf overflow bucket.
        self._counts = [0] * (len(self.bounds) + 1)  # guarded_by: _lock
        self._count = 0  # guarded_by: _lock
        self._sum = 0.0  # guarded_by: _lock
        self._min = math.inf  # guarded_by: _lock
        self._max = -math.inf  # guarded_by: _lock

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        with self._lock:  # vs a concurrent observe() read-modify-write
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._sum

    def _snapshot(self):
        """Counts/count/sum/min/max from ONE lock acquisition — derived
        views (percentiles, Prometheus series) must all come from the same
        snapshot or a concurrent observe() makes them mutually
        inconsistent (e.g. a +Inf bucket that disagrees with _count)."""
        with self._lock:
            return (list(self._counts), self._count, self._sum,
                    self._min, self._max)

    def _percentile_from(self, counts, n, vmin, vmax, q: float) -> float:
        if not n:
            return float("nan")
        rank = q / 100.0 * n
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= rank:
                lower = self.bounds[i - 1] if i > 0 else vmin
                upper = self.bounds[i] if i < len(self.bounds) else vmax
                frac = (rank - cum) / c
                v = lower + frac * (upper - lower)
                return min(max(v, vmin), vmax)
            cum += c
        return vmax

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile (q in [0, 100]); NaN when empty."""
        counts, n, _, vmin, vmax = self._snapshot()
        return self._percentile_from(counts, n, vmin, vmax, q)

    def quantile(self, q: float) -> float:
        """Estimated q-quantile, q in [0, 1] — ``quantile(0.99)`` is
        ``percentile(99)``.  The SLO-spec convention (loadgen/slo.py,
        ``/debug/vars`` live percentiles) alongside the Prometheus-style
        ``percentile``; NaN when empty, asserts on out-of-range q."""
        assert 0.0 <= q <= 1.0, q
        return self.percentile(q * 100.0)

    def summary(self) -> Dict[str, float]:
        counts, n, total, vmin, vmax = self._snapshot()
        if not n:
            return {"count": 0}
        pct = lambda q: self._percentile_from(counts, n, vmin, vmax, q)  # noqa: E731
        return {
            "count": n,
            "total": total,
            "mean": total / n,
            "min": vmin,
            "max": vmax,
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
        }

    def cumulative(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs ending with (+inf, count) —
        the Prometheus ``_bucket{le=...}`` series.  See ``prometheus``
        for the series together with its consistent sum/count."""
        return self.prometheus()[0]

    def prometheus(self):
        """(bucket_pairs, count, sum) from one atomic snapshot, so the
        rendered ``_count`` always equals the ``le="+Inf"`` bucket."""
        counts, n, total, _, _ = self._snapshot()
        out, cum = [], 0
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, n))
        return out, n, total

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf


class Timer:
    """Wall-clock segment timer with named accumulators.

        t = Timer()
        with t("data"): batch = next(it)
        with t("step"): state, m = train_step(state, batch)
        t.summary()  # {'data': {'total': ..., 'mean': ..., 'count': N}, ...}

    O(1) memory per segment name: each accumulator is (count, total, min,
    max), never a list of observations — a Timer left running in a serving
    or long-train process must not grow without bound.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> [count, total, min, max]  # guarded_by: _lock
        self._acc: Dict[str, List[float]] = {}

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                acc = self._acc.get(name)
                if acc is None:
                    self._acc[name] = [1, dt, dt, dt]
                else:
                    acc[0] += 1
                    acc[1] += dt
                    acc[2] = min(acc[2], dt)
                    acc[3] = max(acc[3], dt)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            snap = {k: list(v) for k, v in self._acc.items()}
        return {
            k: {"total": total, "mean": total / n, "count": n,
                "min": lo, "max": hi}
            for k, (n, total, lo, hi) in snap.items() if n
        }

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()


class ProfilerBusy(RuntimeError):
    """An on-demand capture was requested while one is already running."""


class OnDemandProfiler:
    """Bounded on-demand ``jax.profiler`` windows (``POST /debug/profile``).

    One capture at a time, started from any thread, stopped by a timer
    thread after ``seconds`` — profiling is heavyweight (device trace +
    host callstacks), so two overlapping windows would corrupt each other
    and uncapped duration would let a debug endpoint degrade serving
    indefinitely.
    """

    def __init__(self, log_dir: str = "profile",
                 max_seconds: float = 120.0):
        self.log_dir = log_dir
        self.max_seconds = max_seconds
        self._lock = threading.Lock()
        self._until: Optional[float] = None  # guarded_by: _lock
        self._captures = 0  # guarded_by: _lock

    @property
    def running(self) -> bool:
        with self._lock:
            return self._until is not None

    def start(self, seconds: float,
              log_dir: Optional[str] = None) -> Dict[str, object]:
        """Begin a capture of ``seconds``; raises ``ProfilerBusy`` when one
        is already running (the mutual exclusion the endpoint maps to HTTP
        409).  Returns ``{"log_dir", "seconds", "capture"}``."""
        import jax

        seconds = float(seconds)
        if not 0 < seconds <= self.max_seconds:
            raise ValueError(
                f"seconds must be in (0, {self.max_seconds}], got {seconds}")
        target = log_dir or self.log_dir
        with self._lock:
            if self._until is not None:
                raise ProfilerBusy(
                    f"capture already running until ~{self._until:.1f} "
                    f"(perf_counter)")
            self._until = time.perf_counter() + seconds
            self._captures += 1
            capture = self._captures
        try:
            jax.profiler.start_trace(target)
        except BaseException:
            with self._lock:
                self._until = None
            raise
        logger.info("on-demand profile #%d: %.2fs -> %s",
                    capture, seconds, target)

        def _stop():
            time.sleep(seconds)
            try:
                jax.profiler.stop_trace()
                logger.info("on-demand profile #%d written to %s",
                            capture, target)
            finally:
                with self._lock:
                    self._until = None

        threading.Thread(target=_stop, daemon=True,
                         name=f"profile-stop-{capture}").start()
        return {"log_dir": target, "seconds": seconds, "capture": capture}
