"""Tracing / profiling subsystem.

The reference's only observability is wall-clock FPS in the KITTI evaluator
(reference: evaluate_stereo.py:77-81,105-107).  The TPU-native equivalent is
the XLA profiler: device traces viewable in TensorBoard / Perfetto, plus
host-side step annotations that bracket each training step so device work
lines up with program phases.  This module wraps ``jax.profiler`` so the train
CLI (``--profile_steps``) and ad-hoc scripts never import it directly, and
adds a lightweight wall-clock ``Timer`` for the places where a full trace is
overkill.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["trace", "step_annotation", "StepProfiler", "Timer"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA device+host trace into ``log_dir``.

    View with ``tensorboard --logdir <log_dir>`` (Profile tab) or open the
    generated ``.trace.json.gz`` in Perfetto.  Works on TPU and CPU backends.
    """
    import jax

    jax.profiler.start_trace(log_dir)
    logger.info("Profiler trace started -> %s", log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Profiler trace written to %s", log_dir)


def step_annotation(name: str, step: int):
    """Named host annotation that the trace viewer correlates with device ops
    launched inside it (use around one training step)."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class StepProfiler:
    """Trace a window of training steps [start, stop).

    Drives ``trace`` + ``step_annotation`` from a plain per-step ``step()``
    call so the train loop stays branch-free:

        prof = StepProfiler(log_dir, start=100, stop=105)
        for i in range(num_steps):
            with prof.step(i):
                train_step(...)
    """

    def __init__(self, log_dir: str, start: int = -1, stop: int = -1):
        self.log_dir = log_dir
        self.start, self.stop = start, stop
        self._active = False

    @property
    def enabled(self) -> bool:
        return 0 <= self.start < self.stop

    @contextlib.contextmanager
    def step(self, i: int) -> Iterator[None]:
        import jax

        if not self.enabled:
            yield
            return
        # >= not ==: a resumed run whose restored step is already inside (or
        # past the start of) the window must still trace the remainder.
        if self.start <= i < self.stop and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            logger.info("Profiling steps [%d, %d) -> %s",
                        self.start, self.stop, self.log_dir)
        try:
            if self._active:
                with step_annotation("train", i):
                    yield
            else:
                yield
        except BaseException:
            # Flush the trace even when the profiled step dies — the data is
            # most wanted exactly then.
            self.close()
            raise
        if self._active and i >= self.stop - 1:
            jax.profiler.stop_trace()
            self._active = False
            logger.info("Profiler trace written to %s", self.log_dir)

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


class Timer:
    """Wall-clock segment timer with named accumulators.

        t = Timer()
        with t("data"): batch = next(it)
        with t("step"): state, m = train_step(state, batch)
        t.summary()  # {'data': {'total': ..., 'mean': ..., 'count': N}, ...}
    """

    def __init__(self):
        self._acc: Dict[str, List[float]] = {}

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc.setdefault(name, []).append(time.perf_counter() - t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {"total": sum(v), "mean": sum(v) / len(v), "count": len(v)}
            for k, v in self._acc.items() if v
        }

    def reset(self) -> None:
        self._acc.clear()
