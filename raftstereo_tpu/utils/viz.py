"""Disparity visualisation: jet colormap + PNG writer, dependency-free.

The reference saves demo disparities with matplotlib's jet colormap
(reference: demo.py:49); this is the same classic jet ramp in pure numpy so
the demo CLI does not depend on matplotlib, written out through PIL.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from PIL import Image


def jet(x: np.ndarray) -> np.ndarray:
    """Map values in [0, 1] to the classic jet RGB ramp, uint8 (H, W, 3)."""
    x = np.clip(np.asarray(x, np.float32), 0.0, 1.0)
    r = np.clip(1.5 - np.abs(4.0 * x - 3.0), 0.0, 1.0)
    g = np.clip(1.5 - np.abs(4.0 * x - 2.0), 0.0, 1.0)
    b = np.clip(1.5 - np.abs(4.0 * x - 1.0), 0.0, 1.0)
    return (np.stack([r, g, b], axis=-1) * 255.0 + 0.5).astype(np.uint8)


def colorize(arr: np.ndarray, vmin: Optional[float] = None,
             vmax: Optional[float] = None) -> np.ndarray:
    """Normalise a scalar field to [0, 1] and apply jet (matplotlib
    ``imsave`` semantics: min/max of the data unless given)."""
    arr = np.asarray(arr, np.float32)
    lo = float(np.nanmin(arr)) if vmin is None else vmin
    hi = float(np.nanmax(arr)) if vmax is None else vmax
    scale = hi - lo if hi > lo else 1.0
    return jet((arr - lo) / scale)


def save_disparity_png(path: str, disparity: np.ndarray,
                       vmin: Optional[float] = None,
                       vmax: Optional[float] = None) -> None:
    """Write a jet-colormapped disparity image (reference: demo.py:49)."""
    Image.fromarray(colorize(disparity, vmin, vmax)).save(path)
