"""PyTorch checkpoint -> JAX variables conversion.

Loads the reference's released ``.pth`` state dicts (reference:
download_models.sh:4; saved with the DataParallel ``module.`` prefix,
train_stereo.py:187) into this framework's variables pytree, for numerical
parity evaluation and for fine-tuning from released weights.

Layout translation: torch convs are NCHW/OIHW, ours NHWC/HWIO; norm params map
weight->scale, bias->bias, running_{mean,var}->batch_stats {mean,var}.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from ..config import RAFTStereoConfig


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.detach().cpu().numpy()  # torch tensor without importing torch


def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a .pth file into a flat numpy dict (strips ``module.``)."""
    import torch

    sd = torch.load(path, map_location="cpu")
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    out = {}
    for k, v in sd.items():
        if k.startswith("module."):
            k = k[len("module."):]
        out[k] = _np(v)
    return out


# ---------------------------------------------------------------------------
# flax module path -> torch parameter prefix
# ---------------------------------------------------------------------------

def _translate_module(flax_path: tuple, shared_backbone: bool) -> str:
    """Map a flax module path (without the leaf name) to the torch prefix."""
    top, rest = flax_path[0], list(flax_path[1:])

    if top == "zqr":
        # zqr{i} -> context_zqr_convs.{i}
        assert len(rest) == 1 and rest[0].startswith("zqr")
        return f"context_zqr_convs.{rest[0][3:]}"

    def enc_part(parts):
        out = []
        for p in parts:
            if p.startswith("layer") and "_" in p:
                stage, blk = p[len("layer"):].split("_")
                out.append(f"layer{stage}.{blk}")
            elif p.startswith("head"):
                # head08_{hi}_res -> outputs08.{hi}.0 ; head08_{hi}_conv -> .1
                # head32_{hi}_conv -> outputs32.{hi}
                lvl = p[4:6]
                hi, kind = p[7:].split("_")
                if lvl == "32":
                    out.append(f"outputs32.{hi}")
                else:
                    out.append(f"outputs{lvl}.{hi}." + ("0" if kind == "res" else "1"))
            elif p == "downsample_conv":
                out.append("downsample.0")
            elif p == "downsample_norm":
                out.append("downsample.1")
            else:
                out.append(p)
        return ".".join(out)

    if top == "cnet":
        return "cnet." + enc_part(rest) if rest else "cnet"
    if top == "fnet":
        if shared_backbone:
            # SharedBackboneHead: res -> conv2.0, out -> conv2.1
            m = {"res": "conv2.0", "out": "conv2.1"}
            return enc_part([m[rest[0]]] + rest[1:])
        return "fnet." + enc_part(rest) if rest else "fnet"
    if top == "update":
        m = {"gru0": "gru08", "gru1": "gru16", "gru2": "gru32",
             "mask_conv1": "mask.0", "mask_conv2": "mask.2"}
        parts = [m.get(p, p) for p in rest]
        return "update_block." + ".".join(parts)
    raise KeyError(f"unknown flax top module {top}")


def _convert_leaf(name: str, torch_prefix: str,
                  sd: Mapping[str, np.ndarray]) -> np.ndarray:
    if torch_prefix.endswith(".convzr"):
        # Our ConvGRU fuses the reference's convz+convr into one conv
        # (models/update.py) — concatenate the torch weights on the output
        # axis; per-channel arithmetic is unchanged.
        parts = [torch_prefix[:-len("convzr")] + c for c in ("convz", "convr")]
        if name == "kernel":
            return np.concatenate(
                [np.transpose(sd[f"{p}.weight"], (2, 3, 1, 0))
                 for p in parts], axis=-1)
        if name == "bias":
            return np.concatenate([sd[f"{p}.bias"] for p in parts])
        raise KeyError(name)
    if name == "kernel":
        w = sd[f"{torch_prefix}.weight"]
        assert w.ndim == 4, (torch_prefix, w.shape)
        return np.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
    if name == "bias":
        return sd[f"{torch_prefix}.bias"]
    if name == "scale":
        return sd[f"{torch_prefix}.weight"]
    if name == "mean":
        return sd[f"{torch_prefix}.running_mean"]
    if name == "var":
        return sd[f"{torch_prefix}.running_var"]
    raise KeyError(name)


def _walk(tree: Mapping, path=()):
    for k, v in tree.items():
        if isinstance(v, Mapping):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def torch_to_variables(sd: Mapping[str, np.ndarray], template: Dict,
                       config: RAFTStereoConfig) -> Dict:
    """Fill a ``model.init``-produced variables pytree from a torch state dict.

    The template supplies structure and dtypes; every leaf is replaced by the
    translated torch tensor.  Raises KeyError on any missing torch weight —
    conversion is strict, like the reference's ``load_state_dict(strict=True)``
    (reference: train_stereo.py:147).
    """
    import jax.numpy as jnp

    out: Dict[str, Any] = {"params": {}, "batch_stats": {}}
    consumed = set()
    leaf_to_torch = {"kernel": "weight", "bias": "bias", "scale": "weight",
                     "mean": "running_mean", "var": "running_var"}

    for coll in ("params", "batch_stats"):
        for path, leaf in _walk(template.get(coll, {})):
            *mods, name = path
            prefix = _translate_module(tuple(mods), config.shared_backbone)
            arr = _convert_leaf(name, prefix, sd)
            assert arr.shape == leaf.shape, (path, arr.shape, leaf.shape)
            if prefix.endswith(".convzr"):  # fused GRU gate conv: two sources
                for c in ("convz", "convr"):
                    consumed.add(f"{prefix[:-len('convzr')]}{c}."
                                 f"{leaf_to_torch[name]}")
            else:
                consumed.add(f"{prefix}.{leaf_to_torch[name]}")
            if prefix.endswith(".downsample.1"):
                # The reference's ResidualBlock registers the projection norm
                # twice (as `norm3` and inside the downsample Sequential —
                # core/extractor.py:20,44-45), so state dicts carry aliased
                # duplicates.
                consumed.add(prefix.replace(".downsample.1", ".norm3")
                             + f".{leaf_to_torch[name]}")
            _set(out[coll], path, jnp.asarray(arr, dtype=leaf.dtype))

    # Strict in both directions, like torch's strict=True: any torch weight
    # the template did not demand means a config/architecture mismatch.
    # Exception: the reference instantiates all three GRU levels regardless of
    # n_gru_layers (core/update.py:104-106, core/extractor.py:224-250), so
    # checkpoints of shallower configs carry dead weights — allow exactly those.
    dead_prefixes = []
    if config.n_gru_layers < 3:
        dead_prefixes += ["cnet.layer5.", "cnet.outputs32.", "update_block.gru32."]
    if config.n_gru_layers < 2:
        dead_prefixes += ["cnet.layer4.", "cnet.outputs16.", "update_block.gru16."]
    leftover = {k for k in sd
                if k not in consumed and not k.endswith("num_batches_tracked")
                and not any(k.startswith(p) for p in dead_prefixes)}
    if leftover:
        raise KeyError(
            f"checkpoint has {len(leftover)} weights the model config does not "
            f"use (config mismatch?): {sorted(leftover)[:8]}...")

    if not out["batch_stats"]:
        del out["batch_stats"]
    return out


def _set(tree: Dict, path, value):
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = value


def migrate_prefusion_variables(variables: Mapping) -> Dict:
    """Migrate a weights pytree saved before the GRU gate-conv fusion
    (round 2): every ConvGRU's separate ``convz``/``convr`` become one
    ``convzr`` with kernels/biases concatenated on the output axis — the
    exact transformation the .pth converter applies, so the migrated model
    is numerically identical."""
    import jax.numpy as jnp

    def walk(tree):
        if not isinstance(tree, Mapping):
            return tree
        out = {}
        keys = set(tree)
        if {"convz", "convr"} <= keys:
            out["convzr"] = {
                "kernel": jnp.concatenate([tree["convz"]["kernel"],
                                           tree["convr"]["kernel"]], axis=-1),
                "bias": jnp.concatenate([tree["convz"]["bias"],
                                         tree["convr"]["bias"]]),
            }
            keys -= {"convz", "convr"}
        for k in keys:
            out[k] = walk(tree[k])
        return out

    return walk(variables)


def convert_checkpoint(pth_path: str, config: RAFTStereoConfig,
                       image_hw=(64, 96)) -> Dict:
    """One-call conversion: .pth -> ready-to-use variables pytree."""
    import jax

    from ..models import RAFTStereo

    model = RAFTStereo(config)
    template = model.init(jax.random.key(0), image_hw=image_hw)
    return torch_to_variables(load_state_dict(pth_path), template, config)
