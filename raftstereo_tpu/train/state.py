"""Train state pytree: step + weights + frozen BN stats + optimizer state.

Unlike the reference, which checkpoints weights only and restarts the LR
schedule on resume (reference: train_stereo.py:143-148, SURVEY.md §5), the
full state here round-trips through Orbax so resume is exact.

``batch_stats`` is constant during training: the reference freezes BatchNorm
from step 0 (``model.freeze_bn()``, train_stereo.py:152; core/raft_stereo.py:
41-44), so running stats are never updated — they only change when loading a
converted torch checkpoint.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class TrainState:
    step: jax.Array          # () int32, number of completed updates
    params: Any
    batch_stats: Any         # {} when the model has no BatchNorm
    opt_state: Any

    @property
    def variables(self) -> Dict:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v


def create_train_state(model, rng: jax.Array, tx,
                       image_hw: Tuple[int, int]) -> TrainState:
    variables = model.init(rng, image_hw)
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(params),
    )


def state_from_variables(variables: Dict, tx) -> TrainState:
    """Wrap converted/loaded weights (e.g. a torch .pth via utils.convert)
    into a fresh train state for fine-tuning."""
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(params),
    )
