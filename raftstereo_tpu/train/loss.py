"""Sequence loss over per-iteration disparity predictions.

Capability mirror of the reference's ``sequence_loss``
(reference: train_stereo.py:36-70), with the same semantics:

* gamma is adjusted to ``loss_gamma ** (15 / (n_predictions - 1))`` so the
  weight profile is invariant to the iteration count (train_stereo.py:54)
* validity mask = ``(valid >= 0.5) & (|flow_gt| < max_flow)``
  (train_stereo.py:44-47)
* per-iteration L1 is a mean over VALID pixels only (train_stereo.py:58)
* metrics: masked EPE mean + fraction of valid pixels under 1/3/5 px
  (train_stereo.py:60-68)

Predictions carry a single disparity channel (the reference zeroes the y-flow
each iteration, core/raft_stereo.py:120, so its 2-channel EPE reduces to
|dx| exactly).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def sequence_loss(disp_preds: jax.Array, disp_gt: jax.Array, valid: jax.Array,
                  loss_gamma: float = 0.9, max_flow: float = 700.0,
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """gamma-weighted L1 over all iteration predictions.

    Args:
      disp_preds: (iters, B, H, W, 1) full-resolution disparity predictions.
      disp_gt:    (B, H, W, 1) ground-truth disparity (negative x-flow).
      valid:      (B, H, W) float/bool validity.

    Returns (scalar loss, metrics dict); all values float32 scalars.
    """
    n = disp_preds.shape[0]
    assert n >= 1, n
    disp_gt = disp_gt.astype(jnp.float32)
    preds = disp_preds.astype(jnp.float32)

    mag = jnp.abs(disp_gt[..., 0])                       # (B, H, W)
    mask = (valid.astype(jnp.float32) >= 0.5) & (mag < max_flow)
    m = mask.astype(jnp.float32)[..., None]              # (B, H, W, 1)
    denom = jnp.maximum(m.sum(), 1.0)

    gamma = loss_gamma ** (15.0 / (n - 1)) if n > 1 else 1.0
    # i-th prediction weighted gamma^(n-i-1): final prediction weight 1.
    weights = jnp.power(jnp.float32(gamma),
                        jnp.arange(n - 1, -1, -1, dtype=jnp.float32))
    abs_err = jnp.abs(preds - disp_gt[None])             # (iters, B, H, W, 1)
    per_iter = (abs_err * m[None]).sum(axis=(1, 2, 3, 4)) / denom
    loss = jnp.sum(weights * per_iter)

    epe = jnp.abs(preds[-1, ..., 0] - disp_gt[..., 0])   # (B, H, W)
    mden = jnp.maximum(m[..., 0].sum(), 1.0)

    def frac_under(t):
        return ((epe < t).astype(jnp.float32) * m[..., 0]).sum() / mden

    metrics = {
        "epe": (epe * m[..., 0]).sum() / mden,
        "1px": frac_under(1.0),
        "3px": frac_under(3.0),
        "5px": frac_under(5.0),
    }
    return loss, metrics
