"""Train-side metrics bundle for the ``--metrics_port`` exporter.

Long TPU runs previously exposed their health only through the JSONL log
on disk (train/logger.py); this bundle mirrors the hot signals into a
``MetricsRegistry`` (serve/metrics.py) that ``obs.TelemetryServer`` serves
over HTTP, so a scraper sees steps/s, the data-wait fraction (is the TPU
idle waiting on the input pipeline?), the loader's self-healing gauges
(quarantines, resamples, pool recycles) and checkpoint-save latency live —
the same render format, validator and name lint as the serving metrics
(scripts/check_metrics.py keeps both namespaces collision-free).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..serve.metrics import MetricsRegistry

__all__ = ["TrainMetrics"]

# Mirrors data/loader.DataLoader.health_metrics() keys; fixed here so the
# gauges exist (and lint) from step 0, not after the first incident.
_HEALTH_GAUGES = (
    ("data_samples_retried", "sample loads that needed a retry"),
    ("data_samples_quarantined", "dataset indices quarantined as bad"),
    ("data_samples_replaced", "quarantined samples deterministically "
                              "resampled"),
    ("data_load_timeouts", "worker batches that exceeded batch_timeout"),
    ("data_pool_recycles", "worker pools recycled after a timeout"),
)

# Steps/s smoothing: high enough to damp per-step jitter, low enough that
# a throughput regression shows within ~20 steps.
_RATE_DECAY = 0.9


class TrainMetrics:
    """Every instrument the train loop exports, in one bundle."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.steps = r.counter(
            "train_steps_total", "optimizer steps completed this process")
        self.steps_per_sec = r.gauge(
            "train_steps_per_sec",
            "recent throughput, EMA over (data wait + step) wall-clock")
        self.data_wait_frac = r.gauge(
            "train_data_wait_fraction",
            "cumulative fraction of loop wall-clock spent waiting on the "
            "input pipeline (the TPU-idle signal)")
        self.skipped = r.counter(
            "train_steps_skipped_total",
            "steps whose update was dropped (nan_policy=skip)")
        self.watchdog_slow = r.counter(
            "train_watchdog_slow_total",
            "steps flagged by the step watchdog (> watchdog_factor x "
            "running median)")
        self.step_seconds = r.histogram(
            "train_step_seconds",
            "device step wall-clock (dispatch through metrics fetch)",
            lo=1e-3, hi=600.0)
        self.data_wait_seconds = r.histogram(
            "train_data_wait_seconds",
            "host wall-clock blocked on the next prefetched batch",
            lo=1e-5, hi=600.0)
        self.checkpoint_seconds = r.histogram(
            "train_checkpoint_save_seconds",
            "CheckpointManager.save call wall-clock (async saves measure "
            "the dispatch, wait=True saves the full write)",
            lo=1e-3, hi=600.0)
        self.health = {name: r.gauge(name, help_)
                       for name, help_ in _HEALTH_GAUGES}
        self._data_total = 0.0
        self._step_total = 0.0

    def observe_step(self, step_s: float, data_s: float) -> None:
        """Record one loop iteration's phase split."""
        self.steps.inc()
        self.step_seconds.observe(step_s)
        self.data_wait_seconds.observe(data_s)
        self._data_total += data_s
        self._step_total += step_s
        busy = self._data_total + self._step_total
        if busy > 0:
            self.data_wait_frac.set(self._data_total / busy)
        rate = 1.0 / max(step_s + data_s, 1e-9)
        prev = self.steps_per_sec.value
        self.steps_per_sec.set(
            rate if prev == 0.0
            else _RATE_DECAY * prev + (1 - _RATE_DECAY) * rate)

    def observe_health(self, health: Dict[str, float]) -> None:
        """Mirror ``DataLoader.health_metrics()`` (cumulative counts set
        as gauges) plus the loop's per-step flags."""
        for k, v in health.items():
            g = self.health.get(k)
            if g is not None:
                g.set(float(v))
        if health.get("watchdog_slow", 0.0) >= 0.5:
            self.watchdog_slow.inc()
