"""Training layer: loss, optimizer, sharded step, checkpointing, logging.

TPU-first replacement for the reference's training loop
(reference: train_stereo.py:133-212).
"""

from .checkpoint import (CheckpointManager, PreemptionGuard, load_weights,
                         save_weights)
from .logger import Logger
from .loss import sequence_loss
from .optim import make_optimizer, onecycle_lr
from .state import TrainState, create_train_state, state_from_variables
from .step import jit_train_step, make_train_step, merge_skipped_update

__all__ = [
    "sequence_loss", "make_optimizer", "onecycle_lr",
    "TrainState", "create_train_state", "state_from_variables",
    "make_train_step", "jit_train_step", "merge_skipped_update",
    "CheckpointManager", "PreemptionGuard", "save_weights", "load_weights",
    "Logger",
]
