"""Training metrics: running-mean console prints + TensorBoard + JSONL.

Mirror of the reference's ``Logger`` (reference: train_stereo.py:83-130):
running means over ``SUM_FREQ=100`` steps, per-batch live loss / lr scalars
(:171-172), validation dicts via ``write_dict`` (:122-127).  TensorBoard goes
through ``torch.utils.tensorboard`` when present (torch is host-side only
here); a JSONL stream is always written so metrics survive without TB.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional

SUM_FREQ = 100

logger = logging.getLogger(__name__)


def _make_tb_writer(log_dir: str):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    except Exception:  # tensorboard not installed — JSONL still covers it
        return None


class Logger:
    def __init__(self, log_dir: str = "runs", total_steps: int = 0,
                 jsonl_path: Optional[str] = None):
        self.total_steps = total_steps
        self._window = 0
        self.running: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self.log_dir = log_dir
        self.writer = _make_tb_writer(log_dir)
        self._jsonl = None
        if jsonl_path is None:
            jsonl_path = os.path.join(log_dir, "metrics.jsonl")
        os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
        self._jsonl = open(jsonl_path, "a")
        self._t0 = time.time()

    # -- per-step -----------------------------------------------------------

    def push(self, metrics: Dict[str, float]) -> None:
        """Accumulate one step's metrics; print running means every SUM_FREQ
        steps (reference: train_stereo.py:109-119)."""
        self.total_steps += 1
        self._window += 1
        for k, v in metrics.items():
            self.running[k] = self.running.get(k, 0.0) + float(v)
            self._counts[k] = self._counts.get(k, 0) + 1
        if self.total_steps % SUM_FREQ == 0:
            # Per-key divisor: not every step pushes every key (a resume
            # starts mid-window; nan_policy=skip steps push only 'skipped'),
            # and dividing a key by pushes it did not appear in would dilute
            # its mean exactly when it matters.
            means = {k: v / self._counts[k] for k, v in self.running.items()}
            rate = self._window / max(time.time() - self._t0, 1e-9)
            self._window = 0
            self._t0 = time.time()
            keys = sorted(means)
            msg = f"[{self.total_steps:6d}] " + ", ".join(
                f"{k}={means[k]:10.4f}" for k in keys)
            logger.info("%s  (%.2f it/s)", msg, rate)
            self._emit({"step": self.total_steps, "steps_per_sec": rate,
                        **means})
            if self.writer is not None:
                for k, v in means.items():
                    self.writer.add_scalar(k, v, self.total_steps)
            self.running = {}
            self._counts = {}

    def write_scalar(self, name: str, value: float,
                     step: Optional[int] = None) -> None:
        """Per-batch scalar (live_loss / lr, reference: train_stereo.py:171).

        Always lands in the JSONL stream, not just TensorBoard — on a
        torch-free host the scalars used to vanish silently."""
        step = self.total_steps if step is None else step
        self._emit({"step": step, name: float(value)})
        if self.writer is not None:
            self.writer.add_scalar(name, float(value), step)

    def write_dict(self, results: Dict[str, float]) -> None:
        """Validation results (reference: train_stereo.py:122-127)."""
        self._emit({"step": self.total_steps, **{k: float(v)
                                                 for k, v in results.items()}})
        if self.writer is not None:
            for k, v in results.items():
                self.writer.add_scalar(k, float(v), self.total_steps)

    # -- internals ----------------------------------------------------------

    def _emit(self, record: Dict) -> None:
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(record) + "\n")
            self._jsonl.flush()

    def close(self) -> None:
        # Flush the partial window: short runs (and the tail of long ones)
        # would otherwise lose up to SUM_FREQ-1 steps of metrics — including
        # the robustness gauges chaos tests assert on.
        if self._counts:
            means = {k: v / self._counts[k] for k, v in self.running.items()}
            self._emit({"step": self.total_steps, **means})
            self.running = {}
            self._counts = {}
        if self.writer is not None:
            self.writer.close()
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
