"""Orbax checkpointing: full train state + step, with retention.

Upgrades the reference's weights-only ``torch.save(model.state_dict())``
(reference: train_stereo.py:184-187,209-210; restore :143-148) to exact-resume
checkpoints: params, frozen BN stats, optimizer state, and step all
round-trip, so the LR schedule continues instead of restarting (SURVEY.md §5).
"""

from __future__ import annotations

import logging
import os
import signal
import time
from typing import Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..utils.faults import FaultPlan
from .state import TrainState

logger = logging.getLogger(__name__)


def _all_keys(tree):
    for k, v in tree.items():
        yield k
        if isinstance(v, dict):
            yield from _all_keys(v)


def _tree_has_exact_key(tree, key: str) -> bool:
    """True if any dict node in ``tree`` has a child named exactly ``key``
    (NOT substring — SepConvGRU's convz1/convr1 must not match 'convz')."""
    return isinstance(tree, dict) and key in _all_keys(tree)


def _metadata_tree(md):
    """The nested-dict structure out of an orbax metadata object
    (StepMetadata wraps TreeMetadata in .item_metadata; TreeMetadata holds
    the dict in .tree)."""
    item = getattr(md, "item_metadata", md)
    tree = getattr(item, "tree", item)
    return tree if isinstance(tree, dict) else {}


class PrefusionCheckpointError(ValueError):
    """A checkpoint with the pre-round-2 separate convz/convr GRU gates was
    loaded against the fused-convzr layout — a user error, not corruption
    (the fallback-restore path must NOT treat it as a bad step: every
    retained step shares the layout)."""


_PREFUSION_MSG = (
    "checkpoint predates the fused GRU gate conv (convz/convr -> convzr, "
    "round 2): re-export it through the .pth converter or load weights-only "
    "via utils.convert.migrate_prefusion_variables; full train states (Adam "
    "moments) cannot be migrated mechanically")


class CheckpointManager:
    """Step-indexed checkpoints under ``directory`` with max_to_keep.

    ``fault_plan`` (default: parsed from ``RAFTSTEREO_FAULTS``) lets chaos
    tests corrupt a just-saved step (``corrupt_ckpt@step=N``) to prove the
    fallback-restore path.
    """

    def __init__(self, directory: str, keep: int = 5,
                 fault_plan: Optional[FaultPlan] = None):
        directory = os.path.abspath(directory)
        self.directory = directory
        self._plan = FaultPlan.from_env() if fault_plan is None else fault_plan
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True))

    def save(self, step: int, state: TrainState, wait: bool = False) -> None:
        if step in self._mngr.all_steps():
            # Re-saving an existing step only happens after a fallback
            # restore skipped a corrupt newer step and training re-reached
            # it; the in-memory state supersedes whatever is on disk.
            # Quiesce in-flight async saves before deleting — racing a
            # pending write of this very step leaves a half-removed dir.
            logger.warning("overwriting existing checkpoint step %d", step)
            self._mngr.wait_until_finished()
            self._mngr.delete(step)
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if self._plan and self._plan.peek("corrupt_ckpt", "step", step):
            # The save is async; the corruption hook must scribble over a
            # COMPLETE checkpoint — a partial one would be caught by orbax's
            # own commit protocol, which is not the failure mode under test.
            self._mngr.wait_until_finished()
            self._plan.on_checkpoint_saved(
                step, os.path.join(self.directory, str(step)))
        elif wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mngr.all_steps())

    def restore(self, state_like: TrainState,
                step: Optional[int] = None) -> TrainState:
        """Restore into the structure of ``state_like`` (shapes/dtypes/
        shardings are taken from it)."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        tgt = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        try:
            return self._mngr.restore(step, args=ocp.args.StandardRestore(tgt))
        except Exception as e:
            # Classify by the SAVED tree's structure, not the exception text
            # (error strings need not name the keys, and substring matching
            # would also catch SepConvGRU's convz1/convr1).
            if self._saved_has_prefusion_gates(step):
                raise PrefusionCheckpointError(_PREFUSION_MSG) from e
            raise

    def restore_latest_valid(
            self, state_like: TrainState
    ) -> Tuple[Optional[TrainState], Optional[int]]:
        """Restore the newest retained step that verifies, falling back to
        older steps when the latest is corrupt (torn write, bit rot, a
        preemption mid-upload).  Returns ``(state, step)``, or ``(None,
        None)`` when no retained step restores cleanly — the caller decides
        whether that means "fresh init" (elastic recovery) or an error.

        A prefusion-layout mismatch still raises: every retained step shares
        the layout, so falling back would just fail ``keep`` times and then
        silently retrain from scratch on a *user error*.
        """
        for step in reversed(self.all_steps()):
            try:
                return self.restore(state_like, step=step), step
            except PrefusionCheckpointError:
                raise                     # layout user error — not corruption
            except Exception as e:
                logger.error(
                    "checkpoint step %d failed to restore (%s: %s) — "
                    "falling back to the previous retained step",
                    step, type(e).__name__, e)
        return None, None

    def _saved_has_prefusion_gates(self, step: int) -> bool:
        try:
            md = self._mngr.item_metadata(step)
        except Exception:
            return False
        return _tree_has_exact_key(_metadata_tree(md), "convz")

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


class PreemptionGuard:
    """SIGTERM/SIGINT → request a checkpoint at the next step boundary.

    TPU-pod preemptions (and SLURM/k8s evictions) deliver SIGTERM with a
    grace period before SIGKILL.  The handler only sets a flag; the train
    loop checks :attr:`requested` at each step boundary, saves, and exits
    cleanly (exit code 0) so the relaunch resumes at the exact step.  A
    second signal restores the previous handler and re-delivers, for
    operators who really mean "die now".
    """

    def __init__(self, grace_s: float = 30.0):
        self.grace_s = grace_s
        self._requested_at: Optional[float] = None
        self._prev = {}

    def install(self) -> "PreemptionGuard":
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handle)
        except ValueError:
            # Not the main thread (e.g. the loop embedded in a server):
            # signals go to the main thread anyway; run unguarded.
            logger.warning("PreemptionGuard: not on the main thread — "
                           "SIGTERM/SIGINT will not trigger a boundary save")
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev = {}

    def _handle(self, signum, frame):
        if self._requested_at is not None:   # second signal: die now
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        self._requested_at = time.monotonic()
        logger.warning(
            "received signal %d: checkpointing at the next step boundary "
            "and exiting (grace %.0fs; signal again to exit immediately)",
            signum, self.grace_s)

    @property
    def requested(self) -> bool:
        return self._requested_at is not None

    @property
    def deadline_passed(self) -> bool:
        return (self._requested_at is not None
                and time.monotonic() - self._requested_at > self.grace_s)


def save_weights(path: str, variables: Dict) -> None:
    """Weights-only save (the ``.pth`` equivalent) for eval/demo artifacts."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), variables, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_weights(path: str, variables_like: Optional[Dict] = None) -> Dict:
    """Load a weights-only checkpoint; ``variables_like`` (e.g. from
    ``model.init``) pins the pytree structure if given."""
    ckptr = ocp.StandardCheckpointer()
    path = os.path.abspath(path)
    if variables_like is None:
        out = ckptr.restore(path)
        # Pre-round-2 weights carry separate GRU convz/convr; migrate to
        # the fused convzr in place (numerically identical — the same
        # concat the .pth converter applies).
        leaves = {k for tree in out.values() if isinstance(tree, dict)
                  for k in _all_keys(tree)}
        if "convz" in leaves:
            from ..utils.convert import migrate_prefusion_variables
            out = migrate_prefusion_variables(out)
    else:
        tgt = jax.tree.map(ocp.utils.to_shape_dtype_struct, variables_like)
        try:
            out = ckptr.restore(path, tgt)
        except Exception as e:
            try:
                saved = _metadata_tree(ckptr.metadata(path))
            except Exception:
                saved = {}
            if _tree_has_exact_key(saved, "convz"):
                raise PrefusionCheckpointError(_PREFUSION_MSG) from e
            raise
    ckptr.close()
    return out
