"""Orbax checkpointing: full train state + step, with retention.

Upgrades the reference's weights-only ``torch.save(model.state_dict())``
(reference: train_stereo.py:184-187,209-210; restore :143-148) to exact-resume
checkpoints: params, frozen BN stats, optimizer state, and step all
round-trip, so the LR schedule continues instead of restarting (SURVEY.md §5).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import orbax.checkpoint as ocp

from .state import TrainState


def _all_keys(tree):
    for k, v in tree.items():
        yield k
        if isinstance(v, dict):
            yield from _all_keys(v)


def _tree_has_exact_key(tree, key: str) -> bool:
    """True if any dict node in ``tree`` has a child named exactly ``key``
    (NOT substring — SepConvGRU's convz1/convr1 must not match 'convz')."""
    return isinstance(tree, dict) and key in _all_keys(tree)


def _metadata_tree(md):
    """The nested-dict structure out of an orbax metadata object
    (StepMetadata wraps TreeMetadata in .item_metadata; TreeMetadata holds
    the dict in .tree)."""
    item = getattr(md, "item_metadata", md)
    tree = getattr(item, "tree", item)
    return tree if isinstance(tree, dict) else {}


_PREFUSION_MSG = (
    "checkpoint predates the fused GRU gate conv (convz/convr -> convzr, "
    "round 2): re-export it through the .pth converter or load weights-only "
    "via utils.convert.migrate_prefusion_variables; full train states (Adam "
    "moments) cannot be migrated mechanically")


class CheckpointManager:
    """Step-indexed checkpoints under ``directory`` with max_to_keep."""

    def __init__(self, directory: str, keep: int = 5):
        directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True))

    def save(self, step: int, state: TrainState, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, state_like: TrainState,
                step: Optional[int] = None) -> TrainState:
        """Restore into the structure of ``state_like`` (shapes/dtypes/
        shardings are taken from it)."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        tgt = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        try:
            return self._mngr.restore(step, args=ocp.args.StandardRestore(tgt))
        except Exception as e:
            # Classify by the SAVED tree's structure, not the exception text
            # (error strings need not name the keys, and substring matching
            # would also catch SepConvGRU's convz1/convr1).
            if self._saved_has_prefusion_gates(step):
                raise ValueError(_PREFUSION_MSG) from e
            raise

    def _saved_has_prefusion_gates(self, step: int) -> bool:
        try:
            md = self._mngr.item_metadata(step)
        except Exception:
            return False
        return _tree_has_exact_key(_metadata_tree(md), "convz")

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def save_weights(path: str, variables: Dict) -> None:
    """Weights-only save (the ``.pth`` equivalent) for eval/demo artifacts."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), variables, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_weights(path: str, variables_like: Optional[Dict] = None) -> Dict:
    """Load a weights-only checkpoint; ``variables_like`` (e.g. from
    ``model.init``) pins the pytree structure if given."""
    ckptr = ocp.StandardCheckpointer()
    path = os.path.abspath(path)
    if variables_like is None:
        out = ckptr.restore(path)
        # Pre-round-2 weights carry separate GRU convz/convr; migrate to
        # the fused convzr in place (numerically identical — the same
        # concat the .pth converter applies).
        leaves = {k for tree in out.values() if isinstance(tree, dict)
                  for k in _all_keys(tree)}
        if "convz" in leaves:
            from ..utils.convert import migrate_prefusion_variables
            out = migrate_prefusion_variables(out)
    else:
        tgt = jax.tree.map(ocp.utils.to_shape_dtype_struct, variables_like)
        try:
            out = ckptr.restore(path, tgt)
        except Exception as e:
            try:
                saved = _metadata_tree(ckptr.metadata(path))
            except Exception:
                saved = {}
            if _tree_has_exact_key(saved, "convz"):
                raise ValueError(_PREFUSION_MSG) from e
            raise
    ckptr.close()
    return out
