"""Optimizer: AdamW + one-cycle LR + global-norm gradient clipping.

Mirrors the reference recipe (reference: train_stereo.py:73-80):
``AdamW(lr, wdecay, eps=1e-8)`` + ``OneCycleLR(lr, num_steps+100,
pct_start=0.01, anneal_strategy='linear')`` + ``clip_grad_norm_(1.0)``
(train_stereo.py:176).  The schedule reproduces torch's two-phase linear
OneCycle exactly (phase boundary at ``pct_start*total - 1``, floor at
``lr / div_factor / final_div_factor``) — verified numerically against
``torch.optim.lr_scheduler.OneCycleLR`` in tests/test_train.py.

No GradScaler equivalent is needed: the bf16 policy keeps master weights and
the loss in float32, and bf16 has the same exponent range as float32, so the
underflow problem torch's AMP scaler solves (train_stereo.py:156) does not
exist on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from ..config import TrainConfig


def onecycle_lr(max_lr: float, total_steps: int, pct_start: float = 0.01,
                div_factor: float = 25.0, final_div_factor: float = 1e4):
    """Two-phase linear one-cycle schedule, torch-semantics.

    Phase 1 (steps 0 .. up_end): linear initial_lr -> max_lr,
    up_end = pct_start*total_steps - 1.
    Phase 2 (up_end .. total_steps-1): linear max_lr -> min_lr.
    """
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    up_end = pct_start * total_steps - 1.0
    down_span = (total_steps - 1.0) - up_end

    def schedule(count):
        s = jnp.asarray(count, jnp.float32)
        if up_end > 0:
            lr_up = initial_lr + (max_lr - initial_lr) * jnp.clip(
                s / up_end, 0.0, 1.0)
        else:
            lr_up = jnp.float32(max_lr)
        lr_down = max_lr + (min_lr - max_lr) * jnp.clip(
            (s - up_end) / down_span, 0.0, 1.0)
        return jnp.where(s <= up_end, lr_up, lr_down)

    return schedule


def make_optimizer(cfg: TrainConfig):
    """(optax transform, lr schedule) for the reference training recipe."""
    schedule = onecycle_lr(cfg.lr, cfg.num_steps + 100, pct_start=0.01)
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(learning_rate=schedule, b1=0.9, b2=0.999, eps=1e-8,
                    weight_decay=cfg.wdecay),
    )
    return tx, schedule
