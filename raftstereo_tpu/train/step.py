"""The training step: loss, grads, update — compiled once, sharded over a mesh.

Replaces the reference's per-batch body (reference: train_stereo.py:162-200):
forward through DataParallel, sequence loss, AMP-scaled backward, clip, step,
scheduler step.  Here the whole thing is ONE jitted function; data parallelism
is expressed by sharding the batch over the mesh's ``data`` axis while state
stays replicated — XLA emits the gradient all-reduce (SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..config import TrainConfig
from ..parallel import batch_sharded, replicated
from .loss import sequence_loss
from .state import TrainState

Batch = Tuple[jax.Array, jax.Array, jax.Array, jax.Array]  # img1,img2,disp,valid


def merge_skipped_update(finite, params, old_params, opt_state, old_opt_state):
    """The ``nan_policy=skip`` merge: where ``finite`` is False, drop the bad
    update on-device — params and optimizer moments keep their old values,
    but the LR-schedule count still advances — torch semantics, where
    GradScaler skips optimizer.step() while the loop's scheduler.step() runs
    unconditionally (reference: train_stereo.py:175-180).
    """
    keep = lambda new, old: jnp.where(finite, new, old)

    def merge(new, old):
        if isinstance(new, optax.ScaleByScheduleState):
            return new                      # schedule count advances
        if hasattr(new, "_fields"):         # optax NamedTuple states
            return type(new)(*(merge(a, b) for a, b in zip(new, old)))
        if isinstance(new, (tuple, list)):
            return type(new)(merge(a, b) for a, b in zip(new, old))
        if isinstance(new, dict):
            return {k: merge(new[k], old[k]) for k in new}
        return keep(new, old)

    return (jax.tree.map(keep, params, old_params),
            merge(opt_state, old_opt_state))


def make_train_step(model, tx, cfg: TrainConfig, lr_schedule=None,
                    photometric_params: Dict = None
                    ) -> Callable[[TrainState, Batch], Tuple[TrainState, Dict]]:
    """Build the un-jitted (state, batch) -> (state, metrics) step.

    ``photometric_params``: kwargs for ``DevicePhotometric`` when
    ``cfg.device_photometric`` — pass the output of
    ``datasets.take_photometric_params(dataset)`` so the on-device chain
    mirrors the exact host distribution (the CLI does). When None, dense
    FlowAugmentor defaults modulated by cfg's saturation/gamma flags apply.
    """

    def loss_fn(params, batch_stats, img1, img2, disp_gt, valid):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        # No trace-time STEM override here any more (round 5): the fused
        # encoder's backward now consumes the forward's saved residuals
        # (pallas_encoder._stage_bwd_xla) instead of re-linearizing the
        # XLA forward, and measures >= plain under training at the
        # per-shard batches where the auto gate engages it (b1 320x720:
        # 5.806 vs 5.777 steps/sec; at the reference recipe's 16
        # images/shard the gate declines — the Pallas FORWARD loses to
        # XLA's batch-amortized blocked lowering there, 1.205 vs 1.297,
        # same crossover as inference).  The LAYER2 stage still gates off
        # under differentiation — its backward re-linearizes the XLA
        # layer2 (the pattern that was a measured training loss on the
        # stem).  config.fused_encoder=True still forces both.
        from ..ops.pallas_layer2 import override_fused_layer2
        with override_fused_layer2(False):
            preds = model.forward(variables, img1, img2,
                                  iters=cfg.train_iters)
        return sequence_loss(preds, disp_gt, valid,
                             loss_gamma=cfg.loss_gamma, max_flow=cfg.max_flow)

    if cfg.device_photometric:
        from ..data.device_aug import DevicePhotometric
        photo_kw = photometric_params
        if photo_kw is None:
            from ..data.datasets import expand_img_gamma
            photo_kw = {}
            if cfg.saturation_range is not None:
                photo_kw["saturation"] = cfg.saturation_range
            if cfg.img_gamma is not None:
                photo_kw["gamma"] = expand_img_gamma(cfg.img_gamma)
        device_photo = DevicePhotometric(**photo_kw)
        photo_key = jax.random.key(cfg.seed)
    else:
        device_photo = None

    def step(state: TrainState, batch: Batch):
        img1, img2, disp_gt, valid = batch
        if device_photo is not None:
            # Deterministic per-step randomness: fold the step counter into
            # the seed key, split per sample inside (device_aug.py).
            img1, img2 = device_photo(
                jax.random.fold_in(photo_key, state.step), img1, img2)
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.batch_stats, img1, img2, disp_gt, valid)
        grad_norm = optax.global_norm(grads)
        # Failure detection (reference asserts on this, train_stereo.py:49-52).
        # A finite global norm implies every gradient entry is finite.
        finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if cfg.nan_policy == "skip":
            params, opt_state = merge_skipped_update(
                finite, params, state.params, opt_state, state.opt_state)
        metrics = dict(metrics, loss=loss, grad_norm=grad_norm,
                       nonfinite=1.0 - finite.astype(jnp.float32))
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state.step)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state)
        return new_state, metrics

    return step


def jit_train_step(step_fn, mesh):
    """Compile the step over a mesh: state/metrics replicated, batch sharded
    on ``data``.  ``donate_argnums=0`` reuses the old state's HBM buffers.

    The mesh is also exposed to tracing via ``use_corr_mesh`` so Pallas corr
    backends partition over it (shard_map) instead of being replicated
    custom-call islands (parallel/context.py)."""
    from ..parallel.context import use_corr_mesh

    repl = replicated(mesh)
    data = batch_sharded(mesh)
    jitted = jax.jit(step_fn,
                     in_shardings=(repl, (data, data, data, data)),
                     out_shardings=(repl, repl),
                     donate_argnums=(0,))

    def call(state, batch):
        with use_corr_mesh(mesh):  # active at (first-call) trace time
            return jitted(state, batch)

    return call
