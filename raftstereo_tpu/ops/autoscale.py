"""Autoscaling recommendation loop over the landed cluster signals.

docs/serving.md has named ``cluster_utilization``, ``sched_occupancy``
and the shed rate (``cluster_dispatch_total{outcome="shed"}``) as the
autoscaling inputs since PR 8 — this module is the first consumer.  It
is deliberately stdlib-only (no jax, no numpy): the in-process
dispatcher and the model-free ``cli.router`` both embed it, and the
router must stay importable without the engine stack
(``tests/test_cluster.py::test_router_import_is_model_free``).

The loop only RECOMMENDS — surfacing advice in ``/debug/vars`` and the
``cluster_autoscale_recommendation`` gauge (positive = scale out,
negative = scale in, 0 = hold).  Acting on it is the operator's (or an
external controller's) job: this container cannot add chips, and a
wrong automatic scale-in would shed real traffic.  Recommendations are
hysteresis-damped (``AutoscalePolicy.hysteresis`` consecutive
observations agree before advice becomes non-zero) so a single bursty
scrape never flaps the gauge — except sheds, which mean traffic was
REFUSED and warrant immediate scale-out advice.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
from typing import Dict, Optional, Tuple

__all__ = ["AutoscalePolicy", "Autoscaler", "load_capacity_model",
           "recommend"]


def load_capacity_model(path: str) -> Dict:
    """Read a ``loadgen.capacity`` JSON model (``cli.loadgen fit``)
    for the autoscaler.  Stdlib re-implementation of
    ``loadgen.capacity.load_model`` ON PURPOSE: the model-free router
    embeds this module and must not import the loadgen package (whose
    replay engine pulls the serve client stack)."""
    with open(path) as f:
        model = json.load(f)
    if model.get("capacity_model") != "raftstereo_tpu.loadgen.capacity":
        raise ValueError(f"{path}: not a capacity model file")
    if not isinstance(model.get("per_chip_rps"), (int, float)):
        raise ValueError(f"{path}: capacity model has no per_chip_rps")
    return model


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Thresholds for the recommendation loop (fractions are 0-1)."""

    # Mean occupied fraction of ready replicas' batch capacity above
    # which the cluster is running hot (cluster_utilization).
    high_utilization: float = 0.75
    # Below this, capacity is idle enough to recommend scale-in.
    low_utilization: float = 0.25
    # Scheduler-mode occupancy (sched_occupancy) that signals the
    # running batches themselves are saturated.
    high_occupancy: float = 0.85
    # Session-state memory pressure (accounted stream_session_bytes over
    # the fleet's configured byte budget, stream/session.py) above which
    # the fleet is about to pay budget evictions — each one turns a live
    # stream's next frame cold, so scale out BEFORE the budget trips.
    high_memory_pressure: float = 0.9
    # Page-qualified SLO burn rate (obs/alerts.py: min(fast, slow)
    # window burn across alert classes) at or above which the error
    # budget is burning fast enough to PAGE — the fleet is failing its
    # SLO right now, so scale out even while utilization looks sane
    # (e.g. errors from a degraded backend, not from saturation).
    high_alert_burn: float = 2.0
    # Never recommend scaling below this many replicas.
    min_replicas: int = 1
    # Largest single-step recommendation in either direction.
    max_step: int = 1
    # Consecutive agreeing observations before non-shed advice fires.
    hysteresis: int = 2


def recommend(policy: AutoscalePolicy, *, ready: int, utilization: float,
              occupancy: Optional[float] = None,
              shed_delta: float = 0.0,
              memory_pressure: float = 0.0,
              alert_burn: float = 0.0) -> Tuple[int, str]:
    """Classify ONE observation into ``(direction, reason)`` with
    direction in {-1, 0, +1}.  Pure — the stateful hysteresis/shed-rate
    tracking lives in :class:`Autoscaler`."""
    if ready <= 0:
        return 0, "no ready replicas to measure"
    if shed_delta > 0:
        return 1, (f"shed {shed_delta:g} request(s) since last "
                   "observation — capacity was refused")
    if alert_burn >= policy.high_alert_burn:
        return 1, (f"SLO burn rate {alert_burn:.2f} >= "
                   f"{policy.high_alert_burn:.2f} — error budget "
                   "burning at page rate")
    if utilization >= policy.high_utilization:
        return 1, (f"utilization {utilization:.2f} >= "
                   f"{policy.high_utilization:.2f}")
    if occupancy is not None and occupancy >= policy.high_occupancy:
        return 1, (f"sched occupancy {occupancy:.2f} >= "
                   f"{policy.high_occupancy:.2f}")
    if memory_pressure >= policy.high_memory_pressure:
        return 1, (f"session memory pressure {memory_pressure:.2f} >= "
                   f"{policy.high_memory_pressure:.2f} — budget "
                   "evictions imminent")
    if utilization <= policy.low_utilization and \
            ready > policy.min_replicas:
        return -1, (f"utilization {utilization:.2f} <= "
                    f"{policy.low_utilization:.2f} with {ready} ready")
    return 0, "signals within band"


class Autoscaler:
    """Stateful wrapper: tracks the shed-counter delta and the
    hysteresis streak across observations.  Thread-safe — the dispatcher
    calls ``observe`` from every request-settling thread."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None,
                 capacity: Optional[Dict] = None,
                 target_rps: float = 0.0):
        """``capacity`` is an optional fitted model dict
        (``load_capacity_model`` / ``loadgen.capacity.fit``); with one,
        every advice carries a ``capacity`` block sizing the cluster
        for ``target_rps`` (requests/s the operator plans for) instead
        of only reacting to gauges."""
        self.policy = policy or AutoscalePolicy()
        self.capacity = capacity
        self.target_rps = float(target_rps)
        self._lock = threading.Lock()
        self._last_shed = 0.0  # guarded_by: _lock
        self._streak_dir = 0  # guarded_by: _lock
        self._streak = 0  # guarded_by: _lock

    def capacity_advice(self, ready: int) -> Optional[Dict[str, object]]:
        """Model-based sizing for the planned ``target_rps``:
        recommended replica count and the headroom fraction of the
        CURRENT fleet (1 = fully idle capacity, 0 = at the fitted
        limit, negative = past it).  None without a model."""
        if self.capacity is None:
            return None
        per_chip = float(self.capacity.get("per_chip_rps", 0.0))
        target = self.target_rps
        if per_chip <= 0:
            recommended = None
            headroom = 0.0
        else:
            recommended = max(self.policy.min_replicas,
                              int(math.ceil(target / per_chip))
                              if target > 0 else self.policy.min_replicas)
            fleet_rps = max(ready, 0) * per_chip
            headroom = (1.0 - target / fleet_rps) if fleet_rps > 0 else 0.0
        return {
            "per_chip_rps": per_chip,
            "target_rps": target,
            "recommended_replicas": recommended,
            "headroom": round(headroom, 4),
        }

    def observe(self, *, ready: int, utilization: float,
                occupancy: Optional[float] = None,
                shed_total: float = 0.0,
                memory_pressure: float = 0.0,
                alert_burn: float = 0.0) -> Dict[str, object]:
        """Fold one observation in; returns the advice dict surfaced in
        ``/debug/vars`` (``delta`` is what the gauge exports).
        ``alert_burn`` is the live page-qualified SLO burn
        (``obs.alerts.BurnRateAlerts.max_burn``) — 0.0 when alerting is
        not wired or has not evaluated yet."""
        policy = self.policy
        with self._lock:
            shed_delta = max(0.0, shed_total - self._last_shed)
            self._last_shed = max(self._last_shed, shed_total)
            direction, reason = recommend(
                policy, ready=ready, utilization=utilization,
                occupancy=occupancy, shed_delta=shed_delta,
                memory_pressure=memory_pressure, alert_burn=alert_burn)
            if direction == self._streak_dir:
                self._streak += 1
            else:
                self._streak_dir, self._streak = direction, 1
            # Sheds mean refused traffic: act on the first observation.
            fire = direction != 0 and (shed_delta > 0
                                       or self._streak >= policy.hysteresis)
            delta = direction * policy.max_step if fire else 0
            if delta < 0:
                delta = -min(-delta, max(0, ready - policy.min_replicas))
        action = ("scale_up" if delta > 0
                  else "scale_down" if delta < 0 else "hold")
        advice: Dict[str, object] = {
            "action": action,
            "delta": delta,
            "reason": reason,
            "signals": {
                "ready": ready,
                "utilization": round(utilization, 4),
                "occupancy": (round(occupancy, 4)
                              if occupancy is not None else None),
                "shed_delta": shed_delta,
                "memory_pressure": round(memory_pressure, 4),
                "alert_burn": round(alert_burn, 4),
            },
        }
        cap = self.capacity_advice(ready)
        if cap is not None:
            advice["capacity"] = cap
        return advice
