"""Image-space primitives shared by the model, correlation engine and eval.

All tensors are NHWC (TPU-native convolution layout), in contrast to the
reference's NCHW.  Semantics are kept bit-compatible with the reference ops
they replace so that converted checkpoints reproduce the same numerics:

* ``resize_bilinear_align_corners``  ==  ``F.interpolate(..., mode='bilinear',
  align_corners=True)`` (reference: core/update.py:93-95, core/utils/utils.py:82-84)
* ``avg_pool2x``  ==  ``F.avg_pool2d(x, 3, stride=2, padding=1)`` with
  count_include_pad=True (reference: core/update.py:87-88)
* ``avg_pool_w2``  ==  ``F.avg_pool2d(x, [1,2], stride=[1,2])`` over the W axis
  (reference: core/corr.py:124)
* ``InputPadder``  ==  replicate padding to a divisibility constraint
  (reference: core/utils/utils.py:7-26)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _axis_resize_indices(in_size: int, out_size: int):
    """Source indices + lerp weight for align-corners resize along one axis."""
    if out_size == 1 or in_size == 1:
        idx = np.zeros((out_size,), np.int32)
        return idx, idx, np.zeros((out_size,), np.float32)
    pos = np.arange(out_size, dtype=np.float64) * (in_size - 1) / (out_size - 1)
    i0 = np.floor(pos).astype(np.int32)
    i0 = np.minimum(i0, in_size - 1)
    i1 = np.minimum(i0 + 1, in_size - 1)
    w = (pos - i0).astype(np.float32)
    return i0, i1, w


def resize_bilinear_align_corners(x: jax.Array, out_hw: Tuple[int, int]) -> jax.Array:
    """Bilinear resize with align_corners=True semantics.  x: (B, H, W, C).

    ``jax.image.resize`` uses half-pixel centres, which does not match the
    reference's ``align_corners=True`` (core/update.py:94); this separable
    gather+lerp formulation does, and XLA fuses it cleanly.
    """
    b, h, w, c = x.shape
    oh, ow = out_hw
    if (h, w) == (oh, ow):
        return x
    # Lerp in the INPUT dtype for the two compute dtypes the model uses:
    # fp32 inputs keep exact fp32 lerps (the eval-parity path), while
    # bf16 inputs stay bf16 end to end — the fp32 upcast doubled the
    # in-loop resizes' HBM traffic for weight precision the
    # bf16-quantized operands cannot use.  Everything else (ints, fp16)
    # lerps in fp32 as before.
    dtype = x.dtype
    cdt = dtype if dtype in (jnp.float32, jnp.bfloat16) else jnp.float32
    xf = x.astype(cdt)
    i0, i1, wh = _axis_resize_indices(h, oh)
    wh = wh.astype(cdt)
    xf = (xf[:, i0] * (1 - wh)[None, :, None, None]
          + xf[:, i1] * wh[None, :, None, None])
    j0, j1, ww = _axis_resize_indices(w, ow)
    ww = ww.astype(cdt)
    xf = (xf[:, :, j0] * (1 - ww)[None, None, :, None]
          + xf[:, :, j1] * ww[None, None, :, None])
    return xf.astype(dtype)


def avg_pool2x(x: jax.Array) -> jax.Array:
    """3x3/stride-2/pad-1 average pool, zeros counted in the divisor.

    Matches torch ``F.avg_pool2d(x, 3, stride=2, padding=1)`` defaults
    (count_include_pad=True), used to pass fine GRU state down one level
    (reference: core/update.py:87-88).
    """
    # Plain-python 0.0 init (weak-typed): a concrete bf16 zero constant here
    # breaks linearization when the surrounding computation is differentiated
    # inside a lax.fori_loop body (bench --train hits this).
    s = jax.lax.reduce_window(
        x, 0.0, jax.lax.add,
        window_dimensions=(1, 3, 3, 1), window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)))
    return s / jnp.asarray(9.0, dtype=x.dtype)


def avg_pool4x(x: jax.Array) -> jax.Array:
    """5x5/stride-4/pad-1 average pool (reference: core/update.py:90-91)."""
    s = jax.lax.reduce_window(
        x, 0.0, jax.lax.add,
        window_dimensions=(1, 5, 5, 1), window_strides=(1, 4, 4, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)))
    return s / jnp.asarray(25.0, dtype=x.dtype)


def avg_pool_w2(x: jax.Array) -> jax.Array:
    """Average-pool by 2 along the second-to-last (W2) axis of (..., W2).

    Valid padding: an odd trailing element is dropped, matching torch's floor
    behaviour for ``F.avg_pool2d(x, [1,2], stride=[1,2])``
    (reference: core/corr.py:124).  Operates on the LAST axis.
    """
    w = x.shape[-1]
    x = x[..., : (w // 2) * 2]
    shape = x.shape[:-1] + (w // 2, 2)
    return jnp.mean(x.reshape(shape), axis=-1)


def gauss_blur(x: jax.Array, n: int = 5, std: float = 1.0) -> jax.Array:
    """Depthwise Gaussian blur (reference: core/utils/utils.py:86-93)."""
    g = np.arange(n, dtype=np.float64) - n // 2
    k = np.exp(-(g[:, None] ** 2 + g[None, :] ** 2) / (2 * std ** 2))
    k = (k / max(k.sum(), 1e-4)).astype(np.float32)
    c = x.shape[-1]
    kernel = jnp.tile(jnp.asarray(k)[:, :, None, None], (1, 1, 1, c))
    return jax.lax.conv_general_dilated(
        x.astype(jnp.float32), kernel,
        window_strides=(1, 1), padding=[(n // 2, n // 2)] * 2,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c).astype(x.dtype)


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """Forward-warp a flow field for warm-starting the next frame's estimate
    (reference: core/utils/utils.py:28-56).

    Host-side by design (as in the reference, which moves to CPU first): this
    runs once per frame between device steps, feeding the model's
    ``flow_init`` hook.  ``flow`` is (2, H, W) [dx, dy] or (H, W) x-flow only
    (the stereo case); returns the same shape, float32.  Each source pixel's
    flow is splatted to where it lands; holes are filled by nearest-neighbour
    interpolation, out-of-frame splats are dropped.
    """
    from scipy import interpolate as _interp

    flow = np.asarray(flow, np.float32)
    stereo = flow.ndim == 2
    if stereo:
        flow = np.stack([flow, np.zeros_like(flow)], axis=0)
    dx, dy = flow[0], flow[1]
    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))
    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dxf, dyf = dx.reshape(-1), dy.reshape(-1)
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    if not valid.any():
        out = np.zeros_like(flow)
        return out[0] if stereo else out
    pts = (x1[valid], y1[valid])
    fx = _interp.griddata(pts, dxf[valid], (x0, y0), method="nearest",
                          fill_value=0)
    if stereo:
        return fx.astype(np.float32)
    fy = _interp.griddata(pts, dyf[valid], (x0, y0), method="nearest",
                          fill_value=0)
    return np.stack([fx, fy], axis=0).astype(np.float32)


def replicate_pad(x: jax.Array, pad: Sequence[int]) -> jax.Array:
    """Edge-replicate pad; pad = (left, right, top, bottom) on (B, H, W, C)."""
    l, r, t, b = pad
    return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")


class InputPadder:
    """Pads NHWC images so H and W are divisible by ``divis_by``.

    Same layout policy as the reference (core/utils/utils.py:7-26):
    'sintel' mode splits padding around the image, otherwise all height
    padding goes to the bottom.  Works on jax arrays and numpy arrays.
    """

    def __init__(self, dims: Sequence[int], mode: str = "sintel", divis_by: int = 8):
        self.ht, self.wd = dims[-3:-1] if len(dims) == 4 else dims[-2:]
        pad_ht = (((self.ht // divis_by) + 1) * divis_by - self.ht) % divis_by
        pad_wd = (((self.wd // divis_by) + 1) * divis_by - self.wd) % divis_by
        if mode == "sintel":
            self._pad = (pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2)
        else:
            self._pad = (pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht)

    @property
    def padded_hw(self) -> Tuple[int, int]:
        l, r, t, b = self._pad
        return self.ht + t + b, self.wd + l + r

    def pad(self, *inputs: jax.Array):
        assert all(x.ndim == 4 for x in inputs)
        out = [replicate_pad(x, self._pad) for x in inputs]
        return out if len(out) > 1 else out[0]

    def unpad(self, x: jax.Array) -> jax.Array:
        assert x.ndim == 4
        l, r, t, b = self._pad
        ht, wd = x.shape[1:3]
        return x[:, t:ht - b, l:wd - r, :]


class BucketPadder:
    """Single source of truth for the pad-and-bucket shape policy shared by
    the eval runner (eval/runner.py) and the serving engine (serve/engine.py).

    Two stages: ``InputPadder`` alignment to ``divis_by`` first (same split
    policy as the reference), then an optional round-up of the padded shape
    to the coarser ``bucket_multiple`` grid with edge-replicate rows/columns
    on the bottom/right, so near-identical image sizes share one compiled
    executable.  Callers that agree on (divis_by, bucket_multiple, mode)
    produce bitwise-identical padded tensors — the property the serve layer's
    batched outputs == single-image Evaluator outputs test rests on.

    ``dims`` may be (H, W), (H, W, C) or (B, H, W, C).
    """

    def __init__(self, dims: Sequence[int], divis_by: int = 32,
                 bucket_multiple: Optional[int] = None, mode: str = "sintel"):
        if len(dims) == 3:
            hw: Sequence[int] = dims[:2]
        elif len(dims) == 4:
            hw = dims[1:3]
        else:
            hw = dims
        self._padder = InputPadder(hw, mode=mode, divis_by=divis_by)
        ph, pw = self._padder.padded_hw
        m = bucket_multiple or 1
        self.extra_h = (-ph) % m
        self.extra_w = (-pw) % m
        self.bucket_hw: Tuple[int, int] = (ph + self.extra_h,
                                           pw + self.extra_w)

    def pad(self, *inputs: jax.Array):
        out = self._padder.pad(*inputs)
        if len(inputs) == 1:
            out = [out]
        if self.extra_h or self.extra_w:
            out = [replicate_pad(x, (0, self.extra_w, 0, self.extra_h))
                   for x in out]
        return out if len(out) > 1 else out[0]

    def unpad(self, x: jax.Array) -> jax.Array:
        if self.extra_h or self.extra_w:
            x = x[:, :x.shape[1] - self.extra_h,
                  :x.shape[2] - self.extra_w, :]
        return self._padder.unpad(x)


def coords_grid_x(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """x-coordinate grid (B, H, W, 1).

    The reference carries a full 2-channel (x, y) grid (core/utils/utils.py:76-79)
    but zeroes the y update every iteration (core/raft_stereo.py:120) — for
    stereo only the x channel ever changes.  We carry x only and materialise a
    zero y channel where the motion encoder needs 2-channel flow.
    """
    x = jnp.arange(wd, dtype=dtype)
    return jnp.broadcast_to(x[None, None, :, None], (batch, ht, wd, 1))
