"""Pallas TPU kernel for the correlation-pyramid lookup.

This is the TPU-native equivalent of the reference's CUDA extension
(reference: sampler/sampler.cpp, sampler/sampler_kernel.cu): per output pixel,
interpolate 2r+1 taps from its correlation row.  Where the CUDA kernel gathers
2r+2 integer taps and lerps (sampler_kernel.cu:19-60), a TPU kernel must avoid
per-lane gathers entirely — instead each (row-block, tap) output is computed as
a masked reduction over the whole W2 row with the hat weight

    w(j) = relu(1 - |j - x_k|)

which is algebraically identical to two-tap linear interpolation with zero
padding (see ops/sampler.linear_sample_1d_dense, the XLA oracle for this
kernel).  The reduction is pure VPU work: broadcast-compare-multiply-add over
a VMEM-resident row block, no scatter/gather anywhere.

The backward pass mirrors the CUDA scatter-add backward
(sampler_kernel.cu:63-105) but again as a dense product:
    dvol[w1, j] = sum_k g[w1, k] * w_k(j)
Gradients w.r.t. coordinates are not needed: the model detaches the disparity
at the top of every refinement iteration (reference: core/raft_stereo.py:109,
CorrSampler.backward likewise returns None for coords, core/corr.py:24-29).

Supports fp32 and bf16 volumes (the CUDA kernel's
AT_DISPATCH_FLOATING_TYPES_AND_HALF, sampler_kernel.cu:126); accumulation is
always fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Max rows (W1 pixels) per block; lane-width multiple keeps the VPU fully busy.
_BLOCK_W1 = 256

# (B*H) rows per grid step.  One row per step (round 1) made the flagship
# lookup grid 136 steps long and per-step overhead (~7 us: Mosaic grid
# bookkeeping + DMA issue latency through this chip's fabric) dominated the
# kernel — measured 0.97 ms/call while the pure matmul+VPU work costs ~0.3 ms.
# Batching rows per step amortizes that overhead; flat inputs are row-padded
# to this multiple (zero rows correlate/scatter to exactly zero, and padded
# outputs are sliced off).
_BLOCK_ROWS = 8


# Row-blocked grids need more scoped VMEM than Mosaic's 16 MB default
# (R=8 fp32 flagship blocks are ~44 MB across double buffers); v5e carries
# 128 MB of VMEM per core, so raise the scoped limit rather than shrink R.
# (``TPUCompilerParams`` is the pre-0.4.34 name of ``CompilerParams``.)
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def _pad_rows(x: jax.Array, r: int = _BLOCK_ROWS) -> jax.Array:
    """Zero-pad axis 0 (flattened B*H rows) to a multiple of ``r``."""
    pad = (-x.shape[0]) % r
    if not pad:
        return x
    return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))

# None = auto (compile on TPU backends, interpret elsewhere).  Set True to
# force interpret mode, e.g. when debugging CPU-placed execution on a TPU host
# (auto-detection keys off the default backend, not actual placement).
interpret_override = None


def _interpret() -> bool:
    if interpret_override is not None:
        return interpret_override
    return jax.default_backend() not in ("tpu",)


def _block_w1(w1: int) -> int:
    """Row-block size: cap at _BLOCK_W1 but don't pad small W1 up to it —
    the dense reduction's FLOPs scale with the padded row count."""
    return min(_BLOCK_W1, -(-w1 // 8) * 8)


def _lookup_kernel(vol_ref, taps_ref, out_ref, *, bounds):
    """One (n, w1-block), ALL pyramid levels against a W2-concatenated
    volume: out[w1, l*K + k] = sum_j vol_l[w1, j] * hat(j - taps[w1, l*K+k]).

    ``bounds`` is a static (offset, padded-width) per level; levels are
    zero-padded to lane multiples so each slice is lane-aligned and a
    padded column contributes exactly zero (zero-outside semantics without
    masks — same construction as pallas_alt). Single-level callers use
    bounds=((0, w2),).
    """
    vol = vol_ref[...].astype(jnp.float32)        # (R, W1_t, W2cat)
    taps = taps_ref[...].astype(jnp.float32)      # (R, W1_t, L*K)
    kk = taps.shape[-1] // len(bounds)
    cols = []
    for li, (off, w2p) in enumerate(bounds):
        vl = vol[:, :, off:off + w2p]
        # Mosaic requires integer iota; cast to f32 for the hat weights.
        j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2p), 2).astype(jnp.float32)
        for ki in range(kk):                       # L*K is small: unrolled
            t = taps[:, :, li * kk + ki][..., None]
            w = jnp.maximum(0.0, 1.0 - jnp.abs(j - t))
            cols.append(jnp.sum(vl * w, axis=-1))  # (R, W1_t)
    out_ref[...] = jnp.stack(cols, axis=-1).astype(out_ref.dtype)


def _lookup_bwd_kernel(taps_ref, g_ref, dvol_ref, *, bounds):
    """dvol_l[w1, j] = sum_k g[w1, l*K + k] * hat(j - taps[w1, l*K + k])."""
    taps = taps_ref[...].astype(jnp.float32)      # (R, W1_t, L*K)
    g = g_ref[...].astype(jnp.float32)            # (R, W1_t, L*K)
    kk = taps.shape[-1] // len(bounds)
    parts = []
    for li, (off, w2p) in enumerate(bounds):
        j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2p), 2).astype(jnp.float32)
        acc = jnp.zeros(taps.shape[:2] + (w2p,), jnp.float32)
        for ki in range(kk):
            t = taps[:, :, li * kk + ki][..., None]
            w = jnp.maximum(0.0, 1.0 - jnp.abs(j - t))
            acc = acc + g[:, :, li * kk + ki][..., None] * w
        parts.append(acc)
    # Grad mass on padded columns lands in rows the caller's concat-pad
    # autodiff discards.
    dvol_ref[...] = jnp.concatenate(parts, axis=-1).astype(dvol_ref.dtype)


def _pad_w1(x, block):
    w1 = x.shape[1]
    pad = (-w1) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x, w1


def preflatten_volume(vol: jax.Array) -> jax.Array:
    """(B, H, W1, W2) -> (B*H, W1p, W2) flattened + W1-padded for the kernel.

    Do this ONCE per volume, outside any iteration loop: the pad is a real
    HBM copy of the whole volume.  Hoisting it here guarantees a single copy
    structurally instead of relying on XLA's loop-invariant code motion to
    lift it out of the GRU scan (measured: XLA does hoist it on TPU today,
    so this is neutral there — but interpret-mode/CPU callers and future
    compiler versions get the guarantee).
    """
    blk = _block_w1(vol.shape[2])
    v, _ = _pad_w1(vol.reshape(vol.shape[0] * vol.shape[1], *vol.shape[2:]),
                   blk)
    return _pad_rows(v)


LANE = 128


def pad_lane(x: jax.Array, axis: int) -> jax.Array:
    """Zero-pad ``axis`` to a lane-width multiple so static slices of a
    level concat are lane-aligned inside the fused kernels; zero columns
    contribute exactly zero to every lookup. Shared by both fused pyramid
    paths (this module's volume lookup and pallas_alt's on-demand one)."""
    pad = (-x.shape[axis]) % LANE
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def bounds_from_widths(w2s) -> tuple:
    """Per-level (offset, width) pairs for a W2-concatenated pyramid."""
    bounds = []
    off = 0
    for w2 in w2s:
        bounds.append((off, w2))
        off += w2
    return tuple(bounds)


def pad_vol_lane(vflat: jax.Array) -> jax.Array:
    """(B*H, W1p, W2) volume level -> lane-multiple W2 (see pad_lane)."""
    return pad_lane(vflat, 2)


def pallas_lookup_flat(vflat: jax.Array, taps: jax.Array) -> jax.Array:
    """Lookup against a :func:`preflatten_volume` result.  taps stays in
    model layout (B, H, W1, K); only the (small) taps tensor is reshaped and
    padded per call.  Single-level special case of the fused pyramid path."""
    return _make_lookup(vflat.shape, (vflat.shape[2],),
                        vflat.dtype.name)(vflat, taps)


def pallas_lookup_pyramid_flat(vcat: jax.Array, taps: jax.Array,
                               w2s: tuple) -> jax.Array:
    """All pyramid levels in ONE kernel call.

    vcat: per-level ``preflatten_volume`` + ``pad_vol_lane`` results
    concatenated along W2; taps: (B, H, W1, L*K) per-level LOCAL taps,
    level-major; w2s: static per-level PADDED widths.
    """
    return _make_lookup(vcat.shape, tuple(w2s), vcat.dtype.name)(vcat, taps)


def pallas_lookup(vol: jax.Array, taps: jax.Array) -> jax.Array:
    """Forward-equivalent of :func:`linear_sample_1d` running as a Pallas TPU
    kernel.  vol: (B, H, W1, W2); taps: (B, H, W1, K) -> (B, H, W1, K) f32.

    Autodiff divergence from the oracle, by design: gradients w.r.t. ``taps``
    are hard zeros (the model detaches disparity every iteration, and the
    reference CUDA op likewise returns no coords grad: core/corr.py:29), and
    forward-mode AD is unsupported (custom_vjp).  Use ``linear_sample_1d`` if
    you need either.  Loop callers should :func:`preflatten_volume` once and
    use :func:`pallas_lookup_flat` per iteration.
    """
    return pallas_lookup_flat(preflatten_volume(vol), taps)


@functools.lru_cache(maxsize=None)
def _make_lookup(vflat_shape, w2s, vol_dtype_name):
    """custom_vjp instance per static (flat shape, level widths, dtype) —
    residuals carry only the taps; the volume's shape/dtype ride in the
    closure."""
    bounds = bounds_from_widths(w2s)

    @jax.custom_vjp
    def f(vflat, taps):
        return _lookup_fwd_impl(vflat, taps, bounds)

    def fwd(vflat, taps):
        return _lookup_fwd_impl(vflat, taps, bounds), taps

    def bwd(taps, g):
        dvflat = _lookup_bwd_impl(taps, g, vflat_shape, vol_dtype_name,
                                  bounds)
        # No coordinate gradient by design (disparity is detached per
        # iteration; the reference kernel likewise returns None:
        # core/corr.py:29).
        return dvflat, jnp.zeros_like(taps)

    f.defvjp(fwd, bwd)
    return f


def _pad_taps(taps, nrows=None):
    """(B, H, W1, K) -> (nrows, W1p, K) matching the flat operand's row pad."""
    b, h, w1, kk = taps.shape
    blk = _block_w1(w1)
    t, _ = _pad_w1(taps.reshape(b * h, w1, kk), blk)
    t = _pad_rows(t)
    if nrows is not None and t.shape[0] != nrows:
        raise ValueError(f"taps rows {t.shape[0]} != flat rows {nrows}; "
                         "was the flat operand preflattened with a "
                         "different batch/height?")
    return t, blk


def _lookup_fwd_impl(vflat, taps, bounds):
    vflat = _pad_rows(vflat)  # no-op for preflatten_volume outputs
    n, w1p, w2 = vflat.shape
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps, n)
    r = _BLOCK_ROWS
    out = pl.pallas_call(
        functools.partial(_lookup_kernel, bounds=bounds),
        out_shape=jax.ShapeDtypeStruct((n, w1p, kk), jnp.float32),
        grid=(n // r, w1p // blk),
        in_specs=[
            pl.BlockSpec((r, blk, w2), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, blk, kk), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(vflat, t)
    return out[:b * h, :w1].reshape(b, h, w1, kk)


def _lookup_bwd_impl(taps, g, vflat_shape, vol_dtype_name, bounds):
    n0, w1p, w2 = vflat_shape  # the primal's rows (maybe not block-padded)
    n = n0 + (-n0) % _BLOCK_ROWS
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps, n)
    gg, _ = _pad_w1(g.reshape(b * h, w1, kk), blk)
    gg = _pad_rows(gg)
    r = _BLOCK_ROWS
    dvol = pl.pallas_call(
        functools.partial(_lookup_bwd_kernel, bounds=bounds),
        out_shape=jax.ShapeDtypeStruct((n, w1p, w2), jnp.float32),
        grid=(n // r, w1p // blk),
        in_specs=[
            pl.BlockSpec((r, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, blk, w2), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(t, gg)
    return dvol[:n0].astype(vol_dtype_name)
