"""Pallas TPU kernel for the correlation-pyramid lookup.

This is the TPU-native equivalent of the reference's CUDA extension
(reference: sampler/sampler.cpp, sampler/sampler_kernel.cu): per output pixel,
interpolate 2r+1 taps from its correlation row.  Where the CUDA kernel gathers
2r+2 integer taps and lerps (sampler_kernel.cu:19-60), a TPU kernel must avoid
per-lane gathers entirely — instead each (row-block, tap) output is computed as
a masked reduction over the whole W2 row with the hat weight

    w(j) = relu(1 - |j - x_k|)

which is algebraically identical to two-tap linear interpolation with zero
padding (see ops/sampler.linear_sample_1d_dense, the XLA oracle for this
kernel).  The reduction is pure VPU work: broadcast-compare-multiply-add over
a VMEM-resident row block, no scatter/gather anywhere.

The backward pass mirrors the CUDA scatter-add backward
(sampler_kernel.cu:63-105) but again as a dense product:
    dvol[w1, j] = sum_k g[w1, k] * w_k(j)
Gradients w.r.t. coordinates are not needed: the model detaches the disparity
at the top of every refinement iteration (reference: core/raft_stereo.py:109,
CorrSampler.backward likewise returns None for coords, core/corr.py:24-29).

Supports fp32 and bf16 volumes (the CUDA kernel's
AT_DISPATCH_FLOATING_TYPES_AND_HALF, sampler_kernel.cu:126); accumulation is
always fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Max rows (W1 pixels) per block; lane-width multiple keeps the VPU fully busy.
_BLOCK_W1 = 256

# None = auto (compile on TPU backends, interpret elsewhere).  Set True to
# force interpret mode, e.g. when debugging CPU-placed execution on a TPU host
# (auto-detection keys off the default backend, not actual placement).
interpret_override = None


def _interpret() -> bool:
    if interpret_override is not None:
        return interpret_override
    return jax.default_backend() not in ("tpu",)


def _block_w1(w1: int) -> int:
    """Row-block size: cap at _BLOCK_W1 but don't pad small W1 up to it —
    the dense reduction's FLOPs scale with the padded row count."""
    return min(_BLOCK_W1, -(-w1 // 8) * 8)


def _lookup_kernel(vol_ref, taps_ref, out_ref):
    """One (n, w1-block): out[w1, k] = sum_j vol[w1, j] * hat(j - taps[w1, k])."""
    vol = vol_ref[0].astype(jnp.float32)          # (W1_t, W2)
    taps = taps_ref[0].astype(jnp.float32)        # (W1_t, K)
    w2 = vol.shape[-1]
    k = taps.shape[-1]
    # Mosaic requires integer iota; cast to f32 for the hat weights.
    j = jax.lax.broadcasted_iota(jnp.int32, (1, w2), 1).astype(jnp.float32)
    cols = []
    for ki in range(k):                            # K is small (9): unrolled
        w = jnp.maximum(0.0, 1.0 - jnp.abs(j - taps[:, ki][:, None]))
        cols.append(jnp.sum(vol * w, axis=-1))
    out_ref[0] = jnp.stack(cols, axis=-1).astype(out_ref.dtype)


def _lookup_bwd_kernel(taps_ref, g_ref, dvol_ref):
    """dvol[w1, j] = sum_k g[w1, k] * hat(j - taps[w1, k])."""
    taps = taps_ref[0].astype(jnp.float32)        # (W1_t, K)
    g = g_ref[0].astype(jnp.float32)              # (W1_t, K)
    w2 = dvol_ref.shape[-1]
    k = taps.shape[-1]
    j = jax.lax.broadcasted_iota(jnp.int32, (1, w2), 1).astype(jnp.float32)
    acc = jnp.zeros((taps.shape[0], w2), jnp.float32)
    for ki in range(k):
        w = jnp.maximum(0.0, 1.0 - jnp.abs(j - taps[:, ki][:, None]))
        acc = acc + g[:, ki][:, None] * w
    dvol_ref[0] = acc.astype(dvol_ref.dtype)


def _pad_w1(x, block):
    w1 = x.shape[1]
    pad = (-w1) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x, w1


def preflatten_volume(vol: jax.Array) -> jax.Array:
    """(B, H, W1, W2) -> (B*H, W1p, W2) flattened + W1-padded for the kernel.

    Do this ONCE per volume, outside any iteration loop: the pad is a real
    HBM copy of the whole volume.  Hoisting it here guarantees a single copy
    structurally instead of relying on XLA's loop-invariant code motion to
    lift it out of the GRU scan (measured: XLA does hoist it on TPU today,
    so this is neutral there — but interpret-mode/CPU callers and future
    compiler versions get the guarantee).
    """
    blk = _block_w1(vol.shape[2])
    v, _ = _pad_w1(vol.reshape(vol.shape[0] * vol.shape[1], *vol.shape[2:]),
                   blk)
    return v


def pallas_lookup_flat(vflat: jax.Array, taps: jax.Array) -> jax.Array:
    """Lookup against a :func:`preflatten_volume` result.  taps stays in
    model layout (B, H, W1, K); only the (small) taps tensor is reshaped and
    padded per call."""
    return _make_lookup(vflat.shape, vflat.dtype.name)(vflat, taps)


def pallas_lookup(vol: jax.Array, taps: jax.Array) -> jax.Array:
    """Forward-equivalent of :func:`linear_sample_1d` running as a Pallas TPU
    kernel.  vol: (B, H, W1, W2); taps: (B, H, W1, K) -> (B, H, W1, K) f32.

    Autodiff divergence from the oracle, by design: gradients w.r.t. ``taps``
    are hard zeros (the model detaches disparity every iteration, and the
    reference CUDA op likewise returns no coords grad: core/corr.py:29), and
    forward-mode AD is unsupported (custom_vjp).  Use ``linear_sample_1d`` if
    you need either.  Loop callers should :func:`preflatten_volume` once and
    use :func:`pallas_lookup_flat` per iteration.
    """
    return pallas_lookup_flat(preflatten_volume(vol), taps)


@functools.lru_cache(maxsize=None)
def _make_lookup(vflat_shape, vol_dtype_name):
    """custom_vjp instance per static (flat shape, dtype) — residuals carry
    only the taps; the volume's shape/dtype ride in the closure."""

    @jax.custom_vjp
    def f(vflat, taps):
        return _lookup_fwd_impl(vflat, taps)

    def fwd(vflat, taps):
        return _lookup_fwd_impl(vflat, taps), taps

    def bwd(taps, g):
        dvflat = _lookup_bwd_impl(taps, g, vflat_shape, vol_dtype_name)
        # No coordinate gradient by design (disparity is detached per
        # iteration; the reference kernel likewise returns None:
        # core/corr.py:29).
        return dvflat, jnp.zeros_like(taps)

    f.defvjp(fwd, bwd)
    return f


def _pad_taps(taps):
    b, h, w1, kk = taps.shape
    blk = _block_w1(w1)
    t, _ = _pad_w1(taps.reshape(b * h, w1, kk), blk)
    return t, blk


def _lookup_fwd_impl(vflat, taps):
    n, w1p, w2 = vflat.shape
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps)
    out = pl.pallas_call(
        _lookup_kernel,
        out_shape=jax.ShapeDtypeStruct((n, w1p, kk), jnp.float32),
        grid=(n, w1p // blk),
        in_specs=[
            pl.BlockSpec((1, blk, w2), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(vflat, t)
    return out[:, :w1].reshape(b, h, w1, kk)


def _lookup_bwd_impl(taps, g, vflat_shape, vol_dtype_name):
    n, w1p, w2 = vflat_shape
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps)
    gg, _ = _pad_w1(g.reshape(b * h, w1, kk), blk)
    dvol = pl.pallas_call(
        _lookup_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct((n, w1p, w2), jnp.float32),
        grid=(n, w1p // blk),
        in_specs=[
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk, w2), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(t, gg)
    return dvol.astype(vol_dtype_name)
