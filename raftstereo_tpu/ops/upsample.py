"""Learned convex upsampling of the low-resolution disparity field.

Replaces the reference's ``F.unfold`` formulation (core/raft_stereo.py:55-67)
with explicit shifted slices + one einsum: JAX has no unfold, and the
slice/einsum form lets XLA fuse mask softmax, weighting and the final
reshuffle into one kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def extract_3x3_patches(x: jax.Array) -> jax.Array:
    """(B, H, W, C) -> (B, H, W, 9, C): zero-padded 3x3 neighbourhoods.

    Patch index k = ky*3 + kx, matching torch ``F.unfold``'s (kh, kw) flatten
    order so converted mask-head weights keep their meaning
    (reference: core/raft_stereo.py:62-63).
    """
    b, h, w, c = x.shape
    p = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    rows = [p[:, ky:ky + h, kx:kx + w, :] for ky in range(3) for kx in range(3)]
    return jnp.stack(rows, axis=3)


def convex_upsample(flow: jax.Array, mask: jax.Array, factor: int) -> jax.Array:
    """Upsample (B, H, W, D) -> (B, factor*H, factor*W, D) by a learned
    softmax-convex combination over each pixel's 3x3 coarse neighbourhood.

    ``mask`` is (B, H, W, 9*factor*factor) with channel index
    ((k*factor + fy)*factor + fx), the layout of the reference's mask head
    (core/raft_stereo.py:59).  Flow values are scaled by ``factor`` because
    disparities are measured in pixels of the respective resolution.
    """
    b, h, w, d = flow.shape
    mask = mask.reshape(b, h, w, 9, factor, factor).astype(jnp.float32)
    mask = jax.nn.softmax(mask, axis=3)

    patches = extract_3x3_patches(flow.astype(jnp.float32) * factor)  # (B,H,W,9,D)
    up = jnp.einsum("bhwkd,bhwkyx->bhywxd", patches, mask)
    return up.reshape(b, h * factor, w * factor, d)


def upsample_interp(flow: jax.Array, factor: int) -> jax.Array:
    """Fallback bilinear upsampling (reference: core/utils/utils.py:82-84)."""
    from .image import resize_bilinear_align_corners
    b, h, w, d = flow.shape
    return factor * resize_bilinear_align_corners(flow, (h * factor, w * factor))
