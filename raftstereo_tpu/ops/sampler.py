"""1-D linear sampling along the last axis — the lookup primitive of the
correlation engine.

Reproduces exactly the semantics of the reference's ``bilinear_sampler``
(reference: core/utils/utils.py:59-73): pixel coordinates, align_corners=True,
zero padding outside [0, W-1].  Because the problem is 1-D (the reference
asserts H==1 at core/utils/utils.py:64) the op reduces to a gather + lerp along
one axis, with out-of-range taps contributing zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def linear_sample_1d(vol: jax.Array, x: jax.Array) -> jax.Array:
    """Sample ``vol`` (..., W) at fractional positions ``x`` (..., K).

    Leading dims of ``vol`` and ``x`` must match.  Returns (..., K) with
    out-of-bounds taps treated as zero (grid_sample zero padding).
    """
    w = vol.shape[-1]
    x = x.astype(jnp.float32)
    x0 = jnp.floor(x)
    dx = x - x0
    i0 = x0.astype(jnp.int32)
    i1 = i0 + 1

    v0 = jnp.take_along_axis(vol, jnp.clip(i0, 0, w - 1), axis=-1)
    v1 = jnp.take_along_axis(vol, jnp.clip(i1, 0, w - 1), axis=-1)
    valid0 = (i0 >= 0) & (i0 <= w - 1)
    valid1 = (i1 >= 0) & (i1 <= w - 1)
    v0 = jnp.where(valid0, v0, 0)
    v1 = jnp.where(valid1, v1, 0)
    return (v0.astype(jnp.float32) * (1.0 - dx) + v1.astype(jnp.float32) * dx)


def linear_sample_1d_dense(vol: jax.Array, x: jax.Array) -> jax.Array:
    """Gather-free formulation of :func:`linear_sample_1d`.

    out[..., k] = sum_j vol[..., j] * relu(1 - |j - x[..., k]|)

    The hat weight ``relu(1-|j-x|)`` is exactly the two-tap lerp including the
    zero-padding boundary behaviour, so this is bit-for-bit the same math as
    the gather version but expressed as a broadcast-compare-multiply-reduce,
    which maps onto the TPU VPU with no gathers at all.  This is the XLA-level
    mirror of the Pallas lookup kernel and is used as its test oracle.
    Cost O(W*K) per row instead of O(K) — cheap next to the matmuls here.
    """
    w = vol.shape[-1]
    j = jnp.arange(w, dtype=jnp.float32)
    # (..., K, W) weights
    wt = jnp.maximum(0.0, 1.0 - jnp.abs(j[None, :] - x[..., :, None].astype(jnp.float32)))
    return jnp.einsum("...w,...kw->...k", vol.astype(jnp.float32), wt)
