"""Fused Pallas pipeline for the encoder's layer2 (stride-2) stage.

Extends the stem..layer1 pipeline (ops/pallas_encoder.py) one stage
deeper: round-5 profiling puts ~15 ms of the 23.6 ms flagship fixed stage
in XLA's layer2/layer3 convs and the blocked-layout relayouts around them
(docs/perf_notes_r05.md) — the same storm the stem pipeline removed.

Semantics are exactly BasicEncoder's layer2 (two ResidualBlocks, first
stride 2 with a 1x1 projection shortcut; reference:
core/extractor.py:6-60,122-197 structure) with instance-norm statistics
in fp32:

    c1  = conv3x3_s2(t_in)           p  = conv1x1_s2(t_in)   [projection]
    t_y = relu(in1(c1))              pn = in_p(p)            [no relu]
    c2  = conv3x3(t_y)
    out0 = relu(pn + relu(in2(c2)))
    c3  = conv3x3(out0);  t3 = relu(in3(c3))
    c4  = conv3x3(t3);    out = relu(out0 + relu(in4(c4)))

Layout: the 64-channel input arrives as the stage's packed pixel-pair
view (B, H, W/2, 128); outputs live at half resolution as plain row-major
(B, H/2, W/2, 96) — 96 lanes, no column packing (the halved width still
fills sublanes).  The stride-2 entry kernel resolves its taps against the
packed columns: output col j reads input pixels 2j+dx, i.e. packed cols
{j-1, j}, and the 1x1 stride-2 projection is FREE in this view — input
pixel (2r, 2j) is the dy=0 row view's parity-0 lanes.

Single-device, inference-first: the backward is the XLA reference
formulation's VJP (training keeps the plain XLA layer2 by default, like
the stem stage before round 5), and the gate declines under an active
mesh (shard_map plumbing not yet built for this stage).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import threading

from .pallas_corr import _COMPILER_PARAMS, _interpret
from .pallas_norm import _row_block
from .pallas_encoder import make_override_scope, pack_view

# A/B toggle (scripts/ab_layer2.py flips it in one process).
_fused_layer2_enabled = True

# Thread-local trace scope, like pallas_encoder.override_fused_stem: the
# train step forces this stage OFF under differentiation (its backward
# re-linearizes the full XLA layer2 forward — the exact pattern measured
# as a net training loss on the stem in round 4); an explicit per-model
# config.fused_encoder still wins over the scope.
_tls = threading.local()

# Same trace-scope mechanism as the stem gate — one shared implementation
# (pallas_encoder.make_override_scope) so a fix to one cannot desync the
# other.  The train step holds this one at False (the layer2 backward
# still re-linearizes the XLA stage, a measured training loss).
_get_l2_override, override_fused_layer2 = make_override_scope(
    _tls, "fused_layer2_override")

# Sub-gate for the frozen-BN (constant-affine) variant on top of the main
# layer2 gate: lets the batch-norm branch (context encoder / realtime
# trunk) be A/B'd and, if need be, shipped independently of the
# instance-norm stage (scripts/ab_layer2_bn.py).
_fused_layer2_bn_enabled = True


# ------------------------------------------------------------- weights

def pack_weights3s2(w: jax.Array) -> jax.Array:
    """(3, 3, 64, 96) HWIO stride-2 conv weights -> (3, 2, 128, 96)
    packed [dy, dq+1]: output col j with tap dx reads packed col j + dq,
    parity pi, where dq = floor(dx/2) in {-1, 0}, pi = dx mod 2."""
    kh, kw, ci, co = w.shape
    out = jnp.zeros((kh, 2, 2 * ci, co), w.dtype)
    for dxi, dx in enumerate((-1, 0, 1)):
        dq = dx // 2
        pi = dx % 2
        out = out.at[:, dq + 1, pi * ci:(pi + 1) * ci, :].set(w[:, dxi])
    return out


def pack_weights3(w: jax.Array) -> jax.Array:
    """(3, 3, C, C) HWIO -> (3, 3C, C): per-dy concat over dx taps in
    operand order [dx=-1, 0, +1]."""
    kh, kw, ci, co = w.shape
    return jnp.concatenate([w[:, dxi] for dxi in range(3)],
                           axis=1).reshape(kh, 3 * ci, co)


def _flat_affine(s1, s2, n):
    """(B, 1, C) fp32 sums -> instance-norm prep affine (rstd, -mean*rstd).
    Same E[x^2]-m^2 form and measured precision envelope as the stem
    stage (pallas_encoder.stats_from_packed)."""
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + 1e-5)
    return rstd, -mean * rstd


# -------------------------------------------------------------- kernels

def _acc_flat_stats(y, s1_ref, s2_ref):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    s1_ref[...] += jnp.sum(y, axis=(1, 2))[:, None, :]
    s2_ref[...] += jnp.sum(y * y, axis=(1, 2))[:, None, :]


def _l2_entry_kernel(x_ref, xh_ref, w_ref, b_ref, wp_ref, bp_ref,
                     c1_ref, p_ref, s1a_ref, s1b_ref, spa_ref, spb_ref,
                     *, rows):
    """Stride-2 3x3 conv (64->96) + free 1x1 stride-2 projection (64->96)
    + fp32 output stats for both, from the packed t-domain input.

    x_ref: (1, 2R, Wp, 128) input rows for this block's R output rows;
    xh_ref: (1, 1, 1, Wp, 128) the one halo row ABOVE (input row 2rb-1;
    zeros at the image edge — the input is an activation, so zero padding
    is exact).  Output row r reads input rows 2r-1, 2r, 2r+1 =
    full[2r], full[2r+1], full[2r+2] with full = [above; x]."""
    t = x_ref[...]
    above = xh_ref[...][:, 0]
    # Pad to an even row count and view as (R+1, 2, ...) so every dy tap
    # is a CONTIGUOUS slice at a parity (strided row slices lower to >2D
    # gathers, which Mosaic rejects — same trick as _stem7s2_kernel).
    full = jnp.concatenate([above, t, jnp.zeros_like(above)],
                           axis=1)                  # (1, 2R+2, Wp, 128)
    view = full.reshape(1, rows + 1, 2, full.shape[2], full.shape[3])
    views = [view[:, :rows, 0],                     # dy=-1: full[2r]
             view[:, :rows, 1],                     # dy= 0: full[2r+1]
             view[:, 1:, 0]]                        # dy=+1: full[2r+2]
    zc = jnp.zeros_like(views[0][:, :, :1])
    parts = []
    for v in views:
        # dq=-1: output col j reads packed col j-1 (zero at col 0 = the
        # conv's own zero padding); dq=0: col j.
        parts += [jnp.concatenate([zc, v[:, :, :-1]], axis=2), v]
    xcat = jnp.concatenate(parts, axis=-1)          # (1, R, Wp, 768)
    w = w_ref[...]                                  # (3, 2, 128, 96)
    wcat = w.reshape(3 * 2 * w.shape[2], w.shape[3])
    y = jax.lax.dot_general(xcat, wcat, (((3,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y + b_ref[...][:, :, None, :]
    c1_ref[...] = y.astype(c1_ref.dtype)
    _acc_flat_stats(y, s1a_ref, s1b_ref)
    # Projection: input pixel (2r, 2j) = dy=0 row view, parity-0 lanes.
    pj = views[1][..., :w.shape[2] // 2]
    p = jax.lax.dot_general(pj, wp_ref[...], (((3,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    p = p + bp_ref[...][:, :, None, :]
    p_ref[...] = p.astype(p_ref.dtype)
    _acc_flat_stats(p, spa_ref, spb_ref)


def _prep_f(x, s_ref, t_ref, relu=True):
    s = s_ref[...][:, :, None, :].astype(x.dtype)
    t = t_ref[...][:, :, None, :].astype(x.dtype)
    y = x * s + t
    return jnp.maximum(y, 0) if relu else y


def _edge_mask(th, hv_ref):
    j = pl.program_id(1)
    top = th[:, 0:1] * hv_ref[j, 0].astype(th.dtype)
    bot = th[:, 1:2] * hv_ref[j, 1].astype(th.dtype)
    return jnp.concatenate([top, bot], axis=1)


def _conv3_flat(t, halo, w_ref, b_ref):
    """3x3 same-channel conv of the prepped (1, R, W2, C) tile; halo
    (1, 2, W2, C) prepped rows [above, below]; w_ref (3, 3C, C)."""
    zc = jnp.zeros_like(t[:, :, :1])
    y = None
    for dyi in range(3):
        if dyi == 0:
            rows = jnp.concatenate([halo[:, 0:1], t[:, :-1]], axis=1)
        elif dyi == 1:
            rows = t
        else:
            rows = jnp.concatenate([t[:, 1:], halo[:, 1:2]], axis=1)
        xcat = jnp.concatenate(
            [jnp.concatenate([zc, rows[:, :, :-1]], axis=2),   # dx=-1
             rows,                                             # dx= 0
             jnp.concatenate([rows[:, :, 1:], zc], axis=2)],   # dx=+1
            axis=-1)
        m = jax.lax.dot_general(xcat, w_ref[dyi],
                                (((3,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        y = m if y is None else y + m
    return y + b_ref[...][:, :, None, :]


def _l2_conv_kernel(x_ref, xh_ref, s_ref, t_ref, w_ref, b_ref, hv_ref,
                    y_ref, s1_ref, s2_ref):
    """prep(x) -> 3x3 conv -> raw y + stats (layer2_0.conv2 /
    layer2_1.conv2)."""
    t = _prep_f(x_ref[...], s_ref, t_ref)
    th = _edge_mask(_prep_f(xh_ref[...][:, 0], s_ref, t_ref), hv_ref)
    y = _conv3_flat(t, th, w_ref, b_ref)
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_flat_stats(y, s1_ref, s2_ref)


def _l2_conv_res_kernel(p_ref, ph_ref, sp_ref, tp_ref,
                        c_ref, ch_ref, sc_ref, tc_ref,
                        w_ref, b_ref, hv_ref, y_ref, s1_ref, s2_ref):
    """layer2_1.conv1: its input is out0 = relu(pn + u) with
    pn = p*sp+tp (projection norm, NO relu) and u = relu(c*sc+tc)."""
    t = jnp.maximum(_prep_f(p_ref[...], sp_ref, tp_ref, relu=False)
                    + _prep_f(c_ref[...], sc_ref, tc_ref), 0)
    th = _edge_mask(
        jnp.maximum(_prep_f(ph_ref[...][:, 0], sp_ref, tp_ref, relu=False)
                    + _prep_f(ch_ref[...][:, 0], sc_ref, tc_ref), 0),
        hv_ref)
    y = _conv3_flat(t, th, w_ref, b_ref)
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_flat_stats(y, s1_ref, s2_ref)


def _l2_finish_kernel(p_ref, sp_ref, tp_ref, c2_ref, s2_ref, t2_ref,
                      c4_ref, s4_ref, t4_ref, o_ref):
    """out = relu( relu(pn + u2) + y4 ): the stage output from the three
    raw tensors + their affines."""
    out0 = jnp.maximum(
        _prep_f(p_ref[...], sp_ref, tp_ref, relu=False)
        + _prep_f(c2_ref[...], s2_ref, t2_ref), 0)
    y4 = _prep_f(c4_ref[...], s4_ref, t4_ref)
    o_ref[...] = jnp.maximum(out0 + y4, 0).astype(o_ref.dtype)


# ------------------------------------------------------------ host side

def _halo1_above_s2(xp, r):
    """(B, H, Wp, 128) -> (B, Hout//r, 1, Wp, 128): input row 2*r_out0 - 1
    for each block (zeros above the image)."""
    b, h, wp, c = xp.shape
    nblk = (h // 2) // r
    span = 2 * r
    above = jnp.concatenate(
        [jnp.zeros((b, 1, wp, c), xp.dtype),
         xp[:, span - 1::span][:, :nblk - 1]], axis=1)
    return above[:, :, None]


def _halo2(x, r):
    """(B, H2, W2, C) -> (B, H2//r, 2, W2, C): rows above/below each
    block (zeros at edges; unsharded)."""
    b, h, w2, c = x.shape
    nblk = h // r
    z = jnp.zeros((b, 1, w2, c), x.dtype)
    top = jnp.concatenate([z, x[:, r - 1::r][:, :nblk - 1]], axis=1)
    bot = jnp.concatenate([x[:, r::r], z], axis=1)
    return jnp.stack([top, bot], axis=2)


def _default_hv2(nblk):
    return (jnp.ones((nblk, 2), jnp.float32)
            .at[0, 0].set(0.0).at[nblk - 1, 1].set(0.0))


def _specs(r, w2, c):
    row = pl.BlockSpec((1, r, w2, c), lambda i, j: (i, j, 0, 0),
                       memory_space=pltpu.VMEM)
    halo = pl.BlockSpec((1, 1, 2, w2, c), lambda i, j: (i, j, 0, 0, 0),
                        memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    return row, halo, stat


def _l2_entry(xp, w3, b3, wp1, bp1, dt):
    b, h, wpk, c2 = xp.shape
    h2 = h // 2
    r = _row_block(h2)
    grid = (b, h2 // r)
    xh = _halo1_above_s2(xp, r)
    co = w3.shape[-1]
    w2 = wpk  # output width == packed input width
    row, _, stat = _specs(r, w2, co)
    out = pl.pallas_call(
        functools.partial(_l2_entry_kernel, rows=r),
        out_shape=(jax.ShapeDtypeStruct((b, h2, w2, co), dt),
                   jax.ShapeDtypeStruct((b, h2, w2, co), dt),
                   jax.ShapeDtypeStruct((b, 1, co), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, co), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, co), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, co), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 2 * r, wpk, c2), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1, wpk, c2), lambda i, j: (i, j, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w3.shape, lambda i, j: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, co), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(wp1.shape, lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, co), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(row, row, stat, stat, stat, stat),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(xp, xh, w3, b3[None, None, :], wp1, bp1[None, None, :])
    return out


def _l2_conv(x, aff, w, bias, dt, res=None, res_aff=None):
    b, h2, w2, c = x.shape
    r = _row_block(h2)
    grid = (b, h2 // r)
    hv = _default_hv2(h2 // r)
    row, halo, stat = _specs(r, w2, c)
    hvspec = pl.BlockSpec(hv.shape, lambda i, j: (0, 0),
                          memory_space=pltpu.SMEM)
    wspec = pl.BlockSpec(w.shape, lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    # Bias is SHARED (1, 1, C): its own spec — the per-image stat spec
    # indexes block i on dim 0, out of bounds for batch > 1.
    bspec = pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    s, t = aff
    if res is None:
        kernel = _l2_conv_kernel
        operands = (x, _halo2(x, r), s, t, w, bias[None, None, :], hv)
        in_specs = [row, halo, stat, stat, wspec, bspec, hvspec]
    else:
        rs, rt = res_aff
        kernel = _l2_conv_res_kernel
        operands = (res, _halo2(res, r), rs, rt, x, _halo2(x, r), s, t,
                    w, bias[None, None, :], hv)
        in_specs = [row, halo, stat, stat, row, halo, stat, stat,
                    wspec, bspec, hvspec]
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(x.shape, dt),
                   jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(row, stat, stat),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(*operands)


def _l2_finish(p, ap, c2, a2, c4, a4, dt):
    b, h2, w2, c = p.shape
    r = _row_block(h2)
    row, _, stat = _specs(r, w2, c)
    return pl.pallas_call(
        _l2_finish_kernel,
        out_shape=jax.ShapeDtypeStruct(p.shape, dt),
        grid=(b, h2 // r),
        in_specs=[row, stat, stat, row, stat, stat, row, stat, stat],
        out_specs=row,
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(p, *ap, c2, *a2, c4, *a4)


# ---------------------------------------------------------- entry point

def _params_of(params, key):
    return params[key]["kernel"], params[key]["bias"]


def _fused_layer2_fwd(t_in, params, dt, affines=None):
    """t_in: (B, H, W, 64) stage activation.  params keys: c1 (3,3,64,96
    stride-2), proj (1x1: (64, 96)), c2, c3, c4 (3,3,96,96).
    Returns (B, H/2, W/2, 96).

    ``affines``: None for instance norm (per-image stats computed by the
    kernels' fused accumulators), or 5 constant (s, t) pairs — folded
    frozen-BatchNorm affines (pallas_encoder.bn_affine) in stage order
    (norm1, projection norm, norm2, layer2_1.norm1, layer2_1.norm2);
    the kernels' prep form relu(x*s + t) expresses both exactly."""
    xp = pack_view(t_in)
    b = t_in.shape[0]
    n = float(t_in.shape[1] // 2 * (t_in.shape[2] // 2))

    def aff(stats_pair, i):
        if affines is None:
            return _flat_affine(*stats_pair, n)
        s, t = affines[i]
        return (jnp.broadcast_to(s.astype(jnp.float32)[None, None],
                                 (b, 1, s.shape[-1])),
                jnp.broadcast_to(t.astype(jnp.float32)[None, None],
                                 (b, 1, t.shape[-1])))

    k1, b1 = _params_of(params, "c1")
    kp, bp = _params_of(params, "proj")
    c1, p, s1a, s1b, spa, spb = _l2_entry(
        xp, pack_weights3s2(k1).astype(dt), b1.astype(dt),
        kp.reshape(kp.shape[-2:]).astype(dt), bp.astype(dt), dt)
    a1 = aff((s1a, s1b), 0)
    ap = aff((spa, spb), 1)
    k2, b2 = _params_of(params, "c2")
    c2, s2a, s2b = _l2_conv(c1, a1, pack_weights3(k2).astype(dt),
                            b2.astype(dt), dt)
    a2 = aff((s2a, s2b), 2)
    k3, b3 = _params_of(params, "c3")
    c3, s3a, s3b = _l2_conv(c2, a2, pack_weights3(k3).astype(dt),
                            b3.astype(dt), dt, res=p, res_aff=ap)
    a3 = aff((s3a, s3b), 3)
    k4, b4 = _params_of(params, "c4")
    c4, s4a, s4b = _l2_conv(c3, a3, pack_weights3(k4).astype(dt),
                            b4.astype(dt), dt)
    a4 = aff((s4a, s4b), 4)
    return _l2_finish(p, ap, c2, a2, c4, a4, dt)


def _xla_layer2_reference(t_in, params):
    """Plain-XLA mirror (oracle + backward linearization)."""
    from .pallas_norm import _xla_instance_norm

    def conv(x, k, b, stride=1):
        pad = 1 if k.shape[0] == 3 else 0
        return jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (stride, stride),
            ((pad, pad), (pad, pad)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + b.astype(x.dtype)

    c1 = conv(t_in, *_params_of(params, "c1"), stride=2)
    t_y = _xla_instance_norm(c1, relu=True)
    c2 = conv(t_y, *_params_of(params, "c2"))
    u2 = _xla_instance_norm(c2, relu=True)
    p = conv(t_in, *_params_of(params, "proj"), stride=2)
    pn = _xla_instance_norm(p, relu=False)
    out0 = jnp.maximum(pn + u2, 0)
    c3 = conv(out0, *_params_of(params, "c3"))
    t3 = _xla_instance_norm(c3, relu=True)
    c4 = conv(t3, *_params_of(params, "c4"))
    y4 = _xla_instance_norm(c4, relu=True)
    return jnp.maximum(out0 + y4, 0)


def _xla_layer2_reference_affine(t_in, params, affines):
    """Plain-XLA mirror of the frozen-BN (constant-affine) stage."""
    def nr(x, i, relu=True):
        s, t = affines[i]
        y = x * s.astype(x.dtype) + t.astype(x.dtype)
        return jnp.maximum(y, 0) if relu else y

    def conv(x, k, b, stride=1):
        pad = 1 if k.shape[0] == 3 else 0
        return jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (stride, stride),
            ((pad, pad), (pad, pad)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + b.astype(x.dtype)

    c1 = conv(t_in, *_params_of(params, "c1"), stride=2)
    u2 = nr(conv(nr(c1, 0), *_params_of(params, "c2")), 2)
    pn = nr(conv(t_in, *_params_of(params, "proj"), stride=2), 1,
            relu=False)
    out0 = jnp.maximum(pn + u2, 0)
    c3 = conv(out0, *_params_of(params, "c3"))
    y4 = nr(conv(nr(c3, 3), *_params_of(params, "c4")), 4)
    return jnp.maximum(out0 + y4, 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_layer2(t_in, params, dt=jnp.float32):
    """Fused forward; XLA-reference backward (inference-first — the gate
    in models/encoders.py keeps training on the plain XLA layer2)."""
    return _fused_layer2_fwd(t_in, params, dt)


def _fwd_l2(t_in, params, dt):
    return _fused_layer2_fwd(t_in, params, dt), (t_in, params)


def _bwd_l2(dt, residuals, g):
    t_in, params = residuals
    _, vjp = jax.vjp(_xla_layer2_reference, t_in, params)
    return vjp(g)


fused_layer2.defvjp(_fwd_l2, _bwd_l2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer2_bn(t_in, params, affines, dt=jnp.float32):
    """Frozen-BatchNorm layer2 stage: the same Pallas pipeline with the
    five norm affines constant (pallas_encoder.bn_affine) instead of
    in-kernel instance stats.  Covers the context encoder's layer2 (the
    reference's cnet uses batch norm, core/extractor.py:199-300) and the
    realtime config's shared trunk.  Fused forward; XLA-reference
    backward (training keeps the plain XLA stage via the gate)."""
    return _fused_layer2_fwd(t_in, params, dt, affines=affines)


def _fwd_l2_bn(t_in, params, affines, dt):
    return (_fused_layer2_fwd(t_in, params, dt, affines=affines),
            (t_in, params, affines))


def _bwd_l2_bn(dt, residuals, g):
    t_in, params, affines = residuals
    _, vjp = jax.vjp(_xla_layer2_reference_affine, t_in, params, affines)
    return vjp(g)


fused_layer2_bn.defvjp(_fwd_l2_bn, _bwd_l2_bn)


def use_fused_layer2(norm_fn, stride, shape, override=None) -> bool:
    """Gate: instance or frozen-batch norm, stride-2 layer2, even W, no
    active mesh (shard plumbing not built), single-device TPU unless
    forced.

    Precedence mirrors use_fused_stem: ``override`` (per-model
    config.fused_encoder) > the override_fused_layer2 thread-local scope
    (the train step forces False — the backward re-linearizes) > the
    stem's own scope (tests forcing the fused forms get layer2 too) >
    backend auto.  The auto batch bound mirrors the stem gate's
    <=4-images crossover; auto also requires ONE visible device — a bare
    pallas_call cannot be GSPMD-partitioned, and a user jitting with
    explicit shardings must keep the plain XLA stage."""
    if not _fused_layer2_enabled:
        return False
    if norm_fn not in ("instance", "batch") or stride != 2 or shape[2] % 2:
        return False
    if shape[1] % 2:
        return False
    from ..parallel.context import active_corr_mesh

    if active_corr_mesh() is not None:
        return False
    if override is not None:
        return override
    ov = _get_l2_override()
    if ov is not None:
        return ov
    from .pallas_encoder import _get_override

    ov = _get_override()
    if ov is not None:
        return ov
    return (jax.default_backend() == "tpu" and len(jax.devices()) == 1
            and shape[0] <= 4)
