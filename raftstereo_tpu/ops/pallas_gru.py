"""Fused Pallas megakernel for the finest-level GRU update block.

One kernel call per refinement iteration computes the whole finest-level
update — motion encoder (convc1/convc2/convf1/convf2/conv), the gru0
z/r/q gate convs, the hidden-state blend and the flow head — with every
intermediate (gate pre-activations, r*h, the motion-feature concat, the
flow-head hidden) living only in VMEM.  The XLA scan body materializes
each of those in HBM every iteration (~1000 channel-equivalents per
pixel per step, profiled round 5); the fused step's HBM traffic is the
carried state itself (h, disparity) plus the sampled correlation
features and the loop invariants — roughly a 4x reduction on the loop's
memory traffic at flagship shapes (docs/perf_notes_r06.md).

Design, built on the data-stationary 3x3-conv formulation validated by
scripts/mb_gru_kernel.py (90.8 TF/s packed vs XLA's 74.8 at GRU shapes,
docs/perf_notes_r03.md):

* weights shift, not activations: dy taps are row slices on the untiled
  outer axis (free), the per-tap matmuls take contiguous operands, and
  only the three accumulated outputs are realigned (2 rolls + masks);
* the ``_sliced_conv`` kernel-splits of models/update.py become weight
  SLICES inside the kernel: the gate convs run one dot per (tap,
  operand) over h / motion features / the upsampled coarser state and
  accumulate — the [h, x] concats never exist anywhere;
* grid is (B,); each batch row's full arrays ride in VMEM and a static
  Python loop walks row slabs (overlapping halo recompute, receptive
  field 9 rows end-to-end), so intermediates stay slab-sized and VMEM
  scales with H*W*C of the INPUTS, not the intermediates;
* the 7x7 flow conv contracts only the disparity channel (the y-flow is
  structurally zero) as 49 shifted copies -> one (49 -> 64) matmul,
  the tap-matmul trick from models/update.tap_conv3x3.

Semantics mirror ``BasicMultiUpdateBlock`` for the finest level in test
mode (no mask head — the model computes the final mask once after the
scan).  The backward is the XLA reference formulation's VJP via
``jax.custom_vjp`` (same policy as ops/pallas_encoder.py); the kernel
gates off under device meshes and on CPU (``use_fused_gru``).
"""

from __future__ import annotations

import functools
import threading
import warnings
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _COMPILER_PARAMS, _interpret
from .pallas_encoder import make_override_scope

# Receptive-field depths (rows each side of a slab's center rows) of the
# fused chain, counted back from its two outputs:
#   delta <- fh2(1) <- fh1(1) <- h_new(+-2)
#   h_new <- z/q convs(1) <- r conv(1)        => h, x at +-4
#   x = [motion features, ext]                => ext at +-4
#   mf <- me conv(1) <- convc2(1) <- c1(1x1)  => corr at +-6
#   mf <- me conv(1) <- convf2(1) <- convf1(3)=> disp at +-9
_D_H = 4
_D_X = 4
_D_CORR = 6
_D_DISP = 9

# Weight-pack key order == kernel operand order (ext entries dropped for
# single-level GRUs).  Values are (9, Cin, Cout) taps for 3x3 convs,
# (49, 64) for the 7x7 flow conv, (Ck, 64) for the 1x1 corr conv and
# (1, 1, C) biases.
_WKEYS = ("wzr_h", "wzr_m", "wzr_e", "bzr",
          "wq_h", "wq_m", "wq_e", "bq",
          "wc1", "bc1", "wc2", "bc2",
          "wf1", "bf1", "wf2", "bf2",
          "wmc", "wmf", "bme",
          "wfh1", "bfh1", "wfh2", "bfh2")


_tls = threading.local()
_get_override, override_fused_gru = make_override_scope(
    _tls, "fused_gru_override")


def use_fused_gru(backend: str, test_mode: bool) -> bool:
    """Gate for the fused GRU step.

    ``backend`` is config.gru_backend: "auto" resolves to the fused
    kernel on a single-device TPU backend and to the XLA reference step
    everywhere else; "fused"/"xla" force one path (tests force "fused"
    on CPU to exercise the interpret-mode kernel).  The kernel covers
    the test-mode step only (no per-iteration mask head), so train-mode
    tracing always takes the XLA step.  A bare pallas_call cannot be
    SPMD-partitioned, so any active corr mesh (parallel/context.py)
    gates the kernel off — loudly if it was explicitly requested.
    The thread-local ``override_fused_gru`` scope sits between the two:
    an explicit config backend wins over it (same precedence as
    ops/pallas_encoder.use_fused_stem)."""
    if not test_mode:
        return False
    ov: Optional[bool] = None
    if backend != "auto":
        ov = backend == "fused"
    elif _get_override() is not None:
        ov = _get_override()
    from ..parallel.context import active_corr_mesh

    if active_corr_mesh() is not None:
        if ov:
            warnings.warn(
                "fused GRU backend cannot run under an active corr mesh; "
                "using the XLA reference step", RuntimeWarning, stacklevel=2)
        return False
    if ov is not None:
        return ov
    return jax.default_backend() == "tpu" and len(jax.devices()) == 1


def resolve_gru_backend(config) -> str:
    """The backend string a test-mode executable compiles with — the
    serving engine's cache-key component (serve/engine.py): everything
    that selects a distinct compiled program must reach the key."""
    return "fused" if use_fused_gru(config.gru_backend, True) else "xla"


# ---------------------------------------------------------------- packing

def _w9(k, dt):
    """(3, 3, Cin, Cout) HWIO -> (9, Cin, Cout), dy-major."""
    return k.reshape(9, k.shape[2], k.shape[3]).astype(dt)


def _b(v, dt):
    return v.reshape(1, 1, -1).astype(dt)


def pack_update_params(params: Dict, corr_channels: int, ext_dim: int,
                       dtype) -> Dict[str, jax.Array]:
    """Kernel weight pack from the update block's parameter tree
    (models/update.BasicMultiUpdateBlock variables["params"]).

    The gate convs' fused-input kernels are SLICED along the input axis
    exactly like models/update._sliced_conv — [0:hd] convolves h,
    [hd:hd+128] the motion features, [hd+128:] the upsampled coarser
    state — so the parameter tree is untouched and checkpoints stay
    bit-compatible.  ``corr_channels`` is the width the correlation
    lookup actually emits (the pallas_alt backend's lane-friendly pad);
    convc1's kernel is zero-row-padded to match, the same arithmetic
    identity PointwisePaddedConv applies.  ``ext_dim`` is 0 for
    single-level GRUs (the ext entries are dropped from the pack)."""
    enc, gru, fh = params["encoder"], params["gru0"], params["flow_head"]
    kzr = gru["convzr"]["kernel"]
    hd = kzr.shape[-1] // 2
    assert kzr.shape[2] == hd + 128 + ext_dim, (kzr.shape, hd, ext_dim)
    kq = gru["convq"]["kernel"]
    kc1 = enc["convc1"]["kernel"][0, 0]          # (cor_planes, 64)
    pad = corr_channels - kc1.shape[0]
    assert pad >= 0, (corr_channels, kc1.shape)
    if pad:
        kc1 = jnp.pad(kc1, ((0, pad), (0, 0)))
    kme = enc["conv"]["kernel"]                  # (3, 3, 128, 126)
    me_out = kme.shape[-1]
    w = {
        "wzr_h": _w9(kzr[:, :, :hd], dtype),
        "wzr_m": _w9(kzr[:, :, hd:hd + 128], dtype),
        "bzr": _b(gru["convzr"]["bias"], dtype),
        "wq_h": _w9(kq[:, :, :hd], dtype),
        "wq_m": _w9(kq[:, :, hd:hd + 128], dtype),
        "bq": _b(gru["convq"]["bias"], dtype),
        "wc1": kc1.astype(dtype),
        "bc1": _b(enc["convc1"]["bias"], dtype),
        "wc2": _w9(enc["convc2"]["kernel"], dtype),
        "bc2": _b(enc["convc2"]["bias"], dtype),
        # The y-flow channel is structurally zero (the model builds
        # flow = [d, 0] every iteration): contract only the x slice.
        "wf1": enc["convf1"]["kernel"][:, :, 0].reshape(49, -1).astype(dtype),
        "bf1": _b(enc["convf1"]["bias"], dtype),
        "wf2": _w9(enc["convf2"]["kernel"], dtype),
        "bf2": _b(enc["convf2"]["bias"], dtype),
        # me conv split along its [cor, flo] input concat; output padded
        # 126 -> 128 with zero columns (the flow channels are injected
        # on top of the zero lanes in-kernel).
        "wmc": _w9(jnp.pad(kme[:, :, :64], ((0, 0), (0, 0), (0, 0),
                                            (0, 128 - me_out))), dtype),
        "wmf": _w9(jnp.pad(kme[:, :, 64:], ((0, 0), (0, 0), (0, 0),
                                            (0, 128 - me_out))), dtype),
        "bme": _b(jnp.pad(enc["conv"]["bias"], (0, 128 - me_out)), dtype),
        "wfh1": _w9(fh["conv1"]["kernel"], dtype),
        "bfh1": _b(fh["conv1"]["bias"], dtype),
        "wfh2": _w9(fh["conv2"]["kernel"], dtype),
        "bfh2": _b(fh["conv2"]["bias"], dtype),
    }
    if ext_dim:
        w["wzr_e"] = _w9(kzr[:, :, hd + 128:], dtype)
        w["wq_e"] = _w9(kq[:, :, hd + 128:], dtype)
    return w


def _slab_plan(h: int) -> Tuple[int, Tuple[int, ...]]:
    """(slab rows, static slab starts): bounded unroll (<= 8 slabs), the
    last slab clamped so every start + R <= H (overlapping rows are
    recomputed identically — pure function of the inputs)."""
    if h <= 32:
        return h, (0,)
    r = max(32, -(-h // 8))
    starts = list(range(0, h - r, r)) + [h - r]
    return r, tuple(starts)


# ----------------------------------------------------------------- kernel

def _roll_w(u, o, wd):
    """shift_o(u)[:, w] = u[:, w + o], zero outside [0, wd) — the
    data-stationary dx realignment (scripts/mb_gru_kernel.py)."""
    if o == 0:
        return u
    col = jax.lax.broadcasted_iota(jnp.int32, (1, wd, 1), 1)
    s = pltpu.roll(u, (-o) % wd, 1)
    if o > 0:
        return jnp.where(col < wd - o, s, jnp.zeros_like(s))
    return jnp.where(col >= -o, s, jnp.zeros_like(s))


def _conv3(ops, bias, wd):
    """Data-stationary SAME 3x3 conv over row slabs, fp32 accumulation.

    ``ops`` is a list of (window, w9) pairs summed over — the in-kernel
    form of models/update._sliced_conv's channel partition.  Windows are
    (rows_out + 2, wd, Cin); returns (rows_out, wd, Cout) fp32 + bias."""
    rows_out = ops[0][0].shape[0] - 2
    y = None
    for dxi in range(3):
        u = None
        for x_win, w9 in ops:
            for dyi in range(3):
                m = jax.lax.dot_general(
                    x_win[dyi:dyi + rows_out], w9[dyi * 3 + dxi],
                    (((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                u = m if u is None else u + m
        s = _roll_w(u, dxi - 1, wd)
        y = s if y is None else y + s
    return y + bias.astype(jnp.float32)


def _conv7x1(d_win, w49, bias, wd):
    """7x7 SAME conv of the 1-channel disparity window: 49 shifted
    copies of the scalar field concatenated along lanes, one
    (49 -> Cout) matmul (the tap-matmul trick, models/update.py)."""
    rows_out = d_win.shape[0] - 6
    taps = []
    for dyi in range(7):
        rows = d_win[dyi:dyi + rows_out]
        for dxi in range(7):
            taps.append(_roll_w(rows, dxi - 3, wd))
    z = jnp.concatenate(taps, axis=-1)           # (rows_out, wd, 49)
    y = jax.lax.dot_general(z, w49, (((2,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y + bias.astype(jnp.float32)


def _gru_update_kernel(*refs, hgt, wd, rr, starts, has_ext, hd):
    """One batch row's full fused update: static slab loop, all
    intermediates slab-resident in VMEM."""
    it = iter(refs)
    h_ref = next(it)
    ext_ref = next(it) if has_ext else None
    corr_ref, disp_ref, cz_ref, cr_ref, cq_ref = (next(it) for _ in range(5))
    w = {}
    for k in _WKEYS:
        if not has_ext and k in ("wzr_e", "wq_e"):
            continue
        w[k] = next(it)[...]
    hnew_ref, delta_ref = next(it), next(it)
    ct = h_ref.dtype

    h = h_ref[0]
    ext = ext_ref[0] if has_ext else None
    corr = corr_ref[0]
    disp = disp_ref[0]
    cz, cr, cq = cz_ref[0], cr_ref[0], cq_ref[0]

    def win(x, s, d):
        """Rows [s - d, s + rr + d) with zeros outside the image — the
        conv zero padding, materialized only at edge slabs (interior
        slabs are plain static slices)."""
        lo, hi = s - d, s + rr + d
        a, b2 = max(lo, 0), min(hi, hgt)
        parts = []
        if a > lo:
            parts.append(jnp.zeros((a - lo,) + x.shape[1:], x.dtype))
        parts.append(x[a:b2])
        if hi > b2:
            parts.append(jnp.zeros((hi - b2,) + x.shape[1:], x.dtype))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 0)

    def mask(t, s, d):
        """Zero rows outside the image: a conv output at such rows is
        its bias, but the NEXT conv's zero padding needs exact zeros.
        Static no-op for interior slabs."""
        lo = s - d
        if lo >= 0 and lo + t.shape[0] <= hgt:
            return t
        i = jax.lax.broadcasted_iota(jnp.int32, (t.shape[0], 1, 1), 0) + lo
        return jnp.where((i >= 0) & (i < hgt), t, jnp.zeros_like(t))

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 128), 2)

    for s in starts:
        # ---- motion encoder (fixed 64/128-channel geometry)
        c1 = mask(jnp.maximum(
            (jax.lax.dot_general(win(corr, s, _D_CORR), w["wc1"],
                                 (((2,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
             + w["bc1"].astype(jnp.float32)).astype(ct), 0), s, _D_CORR)
        cor = mask(jnp.maximum(
            _conv3([(c1, w["wc2"])], w["bc2"], wd).astype(ct), 0), s, 5)
        d9 = win(disp, s, _D_DISP).astype(ct)
        f1 = mask(jnp.maximum(
            _conv7x1(d9, w["wf1"], w["bf1"], wd).astype(ct), 0), s, 6)
        flo = mask(jnp.maximum(
            _conv3([(f1, w["wf2"])], w["bf2"], wd).astype(ct), 0), s, 5)
        me = mask(jnp.maximum(
            _conv3([(cor, w["wmc"]), (flo, w["wmf"])],
                   w["bme"], wd).astype(ct), 0), s, _D_X)
        # motion features = [me(126, zero-padded to 128), d, 0]: the
        # disparity rides on lane 126 (lane 127 stays the zero y-flow).
        d4 = d9[5:-5]
        mf = me + jnp.where(lane == 126, d4, jnp.zeros_like(d4)).astype(ct)

        # ---- gru0 gates: one dot per (tap, operand), no concats
        h4 = win(h, s, _D_H)
        zr_ops = [(h4, w["wzr_h"]), (mf, w["wzr_m"])]
        if has_ext:
            e4 = win(ext, s, _D_X)
            zr_ops.append((e4, w["wzr_e"]))
        zr = _conv3(zr_ops, w["bzr"], wd).astype(ct)
        z = jax.nn.sigmoid(zr[..., :hd] + win(cz, s, 3))
        r = jax.nn.sigmoid(zr[..., hd:] + win(cr, s, 3))
        rh = r * h4[1:-1]
        q_ops = [(rh, w["wq_h"]), (mf[1:-1], w["wq_m"])]
        if has_ext:
            q_ops.append((e4[1:-1], w["wq_e"]))
        q = jnp.tanh(_conv3(q_ops, w["bq"], wd).astype(ct)
                     + win(cq, s, 2))
        z2 = z[1:-1]
        hn = mask((1 - z2) * h4[2:-2] + z2 * q, s, 2)

        # ---- flow head
        fh = mask(jnp.maximum(
            _conv3([(hn, w["wfh1"])], w["bfh1"], wd).astype(ct), 0), s, 1)
        delta = _conv3([(fh, w["wfh2"])], w["bfh2"], wd).astype(ct)

        hnew_ref[0, s:s + rr] = hn[2:-2]
        delta_ref[0, s:s + rr] = delta


def _fused_forward(h, ext, corr, disp, cz, cr, cq, wpack):
    b, hgt, wd, hd = h.shape
    has_ext = ext is not None
    ct = h.dtype
    rr, starts = _slab_plan(hgt)

    def full(x):
        return pl.BlockSpec((1,) + x.shape[1:],
                            lambda i: (i,) + (0,) * (x.ndim - 1),
                            memory_space=pltpu.VMEM)

    def const(x):
        return pl.BlockSpec(x.shape, lambda i: (0,) * x.ndim,
                            memory_space=pltpu.VMEM)

    operands = [h] + ([ext] if has_ext else []) + [
        corr.astype(ct), disp.astype(jnp.float32), cz, cr, cq]
    in_specs = [full(x) for x in operands]
    for k in _WKEYS:
        if not has_ext and k in ("wzr_e", "wq_e"):
            continue
        operands.append(wpack[k])
        in_specs.append(const(wpack[k]))

    hn, delta = pl.pallas_call(
        functools.partial(_gru_update_kernel, hgt=hgt, wd=wd, rr=rr,
                          starts=starts, has_ext=has_ext, hd=hd),
        out_shape=(jax.ShapeDtypeStruct((b, hgt, wd, hd), ct),
                   jax.ShapeDtypeStruct((b, hgt, wd, 2), ct)),
        grid=(b,),
        in_specs=in_specs,
        out_specs=(full(h), pl.BlockSpec(
            (1, hgt, wd, 2), lambda i: (i, 0, 0, 0),
            memory_space=pltpu.VMEM)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(*operands)
    return hn, delta


# ------------------------------------------------- XLA reference + VJP

def _xla_reference_update(h, ext, corr, disp, cz, cr, cq, wpack):
    """Plain-XLA mirror of the fused step on the SAME packed weights —
    the kernel's parity oracle (tests/test_pallas_gru.py) and the
    backward formulation (its VJP is the custom_vjp's bwd, the
    pallas_encoder policy: training cost unchanged, no kernel VJP)."""
    ct = h.dtype

    def conv(x, w, bias, kh=3, kw=3):
        # w: (kh*kw, Cin, Cout) taps, or (kh*kw, Cout) for the 1-channel
        # flow conv — reshaped back to HWIO.
        cin = 1 if w.ndim == 2 else w.shape[1]
        k = w.reshape(kh, kw, cin, w.shape[-1])
        p = ((kh // 2, kh // 2), (kw // 2, kw // 2))
        y = jax.lax.conv_general_dilated(
            x, k.astype(ct), (1, 1), p,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bias.astype(ct)

    c1 = jax.nn.relu(jnp.tensordot(corr.astype(ct), wpack["wc1"], 1)
                     + wpack["bc1"].astype(ct))
    cor = jax.nn.relu(conv(c1, wpack["wc2"], wpack["bc2"]))
    dct = disp.astype(ct)
    f1 = jax.nn.relu(conv(dct, wpack["wf1"], wpack["bf1"], kh=7, kw=7))
    flo = jax.nn.relu(conv(f1, wpack["wf2"], wpack["bf2"]))
    me = jax.nn.relu(conv(cor, wpack["wmc"], wpack["bme"])
                     + conv(flo, wpack["wmf"],
                            jnp.zeros_like(wpack["bme"])))
    mf = me + jnp.pad(dct, ((0, 0), (0, 0), (0, 0), (126, 1)))
    hd = h.shape[-1]
    zr = (conv(h, wpack["wzr_h"], wpack["bzr"])
          + conv(mf, wpack["wzr_m"], jnp.zeros_like(wpack["bzr"])))
    qp = (conv(mf, wpack["wq_m"], wpack["bq"]))
    if ext is not None:
        zr = zr + conv(ext, wpack["wzr_e"], jnp.zeros_like(wpack["bzr"]))
        qp = qp + conv(ext, wpack["wq_e"], jnp.zeros_like(wpack["bq"]))
    z = jax.nn.sigmoid(zr[..., :hd] + cz)
    r = jax.nn.sigmoid(zr[..., hd:] + cr)
    q = jnp.tanh(qp + conv(r * h, wpack["wq_h"],
                           jnp.zeros_like(wpack["bq"])) + cq)
    hn = (1 - z) * h + z * q
    fh = jax.nn.relu(conv(hn, wpack["wfh1"], wpack["bfh1"]))
    delta = conv(fh, wpack["wfh2"], wpack["bfh2"])
    return hn, delta


@jax.custom_vjp
def fused_update(h, ext, corr, disp, cz, cr, cq, wpack):
    """Fused finest-level update step: ``(h_new, delta)`` from the
    hidden state, the upsampled coarser state (``ext``, None for
    single-level GRUs), the sampled correlation features, the carried
    disparity and the precomputed context biases.  Forward is the
    Pallas megakernel (interpret mode off-TPU); backward is the XLA
    reference VJP."""
    return _fused_forward(h, ext, corr, disp, cz, cr, cq, wpack)


def _fused_fwd(h, ext, corr, disp, cz, cr, cq, wpack):
    out = _fused_forward(h, ext, corr, disp, cz, cr, cq, wpack)
    return out, (h, ext, corr, disp, cz, cr, cq, wpack)


def _fused_bwd(res, g):
    _, vjp = jax.vjp(_xla_reference_update, *res)
    return vjp(g)


fused_update.defvjp(_fused_fwd, _fused_bwd)
