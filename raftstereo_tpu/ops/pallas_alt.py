"""Pallas TPU kernel for ON-DEMAND correlation lookup (no precomputed volume).

The reference gestures at this capability twice and ships it broken/slow:
``AlternateCorrBlock`` (``alt_cuda``) raises NotImplementedError and its CUDA
extension is absent (reference: core/corr.py:159-188), while the pure-torch
``alt`` path works but is documented as significantly slower
(reference: README.md:121).  This module is the working TPU form.

Design: the correlation row for a block of W1 pixels is

    M[x1, j] = <fmap1[x1, :], fmap2[j, :]> / sqrt(C)

— a (blk x C) @ (C x W2) matmul that fits in VMEM and runs on the MXU.  Each
kernel invocation recomputes its block's rows on the fly, applies the same
hat-weight tap reduction as the precomputed-volume kernel (ops/pallas_corr.py)
and throws the rows away: HBM never holds more than the O(H*W) feature
pyramids, yet the inner loop is MXU matmul + VPU reduction instead of the
XLA gather chain the ``alt`` backend lowers to.

Backward (for completeness/training) fuses the volume-gradient expansion with
the feature-gradient matmuls per block:

    dM[x1, j]   = sum_k g[x1, k] * hat(j - t_k(x1)) * scale
    dfmap1      = dM @ fmap2            (per block, written directly)
    dfmap2     += dM^T @ fmap1_block    (accumulated across W1 blocks in the
                                         output block, relying on the TPU
                                         grid's sequential iteration order)

so the O(W1*W2) gradient also never reaches HBM.  Tap gradients are hard
zeros (disparity is detached every iteration; reference: core/raft_stereo.py:109).
Supports fp32 and bf16 feature maps; accumulation is always fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _block_w1, _interpret, _pad_taps, _pad_w1


def _alt_fwd_kernel(f1_ref, f2_ref, taps_ref, out_ref, *, scale):
    """One (n, w1-block): out[x1, k] = sum_j M[x1, j] * hat(j - taps[x1, k])."""
    f1 = f1_ref[0].astype(jnp.float32)            # (blk, C)
    f2 = f2_ref[0].astype(jnp.float32)            # (W2, C)
    taps = taps_ref[0].astype(jnp.float32)        # (blk, K)
    m = jax.lax.dot_general(f1, f2, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST) * scale
    w2 = f2.shape[0]
    j = jax.lax.broadcasted_iota(jnp.int32, (1, w2), 1).astype(jnp.float32)
    cols = []
    for ki in range(taps.shape[-1]):              # K is small (9): unrolled
        w = jnp.maximum(0.0, 1.0 - jnp.abs(j - taps[:, ki][:, None]))
        cols.append(jnp.sum(m * w, axis=-1))
    out_ref[0] = jnp.stack(cols, axis=-1).astype(out_ref.dtype)


def _alt_bwd_kernel(f1_ref, f2_ref, taps_ref, g_ref, df1_ref, df2_ref, *,
                    scale):
    f1 = f1_ref[0].astype(jnp.float32)            # (blk, C)
    f2 = f2_ref[0].astype(jnp.float32)            # (W2, C)
    taps = taps_ref[0].astype(jnp.float32)        # (blk, K)
    g = g_ref[0].astype(jnp.float32)              # (blk, K)
    w2 = f2.shape[0]
    j = jax.lax.broadcasted_iota(jnp.int32, (1, w2), 1).astype(jnp.float32)
    dm = jnp.zeros((taps.shape[0], w2), jnp.float32)
    for ki in range(taps.shape[-1]):
        w = jnp.maximum(0.0, 1.0 - jnp.abs(j - taps[:, ki][:, None]))
        dm = dm + g[:, ki][:, None] * w
    dm = dm * scale
    df1_ref[0] = jax.lax.dot_general(
        dm, f2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).astype(df1_ref.dtype)

    # dfmap2 accumulates over all W1 blocks of this row; the W1-block index is
    # the innermost grid dimension, so iterations land here sequentially.
    @pl.when(pl.program_id(1) == 0)
    def _init():
        df2_ref[0] = jnp.zeros_like(df2_ref[0])

    df2_ref[0] += jax.lax.dot_general(
        dm, f1, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).astype(df2_ref.dtype)


def preflatten_fmap1(fmap1: jax.Array) -> jax.Array:
    """(B, H, W1, C) -> (B*H, W1p, C) flattened + W1-padded for the kernel.
    Do once outside any loop — the pad is an HBM copy; hoisting here makes
    the single copy structural (same rationale as
    pallas_corr.preflatten_volume)."""
    f1, _ = _pad_w1(
        fmap1.reshape(fmap1.shape[0] * fmap1.shape[1], *fmap1.shape[2:]),
        _block_w1(fmap1.shape[2]))
    return f1


def preflatten_fmap2(fmap2: jax.Array) -> jax.Array:
    """(B, H, W2, C) -> (B*H, W2, C); no padding (W2 rides whole in VMEM)."""
    return fmap2.reshape(fmap2.shape[0] * fmap2.shape[1], *fmap2.shape[2:])


def pallas_alt_lookup_flat(f1flat: jax.Array, f2flat: jax.Array,
                           taps: jax.Array) -> jax.Array:
    """Lookup against preflattened feature maps; taps stay in model layout
    (B, H, W1, K) and are the only tensor reshaped per call."""
    return _make_alt(f1flat.shape, f2flat.shape, f1flat.dtype.name,
                     f2flat.dtype.name)(f1flat, f2flat, taps)


def pallas_alt_lookup(fmap1: jax.Array, fmap2: jax.Array,
                      taps: jax.Array) -> jax.Array:
    """On-demand correlation at the given taps.

    fmap1: (B, H, W1, C); fmap2: (B, H, W2, C) (same level resolution);
    taps: (B, H, W1, K) absolute x-coordinates into W2.
    Returns (B, H, W1, K) float32, scaled by 1/sqrt(C), zero outside
    [0, W2-1], align-corners linear interpolation — the exact semantics of
    the ``reg``/``alt`` backends (cross-checked in tests/test_pallas_alt.py).
    Loop callers should preflatten once and use the ``_flat`` variant.
    """
    return pallas_alt_lookup_flat(preflatten_fmap1(fmap1),
                                  preflatten_fmap2(fmap2), taps)


@functools.lru_cache(maxsize=None)
def _make_alt(f1flat_shape, f2flat_shape, f1_dtype, f2_dtype):
    @jax.custom_vjp
    def f(f1flat, f2flat, taps):
        return _alt_fwd_impl(f1flat, f2flat, taps)

    def fwd(f1flat, f2flat, taps):
        return _alt_fwd_impl(f1flat, f2flat, taps), (f1flat, f2flat, taps)

    def bwd(res, g):
        f1flat, f2flat, taps = res
        df1, df2 = _alt_bwd_impl(f1flat, f2flat, taps, g)
        return (df1.astype(f1_dtype), df2.astype(f2_dtype),
                jnp.zeros_like(taps))

    f.defvjp(fwd, bwd)
    return f


def _alt_fwd_impl(f1flat, f2flat, taps):
    n, w1p, c = f1flat.shape
    w2 = f2flat.shape[1]
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps)
    scale = 1.0 / float(c) ** 0.5
    out = pl.pallas_call(
        functools.partial(_alt_fwd_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((n, w1p, kk), jnp.float32),
        grid=(n, w1p // blk),
        in_specs=[
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w2, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(f1flat, f2flat, t)
    return out[:, :w1].reshape(b, h, w1, kk)


def _alt_bwd_impl(f1flat, f2flat, taps, g):
    n, w1p, c = f1flat.shape
    w2 = f2flat.shape[1]
    b, h, w1, kk = taps.shape
    t, blk = _pad_taps(taps)
    gg, _ = _pad_w1(g.reshape(b * h, w1, kk), blk)
    # Padded g rows are zero, so padded rows contribute nothing to df2 and
    # their df1 rows are themselves zero — the flat grads map back through
    # the one-time preflatten reshapes by ordinary autodiff.
    scale = 1.0 / float(c) ** 0.5
    df1, df2 = pl.pallas_call(
        functools.partial(_alt_bwd_kernel, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((n, w1p, c), jnp.float32),
                   jax.ShapeDtypeStruct((n, w2, c), jnp.float32)),
        grid=(n, w1p // blk),
        in_specs=[
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w2, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk, kk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w2, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(f1flat, f2flat, t, gg)
    return df1, df2
