"""Pallas TPU kernel for ON-DEMAND correlation lookup (no precomputed volume).

The reference gestures at this capability twice and ships it broken/slow:
``AlternateCorrBlock`` (``alt_cuda``) raises NotImplementedError and its CUDA
extension is absent (reference: core/corr.py:159-188), while the pure-torch
``alt`` path works but is documented as significantly slower
(reference: README.md:121).  This module is the working TPU form.

Design: the correlation row for a block of W1 pixels is

    M[x1, j] = <fmap1[x1, :], fmap2[j, :]> / sqrt(C)

— a (blk x C) @ (C x W2) matmul that fits in VMEM and runs on the MXU.  Each
kernel invocation recomputes its block's rows on the fly, applies the same
hat-weight tap reduction as the precomputed-volume kernel (ops/pallas_corr.py)
and throws the rows away: HBM never holds more than the O(H*W) feature
pyramids, yet the inner loop is MXU matmul + VPU reduction instead of the
XLA gather chain the ``alt`` backend lowers to.

Backward (for completeness/training) fuses the volume-gradient expansion with
the feature-gradient matmuls per block:

    dM[x1, j]   = sum_k g[x1, k] * hat(j - t_k(x1)) * scale
    dfmap1      = dM @ fmap2            (per block, written directly)
    dfmap2     += dM^T @ fmap1_block    (accumulated across W1 blocks in the
                                         output block, relying on the TPU
                                         grid's sequential iteration order)

so the O(W1*W2) gradient also never reaches HBM.  Tap gradients are hard
zeros (disparity is detached every iteration; reference: core/raft_stereo.py:109).
Supports fp32 and bf16 feature maps; accumulation is always fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import (_BLOCK_ROWS, _COMPILER_PARAMS, _block_w1,
                          _interpret, _pad_rows, _pad_taps, _pad_w1,
                          bounds_from_widths, pad_lane)


def _dot(a, b, dims, prec: str):
    """dot_general with a precision POLICY string, not a lax.Precision:
    Mosaic only lowers DEFAULT and HIGHEST, so the 3-pass "high" form
    (jax.lax.Precision.HIGH outside kernels) is built manually — split each
    fp32 operand into a bf16 head + bf16 residual and sum the three
    significant cross products (hi*hi + hi*lo + lo*hi), which is exactly
    XLA's bf16x3 emulation.  bf16 operands always take the native single
    pass regardless of the policy."""
    if a.dtype != jnp.float32 or prec == "default":
        return jax.lax.dot_general(a, b, dims,
                                   preferred_element_type=jnp.float32,
                                   precision=jax.lax.Precision.DEFAULT)
    if prec == "highest":
        return jax.lax.dot_general(a, b, dims,
                                   preferred_element_type=jnp.float32,
                                   precision=jax.lax.Precision.HIGHEST)
    a_hi = a.astype(jnp.bfloat16)
    a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    b_hi = b.astype(jnp.bfloat16)
    b_lo = (b - b_hi.astype(jnp.float32)).astype(jnp.bfloat16)

    def d(x, y):
        return jax.lax.dot_general(x, y, dims,
                                   preferred_element_type=jnp.float32,
                                   precision=jax.lax.Precision.DEFAULT)

    return d(a_hi, b_hi) + d(a_hi, b_lo) + d(a_lo, b_hi)


def _alt_pyr_fwd_kernel(f1_ref, f2_ref, taps_ref, out_ref, *, scale, bounds,
                        prec="highest"):
    """Fused all-levels lookup: the fmap2 pyramid is concatenated along W2
    and every level's taps are resolved against one (blk x W2cat) matmul:
    out[x1, l*K + k] = sum_j M_l[x1, j] * hat(j - taps[x1, l*K + k]).

    One kernel launch per (row, w1-block) instead of one per level.
    ``bounds`` is a static tuple of (offset, width) per level; static
    lane-aligned slices of the matmul result keep each tap's hat reduction
    inside its own level (see the body comment), so zero-outside semantics
    at level edges are preserved exactly. The single-level
    ``pallas_alt_lookup`` path is this same kernel with bounds=((0, w2),).
    """
    # Feed the MXU the stored dtype directly: bf16 inputs take the native
    # bf16 path with fp32 accumulation (multi-pass emulation on bf16 inputs
    # would be pure waste); fp32 inputs use the requested emulation depth
    # ("highest" = exact 6-pass, "high" = 3-pass at half the MXU cost;
    # see _dot).
    f1 = f1_ref[...]                              # (R, blk, C)
    f2 = f2_ref[...]                              # (R, W2cat, C)
    taps = taps_ref[...].astype(jnp.float32)      # (R, blk, L*K)
    m = _dot(f1, f2, (((2,), (2,)), ((0,), (0,))),
             prec) * scale                        # (R, blk, W2cat)
    kk = taps.shape[-1] // len(bounds)
    cols = []
    for li, (off, w2p) in enumerate(bounds):
        # Static lane-aligned slice: each tap's hat reduction sweeps only
        # its own level's columns (masking the full concat row costs L x
        # the VPU work; unaligned slices cost lane-realignment copies —
        # both measured slower than per-level kernel launches). Levels are
        # zero-padded to lane multiples, and a padded column's m is exactly
        # zero, so no mask is needed for correct zero-outside semantics.
        ml = m[:, :, off:off + w2p]
        j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2p), 2).astype(jnp.float32)
        for ki in range(kk):                      # L*K is small: unrolled
            t = taps[:, :, li * kk + ki][..., None]
            w = jnp.maximum(0.0, 1.0 - jnp.abs(j - t))
            cols.append(jnp.sum(ml * w, axis=-1))  # (R, blk)
    out_ref[...] = jnp.stack(cols, axis=-1).astype(out_ref.dtype)


def _radial_cols(f1_ref, f2_ref, x_ref, *, scale, bounds, radius, prec,
                 level_scales):
    """Shared core of the radial kernels: the per-tap column list.

    Taps are x + k for k in [-radius, radius], so every tap of a level
    shares floor(x)/frac(x).  Instead of K dense hat sweeps (~6 VPU ops
    per column-visit), sweep K+1 integer WINDOWS
    win[d] = M[x1, floor(x)+d-radius] (~3 ops per visit: one shared integer
    offset, then compare + masked-accumulate per window) and lerp
    per-pixel:  out_k = (1-f)*win[k] + f*win[k+1].  Algebraically identical
    to the hat form — hat(j - (b0+f+k-r)) is nonzero exactly at
    j = b0+k-r (weight 1-f) and j+1 (weight f) — including zero-outside
    edges (out-of-range windows sum nothing) and NaN coords (f = NaN
    poisons the lerp).  ~1.7x fewer VPU ops on the kernel's dominant cost
    (docs/perf_notes_r03.md)."""
    f1 = f1_ref[...]                              # (R, blk, C)
    f2 = f2_ref[...]                              # (R, W2cat, C)
    x = x_ref[...].astype(jnp.float32)            # (R, blk, L)
    m = _dot(f1, f2, (((2,), (2,)), ((0,), (0,))),
             prec) * scale                        # (R, blk, W2cat)
    kk = 2 * radius + 1
    cols = []
    for li, (off, w2p) in enumerate(bounds):
        ml = m[:, :, off:off + w2p]
        # level_scales (static): x carries only the LEVEL-0 center and the
        # per-level locals are derived in-register — the (B, H, W1, L)
        # center tensor cost 28 us/iter of 24 GB/s loop fusion outside.
        xl = (x[:, :, li] if level_scales is None
              else x[:, :, 0] * level_scales[li])
        b0 = jnp.floor(xl)
        f = xl - b0                               # (R, blk)
        j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2p), 2)
        z = j - b0.astype(jnp.int32)[..., None] + radius   # (R, blk, w2p)
        wins = [jnp.sum(jnp.where(z == d, ml, 0.0), axis=-1)
                for d in range(kk + 1)]           # each (R, blk)
        for ki in range(kk):
            cols.append(wins[ki] * (1.0 - f) + wins[ki + 1] * f)
    return cols


def _alt_pyr_radial_kernel(f1_ref, f2_ref, x_ref, out_ref, *, scale, bounds,
                           radius, prec="highest", level_scales=None):
    """Radial lookup emitting the raw correlation features."""
    cols = _radial_cols(f1_ref, f2_ref, x_ref, scale=scale, bounds=bounds,
                        radius=radius, prec=prec, level_scales=level_scales)
    # Zero channel padding up to the declared output width: a 36-lane
    # tensor makes the consuming 1x1 conv's fusion read at ~39 GB/s
    # (measured 60 us/iter); emitting a lane-friendly channel count is
    # free here and the consumer zero-pads its weights to match.
    while len(cols) < out_ref.shape[-1]:
        cols.append(jnp.zeros_like(cols[0]))
    out_ref[...] = jnp.stack(cols, axis=-1).astype(out_ref.dtype)


def _alt_pyr_radial_epi_kernel(f1_ref, f2_ref, x_ref, ew_ref, eb_ref,
                               out_ref, *, scale, bounds, radius,
                               prec="highest", level_scales=None):
    """Radial lookup with the motion encoder's convc1 fused as an
    epilogue: out = relu(cols @ W + b), the 1x1 (L*K -> 64) conv that
    otherwise re-reads the correlation features from HBM at 75 GB/s
    (60 us/iter, round-5 trace).  The dot runs in the consumer's compute
    dtype exactly like the module path (PointwisePaddedConv casts its
    input and kernel to the model dtype and adds bias in that dtype), so
    the fused numerics mirror the unfused ones; inference-only (the
    backward keeps the module conv — see make_pallas_alt_corr_fn)."""
    cols = _radial_cols(f1_ref, f2_ref, x_ref, scale=scale, bounds=bounds,
                        radius=radius, prec=prec, level_scales=level_scales)
    ew = ew_ref[...]                               # (L*K, Co) compute dtype
    z = jnp.stack(cols, axis=-1).astype(ew.dtype)  # (R, blk, L*K)
    pp = (jax.lax.Precision.HIGHEST if ew.dtype == jnp.float32 else None)
    y = jax.lax.dot_general(z, ew, (((2,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=pp)
    y = y.astype(ew.dtype) + eb_ref[...].astype(ew.dtype)  # eb (1, 1, Co)
    out_ref[...] = jnp.maximum(y, 0).astype(out_ref.dtype)


def _alt_pyr_bwd_kernel(f1_ref, f2_ref, taps_ref, g_ref, df1_ref, df2_ref, *,
                        scale, bounds, prec="highest"):
    f1 = f1_ref[...]                              # (R, blk, C)
    f2 = f2_ref[...]                              # (R, W2cat, C)
    taps = taps_ref[...].astype(jnp.float32)      # (R, blk, L*K)
    g = g_ref[...].astype(jnp.float32)            # (R, blk, L*K)
    kk = taps.shape[-1] // len(bounds)
    parts = []
    for li, (off, w2p) in enumerate(bounds):
        j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w2p), 2).astype(jnp.float32)
        dml = jnp.zeros(taps.shape[:2] + (w2p,), jnp.float32)
        for ki in range(kk):
            t = taps[:, :, li * kk + ki][..., None]
            w = jnp.maximum(0.0, 1.0 - jnp.abs(j - t))
            dml = dml + g[:, :, li * kk + ki][..., None] * w
        parts.append(dml)
    # Gradient mass landing on a level's zero-padded columns (a tap within 1
    # of the level edge) flows into df2 rows that the caller's concat-pad
    # autodiff discards — matching the per-level kernels exactly.
    dm = (jnp.concatenate(parts, axis=-1) * scale).astype(f1.dtype)
    df1_ref[...] = _dot(dm, f2, (((2,), (1,)), ((0,), (0,))),
                        prec).astype(df1_ref.dtype)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        df2_ref[...] = jnp.zeros_like(df2_ref[...])

    df2_ref[...] += _dot(dm, f1, (((1,), (1,)), ((0,), (0,))),
                         prec).astype(df2_ref.dtype)


def preflatten_fmap1(fmap1: jax.Array) -> jax.Array:
    """(B, H, W1, C) -> (B*H, W1p, C) flattened + W1-padded for the kernel.
    Do once outside any loop — the pad is an HBM copy; hoisting here makes
    the single copy structural (same rationale as
    pallas_corr.preflatten_volume)."""
    f1, _ = _pad_w1(
        fmap1.reshape(fmap1.shape[0] * fmap1.shape[1], *fmap1.shape[2:]),
        _block_w1(fmap1.shape[2]))
    return _pad_rows(f1)


def preflatten_fmap2(fmap2: jax.Array) -> jax.Array:
    """(B, H, W2, C) -> (B*Hp, W2, C); W2 unpadded (rides whole in VMEM),
    rows padded to the kernel row-block like preflatten_fmap1."""
    return _pad_rows(
        fmap2.reshape(fmap2.shape[0] * fmap2.shape[1], *fmap2.shape[2:]))


def pallas_alt_lookup_flat(f1flat: jax.Array, f2flat: jax.Array,
                           taps: jax.Array,
                           precision: str = "highest") -> jax.Array:
    """Lookup against preflattened feature maps; taps stay in model layout
    (B, H, W1, K) and are the only tensor reshaped per call. Single-level
    special case of the fused pyramid kernel."""
    return _make_alt_pyr(f1flat.shape, f2flat.shape, (f2flat.shape[1],),
                         f1flat.dtype.name, f2flat.dtype.name, precision)(
                             f1flat, f2flat, taps)


def pallas_alt_lookup(fmap1: jax.Array, fmap2: jax.Array,
                      taps: jax.Array) -> jax.Array:
    """On-demand correlation at the given taps.

    fmap1: (B, H, W1, C); fmap2: (B, H, W2, C) (same level resolution);
    taps: (B, H, W1, K) absolute x-coordinates into W2.
    Returns (B, H, W1, K) float32, scaled by 1/sqrt(C), zero outside
    [0, W2-1], align-corners linear interpolation — the exact semantics of
    the ``reg``/``alt`` backends (cross-checked in tests/test_pallas_alt.py).
    Loop callers should preflatten once and use the ``_flat`` variant.
    """
    return pallas_alt_lookup_flat(preflatten_fmap1(fmap1),
                                  preflatten_fmap2(fmap2), taps)


def pad_w2_lane(f2flat: jax.Array) -> jax.Array:
    """(B*H, W2, C) level -> lane-multiple W2 (pallas_corr.pad_lane); zero
    rows correlate to exactly zero, so padding never changes a lookup."""
    return pad_lane(f2flat, 1)


def pallas_alt_pyramid_flat(f1flat: jax.Array, f2cat: jax.Array,
                            taps: jax.Array, w2s: tuple,
                            precision: str = "highest",
                            out_dtype=jnp.float32) -> jax.Array:
    """All pyramid levels in ONE kernel call.

    f1flat: (B*H, W1p, C) from preflatten_fmap1; f2cat: (B*H, sum(w2s), C) —
    the per-level preflattened, ``pad_w2_lane``-padded fmap2 pyramid
    concatenated along W2; taps: (B, H, W1, L*K) per-level LOCAL tap
    coordinates, level-major; w2s: static per-level PADDED widths (each a
    lane multiple). Returns (B, H, W1, L*K) in ``out_dtype`` (fp32
    accumulation in-kernel; emitting bf16 directly saves the model's
    post-lookup convert + one HBM round trip) with the exact per-level
    ``pallas_alt_lookup`` semantics (equivalence pinned in
    tests/test_pallas_alt.py).
    """
    return _make_alt_pyr(f1flat.shape, f2cat.shape, tuple(w2s),
                         f1flat.dtype.name, f2cat.dtype.name, precision,
                         jnp.dtype(out_dtype).name)(f1flat, f2cat, taps)


def pallas_alt_pyramid_radial_flat(f1flat: jax.Array, f2cat: jax.Array,
                                   x_levels: jax.Array, w2s: tuple,
                                   radius: int,
                                   precision: str = "highest",
                                   out_dtype=jnp.float32,
                                   out_channels: int = 0,
                                   level_scales: tuple = None) -> jax.Array:
    """Model-pattern variant of :func:`pallas_alt_pyramid_flat`: instead of
    explicit per-tap coordinates it takes the per-level LOCAL center
    ``x_levels`` (B, H, W1, L) and the static ``radius``, and resolves the
    taps ``x + k, k in [-radius, radius]`` with the cheaper shared-fraction
    window kernel.  Output channel order and semantics are identical to the
    general entry with ``taps = x[..., None] + arange(-r, r+1)``
    (equivalence pinned in tests/test_pallas_alt.py).

    ``out_channels`` (when > L*K) zero-pads the channel axis in-kernel so
    consumers read a lane-friendly width (see the kernel comment).

    ``level_scales`` (static tuple of floats): when given, ``x_levels``
    carries a SINGLE channel — the level-0 center — and each level's
    local center is derived in-kernel as x * level_scales[l], removing
    the per-level center tensor from HBM entirely (the model's pattern:
    scales 2**-l)."""
    return _make_alt_pyr_radial(f1flat.shape, f2cat.shape, tuple(w2s),
                                radius, f1flat.dtype.name, f2cat.dtype.name,
                                precision, jnp.dtype(out_dtype).name,
                                out_channels,
                                tuple(level_scales)
                                if level_scales is not None
                                else None)(f1flat, f2cat, x_levels)


def pallas_alt_pyramid_radial_epi_flat(f1flat, f2cat, x_levels, w2s, radius,
                                       ew, eb,
                                       precision: str = "highest",
                                       out_dtype=jnp.float32,
                                       level_scales: tuple = None):
    """Radial pyramid lookup with a fused 1x1-conv + relu epilogue
    (the motion encoder's convc1): returns relu(corr @ ew + eb) directly,
    (B, H, W1, Co).  ``ew`` is (L*K, Co) in the compute dtype, ``eb``
    (1, 1, Co).  Inference-only — no VJP is defined (training keeps the
    module conv; the gate lives in the model, models/raft_stereo.py)."""
    bounds = bounds_from_widths(tuple(w2s))
    return _alt_pyr_radial_fwd_impl(
        f1flat, f2cat, x_levels, bounds, radius, precision,
        jnp.dtype(out_dtype), 0,
        tuple(level_scales) if level_scales is not None else None,
        epilogue=(ew, eb))


@functools.lru_cache(maxsize=None)
def _make_alt_pyr_radial(f1flat_shape, f2cat_shape, w2s, radius, f1_dtype,
                         f2_dtype, precision="highest", out_dtype="float32",
                         out_channels=0, level_scales=None):
    bounds = bounds_from_widths(w2s)
    odt = jnp.dtype(out_dtype)

    @jax.custom_vjp
    def f(f1flat, f2cat, x):
        return _alt_pyr_radial_fwd_impl(f1flat, f2cat, x, bounds, radius,
                                        precision, odt, out_channels,
                                        level_scales)

    def fwd(f1flat, f2cat, x):
        return _alt_pyr_radial_fwd_impl(
            f1flat, f2cat, x, bounds, radius, precision, odt,
            out_channels, level_scales), (f1flat, f2cat, x)

    def bwd(res, g):
        f1flat, f2cat, x = res
        # The general backward kernel already handles arbitrary taps; the
        # radial pattern is just its special case, so materialize the taps
        # (a small XLA broadcast-add on the backward path only).  Channel
        # padding carries no gradient: slice the cotangent back to L*K.
        if level_scales is not None:
            scales = jnp.asarray(level_scales, jnp.float32)
            xl = x.astype(jnp.float32)[..., 0:1] * scales
        else:
            xl = x.astype(jnp.float32)
        lk = xl.shape[-1] * (2 * radius + 1)
        offsets = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
        taps = (xl[..., None] + offsets).reshape(*xl.shape[:-1], lk)
        df1, df2 = _alt_pyr_bwd_impl(f1flat, f2cat, taps, g[..., :lk],
                                     bounds, precision)
        return (df1[:f1flat.shape[0]].astype(f1_dtype),
                df2[:f2cat.shape[0]].astype(f2_dtype),
                jnp.zeros_like(x))

    f.defvjp(fwd, bwd)
    return f


def _alt_pyr_radial_fwd_impl(f1flat, f2cat, x, bounds, radius,
                             prec="highest", out_dtype=jnp.float32,
                             out_channels=0, level_scales=None,
                             epilogue=None):
    f1flat = _pad_rows(f1flat)  # no-ops for preflatten_* outputs
    f2cat = _pad_rows(f2cat)
    n, w1p, c = f1flat.shape
    b, h, w1, nl = x.shape
    t, blk = _pad_taps(x, n)
    scale = 1.0 / float(c) ** 0.5
    w2cat = f2cat.shape[1]
    n_lvl = len(bounds) if level_scales is not None else nl
    lk = max(n_lvl * (2 * radius + 1), out_channels)
    r = _BLOCK_ROWS
    operands = [f1flat, f2cat, t]
    in_specs = [
        pl.BlockSpec((r, blk, c), lambda i, j: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((r, w2cat, c), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((r, blk, nl), lambda i, j: (i, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    if epilogue is None:
        kernel = functools.partial(
            _alt_pyr_radial_kernel, scale=scale, bounds=bounds,
            radius=radius, prec=prec, level_scales=level_scales)
    else:
        ew, eb = epilogue                         # (L*K, Co), (1, 1, Co)
        lk = ew.shape[-1]
        kernel = functools.partial(
            _alt_pyr_radial_epi_kernel, scale=scale, bounds=bounds,
            radius=radius, prec=prec, level_scales=level_scales)
        operands += [ew, eb]
        in_specs += [
            pl.BlockSpec(ew.shape, lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(eb.shape, lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, w1p, lk), out_dtype),
        grid=(n // r, w1p // blk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((r, blk, lk), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(*operands)
    return out[:b * h, :w1].reshape(b, h, w1, lk)


@functools.lru_cache(maxsize=None)
def _make_alt_pyr(f1flat_shape, f2cat_shape, w2s, f1_dtype, f2_dtype,
                  precision="highest", out_dtype="float32"):
    bounds = bounds_from_widths(w2s)
    prec = precision
    odt = jnp.dtype(out_dtype)

    @jax.custom_vjp
    def f(f1flat, f2cat, taps):
        return _alt_pyr_fwd_impl(f1flat, f2cat, taps, bounds, prec, odt)

    def fwd(f1flat, f2cat, taps):
        return _alt_pyr_fwd_impl(f1flat, f2cat, taps, bounds, prec, odt), (
            f1flat, f2cat, taps)

    def bwd(res, g):
        f1flat, f2cat, taps = res
        df1, df2 = _alt_pyr_bwd_impl(f1flat, f2cat, taps, g, bounds, prec)
        # Row-padding inside the impl is invisible to callers: cotangents
        # are sliced back to the primal row counts.
        return (df1[:f1flat.shape[0]].astype(f1_dtype),
                df2[:f2cat.shape[0]].astype(f2_dtype),
                jnp.zeros_like(taps))

    f.defvjp(fwd, bwd)
    return f


def _alt_pyr_fwd_impl(f1flat, f2cat, taps, bounds, prec="highest",
                      out_dtype=jnp.float32):
    f1flat = _pad_rows(f1flat)  # no-ops for preflatten_* outputs
    f2cat = _pad_rows(f2cat)
    n, w1p, c = f1flat.shape
    b, h, w1, lk = taps.shape
    t, blk = _pad_taps(taps, n)
    scale = 1.0 / float(c) ** 0.5
    w2cat = f2cat.shape[1]
    r = _BLOCK_ROWS
    out = pl.pallas_call(
        functools.partial(_alt_pyr_fwd_kernel, scale=scale, bounds=bounds,
                          prec=prec),
        out_shape=jax.ShapeDtypeStruct((n, w1p, lk), out_dtype),
        grid=(n // r, w1p // blk),
        in_specs=[
            pl.BlockSpec((r, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w2cat, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, blk, lk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, blk, lk), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(f1flat, f2cat, t)
    return out[:b * h, :w1].reshape(b, h, w1, lk)


def _alt_pyr_bwd_impl(f1flat, f2cat, taps, g, bounds, prec="highest"):
    f1flat = _pad_rows(f1flat)  # no-ops for preflatten_* outputs
    f2cat = _pad_rows(f2cat)
    n, w1p, c = f1flat.shape
    b, h, w1, lk = taps.shape
    t, blk = _pad_taps(taps, n)
    gg, _ = _pad_w1(g.reshape(b * h, w1, lk), blk)
    gg = _pad_rows(gg)
    scale = 1.0 / float(c) ** 0.5
    w2cat = f2cat.shape[1]
    r = _BLOCK_ROWS
    df1, df2 = pl.pallas_call(
        functools.partial(_alt_pyr_bwd_kernel, scale=scale, bounds=bounds,
                          prec=prec),
        out_shape=(jax.ShapeDtypeStruct((n, w1p, c), jnp.float32),
                   jax.ShapeDtypeStruct((n, w2cat, c), jnp.float32)),
        grid=(n // r, w1p // blk),
        in_specs=[
            pl.BlockSpec((r, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w2cat, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, blk, lk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, blk, lk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((r, blk, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w2cat, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(f1flat, f2cat, t, gg)
    return df1, df2
