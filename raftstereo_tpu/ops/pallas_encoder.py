"""Fused Pallas pipeline for the feature encoder's instance-norm stage.

Why: at flagship resolution the stem + layer1 stage (five 64-channel convs
with instance norms at 544x960) costs ~27 ms of which ~21 ms is XLA layout
churn — every cross-(H,W) reduction forces ~4 full-tensor relayouts of
135 MB each between the convs' space-to-depth blocked layouts and the
reduce's, and NO XLA-side formulation escapes it (lane-packed views,
direct/fp32 reduces, MXU ones-vector matmuls, 128-channel padding ALL
measured 27-62 ms; scripts/mb_encoder.py, docs/perf_notes_r03.md).

The fix is to own the stage end-to-end in Pallas so every tensor stays in
row-major (B, H, W, C):

* The (H, W, 64) tensor is VIEWED as (H, W/2, 128) — a free row-major
  reinterpretation that packs adjacent pixel pairs into full MXU/VPU
  lanes (the same trick XLA's blocked layouts buy with relayouts).  A
  3x3x64->64 conv becomes a 3x3-tap 128->128 conv over packed columns
  whose (parity-in, parity-out) weight blocks embed the original taps:
  measured 90.8 TF/s packed (= ~45 TF/s of useful 64-ch flops) vs
  XLA's 29.8 TF/s row-major / ~70 TF/s blocked-plus-relayouts.
* Each kernel call fuses the whole conv INPUT preparation — instance-norm
  apply from precomputed stats, relu, optional residual add (itself
  normalized from a second raw tensor) — and accumulates the fp32
  per-channel sum/sum-of-squares of its raw OUTPUT for the next norm, so
  a norm never touches HBM as a separate op.
* dy taps read halo rows (built by cheap strided row slices, 2 rows per
  block); dx taps are resolved post-matmul by rolling the accumulated
  output one packed column and masking the wrap (operands stay
  contiguous — the data-stationary formulation from scripts/mb_gru_kernel).

Semantics are exactly BasicEncoder's stem + layer1 (conv1-norm1-relu,
two ResidualBlocks; reference: core/extractor.py:122-197 structure) with
instance-norm statistics in fp32.  The backward pass is the XLA reference
formulation's VJP via jax.custom_vjp (training keeps its current cost;
this pipeline removes fixed-stage inference time).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _COMPILER_PARAMS, _interpret
from .pallas_norm import _row_block


# None = auto (fused on TPU backends); True/False force — tests force True
# to exercise the interpret-mode kernels on CPU, and config.fused_encoder
# forwards a per-model override (so evaluations can pin one numeric path).
# Thread-local: the override scopes a TRACE, and concurrent tracing from
# another thread must not see this thread's gate (the train step's
# override_fused_stem(False) is load-bearing for training numerics).
_tls = threading.local()

# Conv1 dot structure: True folds the 7 dy row taps into the contraction
# (one big-K dot, 2 nearly-full MXU K-passes) instead of 7 small-K dots
# whose 30/36-deep contractions fill 23-28% of the MXU's 128 K-rows.
# Measured (scripts/ab_conv1_bigk.py, alternating same-process pairs at
# flagship b1): ratios 0.96 / 1.00 vs the 7-dot form — a wash; the r4
# pre-shift restructure already brought the kernel to ~1.1 ms for 3
# images (round-5 trace) and the operand concat eats the MXU saving.
# Committed negative result; default stays on the simpler 7-dot form.
_conv1_bigk = False


def make_override_scope(tls, attr):
    """(getter, contextmanager) pair over a thread-local override slot.

    Shared scaffolding for the fused-stage gates (stem here, layer2 in
    pallas_layer2): the override scopes a TRACE, and concurrent tracing
    from another thread must not see this thread's gate — so the slot
    lives in a ``threading.local``, and the scope restores the previous
    value on exit (nesting-safe)."""
    def get():
        return getattr(tls, attr, None)

    @contextlib.contextmanager
    def scope(value):
        prev = get()
        setattr(tls, attr, value)
        try:
            yield
        finally:
            setattr(tls, attr, prev)

    return get, scope


_get_override, _stem_scope = make_override_scope(_tls, "fused_stem_override")


def override_fused_stem(value):
    """Trace-time scope for the thread-local stem-gate override.  Since
    round 5 the train step no longer forces this off — the stage's
    backward consumes the forward's saved residuals (_stage_bwd_xla)
    instead of re-linearizing the XLA forward, and measures >= plain at
    the per-shard batches where the auto gate engages (train/step.py).
    Tests force True to pin the interpret-mode kernels on CPU; a
    per-model config.fused_encoder still wins over this scope
    (use_fused_stem checks the explicit override first)."""
    return _stem_scope(value)


def _stem_shard_mesh(shape, warn: bool = False):
    """The active (data, space) mesh if the fused stage can partition over
    it via ``shard_map``: B divisible by ``data``, H by ``space`` with >= 2
    rows per shard (each conv needs one real halo row per boundary).
    Returns (mesh, data, space) or None (plain single-device lowering).

    ``warn``: emit the partitionability warning — only use_fused_stem sets
    it, and only when the gate would otherwise have TAKEN the fused stage
    (a CPU/GPU multi-device run with an odd batch would otherwise get a
    misleading RuntimeWarning on a path it never wanted)."""
    import warnings

    from ..parallel.context import active_corr_mesh
    from ..parallel.mesh import DATA_AXIS, SPACE_AXIS

    mesh = active_corr_mesh()
    if mesh is None:
        return None
    b, h = shape[0], shape[1]
    d = mesh.shape.get(DATA_AXIS, 1)
    s = mesh.shape.get(SPACE_AXIS, 1)
    if d * s == 1:
        return None
    if b % d or h % s or (h // s) < 2:
        if warn:
            warnings.warn(
                f"fused encoder stage cannot partition over the active mesh "
                f"(batch {b} % data {d}, height {h} % space {s}); using the "
                f"plain XLA stage", RuntimeWarning, stacklevel=3)
        return None
    return mesh, d, s


def fused_stem_forced(override=None) -> bool:
    """True iff the fused stage is EXPLICITLY forced on — the same
    tri-state precedence use_fused_stem applies (per-model config override
    wins over the module-level one).  Single source of truth for callers
    that branch on forced-ness (encoders' BN-without-conv1 case)."""
    ov = override if override is not None else _get_override()
    return ov is True


def use_fused_stem(norm_fn: str, shape, override=None) -> bool:
    """Gate for the fused stage: instance or frozen-batch norm, even
    width, TPU backend (the kernels interpret on CPU for tests, but the
    plain XLA path is the sane CPU default).

    Sharding: a bare pallas_call cannot be SPMD-partitioned, so under an
    active corr mesh (the evaluator / train / dryrun paths) the stage runs
    inside ``shard_map`` over the mesh's (data, space) axes — see
    ``_fused_forward`` — and the gate only asks that the shapes divide.
    With >1 devices visible but NO mesh context the gate stays off: a user
    may jit with shardings directly, and the plain XLA stage (which XLA
    partitions with halo exchanges) must remain what they get.

    ``override`` (tri-state, from config.fused_encoder) wins over the
    thread-local ``override_fused_stem`` scope, which wins over backend auto.
    The auto path also gates on <= 4 images per shard: at batch 8 the XLA
    stage's blocked lowering amortizes over the batch and the fused
    pipeline measures a net loss (12.45 vs 12.32 pairs/sec same-session
    at flagship b8; the conv1 kernel shows the same crossover).

    ``batch`` norm also qualifies: frozen BatchNorm folds to a constant
    per-channel affine, which the kernels' prep form relu(x*s + t)
    represents exactly (bn_affine) — no stats kernels, no psum."""
    ok = norm_fn in ("instance", "batch") and shape[2] % 2 == 0
    if not ok:
        return False
    ov = override if override is not None else _get_override()
    # Warn about an unpartitionable mesh only if the gate would otherwise
    # have taken the fused stage (explicit True, or TPU auto).
    would_take = ov is True or (ov is None
                                and jax.default_backend() == "tpu")
    shard = _stem_shard_mesh(shape, warn=would_take)
    if shard is not None:
        if ov is not None:
            return ov
        return (jax.default_backend() == "tpu"
                and shape[0] // shard[1] <= 4)
    from ..parallel.context import active_corr_mesh

    if active_corr_mesh() is not None:
        return False  # mesh active but not partitionable (warned above)
    if ov is not None:
        return ov
    return (jax.default_backend() == "tpu" and len(jax.devices()) == 1
            and shape[0] <= 4)


# --------------------------------------------------------------- packing

def pack_view(x: jax.Array) -> jax.Array:
    """(B, H, W, C) -> (B, H, W/2, 2C): free row-major reinterpretation
    (adjacent pixel pair -> one packed column)."""
    b, h, w, c = x.shape
    return x.reshape(b, h, w // 2, 2 * c)


def unpack_view(x: jax.Array) -> jax.Array:
    b, h, wp, c2 = x.shape
    return x.reshape(b, h, wp * 2, c2 // 2)


def pack_weights(w: jax.Array) -> jax.Array:
    """(3, 3, C, C) HWIO conv weights -> (9, 2C, 2C) packed [dy*3 + dp].

    Output pixel w_out = 2p + po with tap dx reads input pixel
    2p + po + dx = packed column p + dp, parity pi, where
    dp = floor((po+dx)/2), pi = (po+dx) mod 2:
      dp=-1: (pi=1 -> po=0) = W[dy, dx=-1]
      dp= 0: full 2x2 parity block
      dp=+1: (pi=0 -> po=1) = W[dy, dx=+1]
    """
    c = w.shape[2]
    out = jnp.zeros((3, 3, 2 * c, 2 * c), w.dtype)
    for po in range(2):
        for dxi, dx in enumerate((-1, 0, 1)):
            dp = (po + dx) // 2
            pi = (po + dx) % 2
            out = out.at[:, dp + 1,
                         pi * c:(pi + 1) * c,
                         po * c:(po + 1) * c].set(w[:, dxi])
    return out.reshape(9, 2 * c, 2 * c)


def pack_vec(v: jax.Array) -> jax.Array:
    """Per-channel vector -> packed duplicate [v, v] (both parities)."""
    return jnp.concatenate([v, v], axis=-1)


def stats_from_packed(s1: jax.Array, s2: jax.Array, n: float
                      ) -> Tuple[jax.Array, jax.Array]:
    """Packed (B, 1, 2C) fp32 sums -> per-original-channel (B, 1, C)
    mean / rstd (parity halves sum exactly: they partition the pixels).
    E[x^2]-m^2 precision envelope: see pallas_norm._pallas_forward and
    tests/test_pallas_encoder.py::TestStatsPrecisionEnvelope."""
    c = s1.shape[-1] // 2
    t1 = s1[..., :c] + s1[..., c:]
    t2 = s2[..., :c] + s2[..., c:]
    mean = t1 / n
    var = jnp.maximum(t2 / n - mean * mean, 0.0)
    return mean, jax.lax.rsqrt(var + 1e-5)


# ---------------------------------------------------------------- kernels

def _prep(x, s_ref, t_ref):
    """Normalization apply + relu from packed AFFINE refs: relu(x*s + t).
    Instance norm passes (rstd, -mean*rstd); frozen batch norm passes its
    folded constants (gamma*rstd, beta - mean*gamma*rstd) — the affine
    form also represents gamma == 0 channels exactly, which (x - m)*s
    cannot."""
    s = s_ref[...][:, :, None, :].astype(x.dtype)
    t = t_ref[...][:, :, None, :].astype(x.dtype)
    return jnp.maximum(x * s + t, 0)


def _edge_mask_halo(th, hv_ref):
    """Zero the prepped halo rows that lie OUTSIDE the image: conv zero
    padding applies in the PREPPED domain, but prepping a zero-filled edge
    halo yields relu(-m*s) != 0.  Validity comes from an (nblk, 2) SMEM
    operand (whole array per block, row selected by program_id — Mosaic
    requires non-divisible block dims to equal the array dims) rather than
    a program_id comparison so that under space sharding a shard-boundary
    halo (a REAL neighbor row delivered by ppermute) is kept while a
    global image edge is still masked."""
    j = pl.program_id(1)
    # Scalar multiplies, not a stacked bool mask: Mosaic cannot shape-cast
    # a vector<2xi1> to the broadcast rank.  Edge halo values are finite
    # (prep of a zero row), so multiply-by-zero is exact.
    top = th[:, 0:1] * hv_ref[j, 0].astype(th.dtype)
    bot = th[:, 1:2] * hv_ref[j, 1].astype(th.dtype)
    return jnp.concatenate([top, bot], axis=1)


def _conv_packed(t, halo, w_ref, bias_ref, wp):
    """3x3 packed conv of the prepped tile.

    t: (1, R, Wp, 2C) prepped center rows; halo: (1, 2, Wp, 2C) prepped
    halo rows [above, below]; w_ref: (9, 2C, 2C); returns (1, R, Wp, 2C)
    fp32 + bias."""
    col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, wp, 1), 2)
    y = None
    for dpi in range(3):
        u = None
        for dyi in range(3):
            if dyi == 0:
                rows = jnp.concatenate([halo[:, 0:1], t[:, :-1]], axis=1)
            elif dyi == 1:
                rows = t
            else:
                rows = jnp.concatenate([t[:, 1:], halo[:, 1:2]], axis=1)
            m = jax.lax.dot_general(
                rows, w_ref[dyi * 3 + dpi],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            u = m if u is None else u + m
        o = dpi - 1
        if o == 0:
            shifted = u
        else:
            shifted = pltpu.roll(u, (-o) % wp, 2)
            if o == 1:
                shifted = jnp.where(col < wp - 1, shifted, 0.0)
            else:
                shifted = jnp.where(col > 0, shifted, 0.0)
        y = shifted if y is None else y + shifted
    return y + bias_ref[...][:, :, None, :]


def _acc_stats(y, stat_refs):
    """Accumulate packed fp32 (sum, sumsq) of the raw output — skipped
    entirely for affine (frozen-BN) pipelines, whose constant prep needs
    no statistics (stat_refs empty)."""
    if not stat_refs:
        return
    s1_ref, s2_ref = stat_refs

    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    s1_ref[...] += jnp.sum(y, axis=(1, 2))[:, None, :]
    s2_ref[...] += jnp.sum(y * y, axis=(1, 2))[:, None, :]


def _enc_conv_kernel(x_ref, xh_ref, s_ref, t_ref, w_ref, b_ref, hv_ref,
                     y_ref, *stat_refs, wp):
    """prep(x) -> packed conv -> raw y (+ packed output stats)."""
    t = _prep(x_ref[...], s_ref, t_ref)
    th = _edge_mask_halo(_prep(xh_ref[...][:, 0], s_ref, t_ref), hv_ref)
    y = _conv_packed(t, th, w_ref, b_ref, wp)
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_stats(y, stat_refs)


def _enc_conv_res_kernel(x_ref, xh_ref, s_ref, t_ref,
                         r_ref, rh_ref, rs_ref, rt_ref,
                         w_ref, b_ref, hv_ref, y_ref, *stat_refs, wp):
    """Residual-block boundary: the conv input is
    relu( prep(res_raw) + prep(x_raw) ) — both tensors arrive RAW with
    their affines and are normalized in-register."""
    t = jnp.maximum(_prep(r_ref[...], rs_ref, rt_ref)
                    + _prep(x_ref[...], s_ref, t_ref), 0)
    th = _edge_mask_halo(
        jnp.maximum(_prep(rh_ref[...][:, 0], rs_ref, rt_ref)
                    + _prep(xh_ref[...][:, 0], s_ref, t_ref), 0), hv_ref)
    y = _conv_packed(t, th, w_ref, b_ref, wp)
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_stats(y, stat_refs)


def _enc_finish_kernel(y1_ref, s1_ref, t1_ref, c11_ref, s11_ref, t11_ref,
                       c21_ref, s21_ref, t21_ref, o_ref):
    """t2 = relu( relu( t0 + u2 ) + v2 ): the stage output in the final
    domain, from the three raw tensors + their affines."""
    t0 = _prep(y1_ref[...], s1_ref, t1_ref)
    u2 = _prep(c11_ref[...], s11_ref, t11_ref)
    v2 = _prep(c21_ref[...], s21_ref, t21_ref)
    o_ref[...] = jnp.maximum(jnp.maximum(t0 + u2, 0) + v2,
                             0).astype(o_ref.dtype)


# ------------------------------------------------------------- host side

def _halo_rows(x: jax.Array, r: int, boundary=None) -> jax.Array:
    """(B, H, Wp, C2) -> (B, H//r, 2, Wp, C2): rows above/below each
    r-row block; strided slices, ~2/r of a pass.  ``boundary`` provides the
    (above, below) rows at the local-array edges — the space-sharding path
    passes the neighbor shards' edge rows (from ppermute); default zeros
    (the image edge, masked in-kernel by the halo-validity operand)."""
    b, h, wp, c2 = x.shape
    nblk = h // r
    if boundary is None:
        above = below = jnp.zeros((b, 1, wp, c2), x.dtype)
    else:
        above, below = boundary
    top = jnp.concatenate([above, x[:, r - 1::r][:, : nblk - 1]], axis=1)
    bot = jnp.concatenate([x[:, r::r], below], axis=1)
    return jnp.stack([top, bot], axis=2)


def _default_hv(nblk: int) -> jax.Array:
    """Halo validity for the unsharded case: only the image edges invalid."""
    return (jnp.ones((nblk, 2), jnp.float32)
            .at[0, 0].set(0.0).at[nblk - 1, 1].set(0.0))


def _enc_conv(x, stats, w9, bias, res=None, res_stats=None,
              hv=None, boundary=None, res_boundary=None, want_stats=True):
    """One fused prep+conv(+stats) call on packed arrays.

    x: (B, H, Wp, C2) raw; stats: AFFINE (s, t) each (B, 1, C2) packed;
    w9: (9, C2, C2); bias: (1, 1, C2); hv: (H//r, 2) halo validity;
    boundary / res_boundary: neighbor edge rows under space sharding.
    ``want_stats=False`` (affine pipelines) skips the output-stats
    accumulation entirely.  Returns (y_raw fp-of-x, (s1, s2) or None)."""
    b, h, wp, c2 = x.shape
    r = _row_block(h)
    grid = (b, h // r)
    xh = _halo_rows(x, r, boundary)
    if hv is None:
        hv = _default_hv(h // r)
    m, s = stats

    def row_spec():
        return pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                            memory_space=pltpu.VMEM)

    def halo_spec():
        return pl.BlockSpec((1, 1, 2, wp, c2), lambda i, j: (i, j, 0, 0, 0),
                            memory_space=pltpu.VMEM)

    def stat_spec():
        return pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    wspec = pl.BlockSpec((9, c2, c2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    bspec = pl.BlockSpec((1, 1, c2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    hvspec = pl.BlockSpec(hv.shape, lambda i, j: (0, 0),
                          memory_space=pltpu.SMEM)

    if res is None:
        kernel = functools.partial(_enc_conv_kernel, wp=wp)
        operands = (x, xh, m, s, w9, bias[None, None, :], hv)
        in_specs = [row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    wspec, bspec, hvspec]
    else:
        rm, rs = res_stats
        rh = _halo_rows(res, r, res_boundary)
        kernel = functools.partial(_enc_conv_res_kernel, wp=wp)
        operands = (x, xh, m, s, res, rh, rm, rs, w9, bias[None, None, :], hv)
        in_specs = [row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    wspec, bspec, hvspec]

    out_shape = [jax.ShapeDtypeStruct(x.shape, x.dtype)]
    out_specs = [row_spec()]
    if want_stats:
        out_shape += [jax.ShapeDtypeStruct((b, 1, c2), jnp.float32)] * 2
        out_specs += [stat_spec(), stat_spec()]
    out = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(*operands)
    if want_stats:
        return out[0], (out[1], out[2])
    return out[0], None


def _packed_stats(x):
    """Packed per-channel fp32 (sum, sumsq) of a raw packed tensor via the
    layout-preserving stats kernel (pallas_norm)."""
    from .pallas_norm import _in_stats_kernel

    b, h, wp, c2 = x.shape
    r = _row_block(h)
    return pl.pallas_call(
        _in_stats_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, 1, c2), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c2), jnp.float32)),
        grid=(b, h // r),
        in_specs=[pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(x)


def _expand_stats(s1, s2, n, axis_name=None):
    """Packed sums -> packed prep AFFINE (rstd, -mean*rstd) duplicated
    over parities (the kernels apply relu(x*s + t)).
    ``axis_name``: psum the partial sums over that mesh axis first (space
    sharding — instance-norm statistics span the whole image height)."""
    if axis_name is not None:
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
    mean, rstd = stats_from_packed(s1, s2, n)
    return pack_vec(rstd), pack_vec(-mean * rstd)


def fused_stem_layer1(y1_raw: jax.Array, params: dict, n=None,
                      space_axis=None, space_size=1) -> jax.Array:
    """norm1 + relu + layer1 (two ResidualBlocks), fused, from conv1's RAW
    output (B, H, W, 64), any even W.

    Both split points were measured E2E: letting norm1 run in XLA (so
    conv1 keeps its fused blocked lowering) costs MORE than it saves —
    conv1 drops 1.4 -> 3.8 ms when its consumer is row-major, but the XLA
    norm1's own relayouts cost ~3 ms more (9.49 vs 9.77 pairs/sec), so
    the pipeline consumes conv1 raw and computes norm1's stats with the
    layout-preserving kernel.
    params: {"c10","c11","c20","c21"} -> {"kernel": (3,3,64,64),
    "bias": (64,)} — layer1_0.conv1/conv2, layer1_1.conv1/conv2.
    Returns the stage output in the final (post-relu) domain.

    Space sharding (``space_axis`` set, called inside shard_map): the
    array is an H-shard; stats psum over the axis, each conv's shard-edge
    halo row arrives from the neighbor by ppermute, and the halo-validity
    operand keeps those rows while still masking the global image edges.
    ``n`` is the GLOBAL H*W pixel count (defaults to the local shape's).
    """
    xp = pack_view(y1_raw)
    if n is None:
        n = float(y1_raw.shape[1] * y1_raw.shape[2])
    st1 = _expand_stats(*_packed_stats(xp), n, space_axis)
    return _stage_on_packed(xp, st1, params, n, space_axis, space_size)


def _shard_ctx(nblk: int, space_axis, space_size: int, rows: int = 1):
    """(halo-validity array, edge-row exchange fn) for one stage geometry.
    ``rows``: how many boundary rows each conv needs from the neighbor."""
    if space_axis is None:
        return _default_hv(nblk), lambda t: None
    idx = jax.lax.axis_index(space_axis)
    hv = (jnp.ones((nblk, 2), jnp.float32)
          .at[0, 0].set((idx > 0).astype(jnp.float32))
          .at[nblk - 1, 1].set((idx < space_size - 1)
                               .astype(jnp.float32)))
    fwd = [(i, i + 1) for i in range(space_size - 1)]
    bwd = [(i + 1, i) for i in range(space_size - 1)]

    def exch(t):
        # Neighbor edge rows: shards with no source (global image
        # edges) receive zeros, which the hv operand masks anyway
        # (or, for the raw-image conv1 path, ARE the zero padding).
        above = jax.lax.ppermute(t[:, -rows:], space_axis, fwd)
        below = jax.lax.ppermute(t[:, :rows], space_axis, bwd)
        return above, below

    return hv, exch


def _stage_on_packed(xp, st1, params, n, space_axis=None, space_size=1,
                     affines=None, want_residuals=False):
    """The four fused convs + finish kernel, from the packed raw stage
    input ``xp`` and its prep affine ``st1``.

    ``affines``: for affine norms (frozen batch norm) — a list of the four
    remaining packed (s, t) prep affines [after c10, c11, c20, c21]; the
    per-tensor statistics accumulated by the kernels are then ignored
    (constant affines need no stats and no psum).

    ``want_residuals``: also return the four raw conv outputs (packed) and
    the five prep affines — the backward's saved state.  The pipeline
    materializes all of these in HBM anyway (each _enc_conv is its own
    pallas_call), so saving them is free; the hand-written backward then
    never re-runs a forward (see _stage_bwd_xla)."""
    dt = xp.dtype
    b, h, wp, c2 = xp.shape
    r = _row_block(h)
    nblk = h // r
    hv, exch = _shard_ctx(nblk, space_axis, space_size)

    def pw(name):
        return (pack_weights(params[name]["kernel"]).astype(dt),
                pack_vec(params[name]["bias"]).astype(dt))

    ws = affines is None

    def nxt(sums, i):
        if affines is not None:
            return affines[i]
        return _expand_stats(*sums, n, space_axis)

    xb = exch(xp)
    c10, s10 = _enc_conv(xp, st1, *pw("c10"), hv=hv, boundary=xb,
                         want_stats=ws)
    st10 = nxt(s10, 0)
    c11, s11 = _enc_conv(c10, st10, *pw("c11"), hv=hv, boundary=exch(c10),
                         want_stats=ws)
    st11 = nxt(s11, 1)
    # block boundary: input of layer1_1.conv1 is relu(t0 + u2)
    c20, s20 = _enc_conv(c11, st11, *pw("c20"), res=xp, res_stats=st1,
                         hv=hv, boundary=exch(c11), res_boundary=xb,
                         want_stats=ws)
    st20 = nxt(s20, 2)
    c21, s21 = _enc_conv(c20, st20, *pw("c21"), hv=hv, boundary=exch(c20),
                         want_stats=ws)
    st21 = nxt(s21, 3)

    def row_spec():
        return pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                            memory_space=pltpu.VMEM)

    def stat_spec():
        return pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        _enc_finish_kernel,
        out_shape=jax.ShapeDtypeStruct(xp.shape, dt),
        grid=(b, h // r),
        in_specs=[row_spec(), stat_spec(), stat_spec(),
                  row_spec(), stat_spec(), stat_spec(),
                  row_spec(), stat_spec(), stat_spec()],
        out_specs=row_spec(),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(xp, *st1, c11, *st11, c21, *st21)
    if want_residuals:
        return (unpack_view(out), (c10, c11, c20, c21),
                (st1, st10, st11, st20, st21))
    return unpack_view(out)


# --------------------------------------------- fused 7x7 stem conv (conv1)

def pack_weights7(w: jax.Array) -> jax.Array:
    """(7, 7, 3, 64) HWIO conv1 weights -> (7, 5, 6, 128) packed
    [dy, dp+2]: output pixel 2p+po with tap dx reads packed column p+dp,
    parity pi, where dp = floor((po+dx)/2) in [-2, 2], pi = (po+dx) mod 2
    (same construction as pack_weights, 7 dx taps instead of 3)."""
    kh, kw, ci, co = w.shape
    out = jnp.zeros((kh, 5, 2 * ci, 2 * co), w.dtype)
    for po in range(2):
        for dxi, dx in enumerate(range(-3, 4)):
            dp = (po + dx) // 2
            pi = (po + dx) % 2
            out = out.at[:, dp + 2,
                         pi * ci:(pi + 1) * ci,
                         po * co:(po + 1) * co].set(w[:, dxi])
    return out


def _stem7_kernel(x_ref, xh_ref, w_ref, b_ref, y_ref, *stat_refs,
                  rows):
    """7x7 stride-1 packed conv of the RAW input image tile + fp32 output
    stats (for norm1).  No prep/halo masking: the input is the [-1, 1]
    image itself, so zero halo rows ARE the conv's zero padding.

    The 5 packed-column offsets are resolved by PRE-SHIFTING the
    6-channel input (roll + zero-mask on 6 lanes) and concatenating into
    one K=30 operand per dy tap — rolling/masking the 128-wide fp32
    accumulator per offset instead (the first formulation) made the
    whole kernel run at a ~38 GB/s effective write rate."""
    t = x_ref[...]                     # (1, R, Wp, 6)
    th = xh_ref[...][:, 0]             # (1, 6, Wp, 6): 3 above, 3 below
    full = jnp.concatenate([th[:, :3], t, th[:, 3:]], axis=1)
    w = w_ref[...]                     # (7, 5, 6, 128)
    zc = jnp.zeros_like(full[:, :, :2])
    shifts = []
    for dpi in range(5):
        o = dpi - 2
        if o == 0:
            shifts.append(full)
        elif o > 0:
            # xshift_o[p] = full[p + o], zero outside [0, wp); static
            # sublane-dim slices (Mosaic cannot rotate bf16 sublanes).
            shifts.append(jnp.concatenate(
                [full[:, :, o:], zc[:, :, :o]], axis=2))
        else:
            shifts.append(jnp.concatenate(
                [zc[:, :, :(-o)], full[:, :, :o]], axis=2))
    xcat = jnp.concatenate(shifts, axis=-1)         # (1, R+6, Wp, 30)
    wcat = w.reshape(7, 5 * w.shape[2], w.shape[3])
    if _conv1_bigk:
        # Fold the 7 dy taps into the contraction too: ONE K=210 dot (2
        # MXU K-passes at ~82% fill) instead of 7 K=30 dots (7 passes at
        # 23% fill) — the dy row slices are free (dim 1 is neither lane
        # nor sublane), so the operand build costs only the lane concat.
        xbig = jnp.concatenate([xcat[:, dyi:dyi + rows] for dyi in range(7)],
                               axis=-1)             # (1, R, Wp, 210)
        y = jax.lax.dot_general(
            xbig, wcat.reshape(7 * wcat.shape[1], wcat.shape[2]),
            (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        y = None
        for dyi in range(7):
            m = jax.lax.dot_general(
                xcat[:, dyi:dyi + rows], wcat[dyi],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            y = m if y is None else y + m
    y = y + b_ref[...][:, :, None, :]
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_stats(y, stat_refs)


def pack_weights7s2(w: jax.Array) -> jax.Array:
    """(7, 7, 3, 64) HWIO conv1 weights -> (7, 3, 12, 128) packed for
    STRIDE 2: output pixel 2p+po reads input column 4p + u, u = 2*po + dx
    in [-3, 5] -> packed-4 column p + dq, sub-position pi, with
    dq = floor(u/4) in [-1, 1], pi = u mod 4."""
    kh, kw, ci, co = w.shape
    out = jnp.zeros((kh, 3, 4 * ci, 2 * co), w.dtype)
    for po in range(2):
        for dxi, dx in enumerate(range(-3, 4)):
            u = 2 * po + dx
            dq = u // 4
            pi = u % 4
            out = out.at[:, dq + 1,
                         pi * ci:(pi + 1) * ci,
                         po * co:(po + 1) * co].set(w[:, dxi])
    return out


def _stem7s2_kernel(x_ref, xh_ref, w_ref, b_ref, y_ref, *stat_refs,
                    rows):
    """7x7 STRIDE-2 packed conv of the raw input image + fp32 output
    stats.  x_ref: (1, 2R, Wq, 12) input rows for this block's R output
    rows; xh_ref: (1, 5, Wq, 12) = 3 rows above + 2 below.  Output row r
    (local) with tap dy' reads input full[2r + dy' + 3]; padding full to
    an even row count and viewing it as (R+3, 2, ...) turns each dy' into
    a CONTIGUOUS row slice at parity (dy'+3) % 2."""
    t = x_ref[...]
    th = xh_ref[...][:, 0]
    full = jnp.concatenate(
        [th[:, :3], t, th[:, 3:5],
         jnp.zeros_like(th[:, :1])], axis=1)        # (1, 2R+6, Wq, 12)
    # Pre-shift the 12-channel input (static sublane-dim slices) and fold
    # the 3 packed-column offsets into one K=36 operand per dy tap —
    # same rationale as _stem7_kernel (rolling the 128-wide accumulator
    # per offset dominated the kernel).
    zc = jnp.zeros_like(full[:, :, :1])
    shifts = []
    for dqi in range(3):
        o = dqi - 1
        if o == 0:
            shifts.append(full)
        elif o > 0:
            shifts.append(jnp.concatenate(
                [full[:, :, o:], zc[:, :, :o]], axis=2))
        else:
            shifts.append(jnp.concatenate(
                [zc[:, :, :(-o)], full[:, :, :o]], axis=2))
    xcat = jnp.concatenate(shifts, axis=-1)         # (1, 2R+6, Wq, 36)
    view = xcat.reshape(1, rows + 3, 2, xcat.shape[2], xcat.shape[3])
    w = w_ref[...]                                  # (7, 3, 12, 128)
    wcat = w.reshape(7, 3 * w.shape[2], w.shape[3])  # dq-major, like xcat
    if _conv1_bigk:
        # Same dy-fold as _stem7_kernel: one K=252 dot (2 nearly-full
        # K-passes) instead of 7 K=36 dots.
        xbig = jnp.concatenate(
            [view[:, dyi // 2:dyi // 2 + rows, dyi % 2]
             for dyi in range(7)], axis=-1)          # (1, R, Wq, 252)
        y = jax.lax.dot_general(
            xbig, wcat.reshape(7 * wcat.shape[1], wcat.shape[2]),
            (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        y = None
        for dyi in range(7):
            e, par = divmod(dyi, 2)
            m = jax.lax.dot_general(
                view[:, e:e + rows, par], wcat[dyi],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            y = m if y is None else y + m
    y = y + b_ref[...][:, :, None, :]
    y_ref[...] = y.astype(y_ref.dtype)
    _acc_stats(y, stat_refs)


def _halo_rows_s2(x: jax.Array, r: int, boundary=None) -> jax.Array:
    """(B, H, Wq, C) input -> (B, Hout//r, 5, Wq, C): the 3 rows above and
    2 below each 2r-input-row block (one block per r output rows)."""
    b, h, wq, c = x.shape
    nblk = (h // 2) // r
    if boundary is None:
        above = jnp.zeros((b, 3, wq, c), x.dtype)
        below = jnp.zeros((b, 2, wq, c), x.dtype)
    else:
        above, below = boundary
        below = below[:, :2]
    span = 2 * r
    xpad_t = jnp.concatenate([above, x[:, : (nblk - 1) * span]], axis=1)
    xpad_b = jnp.concatenate([x[:, span:], below], axis=1)
    tops = [xpad_t[:, k::span][:, :nblk] for k in range(3)]
    bots = [xpad_b[:, k::span][:, :nblk] for k in range(2)]
    return jnp.stack(tops + bots, axis=2)


def _stem_conv1_s2(img, c1_params, dt, boundary=None, want_stats=True):
    """Pallas stride-2 conv1: (B, H, W, 3) image -> packed raw conv1
    output (B, H/2, W/4, 128) + packed fp32 output stats.  Requires
    H % 2 == 0 and W % 4 == 0."""
    b, h, w, ci = img.shape
    xq = img.astype(dt).reshape(b, h, w // 4, 4 * ci)
    r = _row_block(h // 2)
    grid = (b, (h // 2) // r)
    xh = _halo_rows_s2(xq, r, boundary)
    w7 = pack_weights7s2(c1_params["kernel"]).astype(dt)
    bias = pack_vec(c1_params["bias"]).astype(dt)[None, None, :]
    co2 = w7.shape[-1]
    wq = w // 4
    c4 = 4 * ci

    out_shape = [jax.ShapeDtypeStruct((b, h // 2, wq, co2), dt)]
    if want_stats:
        out_shape += [jax.ShapeDtypeStruct((b, 1, co2), jnp.float32)] * 2
    out = pl.pallas_call(
        functools.partial(_stem7s2_kernel, rows=r),
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 2 * r, wq, c4), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 5, wq, c4), lambda i, j: (i, j, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w7.shape, lambda i, j: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, co2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(
            [pl.BlockSpec((1, r, wq, co2), lambda i, j: (i, j, 0, 0),
                          memory_space=pltpu.VMEM)]
            + [pl.BlockSpec((1, 1, co2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)] * (2 * want_stats)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(xq, xh, w7, bias)
    if want_stats:
        return out[0], (out[1], out[2])
    return out[0], None


def _halo_rows3(x: jax.Array, r: int, boundary=None) -> jax.Array:
    """(B, H, Wp, C) -> (B, H//r, 6, Wp, C): the 3 rows above and 3 below
    each r-row block (zeros at local-array edges unless ``boundary``
    provides the neighbor shards' 3 edge rows each way)."""
    b, h, wp, c = x.shape
    nblk = h // r
    if boundary is None:
        above = below = jnp.zeros((b, 3, wp, c), x.dtype)
    else:
        above, below = boundary
    xpad_t = jnp.concatenate([above, x[:, : (nblk - 1) * r]], axis=1)
    xpad_b = jnp.concatenate([x[:, r:], below], axis=1)
    tops = [xpad_t[:, k::r][:, :nblk] for k in range(3)]
    bots = [xpad_b[:, k::r][:, :nblk] for k in range(3)]
    return jnp.stack(tops + bots, axis=2)


def _stem_conv1(img, c1_params, dt, boundary=None, want_stats=True):
    """Pallas conv1: (B, H, W, 3) [-1,1] image -> packed raw conv1 output
    (B, H, Wp, 128) + packed fp32 (sum, sumsq) output stats, one pass.
    Requires stride 1 (downsample <= 2) and W % 2 == 0."""
    xp = pack_view(img.astype(dt))                 # (B, H, W/2, 6)
    b, h, wp, c2 = xp.shape
    r = _row_block(h)
    grid = (b, h // r)
    xh = _halo_rows3(xp, r, boundary)
    w7 = pack_weights7(c1_params["kernel"]).astype(dt)
    bias = pack_vec(c1_params["bias"]).astype(dt)[None, None, :]
    co2 = w7.shape[-1]

    out_shape = [jax.ShapeDtypeStruct((b, h, wp, co2), dt)]
    if want_stats:
        out_shape += [jax.ShapeDtypeStruct((b, 1, co2), jnp.float32)] * 2
    out = pl.pallas_call(
        functools.partial(_stem7_kernel, rows=r),
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 6, wp, c2), lambda i, j: (i, j, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w7.shape, lambda i, j: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, co2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(
            [pl.BlockSpec((1, r, wp, co2), lambda i, j: (i, j, 0, 0),
                          memory_space=pltpu.VMEM)]
            + [pl.BlockSpec((1, 1, co2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)] * (2 * want_stats)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(xp, xh, w7, bias)
    if want_stats:
        return out[0], (out[1], out[2])
    return out[0], None


def _stem_conv1_any(im, c1p, dt, stride, boundary, want_stats=True):
    if stride == 2:
        return _stem_conv1_s2(im, c1p, dt, boundary=boundary,
                              want_stats=want_stats)
    return _stem_conv1(im, c1p, dt, boundary=boundary,
                       want_stats=want_stats)


def _conv1_pack_for_halo(im, dt, stride):
    """The packed view whose edge rows the space-sharding exchange
    ships: pixel pairs for stride 1, packed fours for stride 2."""
    if stride == 2:
        b, h, w, ci = im.shape
        return im.astype(dt).reshape(b, h, w // 4, 4 * ci)
    return pack_view(im.astype(dt))


def _fused_forward1(img, c1_params, params, dt, stride=1,
                    want_residuals=False):
    """conv1 + stage, fused end to end; shard_map'd like _fused_forward.
    The stage's stats span the conv1 OUTPUT resolution (H/stride).
    ``want_residuals`` additionally returns conv1's packed raw output, the
    stage raws, and the prep affines (the backward's saved state)."""
    n = float((img.shape[1] // stride) * (img.shape[2] // stride))

    def local(im, c1p, p, space_axis=None, space_size=1):
        _, exch3 = _shard_ctx(1, space_axis, space_size, rows=3)
        imp = _conv1_pack_for_halo(im, dt, stride)
        yb = exch3(imp) if space_axis is not None else None
        yp, sums = _stem_conv1_any(im, c1p, dt, stride, yb)
        st1 = _expand_stats(*sums, n, space_axis)
        if want_residuals:
            out, raws, affs = _stage_on_packed(
                yp, st1, p, n, space_axis, space_size, want_residuals=True)
            return out, yp, raws, affs
        return _stage_on_packed(yp, st1, p, n, space_axis, space_size)

    return _shard_wrapped(local, img.shape, (img, c1_params, params))


def _xla_conv1(img, c1_params, dt, stride=1):
    """Plain-XLA conv1 (7x7 SAME) — backward linearization.
    No preferred_element_type: a fp32-typed output from bf16 operands
    makes the conv transpose ill-typed (see PointwisePaddedConv), and this
    formulation exists exactly to be differentiated."""
    x = img.astype(dt)
    y = jax.lax.conv_general_dilated(
        x, c1_params["kernel"].astype(dt), (stride, stride),
        ((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + c1_params["bias"].astype(dt)
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def conv1_stem_layer1(img, c1_params, params, dt=jnp.float32, stride=1):
    """Fused conv1 + norm1 + layer1 from the normalized input image.
    Forward is all-Pallas (one boundary: the image read); backward is the
    XLA reference formulation's VJP on global arrays."""
    return _fused_forward1(img, c1_params, params, dt, stride)


def _fwd1(img, c1_params, params, dt, stride):
    out, yp, raws, affs = _fused_forward1(img, c1_params, params, dt,
                                          stride, want_residuals=True)
    return out, (img, c1_params, params, yp, raws, affs)


def _bwd1(dt, stride, residuals, g):
    img, c1_params, params, yp, raws, affs = residuals
    dy1, dparams = _stage_bwd_xla(unpack_view(yp), raws, affs, params, g)
    dimg, dc1 = _conv1_bwd(img, c1_params, dt, stride, dy1)
    return dimg, dc1, dparams


conv1_stem_layer1.defvjp(_fwd1, _bwd1)


# --------------------------------------- affine-norm (frozen BN) pipeline

def bn_affine(norm_params, norm_stats, eps: float = 1e-5):
    """Frozen BatchNorm (use_running_average) folded to the kernels' prep
    affine: relu(x*s + t) with s = gamma*rsqrt(var+eps),
    t = beta - mean*s.  Exact for gamma == 0 channels too."""
    s = norm_params["scale"].astype(jnp.float32) * jax.lax.rsqrt(
        norm_stats["var"].astype(jnp.float32) + eps)
    t = norm_params["bias"].astype(jnp.float32) - \
        norm_stats["mean"].astype(jnp.float32) * s
    return s, t


def _pack_affines(affines, b, c2):
    return [(jnp.broadcast_to(pack_vec(s)[None, None], (b, 1, c2)),
             jnp.broadcast_to(pack_vec(t)[None, None], (b, 1, c2)))
            for s, t in affines]


def _xla_reference_affine(y1_raw, params, affines):
    """Plain-XLA mirror of the affine-norm stage (oracle + backward)."""
    def nr(x, a):
        s, t = a
        return jnp.maximum(x * s.astype(x.dtype) + t.astype(x.dtype), 0)

    def conv(x, p):
        return jax.lax.conv_general_dilated(
            x, p["kernel"].astype(x.dtype), (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p["bias"].astype(x.dtype)

    t0 = nr(y1_raw, affines[0])
    u2 = nr(conv(nr(conv(t0, params["c10"]), affines[1]), params["c11"]),
            affines[2])
    t1 = jnp.maximum(t0 + u2, 0)
    v2 = nr(conv(nr(conv(t1, params["c20"]), affines[3]), params["c21"]),
            affines[4])
    return jnp.maximum(t1 + v2, 0)


def _fused_forward_affine(y1_raw, params, affines, want_residuals=False):
    """Affine-norm fused stage over the active mesh.  No stats, no psum
    — constant affines replicate.  ``want_residuals`` also returns the
    four raw conv outputs (the affines are primals, not residuals)."""
    def local(y1, p, aff, space_axis=None, space_size=1):
        xp = pack_view(y1)
        pa = _pack_affines(aff, xp.shape[0], xp.shape[-1])
        if want_residuals:
            out, raws, _ = _stage_on_packed(
                xp, pa[0], p, n=1.0, space_axis=space_axis,
                space_size=space_size, affines=pa[1:], want_residuals=True)
            return out, raws
        return _stage_on_packed(xp, pa[0], p, n=1.0, space_axis=space_axis,
                                space_size=space_size, affines=pa[1:])

    return _shard_wrapped(local, y1_raw.shape, (y1_raw, params, affines))


@jax.custom_vjp
def bn_stem_layer1(y1_raw, params, affines):
    """Fused affine-norm stage from conv1's raw output (stride-2 conv1
    configs); hand-written backward from saved residuals
    (_stage_bwd_xla_affine).  ``affines``: five UNPACKED per-channel
    (s, t) fp32 pairs — [norm1, l1_0.norm1, l1_0.norm2, l1_1.norm1,
    l1_1.norm2] (see bn_affine) — through which gradients flow to the
    BatchNorm scale/bias."""
    return _fused_forward_affine(y1_raw, params, affines)


def _fwd_bn(y1_raw, params, affines):
    out, raws = _fused_forward_affine(y1_raw, params, affines,
                                      want_residuals=True)
    return out, (y1_raw, params, affines, raws)


def _bwd_bn(residuals, g):
    y1_raw, params, affines, raws = residuals
    return _stage_bwd_xla_affine(y1_raw, raws, params, affines, g)


bn_stem_layer1.defvjp(_fwd_bn, _bwd_bn)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def bn_conv1_stem_layer1(img, c1_params, params, affines, dt=jnp.float32,
                         stride=1):
    """Pallas conv1 + affine-norm stage."""
    return _fused_forward1_affine(img, c1_params, params, affines, dt,
                                  stride)


def _fused_forward1_affine(img, c1_params, params, affines, dt, stride=1,
                           want_residuals=False):
    def local(im, c1p, p, aff, space_axis=None, space_size=1):
        _, exch3 = _shard_ctx(1, space_axis, space_size, rows=3)
        yb = (exch3(_conv1_pack_for_halo(im, dt, stride))
              if space_axis is not None else None)
        yp, _ = _stem_conv1_any(im, c1p, dt, stride, yb, want_stats=False)
        pa = _pack_affines(aff, yp.shape[0], yp.shape[-1])
        if want_residuals:
            out, raws, _ = _stage_on_packed(
                yp, pa[0], p, n=1.0, space_axis=space_axis,
                space_size=space_size, affines=pa[1:], want_residuals=True)
            return out, yp, raws
        return _stage_on_packed(yp, pa[0], p, n=1.0, space_axis=space_axis,
                                space_size=space_size, affines=pa[1:])

    return _shard_wrapped(local, img.shape,
                          (img, c1_params, params, affines))


def _fwd1_bn(img, c1_params, params, affines, dt, stride):
    out, yp, raws = _fused_forward1_affine(img, c1_params, params, affines,
                                           dt, stride, want_residuals=True)
    return out, (img, c1_params, params, affines, yp, raws)


def _bwd1_bn(dt, stride, residuals, g):
    img, c1_params, params, affines, yp, raws = residuals
    dy1, dparams, daff = _stage_bwd_xla_affine(unpack_view(yp), raws,
                                               params, affines, g)
    dimg, dc1 = _conv1_bwd(img, c1_params, dt, stride, dy1)
    return dimg, dc1, dparams, daff


bn_conv1_stem_layer1.defvjp(_fwd1_bn, _bwd1_bn)


# --------------------------------------- backward from saved residuals
#
# The round-4 backward re-linearized the full XLA reference forward
# (jax.vjp(_xla_reference, ...)), so training paid Pallas-fwd + XLA-fwd +
# XLA-bwd and gated the stage off (-1.3% measured).  The Pallas pipeline
# already materializes every backward residual in HBM — each _enc_conv is
# its own pallas_call writing its raw output, and the prep affines carry
# (mean, rstd) — so the backward below consumes THOSE and never re-runs a
# forward: elementwise mask/activation recomputes, 8 transposed convs
# (jax.linear_transpose — no primal evaluation), and the instance-norm
# VJP's per-image reductions.  Reference analogue: the CUDA sampler's
# dedicated backward kernel (/root/reference/sampler/sampler_kernel.cu:63-105)
# rather than autodiff through a re-run forward.

def _drelu(z):
    """Derivative of jnp.maximum(z, 0) under JAX's tie convention (0.5 at
    z == 0 — measured; exact zeros are COMMON here because both operands
    of the residual adds are post-relu).  Emitted in z's dtype (0/0.5/1
    are exact in bf16) so bf16 backward chains stay bf16."""
    return jnp.where(z > 0, 1.0,
                     jnp.where(z < 0, 0.0, 0.5)).astype(z.dtype)


def _aff_stats(st):
    """Packed prep affine (s, t) each (B, 1, 2C) -> broadcastable unpacked
    (mean, rstd) (B, 1, 1, C) fp32.  s IS rstd (> 0 always: rsqrt of
    var + 1e-5) and t = -mean * rstd, so the inversion is exact."""
    s, t = st
    c = s.shape[-1] // 2
    rstd = s[..., :c].astype(jnp.float32)[:, :, None, :]
    mean = -t[..., :c].astype(jnp.float32)[:, :, None, :] / rstd
    return mean, rstd


# Packed-domain reduction path for the backward's instance-norm means.
# Module-level override for tests/A-B: None = auto (TPU, no active mesh,
# even W), True/False force.
_bwd_packed_sums = None


def _dual_sum_kernel(u_ref, v_ref, s1_ref, s2_ref):
    """Accumulate per-(image, packed-channel) fp32 (sum(u), sum(u*v)) —
    the two reductions of the instance-norm VJP, computed layout-preserving
    like the forward's stats kernels (same accumulation pattern as
    pallas_norm._in_stats_kernel)."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    u = u_ref[...].astype(jnp.float32)          # in-register upcast: fp32
    v = v_ref[...].astype(jnp.float32)          # accumulation, any input dt
    s1_ref[...] += jnp.sum(u, axis=(1, 2))[:, None, :]
    s2_ref[...] += jnp.sum(u * v, axis=(1, 2))[:, None, :]


def _in_bwd_means(u, xhat):
    """(mean_HW(u), mean_HW(u * xhat)) as (B, 1, 1, C) fp32.

    On single-device TPU these run as ONE layout-preserving Pallas kernel
    over the packed row-major view: a plain XLA cross-(H,W) reduce of a
    conv-adjacent tensor forces full-tensor blocked<->row-major relayouts,
    and NO XLA-side formulation escapes that (measured exhaustively,
    docs/perf_notes_r03.md) — the exact storm that motivated this module.
    Under an active mesh the XLA form stays: the backward runs on GLOBAL
    arrays that GSPMD partitions, where a bare pallas_call cannot."""
    from ..parallel.context import active_corr_mesh

    use_packed = _bwd_packed_sums
    if use_packed is None:
        use_packed = (jax.default_backend() == "tpu"
                      and active_corr_mesh() is None
                      and u.shape[2] % 2 == 0)
    if not use_packed:
        # dtype=f32: fp32 accumulation without materializing fp32 copies.
        return (jnp.mean(u, axis=(1, 2), keepdims=True, dtype=jnp.float32),
                jnp.mean(u * xhat, axis=(1, 2), keepdims=True,
                         dtype=jnp.float32))
    # Operands stay in their storage dtype (bf16 under training) — the
    # kernel upcasts in-register; .astype(f32) here would MATERIALIZE a
    # ~1 GB fp32 copy per tensor at recipe shapes (measured: HBM OOM).
    up = pack_view(u)
    vp = pack_view(xhat)
    b, h, wp, c2 = up.shape
    r = _row_block(h)
    s1, s2 = pl.pallas_call(
        _dual_sum_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, 1, c2), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c2), jnp.float32)),
        grid=(b, h // r),
        in_specs=[pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM)] * 2,
        out_specs=(pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(up, vp)
    n = float(u.shape[1] * u.shape[2])
    c = c2 // 2
    m1 = (s1[..., :c] + s1[..., c:])[:, :, None, :] / n
    m2 = (s2[..., :c] + s2[..., c:])[:, :, None, :] / n
    return m1, m2


def _in_bwd(xhat, rstd, u):
    """VJP of x -> xhat = (x - mean(x)) * rstd(x) through the per-image
    statistics: dx = rstd * (u - mean_HW(u) - xhat * mean_HW(u * xhat)),
    exact including the 1e-5 epsilon (xhat carries it).  The large
    tensors stay in their storage dtype (bf16 under training — the
    reference backward rounds comparably); only the means are fp32."""
    mu, mux = _in_bwd_means(u, xhat)
    dt = u.dtype
    return rstd.astype(dt) * (u - mu.astype(dt) - xhat * mux.astype(dt))


def _conv_bwd(t, kernel, dy):
    """(dt, dkernel, dbias) of y = conv3x3_same(t, kernel) + bias via
    linear transposition — unlike jax.vjp, never evaluates the primal."""
    def conv_in(a):
        return jax.lax.conv_general_dilated(
            a, kernel, (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def conv_k(k):
        return jax.lax.conv_general_dilated(
            t, k, (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    dt = jax.linear_transpose(conv_in, t)(dy)[0]
    dk = jax.linear_transpose(conv_k, kernel)(dy)[0]
    return dt, dk, dy.sum((0, 1, 2), dtype=jnp.float32)


def _stage_bwd_xla(y1_raw, raws, affs, params, g):
    """Hand-written backward of the instance-norm stage from saved
    residuals.  Returns (dy1_raw, dparams).  Mask/activation recomputes
    are elementwise (XLA fuses them) and STAY in the storage dtype —
    fp32 upcasts here materialize ~1 GB per tensor at recipe shapes
    (measured HBM OOM); the reference backward rounds in bf16 the same
    way.  The tiny per-image statistics are fp32 throughout."""
    cdt = y1_raw.dtype
    c10, c11, c20, c21 = [unpack_view(r) for r in raws]
    y1 = y1_raw

    def nh(c, st):
        m, r = st
        return (c - m.astype(cdt)) * r.astype(cdt)

    stats = [_aff_stats(a) for a in affs]
    r1, r10, r11, r20, r21 = [s[1] for s in stats]

    x0 = nh(y1, stats[0])
    t0 = jnp.maximum(x0, 0)
    x10 = nh(c10, stats[1])
    t10 = jnp.maximum(x10, 0)
    x11 = nh(c11, stats[2])
    u2 = jnp.maximum(x11, 0)
    z1 = t0 + u2
    t1 = jnp.maximum(z1, 0)
    x20 = nh(c20, stats[3])
    t20 = jnp.maximum(x20, 0)
    x21 = nh(c21, stats[4])
    v2 = jnp.maximum(x21, 0)

    def kp(name):
        return params[name]["kernel"].astype(cdt)

    go = g.astype(cdt) * _drelu(t1 + v2)
    dc21 = _in_bwd(x21, r21, go * _drelu(x21))
    dt20, dk21, db21 = _conv_bwd(t20, kp("c21"), dc21)
    dc20 = _in_bwd(x20, r20, dt20 * _drelu(x20))
    dt1c, dk20, db20 = _conv_bwd(t1, kp("c20"), dc20)
    dz1 = (go + dt1c) * _drelu(z1)
    dc11 = _in_bwd(x11, r11, dz1 * _drelu(x11))
    dt10, dk11, db11 = _conv_bwd(t10, kp("c11"), dc11)
    dc10 = _in_bwd(x10, r10, dt10 * _drelu(x10))
    dt0c, dk10, db10 = _conv_bwd(t0, kp("c10"), dc10)
    dy1 = _in_bwd(x0, r1, (dz1 + dt0c) * _drelu(x0))

    def dparam(name, dk, db):
        p = params[name]
        return {"kernel": dk.astype(p["kernel"].dtype),
                "bias": db.astype(p["bias"].dtype)}

    dparams = {"c10": dparam("c10", dk10, db10),
               "c11": dparam("c11", dk11, db11),
               "c20": dparam("c20", dk20, db20),
               "c21": dparam("c21", dk21, db21)}
    return dy1.astype(y1_raw.dtype), dparams


def _stage_bwd_xla_affine(y1_raw, raws, params, affines, g):
    """Backward of the affine-norm (frozen BN) stage from saved residuals.
    Returns (dy1_raw, dparams, daffines) — gradients flow into the folded
    BatchNorm scale/bias pairs like the reference backward."""
    cdt = y1_raw.dtype
    c10, c11, c20, c21 = [unpack_view(r) for r in raws]
    y1 = y1_raw
    aff = [(s.astype(cdt), t.astype(cdt)) for s, t in affines]

    def pre(c, i):
        s, t = aff[i]
        return c * s + t

    z0 = pre(y1, 0)
    t0 = jnp.maximum(z0, 0)
    z10 = pre(c10, 1)
    t10 = jnp.maximum(z10, 0)
    z11 = pre(c11, 2)
    u2 = jnp.maximum(z11, 0)
    z1 = t0 + u2
    t1 = jnp.maximum(z1, 0)
    z20 = pre(c20, 3)
    t20 = jnp.maximum(z20, 0)
    z21 = pre(c21, 4)
    v2 = jnp.maximum(z21, 0)

    daff = [None] * 5

    def aff_bwd(dact, z, c, i):
        u = dact * _drelu(z)
        s, _ = aff[i]
        # fp32 accumulation via the reduce dtype — no fp32 materialization.
        daff[i] = ((u * c).sum((0, 1, 2), dtype=jnp.float32)
                   .astype(affines[i][0].dtype),
                   u.sum((0, 1, 2), dtype=jnp.float32)
                   .astype(affines[i][1].dtype))
        return u * s

    def kp(name):
        return params[name]["kernel"].astype(cdt)

    go = g.astype(cdt) * _drelu(t1 + v2)
    dc21 = aff_bwd(go, z21, c21, 4)
    dt20, dk21, db21 = _conv_bwd(t20, kp("c21"), dc21)
    dc20 = aff_bwd(dt20, z20, c20, 3)
    dt1c, dk20, db20 = _conv_bwd(t1, kp("c20"), dc20)
    dz1 = (go + dt1c) * _drelu(z1)
    dc11 = aff_bwd(dz1, z11, c11, 2)
    dt10, dk11, db11 = _conv_bwd(t10, kp("c11"), dc11)
    dc10 = aff_bwd(dt10, z10, c10, 1)
    dt0c, dk10, db10 = _conv_bwd(t0, kp("c10"), dc10)
    dy1 = aff_bwd(dz1 + dt0c, z0, y1, 0)

    def dparam(name, dk, db):
        p = params[name]
        return {"kernel": dk.astype(p["kernel"].dtype),
                "bias": db.astype(p["bias"].dtype)}

    dparams = {"c10": dparam("c10", dk10, db10),
               "c11": dparam("c11", dk11, db11),
               "c20": dparam("c20", dk20, db20),
               "c21": dparam("c21", dk21, db21)}
    return (dy1.astype(y1_raw.dtype), dparams,
            [tuple(d) for d in daff])


def _conv1_bwd(img, c1_params, dt, stride, dy1):
    """(dimg, dc1_params) of the 7x7 stem conv via linear transposition
    (the astype casts transpose to casts back, so cotangent dtypes match
    the primals')."""
    k = c1_params["kernel"]

    def f_im(im):
        return jax.lax.conv_general_dilated(
            im.astype(dt), k.astype(dt), (stride, stride), ((3, 3), (3, 3)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def f_k(kk):
        return jax.lax.conv_general_dilated(
            img.astype(dt), kk.astype(dt), (stride, stride),
            ((3, 3), (3, 3)), dimension_numbers=("NHWC", "HWIO", "NHWC"))

    g = dy1.astype(dt)
    dimg = jax.linear_transpose(f_im, img)(g)[0]
    dk = jax.linear_transpose(f_k, k)(g)[0]
    db = (dy1.sum((0, 1, 2), dtype=jnp.float32)
          .astype(c1_params["bias"].dtype))
    return dimg, {"kernel": dk, "bias": db}


# ------------------------------------------------- reference + custom VJP

def _xla_reference(y1_raw, params):
    """Plain-XLA mirror of fused_stem_layer1 (oracle + backward)."""
    from .pallas_norm import _xla_instance_norm

    def norm_relu(x):
        return _xla_instance_norm(x, relu=True)

    def conv(x, p):
        # No preferred_element_type — this mirror IS the backward
        # formulation, and a fp32-typed output from bf16 operands makes
        # the conv transpose ill-typed (see PointwisePaddedConv).
        return jax.lax.conv_general_dilated(
            x, p["kernel"].astype(x.dtype), (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p["bias"].astype(x.dtype)

    t0 = norm_relu(y1_raw)
    u2 = norm_relu(conv(norm_relu(conv(t0, params["c10"])), params["c11"]))
    t1 = jnp.maximum(t0 + u2, 0)
    v2 = norm_relu(conv(norm_relu(conv(t1, params["c20"])), params["c21"]))
    return jnp.maximum(t1 + v2, 0)


def _shard_wrapped(local, shape, operands):
    """Run ``local(*operands, space_axis=..., space_size=...)`` inside
    shard_map over the active (data, space) mesh when one is set
    (parallel/context.py) and partitionable, else directly.  The FIRST
    operand is batch/height-sharded; the rest replicate.  Single home for
    the wrapper plumbing all four fused entry points share."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, SPACE_AXIS

    shard = _stem_shard_mesh(shape)
    if shard is None:
        return local(*operands)
    mesh, d, s = shard
    spec = P(DATA_AXIS, SPACE_AXIS, None, None)
    # Residual-returning locals produce a pytree mixing (B, H, Wp, C2)
    # tensors (shard like the input) and (B, 1, 2C) prep affines (psum'd
    # inside, so replicated over space: shard over data only).  The output
    # structure comes from an eval_shape of the UNSHARDED local — identical
    # pytree, zero compute.
    stat = P(DATA_AXIS, None, None)
    outs = jax.eval_shape(lambda *a: local(*a), *operands)
    out_specs = jax.tree.map(lambda l: spec if l.ndim == 4 else stat, outs)
    fn = functools.partial(local, space_axis=SPACE_AXIS if s > 1 else None,
                           space_size=s)
    in_specs = (spec,) + (P(),) * (len(operands) - 1)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*operands)


def _fused_forward(y1_raw, params):
    """The fused pipeline over the active mesh.  Batch sharding needs no
    communication (instance-norm stats are per-image); space sharding adds
    a stats psum + 2 ppermute'd halo rows per conv — both tiny next to the
    conv work.  The trace-time mesh consult mirrors ops/corr.py."""
    n = float(y1_raw.shape[1] * y1_raw.shape[2])
    return _shard_wrapped(
        functools.partial(fused_stem_layer1, n=n),
        y1_raw.shape, (y1_raw, params))


def _fused_forward_res(y1_raw, params):
    """_fused_forward that also returns the backward residuals (raw conv
    outputs + all five prep affines) as global arrays."""
    n = float(y1_raw.shape[1] * y1_raw.shape[2])

    def local(y1, p, space_axis=None, space_size=1):
        xp = pack_view(y1)
        st1 = _expand_stats(*_packed_stats(xp), n, space_axis)
        return _stage_on_packed(xp, st1, p, n, space_axis, space_size,
                                want_residuals=True)

    return _shard_wrapped(local, y1_raw.shape, (y1_raw, params))


@jax.custom_vjp
def stem_layer1(y1_raw: jax.Array, params: dict) -> jax.Array:
    """Fused forward; hand-written backward from the forward's saved
    residuals (_stage_bwd_xla — no forward re-linearization).  The
    backward runs on the GLOBAL arrays as plain XLA ops, so under a mesh
    GSPMD partitions it (conv halo exchanges included) without any manual
    collectives."""
    return _fused_forward(y1_raw, params)


def _fwd(y1_raw, params):
    out, raws, affs = _fused_forward_res(y1_raw, params)
    return out, (y1_raw, raws, affs, params)


def _bwd(residuals, g):
    y1_raw, raws, affs, params = residuals
    return _stage_bwd_xla(y1_raw, raws, affs, params, g)


stem_layer1.defvjp(_fwd, _bwd)
