"""Fused Pallas pipeline for the feature encoder's instance-norm stage.

Why: at flagship resolution the stem + layer1 stage (five 64-channel convs
with instance norms at 544x960) costs ~27 ms of which ~21 ms is XLA layout
churn — every cross-(H,W) reduction forces ~4 full-tensor relayouts of
135 MB each between the convs' space-to-depth blocked layouts and the
reduce's, and NO XLA-side formulation escapes it (lane-packed views,
direct/fp32 reduces, MXU ones-vector matmuls, 128-channel padding ALL
measured 27-62 ms; scripts/mb_encoder.py, docs/perf_notes_r03.md).

The fix is to own the stage end-to-end in Pallas so every tensor stays in
row-major (B, H, W, C):

* The (H, W, 64) tensor is VIEWED as (H, W/2, 128) — a free row-major
  reinterpretation that packs adjacent pixel pairs into full MXU/VPU
  lanes (the same trick XLA's blocked layouts buy with relayouts).  A
  3x3x64->64 conv becomes a 3x3-tap 128->128 conv over packed columns
  whose (parity-in, parity-out) weight blocks embed the original taps:
  measured 90.8 TF/s packed (= ~45 TF/s of useful 64-ch flops) vs
  XLA's 29.8 TF/s row-major / ~70 TF/s blocked-plus-relayouts.
* Each kernel call fuses the whole conv INPUT preparation — instance-norm
  apply from precomputed stats, relu, optional residual add (itself
  normalized from a second raw tensor) — and accumulates the fp32
  per-channel sum/sum-of-squares of its raw OUTPUT for the next norm, so
  a norm never touches HBM as a separate op.
* dy taps read halo rows (built by cheap strided row slices, 2 rows per
  block); dx taps are resolved post-matmul by rolling the accumulated
  output one packed column and masking the wrap (operands stay
  contiguous — the data-stationary formulation from scripts/mb_gru_kernel).

Semantics are exactly BasicEncoder's stem + layer1 (conv1-norm1-relu,
two ResidualBlocks; reference: core/extractor.py:122-197 structure) with
instance-norm statistics in fp32.  The backward pass is the XLA reference
formulation's VJP via jax.custom_vjp (training keeps its current cost;
this pipeline removes fixed-stage inference time).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _COMPILER_PARAMS, _interpret
from .pallas_norm import _row_block


# None = auto (fused on TPU backends); True/False force — tests force True
# to exercise the interpret-mode kernels on CPU.
fused_stem_override = None


def use_fused_stem(norm_fn: str, width: int) -> bool:
    """Gate for the fused stage: instance norm, even width, TPU backend
    (the kernels interpret on CPU for tests, but the plain XLA path is the
    sane CPU default).

    Sharding: a bare pallas_call cannot be SPMD-partitioned, so the fused
    stage must never sit inside a partitioned program.  It is disabled
    under an active corr mesh (the evaluator/train paths) AND whenever
    more than one device is visible — a user may jit with shardings
    directly, without the use_corr_mesh context, and the plain XLA stage
    (which XLA partitions with halo exchanges) must remain what they get.
    Single-device hosts cannot shard, so the gate is exact there; a
    shard_map wrapper is the future multi-chip path."""
    from ..parallel.context import active_corr_mesh

    ok = norm_fn == "instance" and width % 2 == 0
    if active_corr_mesh() is not None:  # None for trivial 1-device meshes
        return False
    if fused_stem_override is not None:
        return fused_stem_override and ok
    return (ok and jax.default_backend() == "tpu"
            and len(jax.devices()) == 1)


# --------------------------------------------------------------- packing

def pack_view(x: jax.Array) -> jax.Array:
    """(B, H, W, C) -> (B, H, W/2, 2C): free row-major reinterpretation
    (adjacent pixel pair -> one packed column)."""
    b, h, w, c = x.shape
    return x.reshape(b, h, w // 2, 2 * c)


def unpack_view(x: jax.Array) -> jax.Array:
    b, h, wp, c2 = x.shape
    return x.reshape(b, h, wp * 2, c2 // 2)


def pack_weights(w: jax.Array) -> jax.Array:
    """(3, 3, C, C) HWIO conv weights -> (9, 2C, 2C) packed [dy*3 + dp].

    Output pixel w_out = 2p + po with tap dx reads input pixel
    2p + po + dx = packed column p + dp, parity pi, where
    dp = floor((po+dx)/2), pi = (po+dx) mod 2:
      dp=-1: (pi=1 -> po=0) = W[dy, dx=-1]
      dp= 0: full 2x2 parity block
      dp=+1: (pi=0 -> po=1) = W[dy, dx=+1]
    """
    c = w.shape[2]
    out = jnp.zeros((3, 3, 2 * c, 2 * c), w.dtype)
    for po in range(2):
        for dxi, dx in enumerate((-1, 0, 1)):
            dp = (po + dx) // 2
            pi = (po + dx) % 2
            out = out.at[:, dp + 1,
                         pi * c:(pi + 1) * c,
                         po * c:(po + 1) * c].set(w[:, dxi])
    return out.reshape(9, 2 * c, 2 * c)


def pack_vec(v: jax.Array) -> jax.Array:
    """Per-channel vector -> packed duplicate [v, v] (both parities)."""
    return jnp.concatenate([v, v], axis=-1)


def stats_from_packed(s1: jax.Array, s2: jax.Array, n: float
                      ) -> Tuple[jax.Array, jax.Array]:
    """Packed (B, 1, 2C) fp32 sums -> per-original-channel (B, 1, C)
    mean / rstd (parity halves sum exactly: they partition the pixels)."""
    c = s1.shape[-1] // 2
    t1 = s1[..., :c] + s1[..., c:]
    t2 = s2[..., :c] + s2[..., c:]
    mean = t1 / n
    var = jnp.maximum(t2 / n - mean * mean, 0.0)
    return mean, jax.lax.rsqrt(var + 1e-5)


# ---------------------------------------------------------------- kernels

def _prep(x, m_ref, s_ref):
    """Instance-norm apply + relu from packed stats refs."""
    m = m_ref[...][:, :, None, :].astype(x.dtype)
    s = s_ref[...][:, :, None, :].astype(x.dtype)
    return jnp.maximum((x - m) * s, 0)


def _edge_mask_halo(th):
    """Zero the prepped halo rows that lie OUTSIDE the image: conv zero
    padding applies in the PREPPED domain, but prepping a zero-filled edge
    halo yields relu(-m*s) != 0.  Row 0 (above) is outside at the first
    row-block, row 1 (below) at the last."""
    j = pl.program_id(1)
    # Scalar multiplies, not a stacked bool mask: Mosaic cannot shape-cast
    # a vector<2xi1> to the broadcast rank.  Edge halo values are finite
    # (prep of a zero row), so multiply-by-zero is exact.
    top = th[:, 0:1] * (j > 0).astype(th.dtype)
    bot = th[:, 1:2] * (j < pl.num_programs(1) - 1).astype(th.dtype)
    return jnp.concatenate([top, bot], axis=1)


def _conv_packed(t, halo, w_ref, bias_ref, wp):
    """3x3 packed conv of the prepped tile.

    t: (1, R, Wp, 2C) prepped center rows; halo: (1, 2, Wp, 2C) prepped
    halo rows [above, below]; w_ref: (9, 2C, 2C); returns (1, R, Wp, 2C)
    fp32 + bias."""
    col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, wp, 1), 2)
    y = None
    for dpi in range(3):
        u = None
        for dyi in range(3):
            if dyi == 0:
                rows = jnp.concatenate([halo[:, 0:1], t[:, :-1]], axis=1)
            elif dyi == 1:
                rows = t
            else:
                rows = jnp.concatenate([t[:, 1:], halo[:, 1:2]], axis=1)
            m = jax.lax.dot_general(
                rows, w_ref[dyi * 3 + dpi],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            u = m if u is None else u + m
        o = dpi - 1
        if o == 0:
            shifted = u
        else:
            shifted = pltpu.roll(u, (-o) % wp, 2)
            if o == 1:
                shifted = jnp.where(col < wp - 1, shifted, 0.0)
            else:
                shifted = jnp.where(col > 0, shifted, 0.0)
        y = shifted if y is None else y + shifted
    return y + bias_ref[...][:, :, None, :]


def _enc_conv_kernel(x_ref, xh_ref, m_ref, s_ref, w_ref, b_ref,
                     y_ref, s1_ref, s2_ref, *, wp):
    """prep(x) -> packed conv -> raw y + packed output stats."""
    t = _prep(x_ref[...], m_ref, s_ref)
    th = _edge_mask_halo(_prep(xh_ref[...][:, 0], m_ref, s_ref))
    y = _conv_packed(t, th, w_ref, b_ref, wp)
    y_ref[...] = y.astype(y_ref.dtype)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    s1_ref[...] += jnp.sum(y, axis=(1, 2))[:, None, :]
    s2_ref[...] += jnp.sum(y * y, axis=(1, 2))[:, None, :]


def _enc_conv_res_kernel(x_ref, xh_ref, m_ref, s_ref,
                         r_ref, rh_ref, rm_ref, rs_ref,
                         w_ref, b_ref, y_ref, s1_ref, s2_ref, *, wp):
    """Residual-block boundary: the conv input is
    relu( prep(res_raw) + prep(x_raw) ) — both tensors arrive RAW with
    their stats and are normalized in-register."""
    t = jnp.maximum(_prep(r_ref[...], rm_ref, rs_ref)
                    + _prep(x_ref[...], m_ref, s_ref), 0)
    th = _edge_mask_halo(
        jnp.maximum(_prep(rh_ref[...][:, 0], rm_ref, rs_ref)
                    + _prep(xh_ref[...][:, 0], m_ref, s_ref), 0))
    y = _conv_packed(t, th, w_ref, b_ref, wp)
    y_ref[...] = y.astype(y_ref.dtype)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    s1_ref[...] += jnp.sum(y, axis=(1, 2))[:, None, :]
    s2_ref[...] += jnp.sum(y * y, axis=(1, 2))[:, None, :]


def _enc_finish_kernel(y1_ref, m1_ref, s1_ref, c11_ref, m11_ref, s11_ref,
                       c21_ref, m21_ref, s21_ref, o_ref):
    """t2 = relu( relu( t0 + u2 ) + v2 ): the stage output in the final
    domain, from the three raw tensors + their stats."""
    t0 = _prep(y1_ref[...], m1_ref, s1_ref)
    u2 = _prep(c11_ref[...], m11_ref, s11_ref)
    v2 = _prep(c21_ref[...], m21_ref, s21_ref)
    o_ref[...] = jnp.maximum(jnp.maximum(t0 + u2, 0) + v2,
                             0).astype(o_ref.dtype)


# ------------------------------------------------------------- host side

def _halo_rows(x: jax.Array, r: int) -> jax.Array:
    """(B, H, Wp, C2) -> (B, H//r, 2, Wp, C2): rows above/below each
    r-row block (zeros at image edges); strided slices, ~2/r of a pass."""
    b, h, wp, c2 = x.shape
    nblk = h // r
    zero = jnp.zeros((b, 1, wp, c2), x.dtype)
    top = jnp.concatenate([zero, x[:, r - 1::r][:, : nblk - 1]], axis=1)
    bot = jnp.concatenate([x[:, r::r], zero], axis=1)
    return jnp.stack([top, bot], axis=2)


def _enc_conv(x, stats, w9, bias, res=None, res_stats=None):
    """One fused prep+conv+stats call on packed arrays.

    x: (B, H, Wp, C2) raw; stats: (mean, rstd) each (B, 1, C2) packed;
    w9: (9, C2, C2); bias: (1, 1, C2).  Returns (y_raw fp-of-x, (s1, s2))."""
    b, h, wp, c2 = x.shape
    r = _row_block(h)
    grid = (b, h // r)
    xh = _halo_rows(x, r)
    m, s = stats

    def row_spec():
        return pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                            memory_space=pltpu.VMEM)

    def halo_spec():
        return pl.BlockSpec((1, 1, 2, wp, c2), lambda i, j: (i, j, 0, 0, 0),
                            memory_space=pltpu.VMEM)

    def stat_spec():
        return pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    wspec = pl.BlockSpec((9, c2, c2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    bspec = pl.BlockSpec((1, 1, c2), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM)

    if res is None:
        kernel = functools.partial(_enc_conv_kernel, wp=wp)
        operands = (x, xh, m, s, w9, bias[None, None, :])
        in_specs = [row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    wspec, bspec]
    else:
        rm, rs = res_stats
        rh = _halo_rows(res, r)
        kernel = functools.partial(_enc_conv_res_kernel, wp=wp)
        operands = (x, xh, m, s, res, rh, rm, rs, w9, bias[None, None, :])
        in_specs = [row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    row_spec(), halo_spec(), stat_spec(), stat_spec(),
                    wspec, bspec]

    y, s1, s2 = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(x.shape, x.dtype),
                   jax.ShapeDtypeStruct((b, 1, c2), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c2), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(row_spec(),
                   stat_spec(), stat_spec()),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(*operands)
    return y, (s1, s2)


def _packed_stats(x):
    """Packed per-channel fp32 (sum, sumsq) of a raw packed tensor via the
    layout-preserving stats kernel (pallas_norm)."""
    from .pallas_norm import _in_stats_kernel

    b, h, wp, c2 = x.shape
    r = _row_block(h)
    return pl.pallas_call(
        _in_stats_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, 1, c2), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c2), jnp.float32)),
        grid=(b, h // r),
        in_specs=[pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(x)


def _expand_stats(s1, s2, n):
    """Packed sums -> packed (mean, rstd) duplicated over parities."""
    mean, rstd = stats_from_packed(s1, s2, n)
    return pack_vec(mean), pack_vec(rstd)


def fused_stem_layer1(y1_raw: jax.Array, params: dict) -> jax.Array:
    """norm1 + relu + layer1 (two ResidualBlocks), fused, from conv1's RAW
    output (B, H, W, 64), any even W.

    Both split points were measured E2E: letting norm1 run in XLA (so
    conv1 keeps its fused blocked lowering) costs MORE than it saves —
    conv1 drops 1.4 -> 3.8 ms when its consumer is row-major, but the XLA
    norm1's own relayouts cost ~3 ms more (9.49 vs 9.77 pairs/sec), so
    the pipeline consumes conv1 raw and computes norm1's stats with the
    layout-preserving kernel.
    params: {"c10","c11","c20","c21"} -> {"kernel": (3,3,64,64),
    "bias": (64,)} — layer1_0.conv1/conv2, layer1_1.conv1/conv2.
    Returns the stage output in the final (post-relu) domain.
    """
    xp = pack_view(y1_raw)
    n = float(y1_raw.shape[1] * y1_raw.shape[2])
    dt = y1_raw.dtype

    def pw(name):
        return (pack_weights(params[name]["kernel"]).astype(dt),
                pack_vec(params[name]["bias"]).astype(dt))

    st1 = _expand_stats(*_packed_stats(xp), n)
    c10, s10 = _enc_conv(xp, st1, *pw("c10"))
    st10 = _expand_stats(*s10, n)
    c11, s11 = _enc_conv(c10, st10, *pw("c11"))
    st11 = _expand_stats(*s11, n)
    # block boundary: input of layer1_1.conv1 is relu(t0 + u2)
    c20, s20 = _enc_conv(c11, st11, *pw("c20"), res=xp, res_stats=st1)
    st20 = _expand_stats(*s20, n)
    c21, s21 = _enc_conv(c20, st20, *pw("c21"))
    st21 = _expand_stats(*s21, n)

    b, h, wp, c2 = xp.shape
    r = _row_block(h)

    def row_spec():
        return pl.BlockSpec((1, r, wp, c2), lambda i, j: (i, j, 0, 0),
                            memory_space=pltpu.VMEM)

    def stat_spec():
        return pl.BlockSpec((1, 1, c2), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        _enc_finish_kernel,
        out_shape=jax.ShapeDtypeStruct(xp.shape, dt),
        grid=(b, h // r),
        in_specs=[row_spec(), stat_spec(), stat_spec(),
                  row_spec(), stat_spec(), stat_spec(),
                  row_spec(), stat_spec(), stat_spec()],
        out_specs=row_spec(),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(xp, *st1, c11, *st11, c21, *st21)
    return unpack_view(out)


# ------------------------------------------------- reference + custom VJP

def _xla_reference(y1_raw, params):
    """Plain-XLA mirror of fused_stem_layer1 (oracle + backward)."""
    from .pallas_norm import _xla_instance_norm

    def norm_relu(x):
        return _xla_instance_norm(x, relu=True)

    def conv(x, p):
        return jax.lax.conv_general_dilated(
            x, p["kernel"].astype(x.dtype), (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype) + p["bias"].astype(x.dtype)

    t0 = norm_relu(y1_raw)
    u2 = norm_relu(conv(norm_relu(conv(t0, params["c10"])), params["c11"]))
    t1 = jnp.maximum(t0 + u2, 0)
    v2 = norm_relu(conv(norm_relu(conv(t1, params["c20"])), params["c21"]))
    return jnp.maximum(t1 + v2, 0)


@jax.custom_vjp
def stem_layer1(y1_raw: jax.Array, params: dict) -> jax.Array:
    """Fused forward; XLA-reference backward (see module docstring)."""
    return fused_stem_layer1(y1_raw, params)


def _fwd(y1_raw, params):
    return fused_stem_layer1(y1_raw, params), (y1_raw, params)


def _bwd(residuals, g):
    y1_raw, params = residuals
    _, vjp = jax.vjp(_xla_reference, y1_raw, params)
    return vjp(g)


stem_layer1.defvjp(_fwd, _bwd)
