"""All-pairs 1-D correlation engine — the perf-critical core.

The reference ships four interchangeable backends (reference: core/corr.py;
selected at core/raft_stereo.py:90-100).  Here the same capability surface is
four backends behind one functional API, designed TPU-first:

* ``reg``    — precompute the full (B, H, W1, W2) volume as one batched matmul
               over B*H rows (MXU), build a W2 pyramid by average pooling,
               look up 2r+1 taps per level with an XLA gather+lerp.
               Mirror of ``CorrBlock1D`` (core/corr.py:110-156).
* ``alt``    — no precomputed volume: per lookup, sample fmap2 at the taps and
               dot with fmap1.  O(H*W) memory; mirror of
               ``PytorchAlternateCorrBlock1D`` (core/corr.py:64-107).
* ``pallas`` — same precomputed pyramid as ``reg`` but the lookup runs in a
               Pallas TPU kernel (gather-free masked reduction), the analogue
               of the reference's CUDA ``corr_sampler`` (sampler/sampler_kernel.cu).
* ``pallas_alt`` — on-demand Pallas kernel: each W1-block's correlation rows
               are recomputed in VMEM (MXU matmul + hat reduction) and thrown
               away.  O(H*W) memory at Pallas-kernel speed; the working form
               of the reference's dead ``alt_cuda`` (core/corr.py:159-188).

All backends share exact semantics: 1/sqrt(C) scaling, align_corners linear
interpolation in x, zero outside [0, W2-1], floor-halving pyramid.  The
reference builds num_levels+1 pyramid entries but only reads num_levels
(core/corr.py:122-125 vs :133); we build exactly num_levels.

A lookup function takes absolute x-coordinates (B, H, W1, 1) at level-0
resolution and returns (B, H, W1, num_levels*(2r+1)) correlation features,
ordered [level0: dx=-r..r, level1: ..., ...] to match the reference's channel
concatenation (core/corr.py:133-146).
"""

from __future__ import annotations

import functools
import warnings
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .sampler import linear_sample_1d

CorrFn = Callable[[jax.Array], jax.Array]


_PRECISIONS = {
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}


def build_corr_volume(fmap1: jax.Array, fmap2: jax.Array,
                      dtype=jnp.float32, precision: str = "highest") -> jax.Array:
    """(B, H, W1, C) x (B, H, W2, C) -> (B, H, W1, W2), scaled by 1/sqrt(C).

    One einsum = a batched matmul over B*H rows, which XLA tiles directly onto
    the MXU (reference equivalent: core/corr.py:148-156).
    """
    c = fmap1.shape[-1]
    # fp32-accurate multiply precision: sub-pixel disparity refinement reads
    # tiny differences between neighbouring correlation values, so the MXU's
    # single-pass bf16 path is not the right default (the reference likewise
    # pins the volume to fp32: core/raft_stereo.py:92).  "highest" is exact
    # 6-pass emulation and stays the default: the cheaper forms measured NO
    # speedup on the flagship path (docs/perf_notes_r03.md), so there is
    # nothing to trade accuracy for.
    corr = jnp.einsum("bhwc,bhvc->bhwv", fmap1, fmap2,
                      preferred_element_type=jnp.float32,
                      precision=_PRECISIONS[precision])
    return (corr / jnp.sqrt(jnp.float32(c))).astype(dtype)


def build_corr_pyramid(corr: jax.Array, num_levels: int) -> List[jax.Array]:
    """Average-pool the W2 axis by 2 per level, floor-halving odd widths
    (reference: core/corr.py:117-125)."""
    pyramid = [corr]
    for _ in range(num_levels - 1):
        c = pyramid[-1]
        w2 = c.shape[-1]
        c = c[..., : (w2 // 2) * 2]
        c = c.reshape(*c.shape[:-1], w2 // 2, 2).mean(axis=-1)
        pyramid.append(c)
    return pyramid


def _tap_offsets(radius: int) -> jax.Array:
    return jnp.arange(-radius, radius + 1, dtype=jnp.float32)


def _reg_lookup(pyramid: Sequence[jax.Array], radius: int,
                coords: jax.Array) -> jax.Array:
    """Tap lookup over a precomputed volume pyramid — the shared body of
    ``make_reg_corr_fn`` and the state-passing ``corr_fn_from_state``, so
    the monolithic and phase-split executables run identical ops."""
    offsets = _tap_offsets(radius)
    x = coords[..., 0].astype(jnp.float32)          # (B, H, W1)
    out = []
    for i, vol in enumerate(pyramid):
        taps = x[..., None] / (2.0 ** i) + offsets  # (B, H, W1, K)
        out.append(linear_sample_1d(vol, taps))
    return jnp.concatenate(out, axis=-1)


def _build_volume(fmap1: jax.Array, fmap2: jax.Array, dtype, precision: str,
                  quant: bool) -> jax.Array:
    """The one volume-construction seam: fp32 einsum (``build_corr_volume``)
    or the int8-quantized product with its dequant epilogue (ops/quant.py).
    ``precision`` only applies to the fp32 path — the int8 accumulator is
    exact integer arithmetic, there is no multiply precision to pick."""
    if quant:
        from .quant import quant_corr_volume
        return quant_corr_volume(fmap1.astype(jnp.float32),
                                 fmap2.astype(jnp.float32), dtype=dtype)
    return build_corr_volume(fmap1.astype(jnp.float32),
                             fmap2.astype(jnp.float32), dtype=dtype,
                             precision=precision)


def make_reg_corr_fn(fmap1: jax.Array, fmap2: jax.Array, num_levels: int,
                     radius: int, dtype=jnp.float32,
                     precision: str = "highest",
                     quant: bool = False) -> CorrFn:
    """Precomputed-volume backend (reference: CorrBlock1D, core/corr.py:110-156)."""
    volume = _build_volume(fmap1, fmap2, dtype, precision, quant)
    pyramid = build_corr_pyramid(volume, num_levels)

    return lambda coords: _reg_lookup(pyramid, radius, coords)


def build_fmap2_pyramid(fmap2: jax.Array, num_levels: int) -> List[jax.Array]:
    """Pool fmap2's W axis (axis=2 in NHWC) by 2 per level, floor-halving.

    Pooling fmap2 then correlating equals pooling the correlation volume
    (both are linear in fmap2), so on-demand backends built on this pyramid
    match ``reg`` exactly (reference: core/corr.py:104)."""
    c = fmap2.shape[-1]
    pyramid = [fmap2]
    for _ in range(num_levels - 1):
        f2 = pyramid[-1]
        w = f2.shape[2]
        f2 = f2[:, :, : (w // 2) * 2, :]
        f2 = f2.reshape(f2.shape[0], f2.shape[1], w // 2, 2, c).mean(axis=3)
        pyramid.append(f2)
    return pyramid


def _alt_lookup(fmap1: jax.Array, f2_pyramid: Sequence[jax.Array],
                radius: int, precision: str,
                coords: jax.Array) -> jax.Array:
    """On-demand tap correlation over an fmap2 pyramid — the shared body of
    ``make_alt_corr_fn`` and the state-passing ``corr_fn_from_state``.
    ``fmap1``/``f2_pyramid`` must already be fp32."""
    c = fmap1.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(c))
    offsets = _tap_offsets(radius)
    x = coords[..., 0].astype(jnp.float32)          # (B, H, W1)
    out = []
    for i, f2 in enumerate(f2_pyramid):
        taps = x[..., None] / (2.0 ** i) + offsets  # (B, H, W1, K)
        w2 = f2.shape[2]
        x0 = jnp.floor(taps)
        dx = taps - x0
        i0 = x0.astype(jnp.int32)
        i1 = i0 + 1
        # Flatten the (W1, K) tap grid into the W axis for one gather.
        b, h, w1, k = taps.shape

        def take(idx):
            idxc = jnp.clip(idx, 0, w2 - 1).reshape(b, h, w1 * k)
            g = jnp.take_along_axis(f2, idxc[..., None], axis=2)
            return g.reshape(b, h, w1, k, c)
        v0 = take(i0)
        v1 = take(i1)
        v0 = jnp.where(((i0 >= 0) & (i0 <= w2 - 1))[..., None], v0, 0)
        v1 = jnp.where(((i1 >= 0) & (i1 <= w2 - 1))[..., None], v1, 0)
        f2_taps = v0 * (1.0 - dx)[..., None] + v1 * dx[..., None]
        corr = jnp.einsum("bhwc,bhwkc->bhwk", fmap1, f2_taps,
                          precision=_PRECISIONS[precision]) * scale
        out.append(corr)
    return jnp.concatenate(out, axis=-1)


def make_alt_corr_fn(fmap1: jax.Array, fmap2: jax.Array, num_levels: int,
                     radius: int, precision: str = "highest") -> CorrFn:
    """On-demand backend: O(H*W) memory, recomputes correlation only at the
    sampled taps (reference: PytorchAlternateCorrBlock1D, core/corr.py:64-107).
    """
    fmap1 = fmap1.astype(jnp.float32)
    f2_pyramid = build_fmap2_pyramid(fmap2.astype(jnp.float32), num_levels)
    return lambda coords: _alt_lookup(fmap1, f2_pyramid, radius, precision,
                                      coords)


@functools.lru_cache(maxsize=None)
def _warn_corr_unshardable(reason: str) -> None:
    """Trace-time warning, once per distinct shape/mesh mismatch."""
    warnings.warn(
        f"corr mesh is active but the Pallas corr backend cannot partition "
        f"over it ({reason}); falling back to replicated lowering",
        RuntimeWarning, stacklevel=4)


def _corr_shard_mesh(b: int, h: int):
    """The active (data, space) mesh if the Pallas backends can partition
    over it: B divisible by data, H (at corr resolution) by space.

    The kernels' grids are per-(B*H)-row independent — the same independence
    the reference's CUDA kernel exploits (one thread block per row,
    sampler/sampler_kernel.cu:19-60) — so batch/height sharding via
    ``shard_map`` needs no cross-shard communication.  Returns
    (mesh, row_spec, flat_spec) or None (plain single-device lowering).
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.context import active_corr_mesh
    from ..parallel.mesh import DATA_AXIS, SPACE_AXIS

    mesh = active_corr_mesh()
    if mesh is None:
        return None
    d = mesh.shape.get(DATA_AXIS, 1)
    s = mesh.shape.get(SPACE_AXIS, 1)
    if d * s == 1:
        return None
    if b % d or h % s:
        # Loud, not silent: on a real mesh a user with e.g. batch 12 on
        # data=8 would otherwise lose corr partitioning with no signal.
        reasons = []
        if b % d:
            reasons.append(f"batch {b} not divisible by '{DATA_AXIS}' "
                           f"mesh axis {d}")
        if h % s:
            reasons.append(f"corr-height {h} not divisible by "
                           f"'{SPACE_AXIS}' mesh axis {s}")
        _warn_corr_unshardable("; ".join(reasons))
        return None
    # Flat (B*H, ...) arrays shard over BOTH axes at once; each device's
    # rows are exactly the ones its (b-block, h-block) produced, because
    # construction and lookup run inside shard_map with the same specs.
    return (mesh, P(DATA_AXIS, SPACE_AXIS, None, None),
            P((DATA_AXIS, SPACE_AXIS), None, None))


def make_pallas_corr_fn(fmap1: jax.Array, fmap2: jax.Array, num_levels: int,
                        radius: int, dtype=jnp.float32,
                        precision: str = "highest",
                        quant: bool = False) -> CorrFn:
    """Precomputed-pyramid backend with the Pallas TPU lookup kernel.

    Each pyramid level is flattened + W1-padded to the kernel's layout ONCE
    here; per-iteration calls reshape only the taps (the volume pad is an HBM
    copy of the whole volume — done once structurally rather than relying on
    XLA's loop-invariant code motion).

    Under an active corr mesh (parallel/context.py) construction and lookups
    run inside ``shard_map`` over the (data, space) axes, so the backend
    partitions across chips like the XLA-native ones."""
    from .pallas_corr import (pad_vol_lane, pallas_lookup_pyramid_flat,
                              preflatten_volume)

    def construct(f1, f2):
        volume = _build_volume(f1, f2, dtype, precision, quant)
        # Lane-padded level concat along W2: every per-iteration lookup is
        # ONE kernel launch covering all levels (same as pallas_alt).
        pyr = [pad_vol_lane(preflatten_volume(v))
               for v in build_corr_pyramid(volume, num_levels)]
        return tuple(pyr)

    shard = _corr_shard_mesh(fmap1.shape[0], fmap1.shape[1])
    if shard is None:
        pyramid = construct(fmap1, fmap2)
        lookup_flat = pallas_lookup_pyramid_flat
    else:
        mesh, row_spec, flat_spec = shard
        fmap_spec = row_spec
        pyramid = jax.shard_map(
            construct, mesh=mesh, in_specs=(fmap_spec, fmap_spec),
            out_specs=tuple([flat_spec] * num_levels),
            check_vma=False)(fmap1, fmap2)

        def lookup_flat(vcat, taps, w2s):
            return jax.shard_map(
                lambda v, t: pallas_lookup_pyramid_flat(v, t, w2s),
                mesh=mesh, in_specs=(flat_spec, row_spec),
                out_specs=row_spec, check_vma=False)(vcat, taps)

    w2s = tuple(v.shape[2] for v in pyramid)
    vcat = jnp.concatenate(pyramid, axis=2)
    offsets = _tap_offsets(radius)

    def corr_fn(coords: jax.Array) -> jax.Array:
        x = coords[..., 0].astype(jnp.float32)          # (B, H, W1)
        taps = jnp.concatenate(
            [x[..., None] / (2.0 ** i) + offsets        # (B, H, W1, K)
             for i in range(len(w2s))], axis=-1)
        return lookup_flat(vcat, taps, w2s)

    return corr_fn


def make_pallas_alt_corr_fn(fmap1: jax.Array, fmap2: jax.Array,
                            num_levels: int, radius: int,
                            dtype=jnp.float32,
                            precision: str = "highest",
                            out_dtype=jnp.float32,
                            out_channels: int = 0,
                            epilogue=None) -> CorrFn:
    """On-demand Pallas backend: O(H*W) HBM like ``alt``, but each W1-block's
    correlation rows are recomputed inside a TPU kernel (MXU matmul + hat
    reduction in VMEM).  Working form of the reference's dead ``alt_cuda``
    backend (reference: core/corr.py:159-188 raises NotImplementedError).

    ``epilogue``: the motion encoder's convc1 parameters
    ({"kernel": (1, 1, L*K, Co), "bias": (Co,)}) — when given, the kernel
    emits relu(corr @ W + b) directly (one fused pass; the separate 1x1
    conv re-read the correlation features at 75 GB/s, 60 us/iter).
    Inference-only: the caller gates it on test_mode (no VJP)."""
    from .pallas_alt import (pad_w2_lane, pallas_alt_pyramid_radial_epi_flat,
                             pallas_alt_pyramid_radial_flat,
                             preflatten_fmap1, preflatten_fmap2)

    # Flatten/pad ONCE so each corr_fn call touches only the taps (the f1
    # pad is a full-fmap HBM copy; one copy guaranteed structurally). The
    # fmap2 pyramid is concatenated along W2 so every per-iteration lookup
    # is ONE kernel launch covering all levels — the per-level variant is
    # launch-overhead-bound (~4x slower at 1/4-res flagship shapes).
    # ``dtype`` selects the stored/matmul precision (the CUDA kernel's
    # fp32+fp16 dispatch, sampler_kernel.cu:126): bf16 halves the kernel's
    # DMA and takes the MXU's native bf16 path (fp32 accumulation). The
    # pyramid is always POOLED in fp32 first; only the kernel inputs are
    # rounded.
    def construct(f1, f2):
        f1flat = preflatten_fmap1(f1.astype(jnp.float32)).astype(dtype)
        f2p = [pad_w2_lane(preflatten_fmap2(x)).astype(dtype) for x in
               build_fmap2_pyramid(f2.astype(jnp.float32), num_levels)]
        return (f1flat,) + tuple(f2p)

    scales = tuple(1.0 / 2.0 ** i for i in range(num_levels))
    epi = None
    if epilogue is not None:
        # Prepared exactly as PointwisePaddedConv consumes them: compute
        # dtype for the dot and the bias add (out_dtype IS the model
        # compute dtype on this path).
        epi = (epilogue["kernel"][0, 0].astype(out_dtype),
               epilogue["bias"].reshape(1, 1, -1).astype(out_dtype))

    shard = _corr_shard_mesh(fmap1.shape[0], fmap1.shape[1])
    if shard is None:
        f1flat, *f2_pyramid = construct(fmap1, fmap2)

        def lookup_flat(f1, f2, xl, w2s):
            if epi is not None:
                return pallas_alt_pyramid_radial_epi_flat(
                    f1, f2, xl, w2s, radius, epi[0], epi[1],
                    precision=precision, out_dtype=out_dtype,
                    level_scales=scales)
            return pallas_alt_pyramid_radial_flat(f1, f2, xl, w2s, radius,
                                                  precision=precision,
                                                  out_dtype=out_dtype,
                                                  out_channels=out_channels,
                                                  level_scales=scales)
    else:
        # Partition over the mesh (see _corr_shard_mesh): construction and
        # every lookup run per-shard inside shard_map; no collectives.
        mesh, row_spec, flat_spec = shard
        f1flat, *f2_pyramid = jax.shard_map(
            construct, mesh=mesh, in_specs=(row_spec, row_spec),
            out_specs=tuple([flat_spec] * (1 + num_levels)),
            check_vma=False)(fmap1, fmap2)

        def lookup_flat(f1, f2, xl, w2s):
            from jax.sharding import PartitionSpec as P

            if epi is not None:
                return jax.shard_map(
                    lambda a, b, t, w, bi: pallas_alt_pyramid_radial_epi_flat(
                        a, b, t, w2s, radius, w, bi, precision=precision,
                        out_dtype=out_dtype, level_scales=scales),
                    mesh=mesh,
                    in_specs=(flat_spec, flat_spec, row_spec, P(), P()),
                    out_specs=row_spec, check_vma=False)(f1, f2, xl, *epi)
            return jax.shard_map(
                lambda a, b, t: pallas_alt_pyramid_radial_flat(
                    a, b, t, w2s, radius, precision=precision,
                    out_dtype=out_dtype, out_channels=out_channels,
                    level_scales=scales),
                mesh=mesh, in_specs=(flat_spec, flat_spec, row_spec),
                out_specs=row_spec, check_vma=False)(f1, f2, xl)

    w2s = tuple(f2.shape[1] for f2 in f2_pyramid)
    f2cat = jnp.concatenate(f2_pyramid, axis=1)

    def corr_fn(coords: jax.Array) -> jax.Array:
        x = coords[..., 0].astype(jnp.float32)          # (B, H, W1)
        # The kernel derives every level's local center in-register from
        # the level-0 center (static level_scales) and resolves the radius
        # taps itself (shared-fraction window form) — even the ONE
        # broadcast multiply that replaced round-2's per-level stack cost
        # 28 us/iter of 24 GB/s loop fusion (round-4 trace).
        return lookup_flat(f1flat, f2cat, x[..., None], w2s)

    return corr_fn


# A/B toggle for the fused convc1 epilogue (scripts/ab_corr_epilogue.py
# flips it in one process; tests pin the fused == unfused numerics).
corr_epilogue_enabled = True


def resolve_implementation(implementation: str, quant: bool = False) -> str:
    """'auto' -> the fastest backend for the active platform.  The ONE
    resolver — make_corr_fn, corr_epilogue_active, and bench.py must agree,
    or the model could set corr_preact for a backend that ignores the
    epilogue (skipping convc1 on raw features entirely).

    ``quant`` (the int8 corr volume, ops/quant.py) overrides the choice
    to a PRECOMPUTED-VOLUME backend regardless of the configured one: the
    int8 win is the one-shot volume matmul, and the on-demand backends
    would re-quantize (and re-pay the int8 pack) at every lookup.  On TPU
    that is the Pallas lookup kernel over the dequantized volume, the XLA
    gather path elsewhere."""
    if quant:
        return "pallas" if jax.default_backend() == "tpu" else "reg"
    if implementation == "auto":
        return "pallas_alt" if jax.default_backend() == "tpu" else "reg"
    return implementation


def corr_epilogue_active(implementation: str, quant: bool = False) -> bool:
    """Whether ``make_corr_fn`` would honor a convc1 ``epilogue`` for this
    implementation — the model consults this to decide if the motion
    encoder's convc1 is fused into the lookup kernel (pallas_alt only;
    never under the quantized volume path, which resolves away from
    pallas_alt)."""
    return (corr_epilogue_enabled
            and resolve_implementation(implementation, quant) == "pallas_alt")


def _roundup(x: int, m: int) -> int:
    return -(-x // m) * m


def _padded_level_widths(w: int, num_levels: int) -> Tuple[int, ...]:
    """Per-level lane-padded W2 widths of a floor-halving pyramid whose
    level-0 width is ``w`` — the static shape info the pre-flattened
    Pallas corr states carry implicitly (level-0 W2 == the lookup
    coordinates' W1 for stereo, so it never needs to be stored)."""
    from .pallas_corr import LANE
    widths = [w]
    for _ in range(num_levels - 1):
        widths.append(widths[-1] // 2)
    return tuple(_roundup(x, LANE) for x in widths)


def _pack_state_rows(x: jax.Array, hp: int, w_axis: int,
                     w_to: int) -> jax.Array:
    """Zero-pad a batch-leading (B, H, ...) array to (B, Hp, ...) rows and
    ``w_axis`` to ``w_to`` — reshape/zero-pad only, so packed lookups are
    bitwise-equal to the unpacked ones (padded rows/columns correlate to
    exactly zero and are sliced off; asserted in tests/test_model.py)."""
    widths = [(0, 0)] * x.ndim
    widths[1] = (0, hp - x.shape[1])
    widths[w_axis] = (0, w_to - x.shape[w_axis])
    return jnp.pad(x, widths)


def build_corr_state(implementation: str, fmap1: jax.Array,
                     fmap2: jax.Array, num_levels: int,
                     dtype=jnp.float32,
                     precision: str = "highest",
                     quant: bool = False) -> Tuple[jax.Array, ...]:
    """Backend-specific correlation state as a FLAT TUPLE of batch-leading
    arrays — the carried-state form of ``make_corr_fn``'s closure, for
    executables that split one request across several XLA programs (the
    iteration-level scheduler's prologue/step split, serve/sched/).

    Every leaf keeps the batch as its leading axis so per-slot selects
    (``jnp.where`` over a (B,) mask) compose requests into a running batch
    without touching other slots' values.  For the Pallas backends the
    kernels' flatten/lane-pad relayout is done HERE, once at the prologue:
    levels are lane-padded and concatenated along W2, rows padded to the
    kernel row block, all with the batch axis kept leading — so each
    lookup through ``corr_fn_from_state`` performs only free reshapes
    (merging the leading (B, Hp) axes) instead of re-copying the pyramid
    per step.  The packing is reshape/zero-pad only and therefore exact
    (asserted in tests/test_model.py); level widths are derived statically
    from the lookup coordinates' W1 (``_padded_level_widths``).

    The arrays are built by the SAME ops as ``make_corr_fn`` at the same
    dtypes, so a lookup through ``corr_fn_from_state`` is bitwise-equal to
    the monolithic closure's (asserted in tests/test_sched.py).
    """
    from .pallas_corr import _BLOCK_ROWS, _block_w1

    implementation = resolve_implementation(implementation, quant)
    if implementation == "reg":
        volume = _build_volume(fmap1, fmap2, jnp.float32, precision, quant)
        return tuple(build_corr_pyramid(volume, num_levels))
    if implementation == "alt":
        return ((fmap1.astype(jnp.float32),)
                + tuple(build_fmap2_pyramid(fmap2.astype(jnp.float32),
                                            num_levels)))
    if implementation == "pallas":
        volume = _build_volume(fmap1, fmap2, dtype, precision, quant)
        pyr = build_corr_pyramid(volume, num_levels)
        b, h, w1 = pyr[0].shape[:3]
        hp = _roundup(h, _BLOCK_ROWS)
        w1p = _roundup(w1, _block_w1(w1))
        w2s = _padded_level_widths(w1, num_levels)
        vcat = jnp.concatenate(
            [jnp.pad(v, ((0, 0), (0, hp - h), (0, w1p - w1),
                         (0, w2s[i] - v.shape[3])))
             for i, v in enumerate(pyr)], axis=3)
        return (vcat,)
    if implementation == "pallas_alt":
        # astype before the pack: elementwise, so the order swap vs
        # make_pallas_alt_corr_fn's construct() is exact.
        f1 = fmap1.astype(jnp.float32).astype(dtype)
        f2p = [x.astype(dtype) for x in
               build_fmap2_pyramid(fmap2.astype(jnp.float32), num_levels)]
        b, h, w1 = f1.shape[:3]
        hp = _roundup(h, _BLOCK_ROWS)
        w1p = _roundup(w1, _block_w1(w1))
        w2s = _padded_level_widths(w1, num_levels)
        f1p = _pack_state_rows(f1, hp, 2, w1p)
        f2cat = jnp.concatenate(
            [_pack_state_rows(f2, hp, 2, w2s[i])
             for i, f2 in enumerate(f2p)], axis=2)
        return (f1p, f2cat)
    raise ValueError(f"unknown corr implementation: {implementation}")


def corr_fn_from_state(implementation: str, state: Sequence[jax.Array],
                       num_levels: int, radius: int,
                       precision: str = "highest", out_dtype=jnp.float32,
                       out_channels: int = 0, epilogue=None,
                       quant: bool = False) -> CorrFn:
    """Rebuild a lookup function over ``build_corr_state`` output.

    Static parameters (radius/precision/out_*/epilogue/quant) are passed
    per call — the state itself is a pure array pytree, so it can live on
    device between step executables.  Semantics match ``make_corr_fn``
    for the same backend (the epilogue/out_channels knobs are honored
    exactly where that function honors them: pallas_alt only).  ``quant``
    only steers implementation resolution — the state arrays are already
    the DEQUANTIZED volume pyramid, so the lookups are the stock ones.
    """
    implementation = resolve_implementation(implementation, quant)
    if implementation == "reg":
        pyramid = tuple(state)
        fn = lambda coords: _reg_lookup(pyramid, radius, coords)  # noqa: E731
    elif implementation == "alt":
        f1, f2p = state[0], tuple(state[1:])
        fn = lambda coords: _alt_lookup(f1, f2p, radius, precision,  # noqa: E731
                                        coords)
    elif implementation == "pallas":
        from .pallas_corr import pallas_lookup_pyramid_flat
        (vcat4,) = state     # (B, Hp, W1p, sum(w2s)) — pre-packed
        offsets = _tap_offsets(radius)

        def fn(coords):
            x = coords[..., 0].astype(jnp.float32)
            b, h, w1 = x.shape
            hp = vcat4.shape[1]
            w2s = _padded_level_widths(w1, num_levels)
            assert sum(w2s) == vcat4.shape[3], (w2s, vcat4.shape)
            taps = jnp.concatenate(
                [x[..., None] / (2.0 ** i) + offsets
                 for i in range(len(w2s))], axis=-1)
            if hp != h:   # row pad mirrors the packed state's
                taps = jnp.pad(taps, ((0, 0), (0, hp - h), (0, 0), (0, 0)))
            # Merging the leading (B, Hp) axes is a free row-major
            # reinterpretation — the only per-lookup "relayout" left.
            vflat = vcat4.reshape((-1,) + vcat4.shape[2:])
            out = pallas_lookup_pyramid_flat(vflat, taps, w2s)
            return out[:, :h] if hp != h else out
    elif implementation == "pallas_alt":
        from .pallas_alt import (pallas_alt_pyramid_radial_epi_flat,
                                 pallas_alt_pyramid_radial_flat)
        f1p4, f2cat4 = state  # (B, Hp, W1p, C), (B, Hp, sum(w2s), C)
        scales = tuple(1.0 / 2.0 ** i for i in range(num_levels))
        epi = None
        if epilogue is not None:
            epi = (epilogue["kernel"][0, 0].astype(out_dtype),
                   epilogue["bias"].reshape(1, 1, -1).astype(out_dtype))

        def fn(coords):
            x = coords[..., 0].astype(jnp.float32)
            b, h, w1 = x.shape
            hp = f1p4.shape[1]
            w2s = _padded_level_widths(w1, num_levels)
            assert sum(w2s) == f2cat4.shape[2], (w2s, f2cat4.shape)
            xl = x[..., None]
            if hp != h:
                xl = jnp.pad(xl, ((0, 0), (0, hp - h), (0, 0), (0, 0)))
            f1flat = f1p4.reshape((-1,) + f1p4.shape[2:])
            f2cat = f2cat4.reshape((-1,) + f2cat4.shape[2:])
            if epi is not None:
                out = pallas_alt_pyramid_radial_epi_flat(
                    f1flat, f2cat, xl, w2s, radius, epi[0], epi[1],
                    precision=precision, out_dtype=out_dtype,
                    level_scales=scales)
            else:
                out = pallas_alt_pyramid_radial_flat(
                    f1flat, f2cat, xl, w2s, radius, precision=precision,
                    out_dtype=out_dtype, out_channels=out_channels,
                    level_scales=scales)
            return out[:, :h] if hp != h else out
        return fn
    else:
        raise ValueError(f"unknown corr implementation: {implementation}")
    if jnp.dtype(out_dtype) == jnp.float32:
        return fn
    return lambda coords: fn(coords).astype(out_dtype)


def make_corr_fn(implementation: str, fmap1: jax.Array, fmap2: jax.Array,
                 num_levels: int, radius: int, dtype=jnp.float32,
                 precision: str = "highest", out_dtype=jnp.float32,
                 out_channels: int = 0, epilogue=None,
                 quant: bool = False) -> CorrFn:
    """Backend dispatch (reference: core/raft_stereo.py:90-100).

    ``auto`` resolves to the fastest backend for the active platform: the
    on-demand Pallas kernel on TPU (fastest measured AND O(H*W) memory),
    the XLA gather path elsewhere (the Pallas kernels are TPU-tuned; their
    interpret mode is for correctness tests, not speed).

    ``out_dtype`` is the dtype of the returned correlation features.  The
    lookup math is identical (fp32 accumulation everywhere); a bf16 model
    requests bf16 directly so the Pallas kernel emits it and the
    post-lookup convert + HBM round trip disappear from the loop.

    ``out_channels`` (> num_levels*(2r+1)) asks the pallas_alt backend to
    zero-pad the channel axis in-kernel to a lane-friendly width; other
    backends return the natural width (consumers must accept both — the
    motion encoder's padded 1x1 conv does).

    ``quant`` swaps the volume construction for the int8-quantized
    product (ops/quant.py) and forces a precomputed-volume backend (see
    ``resolve_implementation``) — lookups over the dequantized volume
    are the stock ones, so monolithic, stream and phase-split callers
    all share the same quantized numerics."""
    implementation = resolve_implementation(implementation, quant)
    if implementation == "reg":
        fn = make_reg_corr_fn(fmap1, fmap2, num_levels, radius,
                              dtype=jnp.float32, precision=precision,
                              quant=quant)
    elif implementation == "alt":
        fn = make_alt_corr_fn(fmap1, fmap2, num_levels, radius,
                              precision=precision)
    elif implementation == "pallas":
        fn = make_pallas_corr_fn(fmap1, fmap2, num_levels, radius,
                                 dtype=dtype, precision=precision,
                                 quant=quant)
    elif implementation == "pallas_alt":
        return make_pallas_alt_corr_fn(fmap1, fmap2, num_levels, radius,
                                       dtype=dtype, precision=precision,
                                       out_dtype=out_dtype,
                                       out_channels=out_channels,
                                       epilogue=epilogue)
    else:
        raise ValueError(f"unknown corr implementation: {implementation}")
    if jnp.dtype(out_dtype) == jnp.float32:
        return fn
    return lambda coords: fn(coords).astype(out_dtype)
