"""Int8 quantized correlation + the serving accuracy-tier vocabulary.

The round-5 perf work left the GRU/head convs at the measured MXU ceiling
(docs/perf_notes_r05.md), so the remaining arithmetic-intensity lever is
precision.  This module supplies the numeric core of the quantized serving
fast path (docs/perf_notes_r07.md):

* **symmetric int8 row quantization** of the left/right feature maps.  One
  scale per correlation ROW (each (b, h, w) feature vector — the matmul
  row/column of the all-pairs product), NOT per contraction channel: a
  per-channel scale sits inside the channel sum and cannot be pulled out
  of the int32 accumulator, while per-row scales factor exactly —
  ``corr[w, v] = s1[w] * s2[v] * sum_c q1[w, c] * q2[v, c]`` — which is
  what lets the dequant run as a cheap epilogue on the int32 output.
* **int8 x int8 -> int32 all-pairs correlation** with that dequant
  epilogue, as a plain XLA einsum (CPU + fallback) and as a Pallas TPU
  kernel (MXU-native int8 pass, 4x the bf16 multiply rate).  Both paths
  apply the identical epilogue expression, so the kernel is
  bitwise-comparable to the XLA path in interpret mode
  (tests/test_quant.py, mirroring tests/test_pallas_gru.py).
* **the accuracy-tier vocabulary** shared by the serving engine, the
  certification harness (eval/certify.py) and the HTTP layer:
  per-request ``accuracy`` tiers resolve to a *precision mode* that joins
  every executable cache key (serve/engine.py):

      certified -> fp32   (the certified-parity path: fp32 everywhere)
      fast      -> bf16   (bf16 encoders/GRU + bf16 correlation)
      turbo     -> int8   (bf16 compute + int8-quantized correlation)

The quantization error is the int8 rounding only — the epilogue algebra
is exact (asserted bit-for-bit on exactly-representable inputs in
tests/test_quant.py).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _COMPILER_PARAMS, _interpret

__all__ = ["MODES", "TIERS", "TIER_MODES", "config_for_mode",
           "default_mode", "mode_for_accuracy", "pallas_int8_corr_volume",
           "quant_corr_volume", "quantize_rows"]


# --------------------------------------------------------------------- tiers

# Request-facing tier names, in decreasing accuracy order.
TIERS = ("certified", "fast", "turbo")

# tier -> the precision mode that joins the executable cache key.
TIER_MODES = {"certified": "fp32", "fast": "bf16", "turbo": "int8"}

# Every precision mode an engine can compile (the cache-key component).
MODES = ("fp32", "bf16", "int8")


def mode_for_accuracy(accuracy: str) -> str:
    """Precision mode for a request's ``accuracy`` tier; raises
    ``ValueError`` on an unknown tier (HTTP 400 at the front-end)."""
    try:
        return TIER_MODES[accuracy]
    except KeyError:
        raise ValueError(
            f"unknown accuracy tier {accuracy!r}; choose from "
            f"{list(TIERS)}") from None


def default_mode(config) -> str:
    """The precision-mode key component of a model config's OWN
    executables — the mode of every request that carries no ``accuracy``
    field, so the default path's executables (and numerics) are untouched
    by the tier system.

    A config aliases onto a tier mode ONLY when it is exactly that
    mode's canonical config (``config_for_mode`` round-trips) — then an
    explicit tier request may share the base executables.  Any other
    numeric mix (e.g. fp32 compute with a bf16 correlation volume)
    returns the distinct ``"base"`` token: its numerics match no
    certified tier, so e.g. ``accuracy="certified"`` must compile the
    true fp32 program rather than silently serving the base one."""
    if getattr(config, "corr_quant", False):
        mode = "int8"
    elif config.compute_dtype == "bfloat16":
        mode = "bf16"
    else:
        mode = "fp32"
    return mode if config_for_mode(config, mode) == config else "base"


def config_for_mode(config, mode: str):
    """The model config a precision mode compiles with: the ONLY fields a
    tier may change are the numeric-policy ones (compute/corr dtype and
    the int8-corr gate) — architecture, corr backend and GRU backend stay
    the base config's, so every tier shares the base model's weights and
    shape policy."""
    if mode == "fp32":
        return dataclasses.replace(config, compute_dtype="float32",
                                   corr_dtype="float32", corr_quant=False)
    if mode == "bf16":
        return dataclasses.replace(config, compute_dtype="bfloat16",
                                   corr_dtype="bfloat16", corr_quant=False)
    if mode == "int8":
        return dataclasses.replace(config, compute_dtype="bfloat16",
                                   corr_dtype="bfloat16", corr_quant=True)
    raise ValueError(f"unknown precision mode {mode!r}; choose from "
                     f"{list(MODES)}")


# -------------------------------------------------------------- quantization

def quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization with one scale per row (all leading
    axes; the LAST axis is the contraction/feature axis).

    Returns ``(q, scale)`` with ``q`` int8 in [-127, 127] and ``scale``
    fp32 of ``x.shape[:-1]`` such that ``q * scale[..., None] ~= x``.
    All-zero rows get scale 1.0 (and q == 0), so the dequant epilogue
    never divides by or multiplies with a zero scale."""
    f = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(f / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def _dequant_epilogue(acc: jax.Array, s1: jax.Array, s2: jax.Array,
                      c: int) -> jax.Array:
    """The ONE dequant expression both the XLA and the Pallas paths apply
    to the int32 accumulator — shared so the two are bitwise-comparable:
    ``(acc * (s1 (x) s2)) * (1/sqrt(C))`` with the same association.

    The 1/sqrt(C) normalization is a host-constant MULTIPLY, not a
    divide: XLA's algebraic simplifier rewrites division by a constant
    into multiplication by its reciprocal inside fused programs (e.g.
    the interpret-mode Pallas kernel) but not across eager op
    boundaries, so a divide here would make the two paths differ by an
    ULP.  A multiply is never rewritten — both paths compute identical
    bits.  When sqrt(C) is a power of two (the model's feature dim 256:
    sqrt = 16) the multiply is also bit-identical to
    ``build_corr_volume``'s division."""
    inv = np.float32(1.0) / np.float32(np.sqrt(np.float32(c)))
    deq = acc.astype(jnp.float32) * (s1[..., :, None] * s2[..., None, :])
    return deq * inv


def _int8_volume_xla(q1: jax.Array, s1: jax.Array, q2: jax.Array,
                     s2: jax.Array) -> jax.Array:
    """(B, H, W1, C) x (B, H, W2, C) int8 -> (B, H, W1, W2) fp32 via an
    int8 x int8 -> int32 einsum (XLA lowers this to the MXU's native int8
    pass on TPU and to integer GEMM on CPU) + the dequant epilogue."""
    acc = jnp.einsum("bhwc,bhvc->bhwv", q1, q2,
                     preferred_element_type=jnp.int32)
    return _dequant_epilogue(acc, s1, s2, q1.shape[-1])


# ------------------------------------------------------------- Pallas kernel

# (B*H) rows per grid step — same amortization rationale as
# pallas_corr._BLOCK_ROWS (per-step Mosaic/DMA overhead dominates
# one-row grids).
_BLOCK_ROWS = 8
_LANE = 128


def _roundup(x: int, m: int) -> int:
    return -(-x // m) * m


def _int8_volume_kernel(q1_ref, q2_ref, s1_ref, s2_ref, out_ref, *, c: int):
    """One R-row block: int8 x int8 -> int32 batched matmul on the MXU,
    dequant epilogue on the VPU.  ``c`` is the REAL (unpadded) channel
    count — the epilogue's 1/sqrt(C); padded channels are zero on both
    operands and contribute exactly nothing to the accumulator."""
    q1 = q1_ref[...]                       # (R, W1p, Cp) int8
    q2 = q2_ref[...]                       # (R, W2p, Cp) int8
    acc = jax.lax.dot_general(
        q1, q2, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.int32)  # (R, W1p, W2p)
    s1 = s1_ref[...].astype(jnp.float32)   # (R, W1p)
    s2 = s2_ref[...].astype(jnp.float32)   # (R, W2p)
    out_ref[...] = _dequant_epilogue(acc, s1, s2, c).astype(out_ref.dtype)


def _pad_axis(x: jax.Array, axis: int, to: int) -> jax.Array:
    pad = to - x.shape[axis]
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def pallas_int8_corr_volume(q1: jax.Array, s1: jax.Array, q2: jax.Array,
                            s2: jax.Array,
                            out_dtype=jnp.float32) -> jax.Array:
    """Pallas form of :func:`_int8_volume_xla`: (B, H, W1, C) x
    (B, H, W2, C) int8 -> (B, H, W1, W2) ``out_dtype``.

    Grid is row blocks of the flattened (B*H) axis; operands are
    zero-padded to int8-friendly tiles (channels and W2 to lane
    multiples) — padded channels are zero on both sides (accumulate to
    exactly 0) and padded rows/columns carry scale 0 and are sliced off,
    so padding is numerically invisible.  Interpret mode runs the same
    program on CPU (tests/test_quant.py asserts bitwise equality with
    the XLA einsum path there)."""
    b, h, w1, c = q1.shape
    w2 = q2.shape[2]
    assert q2.shape[:2] == (b, h) and q2.shape[3] == c, (q1.shape, q2.shape)
    assert s1.shape == (b, h, w1) and s2.shape == (b, h, w2), (
        s1.shape, s2.shape)
    n = b * h
    cp = _roundup(c, _LANE)
    # W1 is lane-padded too (not just sublane-padded): it is the LAST
    # axis of the s1 scale block, and Mosaic wants lane-dim tiles.
    w1p = _roundup(w1, _LANE)
    w2p = _roundup(w2, _LANE)
    npad = _roundup(n, _BLOCK_ROWS)
    r = _BLOCK_ROWS

    def prep_q(q, wp):
        q = q.reshape(n, q.shape[2], c)
        q = _pad_axis(_pad_axis(q, 1, wp), 2, cp)
        return _pad_axis(q, 0, npad)

    def prep_s(s, wp):
        s = s.reshape(n, s.shape[2])
        return _pad_axis(_pad_axis(s, 1, wp), 0, npad)

    q1f, q2f = prep_q(q1, w1p), prep_q(q2, w2p)
    s1f, s2f = prep_s(s1, w1p), prep_s(s2, w2p)
    out = pl.pallas_call(
        functools.partial(_int8_volume_kernel, c=c),
        out_shape=jax.ShapeDtypeStruct((npad, w1p, w2p), out_dtype),
        grid=(npad // r,),
        in_specs=[
            pl.BlockSpec((r, w1p, cp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w2p, cp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w1p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w2p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, w1p, w2p), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(q1f, q2f, s1f, s2f)
    return out[:n, :w1, :w2].reshape(b, h, w1, w2)


# ---------------------------------------------------------------- public API

def quant_corr_volume(fmap1: jax.Array, fmap2: jax.Array,
                      dtype=jnp.float32,
                      kernel: Optional[bool] = None) -> jax.Array:
    """Quantized drop-in for ``ops/corr.build_corr_volume``: symmetric
    per-row int8 quantization of both feature maps, int8 x int8 -> int32
    all-pairs product, scales folded into the dequant epilogue
    (mathematically ``build_corr_volume`` up to the int8 rounding of the
    inputs — the epilogue itself is exact algebra).

    ``kernel``: None = the Pallas kernel on TPU backends, the XLA einsum
    elsewhere; True/False pin one path (tests pin True to run the kernel
    in interpret mode on CPU).  ``dtype`` is the emitted volume dtype,
    same contract as ``build_corr_volume``."""
    if kernel is None:
        kernel = jax.default_backend() == "tpu"
    q1, s1 = quantize_rows(fmap1)
    q2, s2 = quantize_rows(fmap2)
    if kernel:
        return pallas_int8_corr_volume(q1, s1, q2, s2, out_dtype=dtype)
    return _int8_volume_xla(q1, s1, q2, s2).astype(dtype)
