"""Pallas TPU instance-norm: layout-preserving stats + apply kernels.

Why this exists (measured, scripts/mb_encoder.py + the device trace in
docs/perf_notes_r03.md): at the feature encoder's hot shape
(272x480x64 bf16) EVERY XLA formulation of the cross-(H,W) reduction —
lane-packed view, direct reduce, fp32 reduce, even MXU ones-vector
matmuls — costs 4-11 ms per norm, 50-100x its ~80 us bandwidth floor,
because each forces layout transitions against the surrounding convs'
blocked layouts (the [544,2,8,123,64]-style "data formatting" storm in the
trace).  A Pallas kernel reads the conv output in its natural row-major
(B, H, W, C) form: pass 1 accumulates per-(B, C) sum / sum-of-squares in
fp32 across a sequential row-block grid, pass 2 normalizes (optionally
fusing the following relu).  Three streaming passes over the tensor,
no reshapes anywhere.

Semantics match models.layers.InstanceNorm (torch InstanceNorm2d, no
affine, eps 1e-5; reference: core/extractor.py:29): per-image,
per-channel statistics over (H, W).  Statistics are fp32 (MXU-grade
accumulation — tighter than the bf16 tree reduces of the XLA form).

Backward: the XLA instance-norm's VJP, via jax.custom_vjp re-linearizing
the reference formulation — the backward pass keeps its current cost;
this kernel targets the inference/fixed-stage time where the 20+ ms lived.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_corr import _COMPILER_PARAMS, _interpret


def _row_block(h: int, cap: int = 32) -> int:
    """Largest power-of-two divisor of ``h`` up to ``cap`` (encoder heights
    are multiples of 16 at flagship shapes; odd heights degrade gracefully)."""
    r = 1
    while r < cap and h % (r * 2) == 0:
        r *= 2
    return r


def _in_stats_kernel(x_ref, s1_ref, s2_ref):
    """Accumulate per-(image, channel) sum and sum-of-squares in fp32.
    Grid (B, H/R) iterates row-blocks innermost; TPU grids are sequential,
    so the b-th output block is initialized at its first row-block and
    accumulated across the rest (same pattern as pallas_alt's df2)."""

    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref[...])
        s2_ref[...] = jnp.zeros_like(s2_ref[...])

    x = x_ref[...].astype(jnp.float32)                  # (1, R, W, C)
    # Stats blocks are (1, 1, C): Mosaic requires the last two block dims
    # to divide (8, 128) or equal the array dims — (1, C) of a (B, 1, C)
    # array satisfies that for any C.
    s1_ref[...] += jnp.sum(x, axis=(1, 2))[:, None, :]  # (1, 1, C)
    s2_ref[...] += jnp.sum(x * x, axis=(1, 2))[:, None, :]


def _in_apply_kernel(x_ref, m_ref, s_ref, o_ref, *, relu):
    x = x_ref[...]                                # (1, R, W, C)
    m = m_ref[...][:, :, None, :].astype(x.dtype)   # (1, 1, C) -> broadcast
    s = s_ref[...][:, :, None, :].astype(x.dtype)
    y = (x - m) * s
    if relu:
        y = jnp.maximum(y, 0)
    o_ref[...] = y.astype(o_ref.dtype)


def _xla_instance_norm(x, relu):
    """Reference XLA formulation (models.layers.InstanceNorm semantics) —
    used for the backward linearization and as the non-TPU path's oracle."""
    m = jnp.mean(x.astype(jnp.float32), axis=(1, 2), keepdims=True)
    c = x.astype(jnp.float32) - m
    v = jnp.mean(jnp.square(c), axis=(1, 2), keepdims=True)
    y = (c * jax.lax.rsqrt(v + 1e-5)).astype(x.dtype)
    return jnp.maximum(y, 0) if relu else y


def _pallas_forward(x, relu):
    b, h, w, c = x.shape
    r = _row_block(h)
    grid = (b, h // r)
    s1, s2 = pl.pallas_call(
        _in_stats_kernel,
        out_shape=(jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, c), jnp.float32)),
        grid=grid,
        in_specs=[pl.BlockSpec((1, r, w, c), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(x)
    n = jnp.float32(h * w)
    mean = s1 / n
    # E[x^2] - m^2 in fp32: with bf16 inputs the input quantization
    # (~3e-3 relative) dominates any fp32 cancellation; clamped for the
    # pathological all-constant case.  Measured fp32 envelope
    # (tests/test_pallas_encoder.py::TestStatsPrecisionEnvelope): rstd
    # error < 1e-4 at |mean|/std=10, < 1% at |mean|/std=100 — encoder
    # activations stay under ~10; a centered second pass would cost a
    # full extra HBM read of the tensor for precision no consumer needs.
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + 1e-5)
    return pl.pallas_call(
        functools.partial(_in_apply_kernel, relu=relu),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, r, w, c), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, w, c), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
        compiler_params=_COMPILER_PARAMS,
    )(x, mean, rstd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def instance_norm_act(x: jax.Array, relu: bool = False) -> jax.Array:
    """Instance norm (optionally fused with relu) via the Pallas kernels."""
    return _pallas_forward(x, relu)


def _fwd(x, relu):
    return _pallas_forward(x, relu), x


def _bwd(relu, x, g):
    _, vjp = jax.vjp(lambda a: _xla_instance_norm(a, relu), x)
    return (vjp(g)[0],)


instance_norm_act.defvjp(_fwd, _bwd)
