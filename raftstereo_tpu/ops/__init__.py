"""TPU-first primitive ops: sampling, resizing, pooling, correlation, upsampling."""

from .image import (InputPadder, avg_pool2x, avg_pool4x, avg_pool_w2,
                    coords_grid_x, forward_interpolate, gauss_blur,
                    replicate_pad, resize_bilinear_align_corners)
from .sampler import linear_sample_1d, linear_sample_1d_dense
from .upsample import convex_upsample, extract_3x3_patches, upsample_interp
from .corr import (build_corr_pyramid, build_corr_volume,
                   build_fmap2_pyramid, make_alt_corr_fn, make_corr_fn,
                   make_pallas_alt_corr_fn, make_reg_corr_fn)

__all__ = [
    "InputPadder", "avg_pool2x", "avg_pool4x", "avg_pool_w2", "coords_grid_x",
    "forward_interpolate", "gauss_blur", "replicate_pad",
    "resize_bilinear_align_corners",
    "linear_sample_1d", "linear_sample_1d_dense",
    "convex_upsample", "extract_3x3_patches", "upsample_interp",
    "build_corr_pyramid", "build_corr_volume", "build_fmap2_pyramid",
    "make_alt_corr_fn", "make_corr_fn", "make_pallas_alt_corr_fn",
    "make_reg_corr_fn",
]
