"""TPU-first primitive ops: sampling, resizing, pooling, correlation,
upsampling — plus the stdlib-only ``autoscale`` recommendation loop.

Lazy (PEP 562) exports: importing this package must stay cheap so the
model-free surfaces (cli.router, serve/cluster/router.py, ops/autoscale
consumers) never drag in jax — every kernel submodule imports jax at
module scope.  ``from raftstereo_tpu.ops import X`` works unchanged; the
submodule is imported on first attribute access.
"""

import importlib

_EXPORTS = {
    "InputPadder": ".image",
    "avg_pool2x": ".image",
    "avg_pool4x": ".image",
    "avg_pool_w2": ".image",
    "coords_grid_x": ".image",
    "forward_interpolate": ".image",
    "gauss_blur": ".image",
    "replicate_pad": ".image",
    "resize_bilinear_align_corners": ".image",
    "linear_sample_1d": ".sampler",
    "linear_sample_1d_dense": ".sampler",
    "convex_upsample": ".upsample",
    "extract_3x3_patches": ".upsample",
    "upsample_interp": ".upsample",
    "build_corr_pyramid": ".corr",
    "build_corr_volume": ".corr",
    "build_fmap2_pyramid": ".corr",
    "make_alt_corr_fn": ".corr",
    "make_corr_fn": ".corr",
    "make_pallas_alt_corr_fn": ".corr",
    "make_reg_corr_fn": ".corr",
    "Autoscaler": ".autoscale",
    "AutoscalePolicy": ".autoscale",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        rel = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(rel, __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
