"""Model zoo: RAFT-Stereo and its building blocks."""

from .encoders import BasicEncoder, MultiBasicEncoder
from .layers import BottleneckBlock, ResidualBlock
from .raft_stereo import ContextZQR, RAFTStereo, SharedBackboneHead, count_parameters
from .update import (BasicMotionEncoder, BasicMultiUpdateBlock, ConvGRU,
                     FlowHead, SepConvGRU)

__all__ = [
    "BasicEncoder", "MultiBasicEncoder", "BottleneckBlock", "ResidualBlock",
    "ContextZQR", "RAFTStereo", "SharedBackboneHead", "count_parameters",
    "BasicMotionEncoder", "BasicMultiUpdateBlock", "ConvGRU", "FlowHead",
    "SepConvGRU",
]
