"""Iterative refinement: motion encoder + multilevel ConvGRU stack + heads.

Capability mirror of the reference's update module (reference: core/update.py),
NHWC + flax.linen.  Differences by design:

* The GRU context biases (cz, cr, cq) are precomputed once outside the loop
  (reference does the same: core/raft_stereo.py:32,88) and passed in.
* Disparity is carried as a single channel; the 2-channel flow the motion
  encoder expects (its 7x7 conv has 2 input channels) is materialised with a
  zero y channel, preserving converted-weight compatibility while halving the
  recurrent flow state.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config import RAFTStereoConfig
from ..ops.image import avg_pool2x, resize_bilinear_align_corners
from .layers import conv, kaiming_out


# Tri-state override of the tap-matmul head gate (tests force both paths).
tap_head_override = None


def _use_tap_head() -> bool:
    """The tap-matmul form of the narrow 3x3 head conv is a TPU fix (N=2
    output channels waste the MXU's 128 N-lanes — measured 3.5 TF/s,
    costing as much as a 256->128 conv; docs/perf_notes_r03.md).  CPU/GPU
    keep the plain conv.  The tap combination has two epilogues chosen by
    per-shard batch inside tap_conv3x3 (both A/B-measured, the tap form
    wins at every batch size with the right epilogue)."""
    if tap_head_override is not None:
        return tap_head_override
    return jax.default_backend() == "tpu"


def _local_batch(batch: int) -> int:
    from ..parallel.context import active_corr_mesh
    from ..parallel.mesh import DATA_AXIS

    mesh = active_corr_mesh()
    if mesh is not None:  # per-shard batch, like the conv1 gate
        batch = max(1, batch // mesh.shape.get(DATA_AXIS, 1))
    return batch


def tap_conv3x3(conv_mod, y):
    """A bound SAME-padded 3x3 nn.Conv with FEW output channels, computed
    as one 1x1 matmul into kh*kw*co per-tap channels + a tiny constant
    SELECTOR conv that shifts-and-sums the taps.

    o[p] = sum_t K[t] . y[p + t - 1]  ==  sum_t z_t[p + t - 1] where
    z_t = y . K[t] is pointwise — so one (ci -> 9*co) matmul (padded to a
    full MXU N-tile instead of 2/128 lanes) replaces the narrow conv.
    Two epilogues combine the taps, chosen by per-shard batch
    (alternating same-process A/Bs, docs/perf_notes_r04.md):

    * batch <= 2: 9 shifted adds of the 28x-smaller z (batch 1
      9.80 -> 10.45 pairs/sec vs plain; realtime +2.8%; the selector
      conv's launch overhead costs ~10% at realtime's tiny spatial);
    * batch > 2: a 3x3 conv with CONSTANT block-identity weights
      S[dy, dx, tin, c] = [tin == (dy*3+dx)*co + c] (batch 8
      12.58/12.69 -> 12.71/12.90 vs plain; the co=2 strided slices of
      the other epilogue are lane-hostile at batch amortization)."""
    _assert_default_conv_geometry(conv_mod)
    p = conv_mod.variables["params"]
    k = p["kernel"]
    kh, kw, ci, co = k.shape
    assert (kh, kw) == (3, 3), (kh, kw)
    assert tuple(conv_mod.padding) == ((1, 1), (1, 1)), conv_mod.padding
    w = k.transpose(2, 0, 1, 3).reshape(ci, kh * kw * co).astype(y.dtype)
    z = jnp.tensordot(y, w, 1)
    if _local_batch(y.shape[0]) <= 2:
        zp = jnp.pad(z, ((0, 0), (1, 1), (1, 1), (0, 0)))
        h, wd = y.shape[1], y.shape[2]
        o = None
        for t in range(kh * kw):
            dy, dx = divmod(t, kw)
            s = zp[:, dy:dy + h, dx:dx + wd, t * co:(t + 1) * co]
            o = s if o is None else o + s
        return o + p["bias"].astype(y.dtype)
    sel = np.zeros((kh, kw, kh * kw * co, co), np.float32)
    for t in range(kh * kw):
        dy, dx = divmod(t, kw)
        for c in range(co):
            # lax.conv is cross-correlation: tap (a, b) reads
            # in[p + (a-1, b-1)], and o[p] needs z_t[p + (dy-1, dx-1)].
            sel[dy, dx, t * co + c, c] = 1.0
    # HIGHEST for fp32 inputs: the selector's weights are exact 0/1 and its
    # output feeds the certified-parity delta-flow, so the default-precision
    # bf16 pass would round the taps once more than the plain conv (the
    # batch<=2 shift-add epilogue has no such extra rounding).  co=2 makes
    # the fp32 multiply passes free; bf16 inputs keep the default.
    prec = (jax.lax.Precision.HIGHEST if y.dtype == jnp.float32 else None)
    o = jax.lax.conv_general_dilated(
        z, jnp.asarray(sel, y.dtype), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"), precision=prec)
    return o + p["bias"].astype(y.dtype)


class FlowHead(nn.Module):
    """3x3 conv -> relu -> 3x3 conv (reference: core/update.py:6-14).
    Output stays 2-channel for weight parity; the model uses channel 0."""

    hidden_dim: int = 256
    output_dim: int = 2
    dtype: Any = jnp.float32

    def setup(self):
        self.conv1 = conv(self.hidden_dim, 3, dtype=self.dtype)
        self.conv2 = conv(self.output_dim, 3, dtype=self.dtype)

    def __call__(self, x):
        y = nn.relu(self.conv1(x))
        if self.is_initializing() or not _use_tap_head():
            return self.conv2(y)
        return tap_conv3x3(self.conv2, y)

    def from_hidden(self, y):
        """Head output from an already-computed relu(conv1(x)) activation
        (the merged-head path in BasicMultiUpdateBlock)."""
        if _use_tap_head():
            return tap_conv3x3(self.conv2, y)
        return self.conv2(y)


def _assert_default_conv_geometry(conv_mod):
    """Fail loudly if a wrapped nn.Conv ever stops being a stride-1,
    undilated, default-precision conv — the fast paths below re-implement
    exactly that geometry and would otherwise silently diverge."""
    def _pair(v):
        return (v, v) if v is None or isinstance(v, int) else tuple(v)

    assert _pair(conv_mod.strides) in ((1, 1), (None, None)), conv_mod.strides
    assert _pair(conv_mod.kernel_dilation) in ((1, 1), (None, None)), \
        conv_mod.kernel_dilation
    assert conv_mod.precision is None, conv_mod.precision
    assert conv_mod.feature_group_count == 1


def _sliced_conv(conv_mod, x, lo, hi, bias=True):
    """Apply a bound nn.Conv on an input-channel SLICE of its kernel:
    out = conv(x; kernel[:, :, lo:hi]) (+ bias).  Summing the slices over
    a channel partition equals the conv of the concatenated input.

    Assumes the wrapped conv's default geometry/precision — asserted so a
    future nn.Conv change fails loudly instead of silently diverging.
    No ``preferred_element_type``, matching the flax path it replaces: in
    bf16 mode both emit bf16 gate pre-activations (MXU-internal fp32
    accumulation, rounded at the output) — intentional, covered by the
    bf16 torch-parity configs in tests/test_torch_parity.py."""
    _assert_default_conv_geometry(conv_mod)
    p = conv_mod.variables["params"]
    k = p["kernel"][:, :, lo:hi]
    pad = conv_mod.padding
    y = jax.lax.conv_general_dilated(
        x, k.astype(x.dtype), (1, 1), pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if bias and "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


class ConvGRU(nn.Module):
    """Conv gated recurrent unit with external context biases
    (reference: core/update.py:16-32).  Concat order [h, x] and [r*h, x]
    is preserved for checkpoint conversion.

    The z and r gates read the same input, so their convs (the reference's
    separate ``convz``/``convr``) are one fused conv producing 2*hidden
    channels — per output channel the arithmetic is identical (the fusion
    only concatenates along the *output* axis), so converted checkpoints
    stay bit-compatible; the converter concatenates the torch weights
    (utils/convert.py).  One fewer HBM read of ``hx`` per GRU per iteration.
    ``convq``'s input differs (r gates h first) and stays separate."""

    hidden_dim: int
    kernel_size: int = 3
    dtype: Any = jnp.float32

    def setup(self):
        k = self.kernel_size

        def split_fan_out_init(key, shape, dtype=jnp.float32):
            # From-scratch init must match the reference's two SEPARATE
            # kaiming fan_out convs (core/extractor.py:155-162 semantics):
            # per-gate fan_out is hidden*k*k, not the fused 2*hidden*k*k —
            # plain kaiming on the fused shape would under-scale by sqrt(2).
            kh, kw, _, o = shape
            std = (2.0 / (o // 2 * kh * kw)) ** 0.5
            return std * jax.random.normal(key, shape, dtype)

        self.convzr = nn.Conv(2 * self.hidden_dim, (k, k),
                              padding=((k // 2, k // 2), (k // 2, k // 2)),
                              kernel_init=split_fan_out_init,
                              dtype=self.dtype, name="convzr")
        self.convq = conv(self.hidden_dim, k, dtype=self.dtype)

    def __call__(self, h, cz, cr, cq, *x_list):
        hd = self.hidden_dim
        x = jnp.concatenate(x_list, axis=-1)
        if self.is_initializing():
            # Plain concat form once, so the parameter tree is the
            # reference-compatible fused-input conv.
            zr = self.convzr(jnp.concatenate([h, x], axis=-1))
            z = nn.sigmoid(zr[..., :hd] + cz)
            r = nn.sigmoid(zr[..., hd:] + cr)
            q = nn.tanh(self.convq(jnp.concatenate([r * h, x], axis=-1)) + cq)
            return (1 - z) * h + z * q
        # Apply each conv as two kernel-sliced convs instead of
        # materializing the [h, x] concats: kernel[:, :, :hd] convolves h,
        # kernel[:, :, hd:] convolves x, summed — arithmetically identical
        # (a conv is linear in its input channels), parameters unchanged.
        # The concats are real HBM round trips inside the scan loop
        # (~1.3 ms/iter at batch 8, profiled — docs/perf_notes_r03.md).
        zr = (_sliced_conv(self.convzr, h, 0, hd, bias=False)
              + _sliced_conv(self.convzr, x, hd, None))
        z = nn.sigmoid(zr[..., :hd] + cz)
        r = nn.sigmoid(zr[..., hd:] + cr)
        q = (_sliced_conv(self.convq, r * h, 0, hd, bias=False)
             + _sliced_conv(self.convq, x, hd, None))
        q = nn.tanh(q + cq)
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable (1x5 then 5x1) ConvGRU (reference: core/update.py:34-62;
    capability parity — unused by the default path)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        def c(name, kh, kw, ph, pw):
            return nn.Conv(self.hidden_dim, (kh, kw),
                           padding=((ph, ph), (pw, pw)), dtype=self.dtype,
                           name=name)
        self.convz1 = c("convz1", 1, 5, 0, 2)
        self.convr1 = c("convr1", 1, 5, 0, 2)
        self.convq1 = c("convq1", 1, 5, 0, 2)
        self.convz2 = c("convz2", 5, 1, 2, 0)
        self.convr2 = c("convr2", 5, 1, 2, 0)
        self.convq2 = c("convq2", 5, 1, 2, 0)

    def __call__(self, h, *x_list):
        x = jnp.concatenate(x_list, axis=-1)
        for convz, convr, convq in ((self.convz1, self.convr1, self.convq1),
                                    (self.convz2, self.convr2, self.convq2)):
            hx = jnp.concatenate([h, x], axis=-1)
            z = nn.sigmoid(convz(hx))
            r = nn.sigmoid(convr(hx))
            q = nn.tanh(convq(jnp.concatenate([r * h, x], axis=-1)))
            h = (1 - z) * h + z * q
        return h


class PointwisePaddedConv(nn.Module):
    """1x1 conv whose PARAMETER keeps the declared ``in_features`` shape
    (checkpoint-compatible with the reference's conv) but whose input may
    arrive with extra trailing ZERO channels — the kernel is zero-padded
    to match at apply time, which is arithmetically identical.  Lets the
    Pallas corr backend emit a lane-friendly channel count (36 correlation
    lanes made the consuming fusion read at ~39 GB/s, measured
    60 us/iteration at flagship shapes — docs/perf_notes_r03.md)."""

    features: int
    in_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        k = self.param("kernel", kaiming_out,
                       (1, 1, self.in_features, self.features))
        b = self.param("bias", nn.initializers.zeros, (self.features,))
        pad = x.shape[-1] - self.in_features
        if pad:
            k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        x = x.astype(self.dtype)  # flax-Conv-style compute-dtype cast
        # No preferred_element_type: the MXU accumulates bf16 operands in
        # fp32 internally either way, and a fp32-typed OUTPUT from bf16
        # operands makes the conv's transpose ill-typed (cotangent fp32 vs
        # kernel bf16 — lax.conv requires matching dtypes), breaking every
        # bf16 backward through this op.  Cost: one bf16 rounding before
        # the bias add.
        y = jax.lax.conv_general_dilated(
            x, k.astype(self.dtype), (1, 1), ((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + b.astype(x.dtype)


class BasicMotionEncoder(nn.Module):
    """Fuses correlation features and current flow into 128 motion channels,
    the last 2 being the raw flow (reference: core/update.py:64-85).

    ``corr`` may arrive zero-channel-padded past ``cor_planes`` (the
    Pallas backend's lane-friendly emission); convc1 handles it with an
    unchanged parameter shape."""

    cor_planes: int
    dtype: Any = jnp.float32

    def setup(self):
        self.convc1 = PointwisePaddedConv(64, self.cor_planes,
                                          dtype=self.dtype)
        self.convc2 = conv(64, 3, dtype=self.dtype)
        self.convf1 = conv(64, 7, padding=3, dtype=self.dtype)
        self.convf2 = conv(64, 3, dtype=self.dtype)
        self.conv = conv(128 - 2, 3, dtype=self.dtype)

    def __call__(self, flow, corr, preact: bool = False):
        # ``preact``: corr already IS relu(convc1(raw_corr)) — the
        # pallas_alt lookup kernel's fused epilogue (ops/pallas_alt.py);
        # convc1's parameters are consumed by the kernel, not here.
        c1 = corr if preact else nn.relu(self.convc1(corr))
        cor = nn.relu(self.convc2(c1))
        if self.is_initializing() or self.dtype != jnp.bfloat16:
            f1 = self.convf1(flow)
        else:
            # Stereo flow's y channel is STRUCTURALLY zero — the model
            # builds flow = [d, 0] every iteration (raft_stereo.py step;
            # delta y is zeroed, flow_init folds into the 1-channel d) —
            # so the kernel's y input-slice only ever multiplies zeros.
            # Contract only the x slice: algebraically exact (the dropped
            # products are exact fp zeros; convf1's K halves 98 -> 49,
            # +0.5-0.7% b1 x3 alternating), but the compiled contraction
            # ORDER differs, so outputs shift at rounding level — gated to
            # bf16 compute; fp32 keeps the certified-parity conv form
            # (same policy as the corr epilogue, ops/pallas_alt.py).
            f1 = _sliced_conv(self.convf1, flow[..., :1], 0, 1)
        flo = nn.relu(self.convf2(nn.relu(f1)))
        # The [cor, flo] concat feeding self.conv measured FREE here —
        # slicing it like the GRU gates was a wash (alternating b1 pairs
        # 1.00/0.999; XLA fuses this concat into the conv read, unlike
        # the GRU's carry concats) — committed negative, keep the
        # reference form.
        out = nn.relu(self.conv(jnp.concatenate([cor, flo], axis=-1)))
        return jnp.concatenate([out, flow], axis=-1)


def _interp_to(x, dest):
    return resize_bilinear_align_corners(x, dest.shape[1:3])


class BasicMultiUpdateBlock(nn.Module):
    """Coupled multilevel GRU update (reference: core/update.py:97-138).

    Levels are indexed finest-first: net[0] is the 1/2^n_downsample state
    (the reference's net_list ordering, core/raft_stereo.py:84).  GRU call
    order is coarsest -> finest, with avg-pooled finer state and bilinearly
    upsampled coarser state as cross-level inputs.
    """

    config: RAFTStereoConfig
    dtype: Any = jnp.float32

    def setup(self):
        cfg = self.config
        hd = cfg.hidden_dims
        n = cfg.n_gru_layers
        self.encoder = BasicMotionEncoder(cfg.cor_planes, dtype=self.dtype)
        encoder_output_dim = 128
        # Input widths mirror reference wiring (core/update.py:104-106).
        self.gru0 = ConvGRU(hd[0], dtype=self.dtype)   # finest ("gru08")
        if n >= 2:
            self.gru1 = ConvGRU(hd[1], dtype=self.dtype)   # mid ("gru16")
        if n == 3:
            self.gru2 = ConvGRU(hd[2], dtype=self.dtype)   # coarsest ("gru32")
        self.flow_head = FlowHead(hidden_dim=256, output_dim=2, dtype=self.dtype)
        factor = cfg.factor
        self.mask_conv1 = conv(256, 3, dtype=self.dtype)
        self.mask_conv2 = conv(factor * factor * 9, 1, padding=0, dtype=self.dtype)

    def __call__(self, net: Sequence[jax.Array], inp: Sequence[Tuple],
                 corr: Optional[jax.Array] = None,
                 flow: Optional[jax.Array] = None,
                 iter0: bool = True, iter1: bool = True, iter2: bool = True,
                 update: bool = True, with_mask: bool = True,
                 corr_preact: bool = False):
        cfg = self.config
        n = cfg.n_gru_layers
        net = list(net)

        if n == 3 and iter2:
            net[2] = self.gru2(net[2], *inp[2], avg_pool2x(net[1]))
        if n >= 2 and iter1:
            if n > 2:
                net[1] = self.gru1(net[1], *inp[1], avg_pool2x(net[0]),
                                   _interp_to(net[2], net[1]))
            else:
                net[1] = self.gru1(net[1], *inp[1], avg_pool2x(net[0]))
        if iter0:
            motion_features = self.encoder(flow, corr, preact=corr_preact)
            if n > 1:
                net[0] = self.gru0(net[0], *inp[0], motion_features,
                                   _interp_to(net[1], net[0]))
            else:
                net[0] = self.gru0(net[0], *inp[0], motion_features)

        if not update:
            return net

        if with_mask and not self.is_initializing():
            # Train mode: flow_head.conv1 and mask_conv1 are both 3x3
            # 128->256 convs on net[0]; one merged 128->512 conv (kernels
            # concatenated along the output axis — per-channel arithmetic
            # unchanged, parameters untouched) halves the net[0] HBM reads
            # and conv launches in the loop body.
            y = self._merged_head_hidden(net[0])
            hd = self.flow_head.hidden_dim
            delta = self.flow_head.from_hidden(y[..., :hd])
            mask = 0.25 * self.mask_conv2(y[..., hd:])
            return net, mask, delta

        delta = self.flow_head(net[0])
        if not with_mask:
            # Test-mode scan bodies skip the mask head: only the FINAL
            # iteration's mask is consumed, and it depends only on net[0],
            # so the model computes it once after the loop (upsample_mask)
            # — measured ~0.18 ms/iter of conv + f32 cast + carry traffic
            # at flagship shapes (docs/perf_notes_r03.md).
            return net, None, delta
        return net, self.upsample_mask(net[0]), delta

    def _merged_head_hidden(self, net0: jax.Array) -> jax.Array:
        """relu of the concatenated flow/mask first-stage convs on net[0],
        as ONE conv: [relu(flow.conv1(x)), relu(mask_conv1(x))]."""
        _assert_default_conv_geometry(self.flow_head.conv1)
        _assert_default_conv_geometry(self.mask_conv1)
        assert self.flow_head.conv1.padding == self.mask_conv1.padding
        pf = self.flow_head.conv1.variables["params"]
        pm = self.mask_conv1.variables["params"]
        x = net0
        k = jnp.concatenate([pf["kernel"], pm["kernel"]], axis=-1)
        b = jnp.concatenate([pf["bias"], pm["bias"]])
        y = jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (1, 1), self.mask_conv1.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return nn.relu(y + b.astype(x.dtype))

    def upsample_mask(self, net0: jax.Array) -> jax.Array:
        """Convex-upsampling mask from the finest GRU state.  0.25 scaling
        balances mask-head gradients (reference: core/update.py:137)."""
        return 0.25 * self.mask_conv2(nn.relu(self.mask_conv1(net0)))
