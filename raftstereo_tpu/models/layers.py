"""Shared building blocks: norm factory, torch-compatible convs, residual blocks.

Numerical parity notes (for checkpoint conversion against the reference):

* Convs use explicit torch-style padding tuples, never 'SAME' — XLA's SAME
  places stride-2 windows differently from torch's symmetric padding.
* InstanceNorm == GroupNorm with one channel per group, no affine params,
  eps 1e-5 (torch InstanceNorm2d defaults; reference: core/extractor.py:29).
* BatchNorm always runs in frozen (inference-stats) mode: the reference keeps
  BN frozen for the entire training run (reference: train_stereo.py:152,
  core/raft_stereo.py:41-44), so `use_running_average=True` is the training
  semantics too, while scale/bias stay trainable.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

# torch kaiming_normal_(mode='fan_out', nonlinearity='relu'), the reference's
# conv init (core/extractor.py:155-162).
kaiming_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def conv(features: int, kernel: int, stride: int = 1, padding: Optional[int] = None,
         dtype: Any = jnp.float32, name: Optional[str] = None) -> nn.Conv:
    """Conv2D with torch-default geometry (explicit symmetric padding)."""
    if padding is None:
        padding = kernel // 2
    return nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                   padding=((padding, padding), (padding, padding)),
                   kernel_init=kaiming_out, dtype=dtype, name=name)


class Identity(nn.Module):
    @nn.compact
    def __call__(self, x):
        return x


def instance_norm_group_width(c: int, w: int) -> int:
    """The lane-group factor k of the instance-norm view below: (H, W, C)
    is read as (H, W/k, C*k).  Depends only on (C, W), so any H-slab of an
    image shares the full image's view geometry — the property the spatial
    sharding driver (parallel/spatial.py) relies on to apply full-image
    statistics to a local slab."""
    k = 1
    while c * k % 128 and k < 8 and w % (2 * k) == 0:
        k *= 2
    return k


def instance_norm_stats(x):
    """Normalization constants of ``InstanceNorm`` for ``x``: the lane-group
    factor k plus the tiled mean/scale broadcasts (b, 1, 1, c*k), in x.dtype.
    Split out of ``InstanceNorm.__call__`` (pure code motion — the op
    sequence is unchanged) so the spatial-sharding driver can compute stats
    on the gathered full-height activation and normalize each H slab
    locally, bitwise-identical to the single-device norm."""
    b, h, w, c = x.shape
    k = instance_norm_group_width(c, w)
    xr = x.reshape(b, h, w // k, c * k)
    # Variance via CENTERED squares, not E[x^2]-m^2: squaring in bf16
    # rounds x^2 at ~0.4% absolute-of-x^2, which destroys small
    # variances when |mean| >> std (catastrophic cancellation in the
    # subtraction). Centering first keeps the squared values O(var), so
    # bf16 rounding is harmless; the group means themselves round at
    # ~3e-4 relative, contributing only (m_err)^2 to the variance.
    # Reduces stay in x.dtype (TPU accumulates internally in high
    # precision; an explicit dtype=float32 reduce makes XLA materialize
    # an fp32 copy of x, measured 2x slower). Exact in fp32 mode.
    m = jnp.mean(xr, axis=(1, 2))                              # (b, c*k)
    ctr = xr - m[:, None, None, :]
    v = jnp.mean(jnp.square(ctr), axis=(1, 2)).astype(jnp.float32)
    # Per-channel stats across the k interleaved groups (equal sizes):
    # mean = avg_g m_g; var = avg_g var_g + avg_g (m_g - mean)^2.
    m32 = m.astype(jnp.float32).reshape(b, k, c)
    mbar = m32.mean(axis=1)                                    # (b, c)
    var = (v.reshape(b, k, c).mean(axis=1)
           + jnp.square(m32 - mbar[:, None, :]).mean(axis=1))
    scale = jax.lax.rsqrt(jnp.maximum(var, 0.0) + 1e-5)
    mw = jnp.tile(mbar, (1, k)).astype(x.dtype)[:, None, None, :]
    sw = jnp.tile(scale, (1, k)).astype(x.dtype)[:, None, None, :]
    return k, mw, sw


def instance_norm_apply(x, k, mw, sw):
    """Elementwise normalize sweep of ``InstanceNorm`` with precomputed
    constants from ``instance_norm_stats``.  Row-local: applying full-image
    constants to an H slab equals the matching rows of the full-image
    norm."""
    b, h, w, c = x.shape
    xr = x.reshape(b, h, w // k, c * k)
    return ((xr - mw) * sw).reshape(b, h, w, c)


class InstanceNorm(nn.Module):
    """Per-image, per-channel normalization over (H, W); no affine params,
    eps 1e-5 (torch InstanceNorm2d defaults; reference: core/extractor.py:29).

    Hand-rolled instead of ``nn.GroupNorm(num_groups=C)``: the group reshape
    defeats XLA's fusion on TPU and measures ~4x slower at full resolution
    (544x960x64: 7.7 ms vs 1.9 ms on v5e) — and instance norm is most of the
    feature-encoder's runtime, since frozen batch norm fuses away entirely.
    In fp32 mode the statistics are exact. In bf16 mode the reduces stay in
    bf16 (an fp32 upcast of x makes XLA materialize a full-size fp32 copy),
    rounding the group means at ~3e-4 relative; the centered-squares
    formulation in ``instance_norm_stats`` keeps that harmless even when
    |mean| >> std.

    TPU-shaped formulation, measured on v5e at 544x960x64 (the feature
    encoder's hot shape): (H, W, C) is viewed as (H, W/k, C*k) with the
    smallest k making C*k a lane-width (128) multiple — a pure view in
    row-major NHWC, no data movement — so the stats reduces and the
    normalize sweep run with full lanes. With C=64 the naive form leaves
    half the VPU idle and every extra pass over the tensor crawls at ~5%
    of HBM bandwidth (~3 ms per pass vs ~0.3 ms); this view recovers it
    (norm cost 1.9 ms vs 9-12 ms, and 4x vs the GroupNorm form).
    Everything elementwise stays in x.dtype so it fuses with the
    surrounding convs; only the statistics are fp32 (an fp32 upcast of x
    itself makes XLA materialize a ~270 MB fp32 copy of the full-res
    tensor).
    """

    @nn.compact
    def __call__(self, x):
        k, mw, sw = instance_norm_stats(x)
        return instance_norm_apply(x, k, mw, sw)


def make_norm(norm_fn: str, channels: int, dtype: Any = jnp.float32,
              num_groups: Optional[int] = None, name: Optional[str] = None) -> nn.Module:
    """Norm factory mirroring the reference's four options
    (reference: core/extractor.py:16-38)."""
    if norm_fn == "group":
        return nn.GroupNorm(num_groups=num_groups or channels // 8,
                            epsilon=1e-5, dtype=dtype, name=name)
    if norm_fn == "batch":
        return nn.BatchNorm(use_running_average=True, epsilon=1e-5,
                            dtype=dtype, name=name)
    if norm_fn == "instance":
        return InstanceNorm(name=name)
    if norm_fn == "none":
        return Identity(name=name)
    raise ValueError(f"unknown norm: {norm_fn}")


class ResidualBlock(nn.Module):
    """Two 3x3 convs with norms + identity/projection shortcut
    (reference: core/extractor.py:6-60)."""

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    def setup(self):
        self.conv1 = conv(self.planes, 3, self.stride, dtype=self.dtype)
        self.conv2 = conv(self.planes, 3, 1, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, self.planes, self.dtype)
        self.norm2 = make_norm(self.norm_fn, self.planes, self.dtype)
        self.has_projection = not (self.stride == 1 and self.in_planes == self.planes)
        if self.has_projection:
            self.downsample_conv = conv(self.planes, 1, self.stride, padding=0,
                                        dtype=self.dtype)
            self.downsample_norm = make_norm(self.norm_fn, self.planes, self.dtype)

    def __call__(self, x):
        y = nn.relu(self.norm1(self.conv1(x)))
        y = nn.relu(self.norm2(self.conv2(y)))
        if self.has_projection:
            x = self.downsample_norm(self.downsample_conv(x))
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference: core/extractor.py:64-120;
    defined for capability parity — unused by the default architecture)."""

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    def setup(self):
        p4 = self.planes // 4
        g = self.planes // 8
        self.conv1 = conv(p4, 1, 1, padding=0, dtype=self.dtype)
        self.conv2 = conv(p4, 3, self.stride, dtype=self.dtype)
        self.conv3 = conv(self.planes, 1, 1, padding=0, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, p4, self.dtype, num_groups=g)
        self.norm2 = make_norm(self.norm_fn, p4, self.dtype, num_groups=g)
        self.norm3 = make_norm(self.norm_fn, self.planes, self.dtype, num_groups=g)
        if self.stride != 1:
            self.downsample_conv = conv(self.planes, 1, self.stride, padding=0,
                                        dtype=self.dtype)
            self.downsample_norm = make_norm(self.norm_fn, self.planes, self.dtype,
                                             num_groups=g)

    def __call__(self, x):
        y = nn.relu(self.norm1(self.conv1(x)))
        y = nn.relu(self.norm2(self.conv2(y)))
        y = nn.relu(self.norm3(self.conv3(y)))
        if self.stride != 1:
            x = self.downsample_norm(self.downsample_conv(x))
        return nn.relu(x + y)
