"""Shared building blocks: norm factory, torch-compatible convs, residual blocks.

Numerical parity notes (for checkpoint conversion against the reference):

* Convs use explicit torch-style padding tuples, never 'SAME' — XLA's SAME
  places stride-2 windows differently from torch's symmetric padding.
* InstanceNorm == GroupNorm with one channel per group, no affine params,
  eps 1e-5 (torch InstanceNorm2d defaults; reference: core/extractor.py:29).
* BatchNorm always runs in frozen (inference-stats) mode: the reference keeps
  BN frozen for the entire training run (reference: train_stereo.py:152,
  core/raft_stereo.py:41-44), so `use_running_average=True` is the training
  semantics too, while scale/bias stay trainable.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

# torch kaiming_normal_(mode='fan_out', nonlinearity='relu'), the reference's
# conv init (core/extractor.py:155-162).
kaiming_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def conv(features: int, kernel: int, stride: int = 1, padding: Optional[int] = None,
         dtype: Any = jnp.float32, name: Optional[str] = None) -> nn.Conv:
    """Conv2D with torch-default geometry (explicit symmetric padding)."""
    if padding is None:
        padding = kernel // 2
    return nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                   padding=((padding, padding), (padding, padding)),
                   kernel_init=kaiming_out, dtype=dtype, name=name)


class Identity(nn.Module):
    @nn.compact
    def __call__(self, x):
        return x


def make_norm(norm_fn: str, channels: int, dtype: Any = jnp.float32,
              num_groups: Optional[int] = None, name: Optional[str] = None) -> nn.Module:
    """Norm factory mirroring the reference's four options
    (reference: core/extractor.py:16-38)."""
    if norm_fn == "group":
        return nn.GroupNorm(num_groups=num_groups or channels // 8,
                            epsilon=1e-5, dtype=dtype, name=name)
    if norm_fn == "batch":
        return nn.BatchNorm(use_running_average=True, epsilon=1e-5,
                            dtype=dtype, name=name)
    if norm_fn == "instance":
        return nn.GroupNorm(num_groups=channels, use_scale=False, use_bias=False,
                            epsilon=1e-5, dtype=dtype, name=name)
    if norm_fn == "none":
        return Identity(name=name)
    raise ValueError(f"unknown norm: {norm_fn}")


class ResidualBlock(nn.Module):
    """Two 3x3 convs with norms + identity/projection shortcut
    (reference: core/extractor.py:6-60)."""

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    def setup(self):
        self.conv1 = conv(self.planes, 3, self.stride, dtype=self.dtype)
        self.conv2 = conv(self.planes, 3, 1, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, self.planes, self.dtype)
        self.norm2 = make_norm(self.norm_fn, self.planes, self.dtype)
        self.has_projection = not (self.stride == 1 and self.in_planes == self.planes)
        if self.has_projection:
            self.downsample_conv = conv(self.planes, 1, self.stride, padding=0,
                                        dtype=self.dtype)
            self.downsample_norm = make_norm(self.norm_fn, self.planes, self.dtype)

    def __call__(self, x):
        y = nn.relu(self.norm1(self.conv1(x)))
        y = nn.relu(self.norm2(self.conv2(y)))
        if self.has_projection:
            x = self.downsample_norm(self.downsample_conv(x))
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference: core/extractor.py:64-120;
    defined for capability parity — unused by the default architecture)."""

    in_planes: int
    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    def setup(self):
        p4 = self.planes // 4
        g = self.planes // 8
        self.conv1 = conv(p4, 1, 1, padding=0, dtype=self.dtype)
        self.conv2 = conv(p4, 3, self.stride, dtype=self.dtype)
        self.conv3 = conv(self.planes, 1, 1, padding=0, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, p4, self.dtype, num_groups=g)
        self.norm2 = make_norm(self.norm_fn, p4, self.dtype, num_groups=g)
        self.norm3 = make_norm(self.norm_fn, self.planes, self.dtype, num_groups=g)
        if self.stride != 1:
            self.downsample_conv = conv(self.planes, 1, self.stride, padding=0,
                                        dtype=self.dtype)
            self.downsample_norm = make_norm(self.norm_fn, self.planes, self.dtype,
                                             num_groups=g)

    def __call__(self, x):
        y = nn.relu(self.norm1(self.conv1(x)))
        y = nn.relu(self.norm2(self.conv2(y)))
        y = nn.relu(self.norm3(self.conv3(y)))
        if self.stride != 1:
            x = self.downsample_norm(self.downsample_conv(x))
        return nn.relu(x + y)
