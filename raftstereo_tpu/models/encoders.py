"""Feature and context encoders.

Capability mirror of the reference's ``BasicEncoder``/``MultiBasicEncoder``
(reference: core/extractor.py:122-300), NHWC + flax.linen.  Stride placement
follows the reference's downsample-factor logic: conv1 strides iff
downsample>2, layer2 iff downsample>1, layer3 iff downsample>0, so the trunk
output sits at 1/2^downsample resolution.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .layers import ResidualBlock, conv, make_norm


def _plain_stem(enc, x):
    """The ordinary flax stem: conv1 -> norm1 -> relu -> layer1."""
    x = nn.relu(enc.norm1(enc.conv1(x)))
    return enc.layer1_1(enc.layer1_0(x))


def _stem_layer1(enc, x):
    """conv1 + norm1 + relu + layer1, with the fused Pallas fast path on
    TPU.  ``x`` is the normalized input image.

    The plain path's four layer1 instance norms at flagship resolution
    cost ~21 ms of XLA layout churn (measured — docs/perf_notes_r03.md);
    the fused pipeline (ops/pallas_encoder.py) keeps the whole stage in
    row-major packed form.  When conv1 is stride 1 (downsample <= 2) it
    joins the pipeline as a packed Pallas 7x7 kernel too — removing the
    XLA-conv <-> row-major boundary relayouts and the 14 TF/s stem conv
    (round-3 trace) — otherwise the stage consumes conv1's raw XLA output
    directly.  Numerically pinned against this exact module path in
    tests/test_pallas_encoder.py; init always takes the plain path so the
    parameter tree is identical either way."""
    from ..ops.pallas_encoder import (bn_affine, bn_conv1_stem_layer1,
                                      bn_stem_layer1, conv1_stem_layer1,
                                      stem_layer1, use_fused_stem)

    stride = 1 + (enc.downsample > 2)
    oshape = (x.shape[0], -(-x.shape[1] // stride),
              -(-x.shape[2] // stride), 64)
    if (not enc.is_initializing()
            and use_fused_stem(enc.norm_fn, oshape, enc.fused_stem)):
        params = {
            "c10": enc.layer1_0.conv1.variables["params"],
            "c11": enc.layer1_0.conv2.variables["params"],
            "c20": enc.layer1_1.conv1.variables["params"],
            "c21": enc.layer1_1.conv2.variables["params"],
        }
        if enc.norm_fn == "batch":
            # Frozen BN folds to constant prep affines (bn_affine).
            affines = [
                bn_affine(m.variables["params"], m.variables["batch_stats"])
                for m in (enc.norm1, enc.layer1_0.norm1, enc.layer1_0.norm2,
                          enc.layer1_1.norm1, enc.layer1_1.norm2)]
        else:
            affines = None
        # Pallas conv1 only at small per-shard image counts: measured
        # same-session A/B at flagship shapes — batch 1 (2 images)
        # 9.56 -> 9.84 pairs/sec, batch 2 a wash, batch 8 11.87 -> 12.31
        # for the XLA conv (its blocked lowering amortizes over batch
        # while the packed K=6 kernel scales linearly).  The 7x7 conv also
        # needs 3 halo rows from each space-shard neighbor, so each shard
        # must hold >= 3 rows (ppermute reaches one neighbor only).
        from ..ops.pallas_encoder import _stem_shard_mesh

        shard = _stem_shard_mesh(oshape)
        local_imgs = x.shape[0] // (shard[1] if shard is not None else 1)
        local_h = oshape[1] // (shard[2] if shard is not None else 1)
        # Stride 2 (downsample 3 / realtime) uses the packed-fours kernel;
        # it needs W % 4 == 0 and even H.  Both conv1 kernels pre-shift
        # the narrow input and fold the column offsets into one dot per
        # row tap — the first formulation rolled the 128-wide fp32
        # accumulator per offset and measured a net LOSS; restructured,
        # the stride-2 path flips to a +2.5-4% realtime win (alternating
        # same-process A/B — the chip drifts, docs/perf_notes_r04.md).
        ok_geom = (x.shape[-1] == 3 and local_imgs <= 4 and local_h >= 3
                   and (stride == 1
                        or (x.shape[1] % 2 == 0 and x.shape[2] % 4 == 0)))
        if ok_geom:
            c1p = enc.conv1.variables["params"]
            if affines is not None:
                return bn_conv1_stem_layer1(x, c1p, params, affines,
                                            enc.dtype, stride)
            return conv1_stem_layer1(x, c1p, params, enc.dtype, stride)
        if affines is not None:
            # BN stage WITHOUT the Pallas conv1 re-pays the XLA-conv ->
            # row-major boundary relayout and measures a net loss
            # (same-session realtime: 101 vs 111.5 pairs/sec plain),
            # unlike the instance stage whose XLA alternative is the
            # 21 ms relayout storm.  Auto keeps the plain XLA stage here;
            # an explicit True override still forces the fused form (the
            # CPU equivalence tests and forced-path evaluations).
            from ..ops.pallas_encoder import fused_stem_forced
            if fused_stem_forced(enc.fused_stem):
                return bn_stem_layer1(enc.conv1(x), params, affines)
            return _plain_stem(enc, x)
        return stem_layer1(enc.conv1(x), params)
    return _plain_stem(enc, x)


def _trunk_layer2(enc, x):
    """layer2 (two ResidualBlocks, first stride-2 + projection), with the
    fused Pallas fast path on TPU: round-5 profiling puts ~15 ms of the
    flagship fixed stage in XLA's layer2+ convs and their blocked-layout
    relayouts (docs/perf_notes_r05.md); the fused stage keeps everything
    row-major (ops/pallas_layer2.py).  Numerically pinned against this
    exact module path in tests/test_pallas_layer2.py."""
    from ..ops.pallas_layer2 import (fused_layer2, fused_layer2_bn,
                                     use_fused_layer2)

    stride2 = 1 + (enc.downsample > 1)
    if (not enc.is_initializing()
            and use_fused_layer2(enc.norm_fn, stride2, x.shape,
                                 override=enc.fused_stem)):
        params = {
            "c1": enc.layer2_0.conv1.variables["params"],
            "c2": enc.layer2_0.conv2.variables["params"],
            "proj": enc.layer2_0.downsample_conv.variables["params"],
            "c3": enc.layer2_1.conv1.variables["params"],
            "c4": enc.layer2_1.conv2.variables["params"],
        }
        if enc.norm_fn == "batch":
            # Frozen BN folds to constant prep affines, exactly like the
            # stem stage (pallas_encoder.bn_affine); stage order:
            # norm1, projection norm, norm2, layer2_1.norm1/norm2.
            from ..ops import pallas_layer2 as _pl2
            from ..ops.pallas_encoder import bn_affine, fused_stem_forced
            if not (_pl2._fused_layer2_bn_enabled
                    or fused_stem_forced(enc.fused_stem)):
                return enc.layer2_1(enc.layer2_0(x))
            affines = [
                bn_affine(m.variables["params"], m.variables["batch_stats"])
                for m in (enc.layer2_0.norm1, enc.layer2_0.downsample_norm,
                          enc.layer2_0.norm2, enc.layer2_1.norm1,
                          enc.layer2_1.norm2)]
            return fused_layer2_bn(x, params, affines, enc.dtype)
        return fused_layer2(x, params, enc.dtype)
    return enc.layer2_1(enc.layer2_0(x))


class BasicEncoder(nn.Module):
    """Residual trunk -> ``output_dim`` feature maps at 1/2^downsample res
    (reference: core/extractor.py:122-197).  The reference's list-input
    batching trick (stack both images into the batch axis) is the caller's
    job here — pass (2B, H, W, 3)."""

    output_dim: int = 128
    norm_fn: str = "batch"
    downsample: int = 3
    dtype: Any = jnp.float32
    # Tri-state override of the fused-stem gate (config.fused_encoder):
    # None = auto (TPU backend), True/False = force one numeric path.
    fused_stem: Optional[bool] = None

    def setup(self):
        d = self.downsample
        self.conv1 = conv(64, 7, stride=1 + (d > 2), padding=3, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, 64, self.dtype, num_groups=8)
        self.layer1_0 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer1_1 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer2_0 = ResidualBlock(64, 96, self.norm_fn, 1 + (d > 1), self.dtype)
        self.layer2_1 = ResidualBlock(96, 96, self.norm_fn, 1, self.dtype)
        self.layer3_0 = ResidualBlock(96, 128, self.norm_fn, 1 + (d > 0), self.dtype)
        self.layer3_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.conv2 = conv(self.output_dim, 1, padding=0, dtype=self.dtype)

    def __call__(self, x):
        x = _stem_layer1(self, x)
        x = _trunk_layer2(self, x)
        for blk in (self.layer3_0, self.layer3_1):
            x = blk(x)
        return self.conv2(x)


class MultiBasicEncoder(nn.Module):
    """Context encoder: shared trunk + two extra stride-2 stages, with
    per-GRU-level output heads (reference: core/extractor.py:199-300).

    ``output_dims`` is a sequence of channel tuples, one per output head
    group (the model passes (hidden_dims, hidden_dims) for the GRU hidden
    state and the context stream).  Each tuple is indexed finest-first:
    dims[level] is the head width at GRU level ``level`` (0 = finest).

    Returns ``(levels, heads)``-nested lists: ``out[level][head]``, finest
    level first, plus the trunk features when ``dual_inp`` (shared-backbone
    mode, reference: core/raft_stereo.py:78-80).
    """

    output_dims: Sequence[Tuple[int, ...]] = ((128, 128, 128), (128, 128, 128))
    norm_fn: str = "batch"
    downsample: int = 3
    dtype: Any = jnp.float32
    fused_stem: Optional[bool] = None  # see BasicEncoder.fused_stem

    def setup(self):
        d = self.downsample
        self.conv1 = conv(64, 7, stride=1 + (d > 2), padding=3, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, 64, self.dtype, num_groups=8)
        self.layer1_0 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer1_1 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer2_0 = ResidualBlock(64, 96, self.norm_fn, 1 + (d > 1), self.dtype)
        self.layer2_1 = ResidualBlock(96, 96, self.norm_fn, 1, self.dtype)
        self.layer3_0 = ResidualBlock(96, 128, self.norm_fn, 1 + (d > 0), self.dtype)
        self.layer3_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.layer4_0 = ResidualBlock(128, 128, self.norm_fn, 2, self.dtype)
        self.layer4_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.layer5_0 = ResidualBlock(128, 128, self.norm_fn, 2, self.dtype)
        self.layer5_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)

        # Heads: level 0 (finest, trunk res) gets a ResidualBlock + 3x3 conv,
        # level 1 the same, level 2 (coarsest) a bare 3x3 conv — mirroring the
        # reference's outputs08/outputs16/outputs32 structure
        # (core/extractor.py:227-250).
        heads08, heads16, heads32 = [], [], []
        for hi, dims in enumerate(self.output_dims):
            heads08.append((
                ResidualBlock(128, 128, self.norm_fn, 1, self.dtype,
                              name=f"head08_{hi}_res"),
                conv(dims[0], 3, dtype=self.dtype, name=f"head08_{hi}_conv"),
            ))
            if len(dims) >= 2:
                heads16.append((
                    ResidualBlock(128, 128, self.norm_fn, 1, self.dtype,
                                  name=f"head16_{hi}_res"),
                    conv(dims[1], 3, dtype=self.dtype, name=f"head16_{hi}_conv"),
                ))
            if len(dims) >= 3:
                heads32.append(conv(dims[2], 3, dtype=self.dtype,
                                    name=f"head32_{hi}_conv"))
        self.heads08 = heads08
        self.heads16 = heads16
        self.heads32 = heads32

    def __call__(self, x, dual_inp: bool = False, num_layers: int = 3):
        x = _stem_layer1(self, x)
        x = _trunk_layer2(self, x)
        for blk in (self.layer3_0, self.layer3_1):
            x = blk(x)
        trunk = None
        if dual_inp:
            trunk = x
            x = x[: x.shape[0] // 2]

        out08 = [head_conv(head_res(x)) for head_res, head_conv in self.heads08]
        outputs = [out08]
        if num_layers >= 2:
            y = self.layer4_1(self.layer4_0(x))
            outputs.append([hc(hr(y)) for hr, hc in self.heads16])
        if num_layers >= 3:
            z = self.layer5_1(self.layer5_0(y))
            outputs.append([hc(z) for hc in self.heads32])
        if dual_inp:
            return outputs, trunk
        return outputs
