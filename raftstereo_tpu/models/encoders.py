"""Feature and context encoders.

Capability mirror of the reference's ``BasicEncoder``/``MultiBasicEncoder``
(reference: core/extractor.py:122-300), NHWC + flax.linen.  Stride placement
follows the reference's downsample-factor logic: conv1 strides iff
downsample>2, layer2 iff downsample>1, layer3 iff downsample>0, so the trunk
output sits at 1/2^downsample resolution.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .layers import ResidualBlock, conv, make_norm


def _stem_layer1(enc, x):
    """norm1 + relu + layer1, with the fused Pallas fast path on TPU.

    The plain path's four layer1 instance norms at flagship resolution
    cost ~21 ms of XLA layout churn (measured — docs/perf_notes_r03.md);
    the fused pipeline (ops/pallas_encoder.py) keeps the whole stage in
    row-major packed form, consuming conv1's raw output directly (both
    split points measured E2E — see fused_stem_layer1's docstring).
    Numerically pinned against this exact module path in
    tests/test_pallas_encoder.py; init always takes the plain path so the
    parameter tree is identical either way."""
    from ..ops.pallas_encoder import stem_layer1, use_fused_stem

    if (not enc.is_initializing()
            and use_fused_stem(enc.norm_fn, x.shape[2])):
        params = {
            "c10": enc.layer1_0.conv1.variables["params"],
            "c11": enc.layer1_0.conv2.variables["params"],
            "c20": enc.layer1_1.conv1.variables["params"],
            "c21": enc.layer1_1.conv2.variables["params"],
        }
        return stem_layer1(x, params)
    x = nn.relu(enc.norm1(x))
    return enc.layer1_1(enc.layer1_0(x))


class BasicEncoder(nn.Module):
    """Residual trunk -> ``output_dim`` feature maps at 1/2^downsample res
    (reference: core/extractor.py:122-197).  The reference's list-input
    batching trick (stack both images into the batch axis) is the caller's
    job here — pass (2B, H, W, 3)."""

    output_dim: int = 128
    norm_fn: str = "batch"
    downsample: int = 3
    dtype: Any = jnp.float32

    def setup(self):
        d = self.downsample
        self.conv1 = conv(64, 7, stride=1 + (d > 2), padding=3, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, 64, self.dtype, num_groups=8)
        self.layer1_0 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer1_1 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer2_0 = ResidualBlock(64, 96, self.norm_fn, 1 + (d > 1), self.dtype)
        self.layer2_1 = ResidualBlock(96, 96, self.norm_fn, 1, self.dtype)
        self.layer3_0 = ResidualBlock(96, 128, self.norm_fn, 1 + (d > 0), self.dtype)
        self.layer3_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.conv2 = conv(self.output_dim, 1, padding=0, dtype=self.dtype)

    def __call__(self, x):
        x = _stem_layer1(self, self.conv1(x))
        for blk in (self.layer2_0, self.layer2_1,
                    self.layer3_0, self.layer3_1):
            x = blk(x)
        return self.conv2(x)


class MultiBasicEncoder(nn.Module):
    """Context encoder: shared trunk + two extra stride-2 stages, with
    per-GRU-level output heads (reference: core/extractor.py:199-300).

    ``output_dims`` is a sequence of channel tuples, one per output head
    group (the model passes (hidden_dims, hidden_dims) for the GRU hidden
    state and the context stream).  Each tuple is indexed finest-first:
    dims[level] is the head width at GRU level ``level`` (0 = finest).

    Returns ``(levels, heads)``-nested lists: ``out[level][head]``, finest
    level first, plus the trunk features when ``dual_inp`` (shared-backbone
    mode, reference: core/raft_stereo.py:78-80).
    """

    output_dims: Sequence[Tuple[int, ...]] = ((128, 128, 128), (128, 128, 128))
    norm_fn: str = "batch"
    downsample: int = 3
    dtype: Any = jnp.float32

    def setup(self):
        d = self.downsample
        self.conv1 = conv(64, 7, stride=1 + (d > 2), padding=3, dtype=self.dtype)
        self.norm1 = make_norm(self.norm_fn, 64, self.dtype, num_groups=8)
        self.layer1_0 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer1_1 = ResidualBlock(64, 64, self.norm_fn, 1, self.dtype)
        self.layer2_0 = ResidualBlock(64, 96, self.norm_fn, 1 + (d > 1), self.dtype)
        self.layer2_1 = ResidualBlock(96, 96, self.norm_fn, 1, self.dtype)
        self.layer3_0 = ResidualBlock(96, 128, self.norm_fn, 1 + (d > 0), self.dtype)
        self.layer3_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.layer4_0 = ResidualBlock(128, 128, self.norm_fn, 2, self.dtype)
        self.layer4_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)
        self.layer5_0 = ResidualBlock(128, 128, self.norm_fn, 2, self.dtype)
        self.layer5_1 = ResidualBlock(128, 128, self.norm_fn, 1, self.dtype)

        # Heads: level 0 (finest, trunk res) gets a ResidualBlock + 3x3 conv,
        # level 1 the same, level 2 (coarsest) a bare 3x3 conv — mirroring the
        # reference's outputs08/outputs16/outputs32 structure
        # (core/extractor.py:227-250).
        heads08, heads16, heads32 = [], [], []
        for hi, dims in enumerate(self.output_dims):
            heads08.append((
                ResidualBlock(128, 128, self.norm_fn, 1, self.dtype,
                              name=f"head08_{hi}_res"),
                conv(dims[0], 3, dtype=self.dtype, name=f"head08_{hi}_conv"),
            ))
            if len(dims) >= 2:
                heads16.append((
                    ResidualBlock(128, 128, self.norm_fn, 1, self.dtype,
                                  name=f"head16_{hi}_res"),
                    conv(dims[1], 3, dtype=self.dtype, name=f"head16_{hi}_conv"),
                ))
            if len(dims) >= 3:
                heads32.append(conv(dims[2], 3, dtype=self.dtype,
                                    name=f"head32_{hi}_conv"))
        self.heads08 = heads08
        self.heads16 = heads16
        self.heads32 = heads32

    def __call__(self, x, dual_inp: bool = False, num_layers: int = 3):
        x = _stem_layer1(self, self.conv1(x))
        for blk in (self.layer2_0, self.layer2_1,
                    self.layer3_0, self.layer3_1):
            x = blk(x)
        trunk = None
        if dual_inp:
            trunk = x
            x = x[: x.shape[0] // 2]

        out08 = [head_conv(head_res(x)) for head_res, head_conv in self.heads08]
        outputs = [out08]
        if num_layers >= 2:
            y = self.layer4_1(self.layer4_0(x))
            outputs.append([hc(hr(y)) for hr, hc in self.heads16])
        if num_layers >= 3:
            z = self.layer5_1(self.layer5_0(y))
            outputs.append([hc(z) for hc in self.heads32])
        if dual_inp:
            return outputs, trunk
        return outputs
