"""RAFT-Stereo, TPU-first.

Capability mirror of the reference model (reference: core/raft_stereo.py),
re-architected for XLA:

* the entire ``iters``-step GRU refinement loop is ONE ``jax.lax.scan`` —
  the whole inference compiles to a single XLA program instead of the
  reference's Python loop launching kernels per iteration
  (reference: core/raft_stereo.py:108-136)
* disparity is carried as a single channel (the reference zeroes the y-flow
  every iteration anyway: core/raft_stereo.py:120)
* GRU context biases are precomputed once before the loop
  (reference: core/raft_stereo.py:32,88)
* per-iteration coords detach == ``stop_gradient`` at the top of the scan body
  (reference: core/raft_stereo.py:109)

The class composes flax.linen submodules functionally (explicit variables
pytree) so the training step, sharding annotations, and checkpoint conversion
all see a plain dict — no lifted-transform indirection.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config import RAFTStereoConfig
from ..ops.corr import build_corr_state, corr_fn_from_state, make_corr_fn
from ..ops.image import coords_grid_x
from ..ops.upsample import convex_upsample
from .encoders import BasicEncoder, MultiBasicEncoder
from .layers import ResidualBlock, conv
from .update import BasicMultiUpdateBlock, _interp_to


class ContextZQR(nn.Module):
    """Per-level 3x3 convs producing the GRU context biases once
    (reference: core/raft_stereo.py:32).  Output channel order (cz, cr, cq)
    follows the reference's split (core/raft_stereo.py:88)."""

    config: RAFTStereoConfig
    dtype: Any = jnp.float32

    def setup(self):
        hd = self.config.hidden_dims
        self.convs = [conv(hd[i] * 3, 3, dtype=self.dtype, name=f"zqr{i}")
                      for i in range(self.config.n_gru_layers)]

    def __call__(self, inp_list):
        out = []
        for i, (x, c) in enumerate(zip(inp_list, self.convs)):
            h = self.config.hidden_dims[i]
            y = c(x)
            out.append((y[..., :h], y[..., h:2 * h], y[..., 2 * h:]))
        return out


class SLProjection(nn.Module):
    """Pattern-conditioning front for structured-light inputs
    (config.input_mode == "sl", sl/adapter.py, docs/structured_light.md):
    a learned 3x3 projection from the 12-channel stack (ambient RGB + 9
    pattern channels per side) down to the 3 channels the shared feature
    encoders were designed for.  Both images of a pair share one set of
    projection weights — the same weight-sharing contract as fnet."""

    dtype: Any = jnp.float32

    def setup(self):
        self.proj = conv(3, 3, dtype=self.dtype)

    def __call__(self, x):
        return self.proj(x)


class SharedBackboneHead(nn.Module):
    """Feature head for --shared_backbone mode: one residual block + 3x3 conv
    on the context trunk (reference: core/raft_stereo.py:34-37)."""

    dtype: Any = jnp.float32

    def setup(self):
        self.res = ResidualBlock(128, 128, "instance", 1, self.dtype)
        self.out = conv(RAFTStereo.feature_dim, 3, dtype=self.dtype)

    def __call__(self, x):
        return self.out(self.res(x))


def _level_shapes(h: int, w: int, n_levels: int) -> List[Tuple[int, int]]:
    shapes = [(h, w)]
    for _ in range(n_levels - 1):
        h, w = -(-h // 2), -(-w // 2)   # ceil halving (stride-2 k3 p1 convs)
        shapes.append((h, w))
    return shapes


class RAFTStereo:
    """Functional model bundle: submodule definitions + init/forward.

    Usage:
        model = RAFTStereo(config)
        variables = model.init(jax.random.key(0))
        preds = model.forward(variables, img1, img2, iters=16)           # train
        d_low, d_up = model.forward(variables, img1, img2, 32, test_mode=True)

    Images are NHWC, any float/int dtype, value range [0, 255].
    Disparity convention matches the reference: predictions are the x-flow
    from left to right image, i.e. NEGATIVE disparities
    (reference: core/stereo_datasets.py:77).
    """

    # Correlation feature width emitted by fnet / the shared-backbone head
    # (reference: core/extractor.py output_dim=256, core/raft_stereo.py:37).
    feature_dim = 256

    def __init__(self, config: RAFTStereoConfig):
        self.config = config
        self.dtype = (jnp.bfloat16 if config.compute_dtype == "bfloat16"
                      else jnp.float32)
        cfg = config
        self.cnet = MultiBasicEncoder(
            output_dims=(cfg.hidden_dims, cfg.hidden_dims),
            norm_fn=cfg.context_norm, downsample=cfg.n_downsample,
            dtype=self.dtype, fused_stem=cfg.fused_encoder)
        if cfg.shared_backbone:
            self.sb_head = SharedBackboneHead(dtype=self.dtype)
        else:
            self.fnet = BasicEncoder(output_dim=self.feature_dim, norm_fn="instance",
                                     downsample=cfg.n_downsample, dtype=self.dtype,
                                     fused_stem=cfg.fused_encoder)
        self.zqr = ContextZQR(cfg, dtype=self.dtype)
        self.update = BasicMultiUpdateBlock(cfg, dtype=self.dtype)
        # Structured-light front (docs/structured_light.md).  Constructed
        # ONLY in sl mode: the passive path must stay bitwise-identical to
        # pre-SL builds — no extra module, no extra params, no code-path
        # change in _encode (tests/test_sl.py asserts this).
        if cfg.input_mode == "sl":
            self.sl_proj = SLProjection(dtype=self.dtype)

    # ------------------------------------------------------------------ init

    def init(self, rng: jax.Array, image_hw: Tuple[int, int] = (64, 96)) -> Dict:
        cfg = self.config
        h, w = image_hw
        f = cfg.factor
        h0, w0 = h // f, w // f
        lvl = _level_shapes(h0, w0, cfg.n_gru_layers)
        # Passive keeps its historical 4-way split untouched (bitwise-stable
        # init); sl adds a fifth key for the projection front.
        n_keys = 5 if cfg.input_mode == "sl" else 4
        k = jax.random.split(rng, n_keys)
        img = jnp.zeros((1, h, w, 3), jnp.float32)

        variables: Dict[str, Dict] = {"params": {}, "batch_stats": {}}

        def absorb(name, v):
            variables["params"][name] = v["params"]
            if "batch_stats" in v:
                variables["batch_stats"][name] = v["batch_stats"]

        if cfg.input_mode == "sl":
            # The projection maps 12 -> 3 channels, so the encoders below
            # init against the same 3-channel dummy as passive.
            absorb("sl_proj", self.sl_proj.init(
                k[4], jnp.zeros((1, h, w, cfg.input_channels), jnp.float32)))

        if cfg.shared_backbone:
            v = self.cnet.init(k[0], jnp.concatenate([img, img], 0),
                               dual_inp=True, num_layers=cfg.n_gru_layers)
            absorb("cnet", v)
            absorb("fnet", self.sb_head.init(
                k[1], jnp.zeros((2, h0, w0, 128), jnp.float32)))
        else:
            absorb("cnet", self.cnet.init(k[0], img,
                                          num_layers=cfg.n_gru_layers))
            absorb("fnet", self.fnet.init(k[1], img))

        inp_dummy = [jnp.zeros((1, lh, lw, cfg.hidden_dims[i]), jnp.float32)
                     for i, (lh, lw) in enumerate(lvl)]
        absorb("zqr", self.zqr.init(k[2], inp_dummy))

        net_dummy = list(inp_dummy)
        zqr_dummy = [(x, x, x) for x in inp_dummy]
        corr_dummy = jnp.zeros((1, h0, w0, cfg.cor_planes), jnp.float32)
        flow_dummy = jnp.zeros((1, h0, w0, 2), jnp.float32)
        absorb("update", self.update.init(k[3], net_dummy, zqr_dummy,
                                          corr_dummy, flow_dummy))
        if not variables["batch_stats"]:
            del variables["batch_stats"]
        return variables

    # --------------------------------------------------------------- forward

    def _split_vars(self, variables, name):
        out = {"params": variables["params"][name]}
        bs = variables.get("batch_stats", {})
        if name in bs:
            out["batch_stats"] = bs[name]
        return out

    def _encode(self, variables: Dict, image1: jax.Array,
                image2: jax.Array):
        """Encoder phase shared by ``forward`` and ``forward_prologue``:
        normalization, context/feature encoders and the precomputed GRU
        context biases (reference: core/raft_stereo.py:77-88)."""
        cfg = self.config
        dtype = self.dtype
        b = image1.shape[0]

        img1 = (2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
        img2 = (2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)

        if cfg.input_mode == "sl":
            # 12-channel SL stacks (sl/adapter.py scales the binary pattern
            # masks to [0, 255] so the shared normalization above needs no
            # special case) projected to the encoders' 3-channel input.
            sl_vars = self._split_vars(variables, "sl_proj")
            img1 = self.sl_proj.apply(sl_vars, img1)
            img2 = self.sl_proj.apply(sl_vars, img2)

        if cfg.shared_backbone:
            outputs, trunk = self.cnet.apply(
                self._split_vars(variables, "cnet"),
                jnp.concatenate([img1, img2], 0), dual_inp=True,
                num_layers=cfg.n_gru_layers)
            fmaps = self.sb_head.apply(self._split_vars(variables, "fnet"), trunk)
        else:
            outputs = self.cnet.apply(self._split_vars(variables, "cnet"),
                                      img1, num_layers=cfg.n_gru_layers)
            fmaps = self.fnet.apply(self._split_vars(variables, "fnet"),
                                    jnp.concatenate([img1, img2], 0))
        fmap1, fmap2 = fmaps[:b], fmaps[b:]

        net_list = [jnp.tanh(o[0]) for o in outputs]
        inp_list = [nn.relu(o[1]) for o in outputs]
        zqr_list = self.zqr.apply(self._split_vars(variables, "zqr"), inp_list)
        return net_list, zqr_list, fmap1, fmap2

    def _use_fused_gru(self, test_mode: bool) -> bool:
        """Whether this trace takes the fused GRU megakernel step
        (ops/pallas_gru.py) — resolved once per forward and threaded
        through ``_corr_setup`` and ``_step_body`` so the lookup policy
        and the step body always agree."""
        from ..ops.pallas_gru import use_fused_gru
        return use_fused_gru(self.config.gru_backend, test_mode)

    def _corr_setup(self, update_vars: Dict, test_mode: bool,
                    fused: bool = False):
        """Static correlation-lookup policy shared by the monolithic and
        phase-split forwards: the volume dtype, the int8-quant gate,
        whether the motion encoder's convc1 is fused into the lookup
        kernel (and its parameters), and the lane-friendly channel pad."""
        cfg = self.config
        corr_dtype = (jnp.bfloat16 if cfg.corr_dtype == "bfloat16"
                      else jnp.float32)
        # Int8-quantized volume (ops/quant.py): inference-only — the int8
        # rounding defines no useful gradient, so train-mode traces always
        # build the unquantized volume regardless of the config flag.
        quant = bool(cfg.corr_quant) and test_mode
        # Test mode fuses the motion encoder's convc1 (1x1, cor_planes->64)
        # into the lookup kernel as a relu epilogue: the separate conv
        # re-read the correlation features at 75 GB/s (60 us/iter, round-5
        # trace).  Training keeps the module conv — the fused path defines
        # no VJP (gradients flow through convc1 the ordinary way).
        from ..ops.corr import corr_epilogue_active
        # bf16 compute only: the in-kernel bf16 dot reproduces the module
        # conv BIT-EXACTLY (measured: max |disp| diff 0.0 over a 32-iter
        # forward), while fp32's module conv runs at flax default precision
        # — a different rounding than any Mosaic-loweable policy — and fp32
        # is the certified-parity path, which must keep one numeric form.
        # The fused GRU step subsumes the epilogue (convc1 runs inside the
        # megakernel, which reads the correlation features exactly once),
        # so it asks the lookup for RAW features instead.
        use_epi = (test_mode and not fused and self.dtype == jnp.bfloat16
                   and corr_epilogue_active(cfg.corr_implementation, quant))
        epi = (update_vars["params"]["encoder"]["convc1"] if use_epi
               else None)
        # out_channels: the pallas_alt backend zero-pads the correlation
        # features to a lane-multiple-friendly width in-kernel (36 lanes
        # made the motion encoder's 1x1 conv fusion memory-bound); the
        # motion encoder's padded conv accepts either width.
        return corr_dtype, use_epi, epi, -(-cfg.cor_planes // 64) * 64, quant

    def _step_body(self, update_vars: Dict, zqr_list, corr_fn, grid,
                   test_mode: bool, use_epi: bool, fused: bool = False,
                   out_channels: int = 0, quant: bool = False):
        """The per-iteration refinement body, identical between the
        monolithic ``forward`` scan and the scheduler's single-iteration
        step executable (``forward_step``) — sharing the code is what
        makes the two paths bitwise-comparable.

        ``fused`` swaps the finest level (motion encoder + gru0 + flow
        head) for the Pallas megakernel step (ops/pallas_gru.py); the
        coarser GRU levels keep the module path — they run at 1/4 and
        1/16 of the finest level's pixel count and update FIRST, exactly
        as in the module's coarsest->finest call order, so the kernel
        consumes the same upsampled coarser state the module would."""
        cfg = self.config
        dtype = self.dtype
        sf = cfg.slow_fast_gru
        n = cfg.n_gru_layers

        if fused:
            assert test_mode, "fused GRU step is test-mode only"
            from ..ops.corr import resolve_implementation
            from ..ops.pallas_gru import fused_update, pack_update_params
            # The width the lookup actually emits: the pallas_alt backend
            # zero-pads to the lane-friendly ``out_channels`` (from the
            # caller's _corr_setup — the SAME call that built corr_fn);
            # every other backend returns the natural cor_planes.
            corr_width = (out_channels
                          if resolve_implementation(cfg.corr_implementation,
                                                    quant)
                          == "pallas_alt" else cfg.cor_planes)
            ext_dim = cfg.hidden_dims[1] if n > 1 else 0
            wpack = pack_update_params(update_vars["params"], corr_width,
                                       ext_dim, dtype)
            cz0, cr0, cq0 = zqr_list[0]

            def fused_step(carry, _):
                nets, d = carry
                d = jax.lax.stop_gradient(d)
                corr = corr_fn(grid + d)
                nets = list(nets)
                if n == 3 and sf:
                    nets = self.update.apply(update_vars, nets, zqr_list,
                                             iter2=True, iter1=False,
                                             iter0=False, update=False)
                if n >= 2 and sf:
                    nets = self.update.apply(update_vars, nets, zqr_list,
                                             iter2=(n == 3), iter1=True,
                                             iter0=False, update=False)
                if n >= 2:
                    nets = self.update.apply(update_vars, nets, zqr_list,
                                             iter2=(n == 3), iter1=True,
                                             iter0=False, update=False)
                ext = (_interp_to(nets[1], nets[0]) if n > 1 else None)
                hnew, delta = fused_update(nets[0], ext, corr, d,
                                           cz0, cr0, cq0, wpack)
                nets[0] = hnew
                d = d + delta[..., :1].astype(jnp.float32)
                return (tuple(nets), d), None

            return fused_step

        def step(carry, _):
            nets, d = carry
            d = jax.lax.stop_gradient(d)
            corr = corr_fn(grid + d)  # already emitted in model dtype
            flow = jnp.concatenate([d, jnp.zeros_like(d)], axis=-1).astype(dtype)

            if n == 3 and sf:
                nets = self.update.apply(update_vars, nets, zqr_list,
                                         iter2=True, iter1=False, iter0=False,
                                         update=False)
            if n >= 2 and sf:
                nets = self.update.apply(update_vars, nets, zqr_list,
                                         iter2=(n == 3), iter1=True,
                                         iter0=False, update=False)
            # Test mode skips the mask head inside the loop: only the final
            # mask is consumed and it depends only on net[0], so it is
            # computed ONCE after the scan (measured ~0.18 ms/iter saved at
            # flagship shapes: the 128->256 conv, the 1x1 head, the f32
            # cast, and the carry's HBM round trip).
            nets, mask, delta = self.update.apply(
                update_vars, nets, zqr_list, corr, flow,
                iter2=(n == 3), iter1=(n >= 2), with_mask=not test_mode,
                corr_preact=use_epi)

            d = d + delta[..., :1].astype(jnp.float32)
            if test_mode:
                return (tuple(nets), d), None
            up = convex_upsample(d, mask.astype(jnp.float32), cfg.factor)
            return (tuple(nets), d), up

        return step

    def forward(self, variables: Dict, image1: jax.Array, image2: jax.Array,
                iters: int = 12, flow_init: Optional[jax.Array] = None,
                test_mode: bool = False, unroll: int = 1):
        cfg = self.config
        b = image1.shape[0]

        net_list, zqr_list, fmap1, fmap2 = self._encode(variables, image1,
                                                        image2)
        update_vars = self._split_vars(variables, "update")
        fused = self._use_fused_gru(test_mode)
        corr_dtype, use_epi, epi, out_channels, quant = self._corr_setup(
            update_vars, test_mode, fused)
        corr_fn = make_corr_fn(cfg.corr_implementation, fmap1, fmap2,
                               cfg.corr_levels, cfg.corr_radius,
                               dtype=corr_dtype,
                               precision=cfg.corr_precision,
                               out_dtype=self.dtype,
                               out_channels=out_channels,
                               epilogue=epi, quant=quant)

        h0, w0 = net_list[0].shape[1:3]
        grid = coords_grid_x(b, h0, w0)
        disp = jnp.zeros((b, h0, w0, 1), jnp.float32)
        if flow_init is not None:
            disp = disp + flow_init.astype(jnp.float32)

        step = self._step_body(update_vars, zqr_list, corr_fn, grid,
                               test_mode, use_epi, fused=fused,
                               out_channels=out_channels, quant=quant)
        body = jax.checkpoint(step) if cfg.remat else step
        # ``unroll`` feeds lax.scan's unroll factor.  Perf-neutral by default
        # (1); bench.py's FLOP accounting compiles fully-unrolled variants
        # because XLA's cost model counts a rolled loop body ONCE regardless
        # of trip count (verified: scan of a matmul reports identical flops
        # for length 1/4/16), so per-iteration flops are only observable
        # unrolled.
        (nets, disp), ys = jax.lax.scan(
            body, (tuple(net_list), disp), None, length=iters,
            unroll=unroll)
        if test_mode:
            mask = self.update.apply(update_vars, nets[0],
                                     method="upsample_mask")
            disp_up = convex_upsample(disp, mask.astype(jnp.float32),
                                      cfg.factor)
            return disp, disp_up
        return ys  # (iters, B, H*f, W*f, 1)

    # ------------------------------------------------- phase-split forward
    #
    # The same test-mode computation as ``forward``, split into three
    # separately-compilable phases so a scheduler can advance a running
    # batch one iteration at a time and let requests join/leave at
    # iteration boundaries (serve/sched/, docs/serving.md):
    #
    #   state = forward_prologue(v, i1, i2, flow_init)   # encode + corr
    #   state = forward_step(v, state, iters=k)          # k GRU iterations
    #   low, up = forward_epilogue(v, state)             # mask + upsample
    #
    # ``prologue -> step x (N/k) -> epilogue`` is bitwise-identical to
    # ``forward(iters=N, test_mode=True)`` at the same batch shape: the
    # scan body is the SAME function (``_step_body``), the correlation
    # state is built by the same ops (ops/corr.build_corr_state), and the
    # epilogue repeats the post-scan code (asserted in tests/test_sched.py).

    def forward_prologue(self, variables: Dict, image1: jax.Array,
                         image2: jax.Array,
                         flow_init: Optional[jax.Array] = None) -> Dict:
        """Encode + correlation build + initial refinement state.

        Returns the carried state: a dict pytree whose leaves all keep the
        batch as their leading axis (so a scheduler can merge per-slot
        state across requests with a (B,)-mask select).  ``flow_init`` is
        a (B, H/factor, W/factor, 1) warm-start disparity; None and zeros
        produce bitwise-identical results (same property as
        ``jitted_infer_init``), so one prologue executable serves cold
        requests and warm stream frames alike."""
        cfg = self.config
        net_list, zqr_list, fmap1, fmap2 = self._encode(variables, image1,
                                                        image2)
        corr_dtype, _, _, _, quant = self._corr_setup(
            self._split_vars(variables, "update"), test_mode=True)
        corr_state = build_corr_state(cfg.corr_implementation, fmap1, fmap2,
                                      cfg.corr_levels, dtype=corr_dtype,
                                      precision=cfg.corr_precision,
                                      quant=quant)
        b, h0, w0 = net_list[0].shape[:3]
        disp = jnp.zeros((b, h0, w0, 1), jnp.float32)
        if flow_init is not None:
            disp = disp + flow_init.astype(jnp.float32)
        return {"nets": tuple(net_list),
                "zqr": tuple(tuple(z) for z in zqr_list),
                "corr": tuple(corr_state),
                "disp": disp}

    def forward_step(self, variables: Dict, state: Dict,
                     iters: int = 1) -> Dict:
        """Advance the carried state by ``iters`` GRU iterations (the
        scheduler's single-iteration step executable; test-mode only)."""
        cfg = self.config
        update_vars = self._split_vars(variables, "update")
        fused = self._use_fused_gru(test_mode=True)
        _, use_epi, epi, out_channels, quant = self._corr_setup(
            update_vars, test_mode=True, fused=fused)
        corr_fn = corr_fn_from_state(cfg.corr_implementation, state["corr"],
                                     cfg.corr_levels, cfg.corr_radius,
                                     precision=cfg.corr_precision,
                                     out_dtype=self.dtype,
                                     out_channels=out_channels,
                                     epilogue=epi, quant=quant)
        disp = state["disp"]
        b, h0, w0 = disp.shape[:3]
        grid = coords_grid_x(b, h0, w0)
        step = self._step_body(update_vars, state["zqr"], corr_fn, grid,
                               test_mode=True, use_epi=use_epi, fused=fused,
                               out_channels=out_channels, quant=quant)
        (nets, disp), _ = jax.lax.scan(step, (tuple(state["nets"]), disp),
                                       None, length=iters)
        return dict(state, nets=tuple(nets), disp=disp)

    def forward_epilogue(self, variables: Dict, state: Dict):
        """Final mask head + convex upsampling: ``(disp_low, disp_up)`` —
        the same post-scan code as the monolithic test-mode ``forward``."""
        update_vars = self._split_vars(variables, "update")
        mask = self.update.apply(update_vars, state["nets"][0],
                                 method="upsample_mask")
        disp_up = convex_upsample(state["disp"], mask.astype(jnp.float32),
                                  self.config.factor)
        return state["disp"], disp_up

    # ------------------------------------------------------------- interface

    def jitted_infer(self, iters: int = 32):
        """Compiled test-mode forward: (variables, img1, img2) -> (low, up)."""
        return jax.jit(
            lambda v, i1, i2: self.forward(v, i1, i2, iters=iters,
                                           test_mode=True))

    def jitted_infer_init(self, iters: int = 32):
        """Compiled warm-start test-mode forward:
        (variables, img1, img2, flow_init) -> (low, up).

        ``flow_init`` is a (B, H/factor, W/factor, 1) disparity field added
        to the zero initialization, so passing zeros reproduces the plain
        ``jitted_infer`` bitwise (tested) — one executable serves both the
        cold and warm frames of a stream (the serving engine's warm-start
        compile cache wraps this, serve/engine.py)."""
        return jax.jit(
            lambda v, i1, i2, f: self.forward(v, i1, i2, iters=iters,
                                              flow_init=f, test_mode=True))


def count_parameters(variables: Dict) -> int:
    """Total trainable parameter count (reference: evaluate_stereo.py:15-16)."""
    return sum(x.size for x in jax.tree.leaves(variables["params"]))
