"""Client + load generator for the serving endpoint (stdlib http.client).

``ServeClient`` is a thin blocking client for one connection (keep-alive);
``run_load`` drives closed- or open-loop traffic against a server and
reports achieved throughput and latency percentiles:

* closed loop — ``concurrency`` workers each keep exactly one request in
  flight (classic saturation measurement: throughput at offered
  concurrency);
* open loop — requests fire on a fixed ``rate`` schedule regardless of
  completions (arrival-process realism: queueing delay and shedding show up
  instead of being absorbed by client backpressure).  The schedule is only
  honored while a worker is free: size ``concurrency`` >= rate x expected
  p99 latency, and check ``send_lag_p99_ms`` in the stats — when it grows,
  the workers fell behind and the run degraded toward closed loop.
"""

from __future__ import annotations

import http.client
import json
import math
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .. import wire
from ..loadgen.records import Recorder, RequestRow, summarize
from ..utils.backoff import backoff_delay
from .server import decode_array, encode_array

__all__ = ["ServeClient", "ServeError", "run_load", "synthetic_pair_pool"]


def synthetic_pair_pool(height: int, width: int, n: int = 4, seed: int = 0):
    """``make_pair`` callable over a pool of ``n`` pre-generated random
    pairs — request cost stays in the server, not in host-side RNG.
    Shared by ``cli.serve --loadgen`` and ``bench.py --serve`` so the two
    load paths drive identical synthetic traffic."""
    rng = np.random.default_rng(seed)
    pool = [(rng.integers(0, 255, (height, width, 3)).astype(np.float32),
             rng.integers(0, 255, (height, width, 3)).astype(np.float32))
            for _ in range(max(n, 1))]
    return lambda i: pool[i % len(pool)]


class ServeError(RuntimeError):
    """Non-200 reply; ``status``, the decoded error payload and (when the
    server sent one) the ``X-Request-Id`` attached — the id keys the failed
    request's spans in ``/debug/trace``."""

    def __init__(self, status: int, payload: Dict,
                 request_id: Optional[str] = None):
        msg = f"HTTP {status}: {payload.get('error', payload)}"
        if status == 413 and "limit_mb" in payload:
            # Actionable refusal, not a mystery drop: the cap auto-sizes
            # to --spatial_buckets (config.spatial_body_mb), so the fix
            # is a server configured for the resolution, not a retry.
            msg += (f" (server body cap {payload['limit_mb']} MB; an "
                    f"oversized pair needs --spatial_buckets covering it)")
        super().__init__(msg)
        self.status = status
        self.payload = payload
        self.request_id = request_id


class _RetrySafe(Exception):
    """Marks a connection failure that is provably safe to resend: the
    request never reached the server (send phase) or is idempotent
    (GET).  ``__cause__`` carries the underlying error.  The retry loop
    in ``ServeClient._request`` resends ONLY these — a response-phase
    POST failure may have executed server-side and propagates raw."""


class ServeClient:
    """Blocking client over one keep-alive connection (not thread-safe —
    load-gen workers each own one).

    ``retries`` adds bounded retry-with-backoff (exponential from
    ``retry_backoff_ms``, +-50% jitter to decorrelate client storms) on
    (a) send-side connection failures — a refused/reset connect never
    reached the server, so resending is always safe (a restarting or
    failing-over backend answers on a later attempt instead of the old
    immediate hard failure) — and (b) 5xx statuses listed in
    ``retry_statuses`` (default 502/503: shed and router-unavailable are
    transient by contract — both come with Retry-After).  Response
    timeouts are NEVER retried: the server may still be computing and a
    resend would double the work and the wait.  Default ``retries=0``
    preserves the historical fail-fast behaviour.

    ``wire_format`` picks the /predict dialect: ``"binary"`` (default —
    wire frames both ways, docs/wire_format.md) or ``"json"`` (the
    base64 dialect; the ``--json`` opt-out in cli.serve / cli.loadgen).
    ``response_encoding="int16"`` asks a binary server for the
    fixed-point disparity encoding; the exactness manifest arrives as
    ``meta["wire_manifest"]``.  ``bytes_sent``/``bytes_received`` count
    /predict body bytes both ways (the wire-bytes/pair signal the SLO
    harness reports).
    """

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 retries: int = 0, retry_backoff_ms: float = 100.0,
                 retry_statuses: Tuple[int, ...] = (502, 503),
                 wire_format: str = "binary",
                 response_encoding: str = "f32",
                 compress: bool = True, compress_level: int = 1):
        self._conn = http.client.HTTPConnection(host, port, timeout=timeout)
        assert retries >= 0, retries
        assert wire_format in ("binary", "json"), wire_format
        assert response_encoding in ("f32", "int16"), response_encoding
        self.retries = retries
        self.retry_backoff_ms = retry_backoff_ms
        self.retry_statuses = tuple(retry_statuses)
        self.wire_format = wire_format
        self.response_encoding = response_encoding
        self.compress = compress
        # Level 1 by default: the shuffle filter does most of the ratio
        # work (docs/wire_format.md "Compression"), and client-side CPU
        # is the load generator's scarce resource.
        self.compress_level = compress_level
        self.bytes_sent = 0
        self.bytes_received = 0

    def close(self) -> None:
        self._conn.close()

    def _backoff(self, attempt: int) -> None:
        time.sleep(backoff_delay(self.retry_backoff_ms, attempt))

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None,
                 headers: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        last_exc: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self._backoff(attempt - 1)
            try:
                status, raw, headers_out = self._request_once(
                    method, path, body, headers)
            except socket.timeout:
                raise  # never resend: the server may still be computing
            except _RetrySafe as e:
                # Send-phase failure (or idempotent GET): provably safe
                # to resend — the only exceptions this loop may eat.  A
                # response-phase POST failure propagates raw below: the
                # server may have processed it, so resending would run
                # inference twice (and for a session frame, advance the
                # warm-start state — see serve/cluster/router.py, which
                # makes the same send/response distinction).
                last_exc = e.__cause__
                continue
            if status in self.retry_statuses and attempt < self.retries:
                continue
            return status, raw, headers_out
        raise last_exc

    def _request_once(self, method: str, path: str,
                      body: Optional[bytes] = None,
                      headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, bytes, Dict[str, str]]:
        if headers is None:
            headers = ({"Content-Type": "application/json"} if body
                       else {})
        try:
            self._conn.request(method, path, body=body, headers=headers)
        except (http.client.HTTPException, ConnectionError, OSError):
            # Send-side failure (typically a stale keep-alive the server
            # closed while idle): the request never reached the server, so
            # one reconnect + resend is safe even for POST.
            self._conn.close()
            try:
                self._conn.request(method, path, body=body,
                                   headers=headers)
            except socket.timeout:
                self._conn.close()
                raise  # timeouts are never resent, even send-phase
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                # Still send-phase (typically connection refused): the
                # request never left, _request may back off and resend.
                self._conn.close()
                raise _RetrySafe() from e
        try:
            resp = self._conn.getresponse()
            return resp.status, resp.read(), dict(resp.headers)
        except socket.timeout:
            # Never resend on a response timeout — for /predict the server
            # may still be computing; a retry would run inference twice
            # and silently double the effective client timeout.
            self._conn.close()
            raise
        except (http.client.HTTPException, ConnectionError, OSError):
            self._conn.close()
            if method != "GET":
                raise  # non-idempotent: the server may have processed it
            # GET is idempotent: one inline resend regardless of the
            # retry budget (the historical stale-keep-alive recovery).
            try:
                self._conn.request(method, path, body=body,
                                   headers=headers)
                resp = self._conn.getresponse()
                return resp.status, resp.read(), dict(resp.headers)
            except socket.timeout:
                self._conn.close()
                raise  # timeouts are never resent (contract above)
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                self._conn.close()
                raise _RetrySafe() from e

    def predict(self, left: np.ndarray, right: np.ndarray,
                iters: Optional[int] = None,
                session_id: Optional[str] = None,
                seq_no: Optional[int] = None,
                deadline_ms: Optional[float] = None,
                priority: Optional[str] = None,
                accuracy: Optional[str] = None,
                spatial: Optional[bool] = None
                ) -> Tuple[np.ndarray, Dict]:
        """One stereo pair -> ((H, W) disparity, meta dict).

        ``session_id`` marks the pair as a frame of a video stream: the
        server warm-starts it from the session's previous frame
        (docs/streaming.md).  ``seq_no`` is the frame's position in the
        stream; omit it for an in-order client.  ``deadline_ms`` /
        ``priority`` (high/normal/low) are honored by servers running the
        iteration-level scheduler (``--sched``, docs/serving.md).
        ``accuracy`` picks an advertised accuracy tier
        (certified/fast/turbo, docs/serving.md "Accuracy tiers"); an
        unadvertised tier is a 400.  ``spatial=True`` demands the
        multi-chip spatially-sharded path (docs/serving.md "Spatial
        sharding"; the server advertises it under ``/healthz``
        ``spatial``), ``False`` forbids it, ``None`` lets the server
        auto-route pairs above its single-chip ceiling.  Raises
        ``ServeError`` on any non-200 status (503 = shed / 504 =
        timeout are expected under overload; callers count them).  A
        413 carries the server's body cap as ``limit_mb`` in the error
        payload — an oversized pair needs a server whose
        ``--spatial_buckets`` cover it (the cap auto-sizes to those
        buckets), not a retry.
        """
        fields: Dict = {}
        if iters is not None:
            fields["iters"] = int(iters)
        if accuracy is not None:
            fields["accuracy"] = str(accuracy)
        if spatial is not None:
            fields["spatial"] = bool(spatial)
        if deadline_ms is not None:
            fields["deadline_ms"] = float(deadline_ms)
        if priority is not None:
            fields["priority"] = str(priority)
        if session_id is not None:
            fields["session_id"] = str(session_id)
            if seq_no is not None:
                fields["seq_no"] = int(seq_no)
        use_binary = self.wire_format == "binary"
        if use_binary:
            if self.response_encoding != "f32" or not self.compress:
                fields["response"] = {"encoding": self.response_encoding,
                                      "compress": self.compress}
            try:
                body = wire.encode_request(
                    np.asarray(left, np.float32),
                    np.asarray(right, np.float32), fields,
                    compress=self.compress, level=self.compress_level)
            except wire.WireError:
                # A pair the frame format cannot carry (e.g. mismatched
                # shapes) must still reach the server so its validation
                # answers — the dialect choice must not change error
                # semantics. Fall back to JSON for this request.
                use_binary = False
                fields.pop("response", None)
            else:
                req_headers = {
                    "Content-Type": wire.WIRE_CONTENT_TYPE,
                    # Errors are always JSON (wire/negotiate.py):
                    # accept both.
                    "Accept": f"{wire.WIRE_CONTENT_TYPE}, "
                              "application/json",
                }
        if not use_binary:
            payload = dict(fields)
            payload["left"] = encode_array(np.asarray(left, np.float32))
            payload["right"] = encode_array(np.asarray(right, np.float32))
            body = json.dumps(payload).encode()
            req_headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            # The body field reaches the backend's scheduler; the header
            # reaches the ROUTER, which decrements it by its own elapsed
            # time at each hop and answers 504 itself once the budget is
            # exhausted (docs/fault_tolerance.md "Deadline propagation").
            req_headers["X-Deadline-Ms"] = f"{max(float(deadline_ms), 0.0):.0f}"
        self.bytes_sent += len(body)
        status, resp, headers = self._request("POST", "/predict", body,
                                              headers=req_headers)
        self.bytes_received += len(resp)
        if status != 200:
            # Error replies are JSON in both dialects.
            data = json.loads(resp)
            raise ServeError(status, data,
                             request_id=headers.get("X-Request-Id"))
        if wire.is_wire_content_type(headers.get("Content-Type")):
            res = wire.decode_response(resp)
            disparity, meta = res.disparity, dict(res.meta)
            if res.manifest is not None:
                # Exactness certificate for the int16 encoding
                # (docs/wire_format.md "int16 manifest").
                meta.setdefault("wire_manifest", res.manifest)
        else:
            data = json.loads(resp)
            disparity, meta = decode_array(data["disparity"]), data["meta"]
        # The server already puts request_id in meta; the header is
        # authoritative (and present on error replies too).
        meta.setdefault("request_id", headers.get("X-Request-Id"))
        if "X-Backend" in headers:
            # Talking through the cluster router: which backend answered
            # (docs/serving.md "Cluster").
            meta.setdefault("backend", headers["X-Backend"])
        return disparity, meta

    def _get_json(self, path: str) -> Dict:
        status, body, _ = self._request("GET", path)
        if status != 200:
            raise ServeError(status, json.loads(body))
        return json.loads(body)

    def healthz(self) -> Dict:
        return self._get_json("/healthz")

    def metrics_text(self) -> str:
        status, body, _ = self._request("GET", "/metrics")
        if status != 200:
            raise ServeError(status, json.loads(body))
        return body.decode()

    # ---------------------------------------------------- debug endpoints

    def debug_trace(self, last: Optional[int] = None,
                    trace_id: Optional[str] = None) -> Dict:
        """Chrome trace-event JSON of the server's recent spans
        (docs/observability.md); save it and open at ui.perfetto.dev."""
        qs = []
        if last is not None:
            qs.append(f"last={int(last)}")
        if trace_id is not None:
            qs.append(f"trace_id={trace_id}")
        path = "/debug/trace" + ("?" + "&".join(qs) if qs else "")
        return self._get_json(path)

    def debug_vars(self) -> Dict:
        return self._get_json("/debug/vars")

    def debug_threads(self) -> str:
        status, body, _ = self._request("GET", "/debug/threads")
        if status != 200:
            raise ServeError(status, json.loads(body))
        return body.decode()

    def debug_profile(self, seconds: float) -> Dict:
        """Start an on-demand jax.profiler window on the server; raises
        ``ServeError`` (409) while a capture is already running."""
        status, body, _ = self._request(
            "POST", "/debug/profile",
            json.dumps({"seconds": seconds}).encode())
        data = json.loads(body)
        if status != 200:
            raise ServeError(status, data)
        return data


def run_load(host: str, port: int,
             make_pair: Callable[[int], Tuple[np.ndarray, np.ndarray]],
             requests: int = 64, concurrency: int = 4,
             mode: str = "closed", rate: Optional[float] = None,
             iters: Optional[int] = None,
             sequence_len: Optional[int] = None,
             timeout: float = 120.0, retries: int = 0,
             accuracy: Optional[str] = None,
             wire_format: str = "binary",
             response_encoding: str = "f32") -> Dict:
    """Drive ``requests`` pairs at the server; returns a stats dict.

    ``make_pair(i)`` supplies the i-th request's images (mix shapes to
    exercise several compile buckets).  ``mode='open'`` requires ``rate``
    (requests/sec): send times are fixed at ``i / rate`` from start,
    regardless of completions.

    ``retries`` enables the client's bounded retry-with-backoff (see
    ``ServeClient``) — load-gen against a router or a restarting server
    rides out refused connections and transient 502/503 instead of
    counting them as hard errors.

    ``sequence_len`` switches to SEQUENCE REPLAY (streaming traffic):
    request ``i`` is frame ``i % sequence_len`` of session
    ``loadgen-{i // sequence_len}``, sent with ``session_id``/``seq_no``
    so the server warm-starts it.  Workers claim whole sequences (a
    session's frames must arrive in order), and the stats grow
    ``warm_frames``/``cold_frames`` from the response meta — a quick check
    that warm starts actually engaged.

    ``wire_format`` selects the /predict dialect per ``ServeClient``
    (binary wire frames by default, ``"json"`` for the base64 dialect);
    the summary then carries ``wire_bytes_per_pair`` —
    request + response body bytes per served pair — so the two formats
    are directly comparable on the same traffic (docs/wire_format.md).

    Implementation rides the SLO harness's recorder
    (raftstereo_tpu/loadgen/records.py): one ``RequestRow`` per request,
    and the summary — including the historical key set — is
    ``records.summarize`` over the rows, so the same per-request data
    that certifies SLOs backs this quick path too.
    """
    assert mode in ("closed", "open"), mode
    if mode == "open" and not rate:
        raise ValueError("open-loop load needs a rate (requests/sec)")
    if sequence_len is not None:
        assert sequence_len >= 1, sequence_len
        if iters is not None:
            raise ValueError("explicit iters cannot drive sequence replay "
                             "(the server's controller owns per-frame "
                             "iterations)")
    recorder = Recorder()
    lock = threading.Lock()
    next_idx = [0]
    t_start = time.perf_counter()

    def claim() -> Optional[int]:
        """Next request index; sequence replay claims a whole sequence so
        one worker owns a session's frames in order."""
        stride = sequence_len or 1
        with lock:
            i = next_idx[0]
            if i >= requests:
                return None
            next_idx[0] += stride
            return i

    def run_one(client: ServeClient, i: int) -> None:
        lag_ms = 0.0
        sched_ms = math.nan
        if mode == "open":
            sched_ms = i / rate * 1e3
            delay = t_start + i / rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            else:
                lag_ms = -delay * 1e3
        left, right = make_pair(i)
        session = seq = None
        if sequence_len is not None:
            session = f"loadgen-{i // sequence_len}"
            seq = i % sequence_len
        fields = dict(index=i, t_sched_ms=sched_ms,
                      t_send_ms=(time.perf_counter() - t_start) * 1e3,
                      send_lag_ms=lag_ms, tier=accuracy or "default",
                      iters=iters, height=int(left.shape[0]),
                      width=int(left.shape[1]),
                      session=session or "", seq_no=seq,
                      wire=client.wire_format)
        sent0, recv0 = client.bytes_sent, client.bytes_received

        def used() -> Dict:
            return dict(bytes_sent=client.bytes_sent - sent0,
                        bytes_received=client.bytes_received - recv0)

        t0 = time.perf_counter()
        try:
            _, meta = client.predict(left, right, iters=iters,
                                     session_id=session, seq_no=seq,
                                     accuracy=accuracy)
        except ServeError as e:
            kind = {503: "shed", 504: "timeout"}.get(e.status, "error")
            recorder.add(RequestRow(
                outcome=kind, latency_ms=(time.perf_counter() - t0) * 1e3,
                status=e.status, request_id=e.request_id or "",
                **used(), **fields))
        except Exception:
            recorder.add(RequestRow(outcome="error", latency_ms=math.nan,
                                    **used(), **fields))
        else:
            recorder.add(RequestRow(
                outcome="ok",
                latency_ms=(time.perf_counter() - t0) * 1e3,
                status=200, iters_done=meta.get("iters"),
                warm=meta.get("warm"),
                degraded=bool(meta.get("degraded", False)),
                backend=meta.get("backend", ""),
                request_id=meta.get("request_id") or "",
                **used(), **fields))

    def worker():
        client = ServeClient(host, port, timeout=timeout, retries=retries,
                             wire_format=wire_format,
                             response_encoding=response_encoding)
        try:
            while True:
                start = claim()
                if start is None:
                    return
                stop = min(start + (sequence_len or 1), requests)
                for i in range(start, stop):
                    run_one(client, i)
        finally:
            client.close()

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"loadgen-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return summarize(recorder.rows(), mode=mode, requests=requests,
                     concurrency=concurrency, wall_s=wall, rate=rate,
                     sequence_len=sequence_len)
