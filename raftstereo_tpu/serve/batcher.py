"""Dynamic micro-batcher: coalesce concurrent requests into padded batches.

Deadline-aware dynamic batching in the spirit of Clipper (Crankshaw et al.,
NSDI 2017): a single worker thread groups queued requests by (shape bucket,
requested iterations) and closes a batch when it reaches
``max_batch_size`` or when the OLDEST member has waited ``max_wait_ms``,
whichever comes first — so batching never adds more than one deadline of
latency at low load, and amortizes dispatch at high load.

Robustness controls, all tested in tests/test_serve.py:

* admission control — a bounded queue; ``submit`` raises ``Overloaded``
  (HTTP 503) instead of queueing unbounded work, so overload sheds cleanly
  rather than growing latency without bound;
* per-request timeout — requests older than ``request_timeout_ms`` at
  dispatch time fail with ``RequestTimedOut`` instead of wasting a batch
  slot on an answer the client gave up on;
* graceful degradation — when the backlog crosses
  ``degrade_queue_depth``, batches run at ``degraded_iters`` instead of
  ``iters``.  RAFT-Stereo's iterative refinement makes this knob uniquely
  cheap: fewer ConvGRU iterations trade accuracy smoothly for ~linear
  latency, with no second model or resolution change.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, Dict, Optional, Tuple

import numpy as np

from ..config import ServeConfig
from .metrics import ServeMetrics

__all__ = ["DynamicBatcher", "Future", "Overloaded", "RequestTimedOut",
           "ServeResult", "ShuttingDown"]


class Overloaded(RuntimeError):
    """Admission control rejected the request: the queue is full."""


class RequestTimedOut(RuntimeError):
    """The request exceeded request_timeout_ms before dispatch."""


class ShuttingDown(RuntimeError):
    """The batcher is stopping and will not accept or answer requests."""


@dataclasses.dataclass
class ServeResult:
    """One answered request: the disparity plus how it was computed."""

    disparity: np.ndarray  # (H, W) float32, dataset sign convention
    iters: int
    degraded: bool
    batch_size: int
    latency_s: float
    # Which cluster replica answered (serve/cluster/dispatcher.py);
    # None on the single-engine path.
    replica: Optional[str] = None


class Future:
    """Minimal thread-safe single-assignment result slot."""

    def __init__(self):
        self._done = threading.Event()
        self._value: Optional[ServeResult] = None
        self._exc: Optional[BaseException] = None
        self._cb_lock = threading.Lock()
        self._callbacks = []  # guarded_by: _cb_lock

    def _resolve(self, value=None, exc=None) -> None:
        """Settle the future and run callbacks ON THIS THREAD.

        Never call while holding a lock a callback may need: the cluster
        dispatcher's settle callback reads every replica's queue depth
        (serve/cluster/dispatcher.py), so resolving under one replica's
        ``_cv`` while another worker does the same is an ABBA deadlock —
        collect futures under the lock, resolve after releasing it
        (asserted in tests/test_cluster.py)."""
        self._value, self._exc = value, exc
        self._done.set()
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            fn(self)

    def add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` when the future resolves (immediately if it
        already has).  Callbacks run on the resolving thread; a waiter
        blocked in ``result()`` may wake concurrently, so callers that
        must annotate the value before anyone reads it chain a second
        future from the callback (serve/cluster/dispatcher.py does)."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def done(self) -> bool:
        return self._done.is_set()

    def exception(self) -> Optional[BaseException]:
        """The failure, if resolved with one (None while pending)."""
        return self._exc if self._done.is_set() else None

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._exc is not None:
            raise self._exc
        return self._value


@dataclasses.dataclass
class _Request:
    image1: np.ndarray
    image2: np.ndarray
    iters: Optional[int]
    future: Future
    t_enqueue: float
    seq: int
    # Trace id of the originating request (obs/trace.py): the dispatch
    # worker reconstructs queue-wait/dispatch/host-fetch spans under it.
    trace_id: Optional[str] = None
    # Resolved precision mode of the request's accuracy tier
    # (ops/quant.py; None = the engine's default path).
    mode: Optional[str] = None


# Group key: (bucket_h, bucket_w, explicit iters or None, precision mode
# or None).  Requests with an explicit per-request iteration count cannot
# share a batch with adaptive ones — iters is baked into the compiled
# executable — and neither can requests of different accuracy tiers: the
# mode selects a different program with different numerics.
_Key = Tuple[int, int, Optional[int], Optional[str]]


class DynamicBatcher:
    """Thread-safe request queue + single dispatch worker over an engine.

    The engine contract is ``bucket_of(shape) -> (h, w)`` and
    ``infer_batch(pairs, iters, mode=None) -> [disparity]`` (see
    engine.BatchEngine; tests substitute stubs — ``mode`` is the
    request's resolved precision mode, always passed by keyword).
    """

    def __init__(self, engine, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None, tracer=None):
        self.engine = engine
        self.cfg = config
        self.metrics = metrics or ServeMetrics()
        self.tracer = tracer  # obs.Tracer or None (tracing is optional)
        self._cv = threading.Condition()
        self._queues: Dict[_Key, Deque[_Request]] = {}  # guarded_by: _cv
        self._depth = 0  # guarded_by: _cv
        self._seq = 0  # guarded_by: _cv
        self._closed = False  # guarded_by: _cv
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "DynamicBatcher":
        assert self._thread is None, "batcher already started"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-batcher")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the worker.  ``drain=True`` answers everything still queued
        first; ``drain=False`` fails queued requests with ``ShuttingDown``."""
        to_fail = []
        with self._cv:
            self._closed = True
            if not drain:
                for q in self._queues.values():
                    to_fail.extend(r.future for r in q)
                self._queues.clear()
                self._depth = 0
                self.metrics.queue_depth.set(0)
            self._cv.notify_all()
        # Outside _cv: resolving runs done-callbacks that may read this
        # (or another replica's) queue depth — see Future._resolve.
        for fut in to_fail:
            fut._resolve(exc=ShuttingDown("batcher stopped"))
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "DynamicBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- admission

    @property
    def queue_depth(self) -> int:
        with self._cv:  # vs a concurrent submit/close mutating the count
            return self._depth

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               iters: Optional[int] = None,
               trace_id: Optional[str] = None,
               mode: Optional[str] = None) -> Future:
        """Enqueue one stereo pair; returns a ``Future`` for the result.

        Raises ``Overloaded`` immediately when the queue is at
        ``queue_limit`` — the caller maps this to HTTP 503 so clients see a
        clear shed signal instead of an unbounded wait.  ``trace_id`` tags
        the request's spans (queue wait, dispatch, host fetch) in the
        tracer ring.  ``mode`` is the request's resolved precision mode
        (accuracy tier): it joins the grouping key, so tiers never share
        a dispatched batch.
        """
        key: _Key = (*self.engine.bucket_of(image1.shape), iters, mode)
        fut = Future()
        with self._cv:
            if self._closed:
                raise ShuttingDown("batcher stopped")
            if self._depth >= self.cfg.queue_limit:
                self.metrics.shed.inc()
                raise Overloaded(
                    f"queue full ({self._depth}/{self.cfg.queue_limit})")
            self._seq += 1
            self._queues.setdefault(key, collections.deque()).append(
                _Request(image1, image2, iters, fut, time.perf_counter(),
                         self._seq, trace_id, mode))
            self._depth += 1
            self.metrics.queue_depth.set(self._depth)
            self._cv.notify_all()
        return fut

    # --------------------------------------------------------------- worker

    def _oldest_key(self) -> _Key:  # guarded_by: _cv
        """Key whose head request has waited longest (caller holds lock)."""
        return min(self._queues, key=lambda k: self._queues[k][0].seq)

    def _loop(self) -> None:
        max_wait_s = self.cfg.max_wait_ms / 1000.0
        while True:
            with self._cv:
                while not self._closed and self._depth == 0:
                    self._cv.wait()
                if self._depth == 0:  # closed and drained
                    return
                key = self._oldest_key()
                deadline = self._queues[key][0].t_enqueue + max_wait_s
                # Hold the batch open until it fills or the oldest member's
                # deadline passes; new arrivals notify the condition.
                while (len(self._queues.get(key, ()))
                       < self.cfg.max_batch_size and not self._closed):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                q = self._queues.get(key)
                if not q:  # drained by a non-drain stop
                    continue
                batch = [q.popleft() for _ in
                         range(min(len(q), self.cfg.max_batch_size))]
                if not q:
                    del self._queues[key]
                self._depth -= len(batch)
                # Backlog measured at batch close, including this batch:
                # the signal that decides graceful degradation.
                backlog = self._depth + len(batch)
                self.metrics.queue_depth.set(self._depth)
            self._dispatch(key, batch, backlog)

    def _trace_batch(self, key: _Key, batch, iters: int, degraded: bool,
                     t_run0: float, t_done: float, error=None) -> None:
        """Reconstruct each request's phase spans from the dispatch the
        worker just ran: queue wait (enqueue -> batch close), dispatch
        (engine call through device compute) and host fetch — siblings
        under the request's trace id, so their durations sum to the
        server-side latency (asserted in tests/test_obs.py)."""
        seg = getattr(self.engine, "last_segments", None) if error is None \
            else None
        bucket = f"{key[0]}x{key[1]}"
        for r in batch:
            if r.trace_id is None:
                continue
            self.tracer.record(
                "queue_wait", r.t_enqueue, t_run0, r.trace_id,
                attrs={"bucket": bucket})
            attrs = {"bucket": bucket, "iters": iters, "degraded": degraded,
                     "batch_size": len(batch)}
            if error is not None:
                attrs["error"] = str(error)
            if seg is None:
                self.tracer.record("dispatch", t_run0, t_done, r.trace_id,
                                   attrs=attrs)
                continue
            attrs["compile"] = seg["compile"]
            parent = self.tracer.record(
                "dispatch", t_run0, seg["dispatch"][1], r.trace_id,
                attrs=attrs)
            if seg.get("pad"):
                self.tracer.record("pad_bucket", *seg["pad"], r.trace_id,
                                   parent_id=parent)
            self.tracer.record("device_compute", *seg["dispatch"],
                               r.trace_id, parent_id=parent)
            self.tracer.record("host_fetch", *seg["host_fetch"], r.trace_id)

    def _dispatch(self, key: _Key, batch, backlog: int) -> None:
        now = time.perf_counter()
        timeout_s = self.cfg.request_timeout_ms / 1000.0
        alive = []
        for r in batch:
            if now - r.t_enqueue > timeout_s:
                self.metrics.timeouts.inc()
                if self.tracer is not None and r.trace_id is not None:
                    self.tracer.record(
                        "queue_wait", r.t_enqueue, now, r.trace_id,
                        attrs={"outcome": "timeout"})
                r.future._resolve(exc=RequestTimedOut(
                    f"queued {now - r.t_enqueue:.3f}s > "
                    f"{timeout_s:.3f}s limit"))
            else:
                alive.append(r)
        if not alive:
            return
        explicit_iters = key[2]
        if explicit_iters is not None:
            iters, degraded = explicit_iters, False
        else:
            degraded = backlog >= self.cfg.degrade_queue_depth
            iters = (self.cfg.degraded_iters if degraded
                     else self.cfg.iters)
        if degraded:
            self.metrics.degraded_batches.inc()
        t_run0 = time.perf_counter()
        try:
            disps = self.engine.infer_batch(
                [(r.image1, r.image2) for r in alive], iters,
                mode=key[3])
        except Exception as e:  # fail the batch, keep serving
            self.metrics.errors.inc(len(alive))
            if self.tracer is not None:
                self._trace_batch(key, alive, iters, degraded, t_run0,
                                  time.perf_counter(), error=e)
            for r in alive:
                r.future._resolve(exc=e)
            return
        done = time.perf_counter()
        if self.tracer is not None:
            self._trace_batch(key, alive, iters, degraded, t_run0, done)
        self.metrics.batch_size.observe(len(alive))
        for r, d in zip(alive, disps):
            latency = done - r.t_enqueue
            self.metrics.latency.observe(latency)
            self.metrics.responses.inc()
            r.future._resolve(value=ServeResult(
                disparity=d, iters=iters, degraded=degraded,
                batch_size=len(alive), latency_s=latency))
