"""Serving observability: a small metrics registry with Prometheus text
exposition (no client library dependency — the format is plain text).

Three instrument kinds: monotonically increasing ``Counter``, last-value
``Gauge`` and the fixed-bucket ``LatencyHistogram`` from utils/profiling.py
(shared with the Evaluator's per-call timing).  ``MetricsRegistry.render``
emits the text format Prometheus scrapes from ``GET /metrics``:

    # HELP serve_requests_total ...
    # TYPE serve_requests_total counter
    serve_requests_total 42
    serve_request_latency_seconds_bucket{le="0.1"} 17
    ...

``ServeMetrics`` bundles every instrument the serving subsystem records, so
the engine, batcher and HTTP layer share one object and ``/metrics`` is one
render call.
"""

from __future__ import annotations

import math
import threading
from typing import List, Optional, Tuple

from ..utils.profiling import LatencyHistogram

__all__ = ["Counter", "Gauge", "MetricsRegistry", "ServeMetrics"]


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-value instrument (Prometheus ``gauge``)."""

    def __init__(self):
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        return self._value


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return format(v, ".9g")


class MetricsRegistry:
    """Ordered name -> instrument registry with Prometheus text rendering."""

    def __init__(self):
        self._entries: List[Tuple[str, str, str, object]] = []
        self._lock = threading.Lock()

    def _register(self, kind: str, name: str, help_: str, obj):
        with self._lock:
            if any(e[1] == name for e in self._entries):
                raise ValueError(f"metric {name!r} already registered")
            self._entries.append((kind, name, help_, obj))
        return obj

    def counter(self, name: str, help_: str) -> Counter:
        return self._register("counter", name, help_, Counter())

    def gauge(self, name: str, help_: str) -> Gauge:
        return self._register("gauge", name, help_, Gauge())

    def histogram(self, name: str, help_: str,
                  bounds=None, lo: float = 1e-4,
                  hi: float = 60.0) -> LatencyHistogram:
        return self._register("histogram", name, help_,
                              LatencyHistogram(bounds=bounds, lo=lo, hi=hi))

    def render(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        lines: List[str] = []
        with self._lock:
            entries = list(self._entries)
        for kind, name, help_, obj in entries:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                # One atomic snapshot: _count must equal the +Inf bucket.
                pairs, count, total = obj.prometheus()
                for bound, cum in pairs:
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
                lines.append(f"{name}_sum {format(total, '.9g')}")
                lines.append(f"{name}_count {count}")
            else:
                lines.append(f"{name} {_fmt(obj.value)}")
        return "\n".join(lines) + "\n"


class ServeMetrics:
    """Every instrument the serving subsystem records, in one bundle."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "serve_requests_total", "requests submitted to the batcher")
        self.responses = r.counter(
            "serve_responses_total", "requests answered successfully")
        self.shed = r.counter(
            "serve_shed_total",
            "requests rejected at admission because the queue was full")
        self.timeouts = r.counter(
            "serve_timeout_total",
            "requests that exceeded request_timeout_ms while queued")
        self.errors = r.counter(
            "serve_errors_total", "requests failed by an engine error")
        self.degraded_batches = r.counter(
            "serve_degraded_batches_total",
            "batches run at degraded_iters due to queue backlog")
        self.compile_hits = r.counter(
            "serve_compile_cache_hits_total",
            "batches dispatched to an already-compiled executable")
        self.compile_misses = r.counter(
            "serve_compile_cache_misses_total",
            "batches whose (bucket, iters) shape triggered an XLA compile")
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests currently waiting in the queue")
        self.batch_size = r.histogram(
            "serve_batch_size", "real (un-padded) requests per batch",
            bounds=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64))
        self.latency = r.histogram(
            "serve_request_latency_seconds",
            "submit-to-result latency per request (queue wait + compute)")
        self.batch_latency = r.histogram(
            "serve_batch_latency_seconds",
            "engine wall-clock per dispatched batch (forward + host fetch)")
        # Temporal warm-start streaming (stream/, docs/streaming.md).
        self.stream_active = r.gauge(
            "stream_sessions_active", "live sessions in the session store")
        self.stream_warm_frames = r.counter(
            "stream_warm_frames_total",
            "frames warm-started from the previous frame's disparity")
        self.stream_cold_frames = r.counter(
            "stream_cold_frames_total",
            "frames run cold (new/expired/evicted/out-of-sequence session "
            "or controller cold reset)")
        self.stream_evicted = r.counter(
            "stream_sessions_evicted_total",
            "sessions LRU-evicted because the store hit session_limit")
        self.stream_expired = r.counter(
            "stream_sessions_expired_total",
            "sessions dropped after idling past session_ttl_s")
        self.stream_frame_iters = r.histogram(
            "stream_frame_iters", "GRU iterations run per streamed frame",
            bounds=(1, 2, 4, 8, 12, 16, 24, 32, 48, 64))
        self.stream_frame_latency = r.histogram(
            "stream_frame_latency_seconds",
            "per-frame wall-clock (warp + forward + host fetch), "
            "compile-free frames only")

    def render(self) -> str:
        return self.registry.render()
